"""Device-backed conflict index + execution drain for a CommandStore.

This is the live protocol wiring of the two TPU kernels (SURVEY.md §7
stages 3-4): every globally-visible transaction a store witnesses is
registered in a struct-of-arrays DepsTable slot kept incrementally in sync
with the host command state, PreAccept/Accept/BeginRecovery dependency scans
run through ops.deps_kernel.calculate_deps, and the executeAt-gated
execution drain is driven by ops.drain_kernel.ready_frontier over a live
adjacency graph instead of per-dependency listener fan-out.

Ref semantics preserved:
 - deps scan: accord-core/src/main/java/accord/local/CommandsForKey.java:614-650
   (mapReduceActive) + InMemoryCommandStore.java:863-877 (range scan) +
   messages/PreAccept.java:245-265 (calculatePartialDeps)
 - drain: local/Commands.java:656-857 (maybeExecute /
   updateDependencyAndMaybeExecute / NotifyWaitingOn)

Host numpy mirrors are the source of truth (the sim mutates them in place,
deterministically, under the store's single-threaded task queue).  The deps
table's device buffers are refreshed by scatter-updating only dirty rows, so
on TPU the table stays HBM-resident between queries and only deltas cross
the PCIe/ICI boundary; the drain graph is uploaded whole per tick — it is
bounded by the in-flight (stable-but-unapplied) set, which sweep_free keeps
small.  The host command records remain authoritative for execution: the
kernel proposes the ready frontier, and each candidate is re-validated
against its WaitingOn bitset before executing — any mirror divergence
degrades to a no-op, never a wrong execution.

Regime-adaptive dispatch: every batched deps scan is routed per flush to
the cheapest of THREE routes, all of which feed the same snapshot, exact
overlap triples, floors, elision and attribution code — the protocol never
sees which route ran (results are bit-identical by construction).  Since
r10 the device kernels answer EXACTLY (sorted composite overlap-triple
codes; ops.deps_kernel module docstring) and the result download is
two-stage and compacted: the scalar header first, then only the live
entry prefix — the host-side collect is a pure vectorized decode:

 - **host**: a vectorized numpy interval scan over only the LIVE TAIL
   (slots above the batch-global RedundantBefore floor): token-sorted point
   entries probed with searchsorted, flat range entries stabbed with one
   broadcast.  Wins when the live working set is small relative to a device
   round trip (the hot-key / durable-prefix-dominated regime, where 90%+ of
   the table sits below the floor and RTTs dominate a ~10k-entry scan).
 - **bucketed** (device): the CINTIA-analogue bucket index
   (ops.deps_kernel.bucketed_flat) — O(candidates) per query.  Under a
   mesh the bucket rows are row-sharded (parallel.sharded.
   sharded_bucketed_flat) with device-side floor pruning.
 - **dense** (device): the exact O(N) kernel — the fallback when footprint
   distributions defeat bucketing (straggler spill, wide queries).  Under a
   mesh it row-shards the slot table (sharded_calculate_deps_flat[_pruned]).

Device-fault tolerance (the degradation ladder): the accelerator is a
FAILURE DOMAIN, not a trusted coprocessor.  Every device-boundary operation
(kernel launch, upload, result download, capacity grow) can fail — really
(XlaRuntimeError / transfer error / HBM OOM) or injected (utils.faults'
seedable device-fault registry, the accelerator-side analogue of the sim's
network nemesis).  Because all routes are bit-identical, failure handling
is CORRECTNESS-PRESERVING by construction:

    device route -> quarantine -> host route -> compaction -> backpressure

 - any device-boundary exception during a flush quarantines the device
   routes and FAILS THE IN-FLIGHT FLUSH OVER to the host route — the
   protocol sees the same bytes, one flush later than the kernel would
   have delivered them;
 - while quarantined every flush (and drain tick) is pinned to host; the
   quarantine expires after an exponential-backoff flush count with
   deterministic jitter, then ONE probe flush re-tries the device route —
   success restores it, failure re-quarantines deeper;
 - paranoia mode (utils.faults.PARANOIA or DeviceState.paranoia)
   shadow-verifies every device flush against the host route and treats a
   mismatch as a device fault — the detector for silent result corruption
   (the stale_result fault class);
 - a configurable device-memory budget (``device_budget_slots``, also env
   ACCORD_TPU_DEVICE_BUDGET_SLOTS) backpressures ``_grow_capacity``: at
   the budget the mirror COMPACTS (frees slots wholly below the global
   RedundantBefore floor — exactly the entries every attributed scan
   would drop) instead of doubling, and if compaction cannot make room the
   store degrades PINNED-TO-HOST (degraded-but-live) with a loud one-shot
   event rather than dying.

Quarantine/fallback/compaction counters ride the bench ``# index:`` line,
``Cluster.stats`` (DeviceFault.*) and the structured trace
(utils.trace record_fault / record_quarantine).

The crossover is NOT hard-coded: a once-per-process micro-probe measures
the device round-trip cost, the device per-element kernel cost and the
host per-element scan cost (DeviceState._measure_route_calibration); the
router compares a modeled host scan cost (live-above-floor working set,
estimated O(1) per dispatch from _DepsMirror's incremental counters +
RedundantBefore.version) against the modeled device cost and picks the
cheaper side.  ``DeviceState.route_override`` pins a route for tests and
benches; per-route dispatch counters (n_host_queries / n_bucketed_queries /
n_dense_queries / n_mesh_queries) make routing regressions visible in
every BENCH artifact.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs import devprof
from ..ops import deps_kernel as dk
from ..ops import drain_kernel as drk
from ..ops.packing import to_i64
from ..primitives.keys import Range, Ranges
from ..primitives.timestamp import Domain, Kinds, Timestamp, TxnId
from ..utils import faults
from ..utils.random_source import RandomSource

_MIN_CAPACITY = 64
_MIN_INTERVALS = 4


def _pow2_at_least(n: int, floor: int = _MIN_INTERVALS) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


# the fused dirty-row scatter jits moved to ops.deps_kernel (r21): the
# per-slice store-shard sync dispatches the same programs once per slice
# device, so one implementation serves both residencies
_scatter_rows = dk.scatter_table_rows
_scatter_attr_rows = dk.scatter_attr_cols


_PZ = None


def _prune_zeros():
    """Replicated zero floor for the always-pruned sharded kernels: under
    the unsigned ts_lt order nothing sorts below (0, 0, 0), so a zero
    triple prunes nothing (same convention as calculate_deps' default)."""
    global _PZ
    if _PZ is None:
        _PZ = (jnp.asarray(np.int64(0)), jnp.asarray(np.int64(0)),
               jnp.asarray(np.int32(0)))
    return _PZ


def _grow(arr: np.ndarray, new_len: int, fill) -> np.ndarray:
    out = np.full((new_len,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


_FETCH_POOL = None


def _fetch_pool():
    """Shared two-worker pool for the two-stage download prefetch: the
    pipelined path keeps at most two flushes in flight, and spawning a
    fresh thread per flush measured ~2ms/batch of pure start_new_thread
    on the 2-core box — a fifth of the whole headline batch budget."""
    global _FETCH_POOL
    if _FETCH_POOL is None:
        from concurrent.futures import ThreadPoolExecutor
        _FETCH_POOL = ThreadPoolExecutor(max_workers=2,
                                         thread_name_prefix="accord-fetch")
    return _FETCH_POOL


def _prefix_len(maxtot: int, s: int) -> int:
    """Length of the live entry prefix to transfer, rounded up to a coarse
    granularity so the device-side slice compiles a bounded number of
    shapes (at most ~16 per learned ``s``) instead of one per total."""
    gran = max(128, s >> 4)
    return min(s, -(-maxtot // gran) * gran)


def _fetch_entry_prefix(ent_dev, d: int, s: int, maxtot: int) -> np.ndarray:
    """Stage-2 of the compacted download: transfer ONLY the live prefix of
    each shard's entry block (the pow2-padded tail never crosses the wire).
    Returns host [d, L]."""
    length = _prefix_len(maxtot, s)
    if length == 0:
        return np.zeros((d, 0), np.dtype(ent_dev.dtype))
    if d == 1:
        return np.asarray(ent_dev[:length]).reshape(1, length)
    return np.asarray(ent_dev.reshape(d, s)[:, :length])


def _decode_triples(hdr: np.ndarray, ent: np.ndarray, nq: int,
                    shard_n: int, global_ids: bool, mq: int, q_m: int,
                    hoff: int = 2):
    """Vectorized parse of a (possibly multi-shard) exact CSR download:
    one concatenate/gather over the stacked shard headers replaces the
    per-shard Python parse loop.  Returns per-TRIPLE arrays
    (b, slot, dep_col, q_col); slot indices are shard-local for the
    slot-sharded kernels (offset by the shard's slice here) and GLOBAL
    for the bucket-indexed kernels (codes embed global slot ids).
    ``hoff`` is the header's scalar prefix length (2 raw, 5 attributed)."""
    d = hdr.shape[0]
    counts = np.diff(hdr[:, hoff:].astype(np.int64), prepend=0, axis=1)
    totals = hdr[:, 0].astype(np.int64)
    b = np.repeat(np.tile(np.arange(nq, dtype=np.int64), d),
                  counts.reshape(-1))
    live = np.arange(ent.shape[1])[None, :] < totals[:, None]
    j, m_i, q_i = dk.decode_triples(ent[live], mq // q_m, q_m)
    if not global_ids and d > 1:
        j = j + np.repeat(np.arange(d, dtype=np.int64) * shard_n, totals)
    return b, j, m_i, q_i


def _tri_pairs(tb: np.ndarray, tj: np.ndarray):
    """Derive the exact (query, slot) pair list from triple arrays whose
    (b, j) runs are contiguous (true per shard block by the kernels' code
    sort, and preserved by concatenation because shard/part pair sets are
    disjoint).  Returns (b_idx, j_idx, p_i) with p_i mapping each triple
    to its pair row — the shape attribution consumes."""
    n = len(tb)
    first = np.ones(n, bool)
    if n:
        first[1:] = (tb[1:] != tb[:-1]) | (tj[1:] != tj[:-1])
    p_i = np.cumsum(first) - 1
    return tb[first], tj[first], p_i


@jax.jit
def _scatter_bucket_rows(dev, idx, rows):
    """Fused dirty-bucket update for the seven bucket-entry arrays."""
    return tuple(a.at[idx].set(r) for a, r in zip(dev, rows))


def _host_index_of(status, lo_a, hi_a, msb, lsb, node, fkey):
    """Build the host-route index (see _DepsMirror.host_index) from
    explicit arrays — shared by the live cached path and the snapshot-based
    fused fallback/shadow path.  ``fkey`` is the normalized floor (None =
    no floor)."""
    live = (status >= 0) & (status != dk.SLOT_INVALIDATED)
    if fkey is not None:
        from ..ops.packing import to_u64
        fm = np.uint64(to_u64(to_i64(fkey.msb)))
        fl = np.uint64(to_u64(to_i64(fkey.lsb)))
        fn = np.int32(fkey.node)
        um = msb.astype(np.uint64)
        ul = lsb.astype(np.uint64)
        live &= ((um > fm) | ((um == fm)
                             & ((ul > fl) | ((ul == fl) & (node >= fn)))))
    j = np.nonzero(live)[0]
    lo, hi = lo_a[j], hi_a[j]
    used = lo <= hi
    pt = used & (lo == hi)
    rr, cc = np.nonzero(pt)
    ptok = lo[rr, cc]
    order = np.argsort(ptok, kind="stable")
    rr2, cc2 = np.nonzero(used & ~pt)
    return (ptok[order], j[rr][order], cc[order],
            lo[rr2, cc2], hi[rr2, cc2], j[rr2], cc2)


class _DepsMirror:
    """Host mirror of one store's DepsTable, with dirty-row tracking, plus
    the host half of the bucketed interval index (the CINTIA-analogue in
    ops.deps_kernel.bucketed_flat): per-bucket (lo, hi, slot) entry lists
    kept incrementally, wide/overflow entries in a straggler set, dirty
    buckets scatter-updated to the device alongside the slot table."""

    # bucket width = 2^BSHIFT tokens; intervals (and query probes) touching
    # more than SPAN buckets go to the wide/straggler path
    BSHIFT = 6
    SPAN = 4
    BUCKET_K = 128        # entries per bucket before spilling wide
    WIDE_MAX = 4096       # beyond this many stragglers the dense scan wins

    def __init__(self, capacity: int = _MIN_CAPACITY,
                 max_intervals: int = _MIN_INTERVALS):
        self.capacity = capacity
        self.max_intervals = max_intervals
        # owning DeviceState (set by DeviceState.__init__): consulted before
        # any capacity grow so the HBM budget can compact-instead-of-double
        # (see DeviceState._approve_grow)
        self.owner = None
        self.msb = np.zeros(capacity, np.int64)
        self.lsb = np.zeros(capacity, np.int64)
        self.node = np.zeros(capacity, np.int32)
        self.kind = np.zeros(capacity, np.int32)
        self.domain = np.zeros(capacity, np.int8)   # Domain enum value
        self.status = np.full(capacity, dk.SLOT_FREE, np.int32)
        self.lo = np.full((capacity, max_intervals), dk.PAD_LO, np.int64)
        self.hi = np.full((capacity, max_intervals), dk.PAD_HI, np.int64)
        # decided executeAt per slot (host-only; drives the VECTORIZED
        # transitive-elision check in attribution)
        self.emsb = np.zeros(capacity, np.int64)
        self.elsb = np.zeros(capacity, np.int64)
        self.enode = np.zeros(capacity, np.int32)
        self.eknown = np.zeros(capacity, bool)
        self.slot_of: Dict[TxnId, int] = {}
        self.id_of: Dict[int, TxnId] = {}
        # parallel object column: obj[slot] is the TxnId living in the slot
        # (None when free) — snapshot with the packed columns at batch
        # begin, so result attribution is a pure C-level take instead of a
        # per-slot dict lookup + verification
        self.obj = np.full(capacity, None, object)
        self.free_slots: List[int] = list(range(capacity - 1, -1, -1))
        self._dirty: Set[int] = set()
        self._device: Optional[dk.DepsTable] = None
        # r21 store-shard residency (parallel.store_shard.StoreShards, set
        # by the owner's spill rung): while active, the sharded table /
        # attr uploads route through per-slice resident buffers with their
        # OWN dirty sets — device_table() consumes and clears ``_dirty``,
        # so the sliced consumer must not share it
        self.shards = None
        self._dirty_sh: Set[int] = set()
        self._attr_dirty_sh: Set[int] = set()
        # mesh-sharded slot-table copy, cached SEPARATELY from the
        # single-device one (r08 satellite: the router alternating
        # single-device and mesh routes between flushes used to clobber
        # one consumer's copy with the other's placement and re-upload —
        # or worse, implicitly reshard — on every switch).  Keyed on the
        # mutation version: the dep mask reads only liveness from the
        # status column, and every mutation it can observe (alloc/free,
        # invalidate, footprint growth) bumps ``version``
        self._device_sh: Optional[dk.DepsTable] = None
        self._device_sh_key = None
        # -- bucket index (host truth); entries are (lo, hi, slot, col)
        # where col is the interval's column in its slot row — the third
        # leg of the exact overlap triple the kernels emit --
        self.bucket_row: Dict[int, int] = {}     # bucket id -> dense row
        self.bucket_entries: List[List[Tuple[int, int, int, int]]] = []
        self.bucket_dirty: Set[int] = set()
        self.wide_entries: Set[Tuple[int, int, int, int]] = set()
        # live-occupancy high-water across bucket rows (monotonic, like
        # capacity): the kernels slice the entry axis to its pow2 — the
        # [G, BUCKET_K] rows are ~95% padding on spread keyspaces and the
        # candidate matrix (and kernel wall) shrinks proportionally
        self.bucket_max_len = 0
        self._bhost = None                        # 8 host row arrays
        self._bdev = None                         # jnp 8-tuple
        self._bdev_pending: Set[int] = set()      # rows _bdev hasn't seen
        self._g_cap = 0
        # wide/straggler host arrays cached PER PADDED WIDTH (r08): the
        # single-device and mesh consumers may ask for different pow2
        # floors, and alternating routes between flushes must not rebuild
        # (and re-upload) the wide list on every switch — each width keeps
        # its own copy keyed on the wide version counter
        self._whost_cache: Dict[int, tuple] = {}  # w -> (wide_version, arrs)
        self._wdev = None                         # (wlo, whi, wslot...) jnp
        self._wdev_key = None
        self._bsh = None                          # mesh-sharded BucketTable
        self._bsh_key = None
        self._sorted_bids = np.zeros(0, np.int64)
        self._row_of_sorted = np.zeros(0, np.int32)
        self._bids_stale = False
        # -- routing state (see module docstring): incremental mutation /
        # liveness counters + the cached floor stats and host-route index.
        # ``version`` bumps on every slot mutation, ``bucket_version`` /
        # ``wide_version`` on bucket-index mutations (they key the sharded
        # bucket upload), ``n_live`` counts non-free non-invalidated slots
        # exactly — together they make the live-above-floor estimate O(1)
        # amortized per dispatch.
        self.version = 0
        self.bucket_version = 0
        self.wide_version = 0
        self.n_live = 0
        # ``mut_version`` bumps on EVERY column write (unlike ``version``,
        # which skips live->live status moves the kernels cannot observe):
        # it keys the deferred-collect snapshot cache, whose columns the
        # host attribution DOES read in full
        self.mut_version = 0
        self._snap = None
        self._fstats = None                       # cached floor stats
        self._hidx = None                         # cached host-route index
        self._hidx_key = None
        # -- device attribution columns (r15): domain / fresh status /
        # decided executeAt, scatter-updated alongside the slot table so
        # the ATTRIBUTED kernels can apply elision in-kernel.  They get
        # their own dirty set and version: unlike the dep mask, the
        # attribution pass DOES observe live->live status moves and
        # executeAt writes, so the sharded (full-reupload) caches key on
        # ``attr_version``, not ``version``
        self.attr_version = 0
        self._attr_dirty: Set[int] = set()
        self._attr_dev = None                     # dk.AttrCols (1 device)
        self._attr_repl = None                    # replicated under a mesh
        self._attr_repl_key = None
        self._attr_sh = None                      # slot-sharded under a mesh
        self._attr_sh_key = None

    # -- bucket index maintenance -------------------------------------------
    def bucket_keff(self) -> int:
        """Static entry-axis slice for the bucketed kernels: the pow2 of
        the live-occupancy high-water (floor 8, cap BUCKET_K)."""
        return min(self.BUCKET_K,
                   _pow2_at_least(max(self.bucket_max_len, 1), 8))

    def _bucket_add(self, slot: int, lo: int, hi: int, col: int) -> None:
        if self.status[slot] == dk.SLOT_INVALIDATED:
            return   # structurally excluded (de-indexed on invalidation)
        self.bucket_version += 1
        blo, bhi = lo >> self.BSHIFT, hi >> self.BSHIFT
        if bhi - blo + 1 > self.SPAN:
            self.wide_entries.add((lo, hi, slot, col))
            self.wide_version += 1
            return
        for bid in range(blo, bhi + 1):
            row = self.bucket_row.get(bid)
            if row is None:
                row = len(self.bucket_entries)
                self.bucket_row[bid] = row
                self.bucket_entries.append([])
                self._bids_stale = True
            ents = self.bucket_entries[row]
            if len(ents) >= self.BUCKET_K:
                # overflow spill: the straggler list absorbs hot buckets
                self.wide_entries.add((lo, hi, slot, col))
                self.wide_version += 1
            else:
                ents.append((lo, hi, slot, col))
                self.bucket_dirty.add(row)
                if len(ents) > self.bucket_max_len:
                    self.bucket_max_len = len(ents)

    def _bucket_remove(self, slot: int) -> None:
        """De-index every interval of ``slot`` (called before the row's
        lo/hi are cleared on free)."""
        self.bucket_version += 1
        row_lo, row_hi = self.lo[slot], self.hi[slot]
        for m in range(self.max_intervals):
            lo, hi = int(row_lo[m]), int(row_hi[m])
            if lo > hi:
                continue
            ent = (lo, hi, slot, m)
            blo, bhi = lo >> self.BSHIFT, hi >> self.BSHIFT
            if bhi - blo + 1 > self.SPAN:
                if ent in self.wide_entries:
                    self.wide_entries.discard(ent)
                    self.wide_version += 1
                continue
            spilled = False
            for bid in range(blo, bhi + 1):
                r = self.bucket_row.get(bid)
                if r is not None:
                    try:
                        self.bucket_entries[r].remove(ent)
                        self.bucket_dirty.add(r)
                        continue
                    except ValueError:
                        pass
                spilled = True
            if spilled and ent in self.wide_entries:
                self.wide_entries.discard(ent)
                self.wide_version += 1

    def bid_rows(self) -> Tuple[np.ndarray, np.ndarray]:
        """(sorted bucket ids, dense row per id) for vectorized query->row
        mapping via searchsorted."""
        if self._bids_stale or len(self._sorted_bids) != len(self.bucket_row):
            n = len(self.bucket_row)
            bids = np.fromiter(self.bucket_row.keys(), np.int64, n)
            rows = np.fromiter(self.bucket_row.values(), np.int32, n)
            order = np.argsort(bids)
            self._sorted_bids = bids[order]
            self._row_of_sorted = rows[order]
            self._bids_stale = False
        return self._sorted_bids, self._row_of_sorted

    def _fill_bucket_row(self, arrs, r, ents) -> None:
        """Write one bucket's entries into the 8 host row arrays, with the
        immutable id/kind columns read from the mirror (entries are live,
        so the mirror columns are current for their slots)."""
        blo, bhi, bslot, bcol, bmsb, blsb, bnode, bkind = arrs
        blo[r] = dk.PAD_LO
        bhi[r] = dk.PAD_HI
        bslot[r] = -1
        bcol[r] = 0
        for i, (lo, hi, s, col) in enumerate(ents):
            blo[r, i] = lo
            bhi[r, i] = hi
            bslot[r, i] = s
            bcol[r, i] = col
            bmsb[r, i] = self.msb[s]
            blsb[r, i] = self.lsb[s]
            bnode[r, i] = self.node[s]
            bkind[r, i] = self.kind[s]

    def _sync_bucket_host(self) -> None:
        """Bring the 7 host bucket-row arrays (``_bhost``) up to date with
        ``bucket_entries`` — the single source both device consumers (the
        single-device jnp copy and the mesh-sharded upload) build from, so
        alternating consumers (the router switches routes between flushes)
        never see each other's dirty-set consumption."""
        k = self.BUCKET_K
        g_cap = _pow2_at_least(max(len(self.bucket_entries), 1), 64)
        if self._bhost is None or g_cap != self._g_cap:
            blo = np.full((g_cap, k), dk.PAD_LO, np.int64)
            bhi = np.full((g_cap, k), dk.PAD_HI, np.int64)
            bslot = np.full((g_cap, k), -1, np.int32)
            bcol = np.zeros((g_cap, k), np.int32)
            bmsb = np.zeros((g_cap, k), np.int64)
            blsb = np.zeros((g_cap, k), np.int64)
            bnode = np.zeros((g_cap, k), np.int32)
            bkind = np.zeros((g_cap, k), np.int32)
            self._bhost = (blo, bhi, bslot, bcol, bmsb, blsb, bnode, bkind)
            for r, ents in enumerate(self.bucket_entries):
                if ents:
                    self._fill_bucket_row(self._bhost, r, ents)
            self._g_cap = g_cap
            self.bucket_dirty.clear()
            self._bdev = None          # shape changed: full re-upload
            self._bdev_pending.clear()
        elif self.bucket_dirty:
            rows = sorted(self.bucket_dirty)
            for r in rows:
                self._fill_bucket_row(self._bhost, r, self.bucket_entries[r])
            self._bdev_pending.update(rows)
            self.bucket_dirty.clear()

    def _sync_wide_host(self, floor: int):
        """Host arrays for the wide/straggler entries, padded to a pow2 of
        at least ``floor`` (the mesh caller passes its device count so the
        wide dimension row-shards evenly).  Cached per padded width and
        keyed on the wide version counter, so single-device and mesh
        consumers asking for different widths never invalidate each
        other's copy."""
        w = _pow2_at_least(max(len(self.wide_entries), 1), floor)
        hit = self._whost_cache.get(w)
        if hit is None or hit[0] != self.wide_version:
            wlo = np.full(w, dk.PAD_LO, np.int64)
            whi = np.full(w, dk.PAD_HI, np.int64)
            wslot = np.full(w, -1, np.int32)
            wcol = np.zeros(w, np.int32)
            wmsb = np.zeros(w, np.int64)
            wlsb = np.zeros(w, np.int64)
            wnode = np.zeros(w, np.int32)
            wkind = np.zeros(w, np.int32)
            for i, (lo, hi, s, col) in enumerate(self.wide_entries):
                wlo[i] = lo
                whi[i] = hi
                wslot[i] = s
                wcol[i] = col
                wmsb[i] = self.msb[s]
                wlsb[i] = self.lsb[s]
                wnode[i] = self.node[s]
                wkind[i] = self.kind[s]
            hit = (self.wide_version,
                   (wlo, whi, wslot, wcol, wmsb, wlsb, wnode, wkind))
            self._whost_cache[w] = hit
            if len(self._whost_cache) > 4:   # widths only grow; drop stale
                for stale_w in sorted(self._whost_cache)[:-4]:
                    del self._whost_cache[stale_w]
        return hit[1]

    def bucket_device(self) -> "dk.BucketTable":
        """Sync the bucket index to the (single) device — dirty-row scatter,
        like the slot table — and return the BucketTable."""
        self._sync_bucket_host()
        if self._bdev is None or self._bdev_pending:
            faults.check("transfer", "bucket upload")
        if self._bdev is None:
            self._bdev = tuple(jnp.asarray(a) for a in self._bhost)
            self._bdev_pending.clear()
        elif self._bdev_pending:
            rows = sorted(self._bdev_pending)
            padded = _pow2_at_least(len(rows), 8)
            idx = np.concatenate([np.array(rows, np.int32),
                                  np.full(padded - len(rows), rows[-1],
                                          np.int32)])
            self._bdev = _scatter_bucket_rows(
                self._bdev, jnp.asarray(idx),
                tuple(a[idx] for a in self._bhost))
            self._bdev_pending.clear()
        whost = self._sync_wide_host(16)
        wkey = (self.wide_version, whost[0].shape[0])
        if self._wdev is None or self._wdev_key != wkey:
            faults.check("transfer", "wide upload")
            self._wdev = tuple(jnp.asarray(a) for a in whost)
            self._wdev_key = wkey
        return dk.BucketTable(*self._bdev, *self._wdev)

    def bucket_device_sharded(self, mesh) -> "dk.BucketTable":
        """Mesh placement of the bucket index: bucket ROWS and the wide list
        row-sharded across the mesh (the per-shard slices feed
        parallel.sharded.sharded_bucketed_flat).  Any mutation triggers a
        full sharded re-upload, keyed on the bucket/wide version counters —
        same policy as device_table_sharded."""
        self._sync_bucket_host()
        d = int(np.prod(list(mesh.shape.values())))
        whost = self._sync_wide_host(max(16, d))
        key = (self.bucket_version, self.wide_version, self._g_cap,
               whost[0].shape[0], tuple(dev.id for dev in mesh.devices.flat))
        if self._bsh is not None and self._bsh_key == key:
            return self._bsh
        from ..parallel.sharded import shard_bucket_table
        self._bsh = shard_bucket_table(
            mesh, dk.BucketTable(*self._bhost, *whost))
        self._bsh_key = key
        return self._bsh

    # -- slot management ----------------------------------------------------
    def alloc(self, txn_id: TxnId) -> int:
        slot = self.slot_of.get(txn_id)
        if slot is not None:
            return slot
        if not self.free_slots:
            self._grow_capacity()
        slot = self.free_slots.pop()
        self.slot_of[txn_id] = slot
        self.id_of[slot] = txn_id
        self.obj[slot] = txn_id
        self.eknown[slot] = False
        self.msb[slot] = to_i64(txn_id.msb)
        self.lsb[slot] = to_i64(txn_id.lsb)
        self.node[slot] = txn_id.node
        self.kind[slot] = int(txn_id.kind())
        self.domain[slot] = int(txn_id.domain())
        self.status[slot] = dk.SLOT_TRANSITIVE
        self.lo[slot] = dk.PAD_LO
        self.hi[slot] = dk.PAD_HI
        self._dirty.add(slot)
        if self.shards is not None:
            self._dirty_sh.add(slot)
        self._mark_attr(slot)
        self.version += 1
        self.mut_version += 1
        self.n_live += 1
        return slot

    def free(self, txn_id: TxnId) -> None:
        slot = self.slot_of.pop(txn_id, None)
        if slot is None:
            return
        self.id_of.pop(slot, None)
        self.obj[slot] = None
        self.eknown[slot] = False
        self._bucket_remove(slot)
        if self.status[slot] != dk.SLOT_INVALIDATED:
            self.n_live -= 1
        self.status[slot] = dk.SLOT_FREE
        self.lo[slot] = dk.PAD_LO
        self.hi[slot] = dk.PAD_HI
        self.free_slots.append(slot)
        self._dirty.add(slot)
        if self.shards is not None:
            self._dirty_sh.add(slot)
        self._mark_attr(slot)
        self.version += 1
        self.mut_version += 1

    def _grow_capacity(self) -> None:
        if self.owner is not None and not self.owner._approve_grow(self):
            # HBM backpressure: compaction made room under the budget —
            # the caller's free_slots.pop() proceeds without doubling
            return
        old = self.capacity
        new = old * 2
        self.msb = _grow(self.msb, new, 0)
        self.lsb = _grow(self.lsb, new, 0)
        self.node = _grow(self.node, new, 0)
        self.kind = _grow(self.kind, new, 0)
        self.domain = _grow(self.domain, new, 0)
        self.status = _grow(self.status, new, dk.SLOT_FREE)
        self.lo = _grow(self.lo, new, dk.PAD_LO)
        self.hi = _grow(self.hi, new, dk.PAD_HI)
        self.obj = _grow(self.obj, new, None)
        self.emsb = _grow(self.emsb, new, 0)
        self.elsb = _grow(self.elsb, new, 0)
        self.enode = _grow(self.enode, new, 0)
        self.eknown = _grow(self.eknown, new, False)
        self.free_slots.extend(range(new - 1, old - 1, -1))
        self.capacity = new
        self.mut_version += 1
        self._snap = None
        self._device = None  # shape changed: full re-upload
        self._device_sh = None
        self._attr_dev = None
        self._attr_repl = None
        self._attr_sh = None
        self.attr_version += 1

    def _grow_intervals(self) -> None:
        new_m = self.max_intervals * 2
        lo = np.full((self.capacity, new_m), dk.PAD_LO, np.int64)
        hi = np.full((self.capacity, new_m), dk.PAD_HI, np.int64)
        lo[:, : self.max_intervals] = self.lo
        hi[:, : self.max_intervals] = self.hi
        self.lo, self.hi = lo, hi
        self.max_intervals = new_m
        self.mut_version += 1
        self._snap = None
        self._device = None
        self._device_sh = None

    def add_intervals(self, slot: int, tokens: Sequence[int],
                      ranges: Sequence[Range]) -> None:
        """Union new intervals into the slot's footprint (idempotent)."""
        row_lo, row_hi = self.lo[slot], self.hi[slot]
        used = int(np.sum(row_lo <= row_hi))
        new: List[Tuple[int, int]] = []
        for t in tokens:
            new.append((t, t))
        for r in ranges:
            new.append((r.start, r.end - 1))
        for lo_v, hi_v in new:
            present = False
            for m in range(used):
                if row_lo[m] <= lo_v and hi_v <= row_hi[m]:
                    present = True
                    break
            if present:
                continue
            while used >= self.max_intervals:
                self._grow_intervals()
                row_lo, row_hi = self.lo[slot], self.hi[slot]
            row_lo[used] = lo_v
            row_hi[used] = hi_v
            self._dirty.add(slot)
            if self.shards is not None:
                self._dirty_sh.add(slot)
            self.version += 1
            self.mut_version += 1
            self._bucket_add(slot, lo_v, hi_v, used)
            used += 1

    def set_status(self, slot: int, status: int) -> None:
        cur = int(self.status[slot])
        if cur != status:
            if status == dk.SLOT_INVALIDATED and cur != dk.SLOT_FREE:
                self.n_live -= 1
                # liveness changed: the host-route index (which excludes
                # dead slots STRUCTURALLY) is stale.  Live->live status
                # moves deliberately do NOT bump: the index carries only
                # geometry + liveness, and commit/apply churn between
                # flushes would otherwise rebuild it every flush in
                # exactly the hot regime the host route serves
                self.version += 1
            self.status[slot] = status
            self._dirty.add(slot)
            if self.shards is not None:
                self._dirty_sh.add(slot)
            self._mark_attr(slot)
            self.mut_version += 1

    # -- device attribution columns (r15) -----------------------------------
    def _mark_attr(self, slot: int) -> None:
        self._attr_dirty.add(slot)
        if self.shards is not None:
            self._attr_dirty_sh.add(slot)
        self.attr_version += 1

    def mark_exec(self, slot: int) -> None:
        """An executeAt landed on ``slot`` (emsb/elsb/enode/eknown written
        by DeviceState._advance_status): the device attribution columns
        must see it before the next attributed launch."""
        self._attr_dirty.add(slot)
        if self.shards is not None:
            self._attr_dirty_sh.add(slot)
        self.attr_version += 1
        self.mut_version += 1   # snapshot columns changed too

    def _attr_host_cols(self):
        return (self.domain.astype(np.int32), self.status,
                self.msb, self.lsb, self.node,
                self.emsb, self.elsb, self.enode, self.eknown)

    def device_attr_cols(self) -> "dk.AttrCols":
        """Single-device attribution columns, dirty-row scatter-updated in
        lockstep with device_table()."""
        if self._attr_dev is None or self._attr_dirty:
            faults.check("transfer", "attr column upload")
        if self._attr_dev is None:
            self._attr_dev = dk.AttrCols(
                *(jnp.asarray(a) for a in self._attr_host_cols()))
            self._attr_dirty.clear()
        elif self._attr_dirty:
            rows = np.array(sorted(self._attr_dirty), np.int32)
            if len(rows) * 2 >= self.capacity:
                self._attr_dev = None
                return self.device_attr_cols()
            padded = _pow2_at_least(len(rows), 8)
            rows = np.concatenate([rows, np.full(padded - len(rows),
                                                 rows[-1], np.int32)])
            idx = jnp.asarray(rows)
            host = self._attr_host_cols()
            self._attr_dev = _scatter_attr_rows(
                self._attr_dev, idx, *(a[rows] for a in host))
            self._attr_dirty.clear()
        return self._attr_dev

    def device_attr_cols_replicated(self, mesh) -> "dk.AttrCols":
        """Fully-replicated attribution columns for the mesh-sharded
        BUCKETED kernel (entries carry global slot ids, so every shard
        grades every slot).  Keyed on attr_version: any status/executeAt
        write re-replicates — these columns are O(N) scalars, small next
        to the interval table the mesh exists to split."""
        key = (self.attr_version, self.capacity,
               tuple(dev.id for dev in mesh.devices.flat))
        if self._attr_repl is not None and self._attr_repl_key == key:
            return self._attr_repl
        faults.check("transfer", "attr replicated upload")
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        sr = NamedSharding(mesh, P())
        self._attr_repl = dk.AttrCols(
            *(jax.device_put(a, sr) for a in self._attr_host_cols()))
        self._attr_repl_key = key
        return self._attr_repl

    def device_attr_cols_sharded(self, mesh) -> "dk.AttrCols":
        """Slot-sharded attribution columns for the mesh-sharded DENSE
        kernel (each shard grades only its own slice), keyed on
        attr_version (NOT ``version``: elision observes live->live status
        moves and executeAt writes the dep mask never reads)."""
        if self.shards is not None and self.shards.active:
            return self.shards.attr_cols()
        key = (self.attr_version, self.capacity,
               tuple(dev.id for dev in mesh.devices.flat))
        if self._attr_sh is not None and self._attr_sh_key == key:
            return self._attr_sh
        faults.check("transfer", "attr sharded upload")
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        from ..parallel.sharded import STORE_AXIS
        s1 = NamedSharding(mesh, P(STORE_AXIS))
        self._attr_sh = dk.AttrCols(
            *(jax.device_put(a, s1) for a in self._attr_host_cols()))
        self._attr_sh_key = key
        return self._attr_sh

    # -- host route (the third dispatch target; see module docstring) -------
    def _above_floor_mask(self, floor_id) -> np.ndarray:
        """bool[capacity]: packed id >= floor, under EXACTLY the kernel's
        ts_lt order (unsigned on the two int64 words, then signed node)."""
        from ..ops.packing import to_u64
        fm = np.uint64(to_u64(to_i64(floor_id.msb)))
        fl = np.uint64(to_u64(to_i64(floor_id.lsb)))
        fn = np.int32(floor_id.node)
        um = self.msb.astype(np.uint64)
        ul = self.lsb.astype(np.uint64)
        return ((um > fm) | ((um == fm)
                            & ((ul > fl) | ((ul == fl) & (self.node >= fn)))))

    def floor_stats(self, floor_id) -> Dict[str, float]:
        """Estimated shape of the LIVE-above-floor working set: slot count,
        point/range interval-entry counts and the point-token span.  Cached;
        recomputed (one vectorized pass) only when the floor changes or the
        mutation version drifts past 1/8 of the live set — between
        recomputes the slot-count delta (``n_live`` is exact) scales the
        entry estimates, so the router's read is O(1) per dispatch."""
        fkey = floor_id if floor_id is not None and floor_id > TxnId.NONE \
            else None
        st = self._fstats
        if st is None or st["floor"] != fkey or \
                self.version - st["version"] > max(64, st["n_at"] >> 3):
            live = (self.status >= 0) & (self.status != dk.SLOT_INVALIDATED)
            if fkey is not None:
                live &= self._above_floor_mask(fkey)
            j = np.nonzero(live)[0]
            lo, hi = self.lo[j], self.hi[j]
            used = lo <= hi
            pt = used & (lo == hi)
            n_pt = int(pt.sum())
            toks = lo[pt]
            st = self._fstats = {
                "floor": fkey, "version": self.version, "n_at": self.n_live,
                "n_above": len(j), "n_pt": n_pt,
                "n_rng": int(used.sum()) - n_pt,
                "tok_lo": int(toks.min()) if n_pt else 0,
                "tok_hi": int(toks.max()) if n_pt else 0}
        grown = max(self.n_live - st["n_at"], 0)
        per = (st["n_pt"] + st["n_rng"]) / max(st["n_at"], 1)
        frac_pt = st["n_pt"] / max(st["n_pt"] + st["n_rng"], 1)
        return {"n_above": st["n_above"] + grown,
                "n_pt": st["n_pt"] + grown * per * frac_pt,
                "n_rng": st["n_rng"] + grown * per * (1.0 - frac_pt),
                "tok_lo": st["tok_lo"], "tok_hi": st["tok_hi"]}

    def host_index(self, floor_id):
        """(ptok, pslot, pcol, rlo, rhi, rslot, rcol): the live-above-floor
        tail as a token-SORTED point-entry array plus a flat range-entry
        table — the reference's own scan shape (CommandsForKey sorted
        arrays + rangeCommands, ref: local/CommandsForKey.java:614-650),
        rebuilt from the mirror whenever a mutation lands and cached
        between flushes.  ``pcol``/``rcol`` record each entry's interval
        column in its slot row, so probes yield exact emit triples and the
        collect pass never rebuilds the overlap geometry."""
        fkey = floor_id if floor_id is not None and floor_id > TxnId.NONE \
            else None
        key = (fkey, self.version)
        if self._hidx is not None and self._hidx_key == key:
            return self._hidx
        self._hidx = _host_index_of(self.status, self.lo, self.hi,
                                    self.msb, self.lsb, self.node, fkey)
        self._hidx_key = key
        return self._hidx

    def host_pairs(self, qnp: np.ndarray, q_m: int, floor_id,
                   snapshot=None, entries: bool = False):
        """The host route's candidate generation: (b_idx, j_idx) pairs
        satisfying the EXACT kernel predicate (liveness + floor structurally
        via the index; witness / earlier / not-self as vectorized compares
        identical to the device ts_lt), deduped per (query, slot), plus the
        exact emit triples (pair row, entry interval column, query interval
        column) the probes discovered — the same set np.nonzero over the
        device routes' overlap matrix yields, so attribution sees identical
        inputs and results are bit-identical by construction.

        ``snapshot`` = (msb, lsb, node, kind, status, lo, hi) computes the
        scan against a begin-time copy of the mirror instead of the live
        arrays (no caching): the fused harvest path runs a store task
        AFTER dispatch, and its host fallback / shadow verify must answer
        for the snapshot the device kernel scanned, not for mutations that
        landed in between."""
        if snapshot is not None:
            s_msb, s_lsb, s_node, s_kind, s_status, s_lo, s_hi = snapshot
            fkey = floor_id if floor_id is not None \
                and floor_id > TxnId.NONE else None
            idx = _host_index_of(s_status, s_lo, s_hi, s_msb, s_lsb,
                                 s_node, fkey)
            cap = len(s_msb)
        else:
            s_msb, s_lsb, s_node, s_kind = (self.msb, self.lsb, self.node,
                                            self.kind)
            idx = self.host_index(floor_id)
            cap = self.capacity
        ptok, pslot, pcol, rlo, rhi, rslot, rcol = idx
        lo = qnp[:, 7:7 + q_m]
        hi = qnp[:, 7 + q_m:7 + 2 * q_m]
        used = lo <= hi
        # duplicate query intervals (same (lo, hi) as an earlier column of
        # the same row) probe identical slices and emit identical entries
        # the finalize would dedupe anyway — drop them at the probe (the
        # kernels' first-q dedupe is the device analogue)
        for m_i_ in range(1, q_m):
            dup = np.zeros(qnp.shape[0], bool)
            for m_j_ in range(m_i_):
                dup |= ((lo[:, m_i_] == lo[:, m_j_])
                        & (hi[:, m_i_] == hi[:, m_j_]) & used[:, m_j_])
            used[:, m_i_] &= ~dup
        qi, mi = np.nonzero(used)
        flo = lo[qi, mi]
        fhi = hi[qi, mi]
        parts_b: List[np.ndarray] = []
        parts_j: List[np.ndarray] = []
        parts_m: List[np.ndarray] = []
        parts_q: List[np.ndarray] = []
        if len(ptok):
            # token-sorted probe: every query interval (point OR range)
            # selects the contiguous token slice it covers
            l = np.searchsorted(ptok, flo, side="left")
            r = np.searchsorted(ptok, fhi, side="right")
            cnt = r - l
            tot = int(cnt.sum())
            if tot:
                owner = np.repeat(np.arange(len(qi)), cnt)
                # pos = per-probe slice start + within-slice offset, with
                # ONE repeat: arange(tot) already walks each slice 0..cnt
                # after subtracting the repeated running base
                pos = np.arange(tot) + np.repeat(l - (np.cumsum(cnt) - cnt),
                                                 cnt)
                parts_b.append(qi[owner])
                parts_j.append(pslot[pos])
                parts_m.append(pcol[pos])
                parts_q.append(mi[owner])
        if len(rlo) and len(qi):
            ov = (rlo[None, :] <= fhi[:, None]) & (flo[:, None] <= rhi[None, :])
            ii, jj = np.nonzero(ov)
            parts_b.append(qi[ii])
            parts_j.append(rslot[jj])
            parts_m.append(rcol[jj])
            parts_q.append(mi[ii])
        empty = (np.zeros(0, np.int64), np.zeros(0, np.int64))
        if not parts_b:
            if entries:
                return (np.zeros(0, np.int64),) * 4
            return empty + ((np.zeros(0, np.int64),) * 3,)
        cb = np.concatenate(parts_b).astype(np.int64)
        cj = np.concatenate(parts_j).astype(np.int64)
        cm = np.concatenate(parts_m).astype(np.int64)
        cq = np.concatenate(parts_q).astype(np.int64)
        em, el, en = s_msb[cj], s_lsb[cj], s_node[cj]
        keep = (qnp[cb, 3] >> s_kind[cj]) & 1 > 0
        uem, ubm = em.view(np.uint64), qnp[cb, 0].view(np.uint64)
        uel, ubl = el.view(np.uint64), qnp[cb, 1].view(np.uint64)
        bn = qnp[cb, 2]
        keep &= ((uem < ubm) | ((uem == ubm)
                               & ((uel < ubl) | ((uel == ubl) & (en < bn)))))
        keep &= ~((em == qnp[cb, 4]) & (el == qnp[cb, 5])
                  & (en == qnp[cb, 6]))
        if not keep.all():
            cb, cj, cm, cq = cb[keep], cj[keep], cm[keep], cq[keep]
        if entries:
            # the attributed paths consume per-ENTRY arrays directly —
            # skip the (query, slot) pair compression (one 1-D sort of
            # the whole emit set) the legacy pair API pays
            return cb, cj, cm, cq
        pair, p_i = np.unique(cb * np.int64(cap) + cj,
                              return_inverse=True)
        return pair // cap, pair % cap, (p_i, cm, cq)

    def snapshot_cols(self):
        """(ids 9-tuple, ivs 3-tuple, kind) copies of every column the
        deferred collect + attribution path reads, cached on
        ``mut_version`` — back-to-back deferred flushes (pipelined bench
        batches, fused-harvest members) over an unmutated mirror share ONE
        copy instead of re-copying O(capacity x intervals) bytes per
        flush.  Consumers must treat the arrays as frozen."""
        s = self._snap
        if s is None or s[0] != self.mut_version:
            ids = (self.msb.copy(), self.lsb.copy(), self.node.copy(),
                   self.obj.copy(), self.status.copy(), self.emsb.copy(),
                   self.elsb.copy(), self.enode.copy(),
                   self.eknown.copy())
            ivs = (self.lo.copy(), self.hi.copy(), self.domain.copy())
            s = self._snap = (self.mut_version, ids, ivs,
                              self.kind.copy())
        return s[1], s[2], s[3]

    # -- device sync --------------------------------------------------------
    def device_table_sharded(self, mesh) -> dk.DepsTable:
        """Mesh placement: the slot dimension sharded across the mesh,
        cached SEPARATELY from the single-device copy and keyed on the
        mutation version counter — the router alternating single-device
        and mesh routes between flushes keeps BOTH copies live instead of
        invalidating one whenever the other syncs (pre-r08 this clobbered
        the shared cache and paid an implicit reshard per alternation).
        Any version drift triggers a full sharded re-upload (the
        incremental scatter path is single-device; on the virtual CPU mesh
        correctness is the point, and a real multi-chip deployment would
        shard the scatter too).  Live->live status moves don't bump the
        version: the dep mask reads only liveness from the status column,
        so a stale live status byte cannot change any answer."""
        if self.shards is not None and self.shards.active:
            # r21 sliced residency: per-slice scatter sync + zero-copy
            # assembly (with quarantined slices' status masked) replaces
            # the monolithic full re-upload
            return self.shards.table()
        key = (self.version, self.capacity, self.max_intervals,
               tuple(dev.id for dev in mesh.devices.flat))
        if self._device_sh is not None and self._device_sh_key == key:
            return self._device_sh
        faults.check("transfer", "sharded slot upload")
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        from ..parallel.sharded import STORE_AXIS
        s1 = NamedSharding(mesh, P(STORE_AXIS))
        s2 = NamedSharding(mesh, P(STORE_AXIS, None))
        self._device_sh = dk.DepsTable(
            jax.device_put(self.msb, s1), jax.device_put(self.lsb, s1),
            jax.device_put(self.node, s1), jax.device_put(self.kind, s1),
            jax.device_put(self.status, s1), jax.device_put(self.lo, s2),
            jax.device_put(self.hi, s2))
        self._device_sh_key = key
        return self._device_sh

    def device_table(self) -> dk.DepsTable:
        if self._device is None or self._dirty:
            faults.check("transfer", "slot upload")
        if self._device is None:
            self._device = dk.DepsTable(
                jnp.asarray(self.msb), jnp.asarray(self.lsb),
                jnp.asarray(self.node), jnp.asarray(self.kind),
                jnp.asarray(self.status), jnp.asarray(self.lo),
                jnp.asarray(self.hi))
            self._dirty.clear()
        elif self._dirty:
            rows = np.array(sorted(self._dirty), np.int32)
            if len(rows) * 2 >= self.capacity:
                # mostly dirty: a full upload is cheaper than a scatter
                self._device = None
                return self.device_table()
            # pad to a power-of-two bucket (repeating the last row: scatter
            # of identical values is idempotent) so jit caches one
            # compilation per bucket instead of one per dirty-count
            padded = _pow2_at_least(len(rows), 8)
            rows = np.concatenate([rows, np.full(padded - len(rows),
                                                 rows[-1], np.int32)])
            self._device = _scatter_rows(
                self._device, jnp.asarray(rows),
                self.msb[rows], self.lsb[rows], self.node[rows],
                self.kind[rows], self.status[rows],
                self.lo[rows], self.hi[rows])
            self._dirty.clear()
        return self._device


@jax.jit
def _scatter_drain_scalars(status, em, el, en, idx, s_new, em_new, el_new,
                           en_new):
    """One fused dirty-row update for the drain state's scalar columns —
    the delta-upload path that replaced the r07 whole-graph upload per
    tick (the adjacency re-uploads only when edges or membership change)."""
    return (status.at[idx].set(s_new), em.at[idx].set(em_new),
            el.at[idx].set(el_new), en.at[idx].set(en_new))


class _DrainMirror:
    """Host mirror of the execution drain graph: SPARSE adjacency over the
    store's in-flight (stable-but-unapplied) txns and their direct
    dependencies — per-slot dep/waiter sets, the host analogue of the
    reference's WaitingOn bitset-over-txnIds (ref: local/Command.java:
    1295-1332).  The r04 dense bool[capacity, capacity] matrix needed
    O(N^2) host memory (10^10 entries at the 100k-in-flight spec); edge
    count here is bounded by the live waiting sets.

    r08 delta uploads: the compacted device state is CACHED between ticks.
    ``version`` bumps on any device-visible mutation, ``membership_version``
    on alloc/free (the live set — and therefore the compaction mapping —
    changed), ``edge_version`` on adjacency changes; status/executeAt moves
    land in ``_dirty_scalars``.  A tick whose membership and edges are
    unchanged scatter-updates only the dirty scalar rows of the cached
    device state instead of rebuilding and re-uploading the whole graph —
    exactly the dirty-row policy the deps table already uses."""

    def __init__(self, capacity: int = _MIN_CAPACITY):
        self.capacity = capacity
        self.deps_of: List[Set[int]] = [set() for _ in range(capacity)]
        self.waiters_of: List[Set[int]] = [set() for _ in range(capacity)]
        self.status = np.full(capacity, dk.SLOT_FREE, np.int32)
        self.exec_msb = np.zeros(capacity, np.int64)
        self.exec_lsb = np.zeros(capacity, np.int64)
        self.exec_node = np.zeros(capacity, np.int32)
        self.awaits_all = np.zeros(capacity, bool)
        self.active = np.zeros(capacity, bool)   # rows being driven to execution
        self.slot_of: Dict[TxnId, int] = {}
        self.id_of: Dict[int, TxnId] = {}
        self.free_slots: List[int] = list(range(capacity - 1, -1, -1))
        self.version = 0
        self.membership_version = 0
        self.edge_version = 0
        self._dirty_scalars: Set[int] = set()
        self._state_cache: Optional[Dict[str, object]] = None

    # -- edge maintenance ---------------------------------------------------
    def add_edge(self, waiter: int, dep: int) -> None:
        self.deps_of[waiter].add(dep)
        self.waiters_of[dep].add(waiter)
        self.edge_version += 1
        self.version += 1

    def clear_deps(self, slot: int) -> None:
        if self.deps_of[slot]:
            self.edge_version += 1
            self.version += 1
        for dep in self.deps_of[slot]:
            self.waiters_of[dep].discard(slot)
        self.deps_of[slot].clear()

    def _clear_edges(self, slot: int) -> None:
        self.clear_deps(slot)
        if self.waiters_of[slot]:
            self.edge_version += 1
            self.version += 1
        for w in self.waiters_of[slot]:
            self.deps_of[w].discard(slot)
        self.waiters_of[slot].clear()

    def alloc(self, txn_id: TxnId) -> int:
        slot = self.slot_of.get(txn_id)
        if slot is not None:
            return slot
        if not self.free_slots:
            self._grow_capacity()
        slot = self.free_slots.pop()
        self.slot_of[txn_id] = slot
        self.id_of[slot] = txn_id
        self.status[slot] = dk.SLOT_TRANSITIVE
        self.exec_msb[slot] = 0
        self.exec_lsb[slot] = 0
        self.exec_node[slot] = 0
        self.awaits_all[slot] = txn_id.kind().awaits_only_deps()
        self._clear_edges(slot)
        self.active[slot] = False
        self.membership_version += 1
        self.version += 1
        return slot

    def free(self, slot: int) -> None:
        txn_id = self.id_of.pop(slot, None)
        if txn_id is not None:
            del self.slot_of[txn_id]
        self.status[slot] = dk.SLOT_FREE
        self._clear_edges(slot)
        self.active[slot] = False
        self.free_slots.append(slot)
        self.membership_version += 1
        self.version += 1

    def _grow_capacity(self) -> None:
        old = self.capacity
        new = old * 2
        self.deps_of.extend(set() for _ in range(new - old))
        self.waiters_of.extend(set() for _ in range(new - old))
        self.status = _grow(self.status, new, dk.SLOT_FREE)
        self.exec_msb = _grow(self.exec_msb, new, 0)
        self.exec_lsb = _grow(self.exec_lsb, new, 0)
        self.exec_node = _grow(self.exec_node, new, 0)
        self.awaits_all = _grow(self.awaits_all, new, False)
        self.active = _grow(self.active, new, False)
        self.free_slots.extend(range(new - 1, old - 1, -1))
        self.capacity = new

    def set_status(self, slot: int, status: int,
                   execute_at: Optional[Timestamp]) -> None:
        changed = int(self.status[slot]) != status
        self.status[slot] = status
        if execute_at is not None:
            em, el = to_i64(execute_at.msb), to_i64(execute_at.lsb)
            en = execute_at.node
            changed |= (int(self.exec_msb[slot]) != em
                        or int(self.exec_lsb[slot]) != el
                        or int(self.exec_node[slot]) != en)
            self.exec_msb[slot] = em
            self.exec_lsb[slot] = el
            self.exec_node[slot] = en
        if changed:
            self.version += 1
            self._dirty_scalars.add(slot)

    # above this live count the drain ships the ELL (padded row-index)
    # adjacency instead of the dense matrix: dense [n, n] at 100k in-flight
    # is 10GB of bools; ELL is n x max_degree
    DENSE_MAX = 8192

    def state(self):
        """Compacted drain state over LIVE slots only (padded to a power-of-
        two bucket so jit caches per bucket): the kernel cost scales with the
        in-flight set, not the high-water capacity.  Returns (state,
        live_slot_index); ``state`` is a dense DrainState below DENSE_MAX
        live slots (MXU matvec fixpoint) and an EllDrainState above it
        (gather fixpoint — no O(N^2) anywhere).

        The device state is cached between ticks (r08): an unchanged
        mirror re-ticks with ZERO upload; membership- and edge-stable
        mutations (status / executeAt moves — the common tick-to-tick
        churn) scatter only the dirty rows of the scalar columns into the
        cached state; only a changed live set or adjacency rebuilds."""
        c = self._state_cache
        if c is not None and c["version"] == self.version:
            return c["state"], c["live"]
        if (c is not None and c["membership"] == self.membership_version
                and c["edges"] == self.edge_version):
            # scalar delta: the live set and adjacency are exactly the
            # cached upload's — scatter the dirty status/executeAt rows
            rows = np.array(sorted(self._dirty_scalars), np.int64)
            li = c["local"][rows]
            ok = li >= 0
            rows, li = rows[ok], li[ok].astype(np.int32)
            st = c["state"]
            if len(li):
                padded = _pow2_at_least(len(li), 8)
                idx = np.concatenate(
                    [li, np.full(padded - len(li), li[-1], np.int32)])
                rws = np.concatenate(
                    [rows, np.full(padded - len(rows), rows[-1], np.int64)])
                new_s, new_em, new_el, new_en = _scatter_drain_scalars(
                    st.status, st.exec_msb, st.exec_lsb, st.exec_node,
                    jnp.asarray(idx), self.status[rws].astype(np.int32),
                    self.exec_msb[rws], self.exec_lsb[rws],
                    self.exec_node[rws].astype(np.int32))
                st = st._replace(status=new_s, exec_msb=new_em,
                                 exec_lsb=new_el, exec_node=new_en)
                c["state"] = st
            c["version"] = self.version
            self._dirty_scalars.clear()
            return st, c["live"]
        live = np.nonzero(self.status != dk.SLOT_FREE)[0]
        n = _pow2_at_least(len(live), 16)
        local = np.full(self.capacity, -1, np.int32)
        local[live] = np.arange(len(live), dtype=np.int32)
        status = np.full(n, dk.SLOT_FREE, np.int32)
        status[: len(live)] = self.status[live]
        em = np.zeros(n, np.int64)
        el = np.zeros(n, np.int64)
        en = np.zeros(n, np.int32)
        aw = np.zeros(n, bool)
        em[: len(live)] = self.exec_msb[live]
        el[: len(live)] = self.exec_lsb[live]
        en[: len(live)] = self.exec_node[live]
        aw[: len(live)] = self.awaits_all[live]
        if n <= self.DENSE_MAX:
            adj = np.zeros((n, n), bool)
            ris, rjs = [], []
            for i in live:
                row = self.deps_of[int(i)]
                if row:
                    ris.extend([int(local[i])] * len(row))
                    rjs.extend(row)
            if ris:
                li = np.array(ris, np.int64)
                lj = local[np.array(rjs, np.int64)]
                ok = lj >= 0
                adj[li[ok], lj[ok]] = True
            state = drk.DrainState(jnp.asarray(adj), jnp.asarray(status),
                                   jnp.asarray(em), jnp.asarray(el),
                                   jnp.asarray(en), jnp.asarray(aw))
            return self._cache_state(state, live, local)
        max_deg = max((len(self.deps_of[int(i)]) for i in live), default=0)
        d = _pow2_at_least(max(max_deg, 1), 4)
        adj_idx = np.full((n, d), -1, np.int32)
        for i in live:
            row = self.deps_of[int(i)]
            if row:
                li = local[i]
                cols = local[np.fromiter(row, np.int64, len(row))]
                cols = cols[cols >= 0]
                adj_idx[li, : len(cols)] = cols
        state = drk.EllDrainState(jnp.asarray(adj_idx), jnp.asarray(status),
                                  jnp.asarray(em), jnp.asarray(el),
                                  jnp.asarray(en), jnp.asarray(aw))
        return self._cache_state(state, live, local)

    def _cache_state(self, state, live, local):
        self._state_cache = {"state": state, "live": live, "local": local,
                             "version": self.version,
                             "membership": self.membership_version,
                             "edges": self.edge_version}
        self._dirty_scalars.clear()
        return state, live

    def sweep_free(self) -> None:
        """Release slots that can no longer gate anything: terminal status,
        not being driven, and no waiter edge pointing at them."""
        terminal = (self.status == dk.SLOT_APPLIED) | \
                   (self.status == dk.SLOT_INVALIDATED)
        for slot in np.nonzero(terminal & ~self.active)[0]:
            s = int(slot)
            if not self.waiters_of[s] and self.id_of.get(s) is not None:
                self.free(s)


def _group_dedupe(cols):
    """lexsort by ``cols`` (last array = primary key) + shift-compare
    dedupe; returns (order, first_mask) — the tiny-array-friendly
    replacement for np.unique(axis=0), whose void-view machinery costs
    ~0.2ms per call."""
    order = np.lexsort(cols)
    first = np.ones(len(order), bool)
    acc = None
    for c in cols:
        cs = c[order]
        d = cs[1:] != cs[:-1]
        acc = d if acc is None else (acc | d)
    first[1:] = acc
    return order, first


def _finalize_key_batch(builders, bb, tt, trank, ntok, dkey, ndep,
                        objs) -> None:
    """Construct every builder's KeyDeps in ONE vectorized pass over the
    batch's key emits — integer-composite-key sorts + shift-compares;
    per-builder Python touches only group boundaries (the CSR freeze the
    reference does per reply in KeyDeps.Builder, done batch-wide).

    ``trank``/``dkey`` are dense ranks of the token and of the dep's packed
    id (caller-computed over the batch's unique tokens/slots), so the
    (builder, token, dep) dedupe and the per-builder dep ordering are
    single int64 argsorts instead of 5-column lexsorts — the r05 profile
    put ~40% of hot-regime attribution in those lexsorts."""
    from ..primitives.deps import KeyDeps
    from ..primitives.keys import RoutingKeys
    nb = int(bb.max()) + 1 if len(bb) else 1
    if nb * ntok * ndep >= (1 << 62):    # composite would overflow int64
        key1 = None                      # fall back to column lexsort
    else:
        key1 = (bb * ntok + trank) * np.int64(ndep) + dkey
    if key1 is None:
        o = np.lexsort((dkey, tt, bb))
        first = np.ones(len(o), bool)
        first[1:] = _changed((dkey, tt, bb), o)[1:]
    else:
        o = np.argsort(key1, kind="stable")
        k1 = key1[o]
        first = np.ones(len(o), bool)
        first[1:] = k1[1:] != k1[:-1]
    if not first.all():
        o = o[first]
    bb, tt, dkey, objs = bb[o], tt[o], dkey[o], objs[o]
    n = len(bb)
    # per-builder unique deps, ordered by packed id (== TxnId order; dkey
    # ranks preserve it)
    key2 = bb * np.int64(ndep) + dkey
    o2 = np.argsort(key2, kind="stable")
    k2 = key2[o2]
    b2 = bb[o2]
    newb = np.ones(n, bool)
    newb[1:] = b2[1:] != b2[:-1]
    newd = np.ones(n, bool)
    newd[1:] = k2[1:] != k2[:-1]
    gid = np.cumsum(newd) - 1
    base = np.maximum.accumulate(np.where(newb, gid, 0))
    inv = np.empty(n, np.int64)
    inv[o2] = gid - base
    dep_rows = o2[newd]
    dep_bs = bb[dep_rows]
    dep_objs = objs[dep_rows]
    dstart = np.nonzero(newb[newd])[0]
    dbounds = np.append(dstart, len(dep_rows))
    txn_lists = {int(b): dep_objs[dbounds[i]:dbounds[i + 1]].tolist()
                 for i, b in enumerate(dep_bs[dstart].tolist())}
    # (b, token) groups over the (b, tok, dep)-ordered arrays
    # (b, token) groups over the (b, tok, dep)-ordered rows, then one
    # COLUMNAR KeyDeps per builder: np slices only, no per-group Python
    newg = np.ones(n, bool)
    newg[1:] = (bb[1:] != bb[:-1]) | (tt[1:] != tt[:-1])
    gstart = np.nonzero(newg)[0]
    g_b = bb[gstart]
    g_t = tt[gstart]
    gbounds = np.append(gstart, n)
    newb_g = np.ones(len(gstart), bool)
    newb_g[1:] = g_b[1:] != g_b[:-1]
    bstart_g = np.nonzero(newb_g)[0]
    bbounds_g = np.append(bstart_g, len(gstart))
    for k_i in range(len(bstart_g)):
        s0, s1 = bstart_g[k_i], bbounds_g[k_i + 1]
        b = int(g_b[s0])
        row_ptr = gbounds[s0:s1 + 1] - gbounds[s0]
        dep_idx = inv[gbounds[s0]:gbounds[s1]]
        builders[b].key.set_prebuilt(KeyDeps.from_columns(
            RoutingKeys(g_t[s0:s1].tolist(), _presorted=True),
            txn_lists[b], row_ptr, dep_idx))


def _finalize_range_batch(builders, bb, lo, hi, dm, dl, dn, objs) -> None:
    """Range-domain analogue of _finalize_key_batch: the group key is the
    (lo, hi) clip instead of the token."""
    from ..primitives.deps import RangeDeps
    o, first = _group_dedupe((dn, dl, dm, hi, lo, bb))
    o = o[first]
    bb, lo, hi, dm, dl, dn, objs = (bb[o], lo[o], hi[o], dm[o], dl[o],
                                    dn[o], objs[o])
    n = len(bb)
    o2 = np.lexsort((dn, dl, dm, bb))
    b2 = bb[o2]
    newb = np.ones(n, bool)
    newb[1:] = b2[1:] != b2[:-1]
    newd = newb | _changed((dm, dl, dn), o2)
    gid = np.cumsum(newd) - 1
    base = np.maximum.accumulate(np.where(newb, gid, 0))
    inv = np.empty(n, np.int64)
    inv[o2] = gid - base
    dep_rows = o2[newd]
    dep_bs = bb[dep_rows]
    dep_objs = objs[dep_rows]
    dstart = np.nonzero(newb[newd])[0]
    dbounds = np.append(dstart, len(dep_rows))
    txn_lists = {int(b): dep_objs[dbounds[i]:dbounds[i + 1]].tolist()
                 for i, b in enumerate(dep_bs[dstart].tolist())}
    newg = np.ones(n, bool)
    newg[1:] = ((bb[1:] != bb[:-1]) | (lo[1:] != lo[:-1])
                | (hi[1:] != hi[:-1]))
    gstart = np.nonzero(newg)[0]
    g_b = bb[gstart]
    gbounds = np.append(gstart, n)
    newb_g = np.ones(len(gstart), bool)
    newb_g[1:] = g_b[1:] != g_b[:-1]
    bstart_g = np.nonzero(newb_g)[0]
    bbounds_g = np.append(bstart_g, len(gstart))
    for k_i in range(len(bstart_g)):
        s0, s1 = bstart_g[k_i], bbounds_g[k_i + 1]
        b = int(g_b[s0])
        row_ptr = gbounds[s0:s1 + 1] - gbounds[s0]
        dep_idx = inv[gbounds[s0]:gbounds[s1]]
        builders[b].range.set_prebuilt(RangeDeps.from_columns(
            lo[gstart[s0:s1]], hi[gstart[s0:s1]], txn_lists[b],
            row_ptr, dep_idx))


def _changed(cols, order) -> np.ndarray:
    """Shift-compare over reordered columns: True where any column differs
    from the previous row (first row excluded — callers OR with their own
    leading mask)."""
    acc = None
    for c in cols:
        cs = c[order]
        d = cs[1:] != cs[:-1]
        acc = d if acc is None else (acc | d)
    out = np.zeros(len(order), bool)
    out[1:] = acc
    return out


# -- device-resident attribution index (r15) ----------------------------------

def _ts_byte_keys(msb, lsb, node) -> np.ndarray:
    """Pack (msb int64, lsb int64, node int32) columns into V20 byte keys
    whose memcmp order IS the unsigned timestamp order (ts_lt): sign bits
    flipped, big-endian.  One np.searchsorted over these keys replaces a
    three-level lexicographic refinement — the host half of the in-kernel
    rank trick (the device compares precomputed integer RANKS instead)."""
    n = len(msb)
    out = np.empty((n, 20), np.uint8)
    out[:, 0:8] = (np.asarray(msb, np.int64).astype(np.uint64)
                   ^ np.uint64(1 << 63)).astype(">u8")[:, None] \
        .view(np.uint8).reshape(n, 8)
    out[:, 8:16] = (np.asarray(lsb, np.int64).astype(np.uint64)
                    ^ np.uint64(1 << 63)).astype(">u8")[:, None] \
        .view(np.uint8).reshape(n, 8)
    out[:, 16:20] = (np.asarray(node, np.int64).astype(np.int64)
                     .astype(np.uint32, casting="unsafe")
                     ^ np.uint32(1 << 31)).astype(">u4")[:, None] \
        .view(np.uint8).reshape(n, 4)
    return np.ascontiguousarray(out).view("V20").ravel()


_I64_INF = np.int64(np.iinfo(np.int64).max)


def _exact_ranks(sorted_unique: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Ranks of ``keys`` within ``sorted_unique`` when every key IS a
    member: a scatter-map + gather (O(span) memory, one pass) replaces the
    n-log-n searchsorted whenever the value span is modest — the hot-key
    regime's tokens and the snapshot's slot ids are both dense."""
    n = len(sorted_unique)
    if n == 0:
        return np.zeros(len(keys), np.int64)
    lo = int(sorted_unique[0])
    span = int(sorted_unique[-1]) - lo + 1
    if span > max(4 * n, 1 << 16):
        return np.searchsorted(sorted_unique, keys)
    rmap = np.zeros(span, np.int64)
    rmap[sorted_unique - lo] = np.arange(n, dtype=np.int64)
    return rmap[keys - lo]


class _AttrIndexHost:
    """One store's floor + elision index, host side: the numpy arrays the
    host route's vectorized attribution reads directly, plus pow2-padded
    copies that upload as ops.deps_kernel.AttrIndex (padding bounds the
    jit shape count).  Built by DeviceState._attr_index from the
    RedundantBefore segment map and the CFK committed-write pivot lists of
    every registry token; cached until either source's version moves."""

    __slots__ = ("fbnd", "fmsb", "flsb", "fnode", "etok", "eptr",
                 "erank", "exm", "exl", "exn", "uqkeys", "u",
                 "pad", "_dev", "_repl", "_repl_key", "seq")

    _SEQ = [0]

    def __init__(self, floors, etok, eptr, exm, exl, exn):
        # monotone build id: cache keys over index IDENTITY must never
        # use id() (a rebuilt index can reuse a freed predecessor's
        # address and alias a stale cache entry)
        _AttrIndexHost._SEQ[0] += 1
        self.seq = _AttrIndexHost._SEQ[0]
        self.fbnd, self.fmsb, self.flsb, self.fnode = floors
        self.etok = etok
        self.eptr = eptr
        self.exm, self.exl, self.exn = exm, exl, exn
        # dense ranks over the UNIQUE exec triples: exec < bound compares
        # become integer rank compares on device
        keys = _ts_byte_keys(exm, exl, exn)
        self.uqkeys = np.unique(keys)
        self.u = len(self.uqkeys)
        rank = np.searchsorted(self.uqkeys, keys).astype(np.int64)
        seg = np.repeat(np.arange(len(etok), dtype=np.int64),
                        np.diff(eptr))
        self.erank = seg * np.int64(self.u + 1) + rank
        # pow2-padded device images (floors pad +INF / zero rows; elidable
        # tokens pad +INF; padded eptr segments are empty)
        fp = _pow2_at_least(max(len(self.fbnd), 1), 1)
        tp = _pow2_at_least(max(len(etok), 1), 1)
        lp = _pow2_at_least(max(len(self.erank), 1), 1)
        l_real = len(self.erank)

        def tail(a, n, fill, dtype):
            out = np.full(n, fill, dtype)
            out[: len(a)] = a
            return out

        self.pad = (
            tail(self.fbnd, fp, _I64_INF, np.int64),
            tail(self.fmsb, fp + 1, 0, np.int64),
            tail(self.flsb, fp + 1, 0, np.int64),
            tail(self.fnode, fp + 1, 0, np.int32),
            tail(etok, tp, _I64_INF, np.int64),
            tail(eptr, tp + 1, l_real, np.int32),
            tail(self.erank, lp, _I64_INF, np.int64),
            tail(exm, lp, 0, np.int64),
            tail(exl, lp, 0, np.int64),
            tail(exn, lp, 0, np.int32),
            np.int64(self.u + 1))
        self._dev = None
        self._repl = None
        self._repl_key = None

    def rank_bounds(self, qnp: np.ndarray) -> np.ndarray:
        """Per-query rank of the started-before bound among the index's
        unique committed-write executeAts — the ``rankb`` column the
        kernels (and the host route) compare in place of 128-bit
        timestamps."""
        if self.u == 0:
            return np.zeros(qnp.shape[0], np.int64)
        keys = _ts_byte_keys(qnp[:, 0], qnp[:, 1], qnp[:, 2])
        return np.searchsorted(self.uqkeys, keys).astype(np.int64)

    def device(self) -> "dk.AttrIndex":
        if self._dev is None:
            faults.check("transfer", "attr index upload")
            self._dev = dk.AttrIndex(*(jnp.asarray(a) for a in self.pad))
        return self._dev

    def device_replicated(self, mesh) -> "dk.AttrIndex":
        key = tuple(dev.id for dev in mesh.devices.flat)
        if self._repl is None or self._repl_key != key:
            faults.check("transfer", "attr index upload")
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            sr = NamedSharding(mesh, P())
            self._repl = dk.AttrIndex(
                *(jax.device_put(a, sr) for a in self.pad))
            self._repl_key = key
        return self._repl

    # -- host-route mirror of the in-kernel attribution predicate ---------
    def keep_floor(self, tok, dmsb, dlsb, dnode) -> np.ndarray:
        """Per-entry exact-floor keep mask: dep >= deps_floor(token), the
        numpy twin of the kernel's floor leg."""
        fi = np.searchsorted(self.fbnd, tok, side="right")
        fm, fl, fn = self.fmsb[fi], self.flsb[fi], self.fnode[fi]
        um, ufm = dmsb.view(np.uint64), fm.view(np.uint64)
        ul, ufl = dlsb.view(np.uint64), fl.view(np.uint64)
        return ((um > ufm) | ((um == ufm)
                             & ((ul > ufl)
                                | ((ul == ufl) & (dnode >= fn)))))

    def floors_match(self, qnp: np.ndarray, q_m: int, floor_id) -> bool:
        """True when every floor segment the batch window touches equals
        the batch-global floor the host index already applied
        STRUCTURALLY — the per-entry floor leg is then a no-op the host
        route skips wholesale (the hot-key regime: one watermark over the
        hot range)."""
        from ..ops.packing import to_i64 as _ti
        lo = qnp[:, 7:7 + q_m]
        hi = qnp[:, 7 + q_m:7 + 2 * q_m]
        used = lo <= hi
        if not used.any():
            return True
        i0 = int(np.searchsorted(self.fbnd, int(lo[used].min()),
                                 side="right"))
        i1 = int(np.searchsorted(self.fbnd, int(hi[used].max()),
                                 side="right"))
        fm = self.fmsb[i0:i1 + 1]
        fl = self.flsb[i0:i1 + 1]
        fn = self.fnode[i0:i1 + 1]
        if floor_id is not None and floor_id > TxnId.NONE:
            t = (_ti(floor_id.msb), _ti(floor_id.lsb), floor_id.node)
        else:
            t = (0, 0, 0)
        return bool((fm == t[0]).all() and (fl == t[1]).all()
                    and (fn == t[2]).all())

    def elide_decided(self, tok, emsb, elsb, enode, rankb_b) -> np.ndarray:
        """Per-entry decided-elision mask for candidates ALREADY known to
        be decided (Committed..Applied with executeAt): does a committed
        write on the token execute strictly between the dep and the
        bound?  The pivot search collapses to the UNIQUE (segment, bound
        rank) composites — the hot regime has a handful of hot tokens and
        bounds against tens of thousands of entries."""
        t = len(self.etok)
        seg = np.searchsorted(self.etok, tok)
        seg_c = np.minimum(seg, t - 1)
        seg_ok = self.etok[seg_c] == tok
        c = seg_c.astype(np.int64) * np.int64(self.u + 1) + rankb_b
        uc, inv = np.unique(c, return_inverse=True)
        base_u = self.eptr[np.minimum(uc // np.int64(self.u + 1),
                                      t - 1)].astype(np.int64)
        cnt_u = np.searchsorted(self.erank, uc) - base_u
        pidx_u = np.clip(base_u + cnt_u - 1, 0, max(len(self.exm) - 1, 0))
        pm = self.exm[pidx_u][inv]
        pl = self.exl[pidx_u][inv]
        pn = self.exn[pidx_u][inv]
        uem, upm = emsb.view(np.uint64), pm.view(np.uint64)
        uel, upl = elsb.view(np.uint64), pl.view(np.uint64)
        below = ((uem < upm) | ((uem == upm)
                               & ((uel < upl)
                                  | ((uel == upl) & (enode < pn)))))
        return seg_ok & (cnt_u[inv] > 0) & below


class DeviceState:
    """Per-CommandStore device wiring: the deps index + drain graph, kept in
    sync by the Commands transition functions."""

    def __init__(self, store):
        self.store = store
        self.deps = _DepsMirror()
        self.deps.owner = self
        self.drain = _DrainMirror()
        self._tick_scheduled = False
        # mesh mode: with >1 jax device (the virtual 8-device CPU test mesh,
        # or a real multi-chip slice), the deps table's slot dimension is
        # sharded across the mesh and every scan runs as a shard_map with
        # per-shard CSR compaction (ref: the CommandStores scatter-gather,
        # CommandStores.java:575-643; cross-shard Deps.merge, Deps.java:256)
        self.mesh = None
        import jax as _jax
        n_dev = len(_jax.devices())
        if n_dev > 1:
            d = 1
            while d * 2 <= n_dev:
                d *= 2
            from ..parallel.sharded import make_mesh
            self.mesh = make_mesh(d)
        # learned compaction width for batched queries (sticky across
        # batches; see deps_query_batch)
        self._batch_k = 64
        # learned flat-compaction capacity (coarse pairs per batch)
        self._batch_flat = 4096
        # counters surfaced through sim stats / bench
        self.n_queries = 0
        self.n_ticks = 0
        self.n_kernel_deps = 0
        self.n_mesh_queries = 0
        self.n_bucketed_queries = 0
        self.n_dense_queries = 0
        self.n_host_queries = 0
        self.n_mesh_bucketed_queries = 0
        self.n_dispatches = 0       # kernel dispatches: n_queries /
        #                             n_dispatches = mean lived batch size
        # r08 launch coalescing (local.dispatch.DeviceDispatcher): flushes
        # and drain ticks of THIS store that rode a fused, store-tagged
        # launch shared with sibling stores (launch counts live on the
        # dispatcher — one fused launch serves many store flushes)
        self.n_fused_flushes = 0
        self.n_fused_queries = 0
        self.n_fused_ticks = 0
        # routing controls (see module docstring): None = adaptive;
        # "host" / "dense" pin a route; "device" = adaptive kernels but
        # never the host route (the pre-routing behavior, used by kernel
        # equivalence tests).  on_route(route, nq) observes every decision
        # (utils.trace.Trace.record_route is the sim-side consumer).
        self.route_override: Optional[str] = None
        self.on_route = None
        # store-level coalescing queue (enqueue_query/_flush_queries)
        self._q_pending: List[tuple] = []
        # batch-floor memo keyed on (RedundantBefore.version, window):
        # repeated flushes over a stable watermark map resolve the prune
        # floor with one dict hit instead of a segment walk
        self._floor_memo: Optional[tuple] = None
        # token -> (cfk version, may_elide_any) memo for attribution
        self._elidable_cache: Dict[int, tuple] = {}
        # -- device-resident attribution (r15) --
        # elision registry: tokens that ever carried a decided key-domain
        # write (maintained by _advance_status); the batched elision index
        # is built over exactly these tokens from the CFK truth
        self._elide_pending: Set[int] = set()
        self._elide_tokens = np.zeros(0, np.int64)
        # cached elision/floor index: (signature, _AttrIndexHost)
        self._aidx_cache = None
        self._aidx_dev = None       # (np id of host index, dk.AttrIndex)
        self._aidx_repl = None      # replicated under a mesh
        # attributed-path counters (bench ``# index:`` line)
        self.n_elided_transitive = 0
        self.n_elided_decided = 0
        self.attr_download_bytes = 0
        # per-kernel wall timing (SURVEY §5: structured per-kernel timing):
        # kind -> [calls, seconds]; dispatch_* covers host pack + upload +
        # enqueue, wait_* the download join, host_* the host-side passes
        self.kernel_times: Dict[str, List[float]] = {}
        # -- device-fault tolerance (module docstring: degradation ladder) --
        # shadow-verify every device flush against the host route when True
        # (or when utils.faults.PARANOIA is set process-wide)
        self.paranoia = False
        # OOM backpressure terminal state: all flushes/ticks pinned to host
        self.host_pinned = False
        # device-memory budget in table slots (None = unbounded); at the
        # budget _grow_capacity compacts below the RedundantBefore floor
        # instead of doubling, then degrades to host_pinned if still full
        import os as _os
        self.device_budget_slots: Optional[int] = (
            int(_os.environ.get("ACCORD_TPU_DEVICE_BUDGET_SLOTS", "0"))
            or None)
        # quarantine state machine: consecutive device-boundary failures
        # (the backoff exponent) and remaining quarantined flushes; jitter
        # is seeded from (node, store) so the backoff schedule is
        # deterministic yet desynchronized across the replicas of a shard
        # — a cluster-wide device fault must not re-probe in lockstep
        self._dev_backoff = 0
        self._dev_quar_flushes = 0
        node_id = getattr(getattr(store, "node", None), "node_id", 0)
        self._jitter = RandomSource(
            0xFA17 ^ (node_id << 16) ^ getattr(store, "store_id", 0))
        # fault observability: on_fault(event, detail) if set, else the
        # node-level observer the sim cluster wires (node.fault_observer)
        self.on_fault = None
        self.n_device_faults = 0
        self.n_quarantines = 0
        self.n_fallback_queries = 0    # queries served by host fallback/pin
        self.n_reprobes = 0
        self.n_restores = 0
        self.n_shadow_checks = 0
        self.n_shadow_mismatches = 0
        self.n_compactions = 0
        self.n_compacted_slots = 0
        self.n_oom_degraded = 0
        self.n_host_ticks = 0          # drain ticks swept on host fallback
        # r21 store-sharded residency (parallel.store_shard): the spill
        # rung's StoreShards instance (None until the ladder activates it),
        # flush/byte counters, the per-slice quarantine tallies, and the
        # host-pin recovery state — ``_pin_recheck`` pinned flushes between
        # compaction-and-re-probe attempts (doubling to a cap, the same
        # backoff shape the quarantine ladder uses)
        self.store_shards = None
        self.n_store_sharded_flushes = 0
        self.n_slice_quarantines = 0
        self.n_slice_restores = 0
        self.n_shard_merge_bytes = 0
        self.n_oom_recovered = 0
        self._pin_flushes = 0
        self._pin_recheck = 64
        # r19 adaptive drain wavefront: W=1 ticks run the plain frontier
        # sweep (byte-identical to pre-r19 behavior); W grows x2 only when
        # a tick's ENTIRE candidate set synchronously reached Applied (the
        # PreApplied cascade regime, where a serial chain would otherwise
        # pay one tick per link), letting the log-depth level kernel
        # harvest the next W executeAt antichains in one launch.  Any
        # candidate that does not execute resets W to 1 — protocol-flow
        # ticks never see a widened sweep.
        self._drain_wavefront = 1
        self.n_wavefront_ticks = 0     # ticks swept with W > 1
        # two-stage compacted downloads (r10): bytes actually transferred
        # (headers + live entry prefixes) vs what the old full padded
        # flat-buffer download would have moved — the compaction ratio on
        # every bench ``# index:`` line
        self.download_bytes = 0
        self.download_bytes_padded = 0

    # ------------------------------------------------------------------
    # registration hooks (called from local.commands transitions)
    # ------------------------------------------------------------------
    def register(self, txn_id: TxnId, status: int, keys) -> None:
        """Witness/advance a txn in the deps index.  ``keys`` is the txn's
        sliced participation (Keys or Ranges) — its conflict footprint."""
        slot = self.deps.alloc(txn_id)
        if keys is not None:
            if isinstance(keys, Ranges):
                self.deps.add_intervals(slot, (), list(keys))
            else:
                self.deps.add_intervals(slot, [k.token() for k in keys], ())
        self._advance_status(txn_id, slot, status, None)

    def update_status(self, txn_id: TxnId, status: int,
                      execute_at: Optional[Timestamp] = None) -> None:
        slot = self.deps.slot_of.get(txn_id)
        if slot is None:
            slot = self.deps.alloc(txn_id)
        self._advance_status(txn_id, slot, status, execute_at)

    def _advance_status(self, txn_id: TxnId, slot: int, status: int,
                        execute_at: Optional[Timestamp]) -> None:
        cur = int(self.deps.status[slot])
        if status == dk.SLOT_INVALIDATED:
            new = dk.SLOT_INVALIDATED
        else:
            new = max(cur, status)
        self.deps.set_status(slot, new)
        if execute_at is not None:
            self.deps.emsb[slot] = to_i64(execute_at.msb)
            self.deps.elsb[slot] = to_i64(execute_at.lsb)
            self.deps.enode[slot] = execute_at.node
            self.deps.eknown[slot] = True
            self.deps.mark_exec(slot)    # device attr columns + snapshot
        # elision registry (r15): a decided (executeAt-known) key-domain
        # WRITE is a potential elision pivot on each of its footprint
        # points — record the tokens so the batched elision index knows
        # which CommandsForKey pivot lists to include.  Superset semantics:
        # the index build reads the CFK truth per token; a token registered
        # here whose CFK has no committed writes simply contributes nothing
        if dk.SLOT_COMMITTED <= new <= dk.SLOT_APPLIED \
                and self.deps.eknown[slot] and txn_id.kind().is_write() \
                and txn_id.domain() == Domain.Key:
            row_lo, row_hi = self.deps.lo[slot], self.deps.hi[slot]
            pts = row_lo[(row_lo <= row_hi) & (row_lo == row_hi)]
            if len(pts):
                self._elide_pending.update(int(t) for t in pts)
        if new == dk.SLOT_INVALIDATED and cur != dk.SLOT_INVALIDATED:
            # de-index: the bucket path excludes invalidated entries
            # structurally (the dense path excludes them by status)
            self.deps._bucket_remove(slot)
        dslot = self.drain.slot_of.get(txn_id)
        if dslot is not None:
            self.drain.set_status(dslot, new, execute_at)
        # a dependency becoming decided (executeAt known) or terminal can
        # unblock waiters: re-evaluate the frontier
        if new >= dk.SLOT_COMMITTED and self.drain.active.any():
            self.schedule_tick()

    def free(self, txn_id: TxnId) -> None:
        """Truncation/erasure: drop the txn from the deps index (its effect
        is covered by the RedundantBefore watermark from now on)."""
        self.deps.free(txn_id)

    def index_size(self) -> int:
        return len(self.deps.slot_of)

    # ------------------------------------------------------------------
    # device-fault tolerance: quarantine state machine + HBM backpressure
    # (module docstring: the degradation ladder)
    # ------------------------------------------------------------------
    _BACKOFF_BASE = 4      # flushes quarantined after the first failure
    _BACKOFF_MAX = 256     # quarantine ceiling (flushes)

    def _paranoid(self) -> bool:
        return self.paranoia or faults.PARANOIA

    def _fault_event(self, event: str, detail: str = "") -> None:
        obs = self.on_fault
        if obs is None:
            obs = getattr(getattr(self.store, "node", None),
                          "fault_observer", None)
        if obs is not None:
            obs(self.store, event, detail)

    def _device_fault(self, exc_or_kind, detail: str = "",
                      sliced: bool = False) -> None:
        """Record one device-boundary failure and quarantine the device
        routes: exponential backoff in FLUSHES (deterministic per-store
        jitter so co-faulted stores don't re-probe in lockstep).

        ``sliced=True`` (the flush dispatch/collect call sites) composes
        the ladder per slice when the store-sharded residency is active:
        the failure quarantines the SLICE it touched — its slots answer
        from the host twin while healthy slices stay on device — instead
        of the whole node.  Drain-tick faults keep the whole-device
        quarantine (the drain state is not sliced)."""
        kind = exc_or_kind if isinstance(exc_or_kind, str) \
            else faults.kind_of(exc_or_kind)
        self.n_device_faults += 1
        self._fault_event("fault." + kind, detail)
        sh = self.store_shards
        if sliced and sh is not None and sh.active:
            sh.slice_fault(kind, detail)
            return
        self.n_quarantines += 1
        self._dev_backoff = min(self._dev_backoff + 1, 8)
        base = min(self._BACKOFF_BASE << (self._dev_backoff - 1),
                   self._BACKOFF_MAX)
        self._dev_quar_flushes = base + self._jitter.next_int(
            max(base // 2, 1))
        self._fault_event(
            "quarantine", f"{kind} backoff={self._dev_quar_flushes}")

    def _restore_device(self) -> None:
        """A probe flush succeeded end-to-end: the device routes are
        healthy again."""
        self._dev_backoff = 0
        self._dev_quar_flushes = 0
        self.n_restores += 1
        self._fault_event("restore")

    def _flush_gate(self, nq: int):
        """The degradation-ladder gate shared by the solo and fused flush
        paths: (forced, may_probe).  ``forced`` pins this flush to the host
        route ("host-pinned" / "host-fallback", consuming one quarantined
        flush); ``may_probe`` marks that a device-bound flush would be the
        quarantine probe (the caller records the probe only if it actually
        takes a device route)."""
        if self.host_pinned:
            # r21: the OOM degrade is no longer terminal — every
            # _pin_recheck pinned flushes, compact and re-check whether
            # the table fits the device (or the sharded mesh) again; on
            # success the NEXT flush is the recovery probe
            self._pin_flushes += 1
            if self._pin_flushes >= self._pin_recheck:
                self._pin_flushes = 0
                self._pin_recheck = min(self._pin_recheck * 2, 1024)
                if self._try_oom_recover():
                    return None, True
            self.n_fallback_queries += nq
            return "host-pinned", False
        if self._dev_quar_flushes > 0:
            self._dev_quar_flushes -= 1
            self.n_fallback_queries += nq
            return "host-fallback", False
        return None, self._dev_backoff > 0

    def _approve_grow(self, mirror: _DepsMirror) -> bool:
        """HBM capacity backpressure: called by _DepsMirror._grow_capacity
        before doubling.  True = grow as usual; False = compaction made
        room under the budget (free_slots is non-empty), don't grow.

        The r21 ladder: breach -> compact -> SPILL TO SHARDED (when a mesh
        is available and the grown table fits d x the per-chip budget —
        one store's slots split across d devices) -> host-pinned.  When
        every rung fails the store degrades PINNED-TO-HOST (loud one-shot
        event) and the HOST arrays still grow — the protocol stays live,
        the device stops receiving uploads."""
        new = mirror.capacity * 2
        budget = self.device_budget_slots
        sh = self.store_shards
        sharded = sh is not None and sh.active
        # while sharded, the effective budget is the MESH's: d slices
        eff = None if budget is None else (budget * sh.d if sharded
                                           else budget)
        breach = eff is not None and new > eff
        if not breach and faults.should_fire("hbm_oom"):
            self.n_device_faults += 1
            self._fault_event("fault.hbm_oom", f"grow to {new}")
            breach = True
        if not breach:
            return True
        freed = self._compact_below_floor()
        self.n_compactions += 1
        self.n_compacted_slots += freed
        self._fault_event("oom.compact",
                          f"freed={freed} capacity={mirror.capacity}")
        if mirror.free_slots:
            return False
        if not self.host_pinned and not sharded and self.mesh is not None:
            from ..parallel.store_shard import store_shard_enabled
            d = max(len(self.mesh.devices.flat), 1)
            if store_shard_enabled() and (budget is None
                                          or new <= budget * d):
                self._activate_store_shards(f"capacity={mirror.capacity}"
                                            f" -> {new}")
                return True
        if not self.host_pinned:
            # the one-shot loud degrade: host route only from here on
            self.host_pinned = True
            self.n_oom_degraded += 1
            self._fault_event("oom.degrade",
                              f"capacity={mirror.capacity} -> {new}")
        return True

    def _activate_store_shards(self, detail: str = "") -> None:
        """Turn on the r21 sliced residency for this store (the spill rung
        and the sharded leg of OOM recovery): from here the sharded table
        and attr uploads route through per-slice resident buffers."""
        if self.store_shards is None:
            from ..parallel.store_shard import StoreShards
            self.store_shards = StoreShards(self, self.deps, self.mesh)
        self.store_shards.activate()
        self._fault_event("oom.spill", detail)

    def _try_oom_recover(self) -> bool:
        """Un-terminal the OOM degrade (r21): compact, then re-check the
        budget — a raised budget (or a mesh whose d slices now cover the
        table) lets a host-pinned store re-probe the device route.  Loud
        one-shot recovery, counted in ``oom_recovered``; mirrors the
        quarantine -> probe -> restore cycle of the device ladder."""
        mirror = self.deps
        freed = self._compact_below_floor()
        if freed:
            self.n_compactions += 1
            self.n_compacted_slots += freed
            self._fault_event("oom.compact",
                              f"freed={freed} capacity={mirror.capacity}")
        budget = self.device_budget_slots
        cap = mirror.capacity
        if budget is not None and cap > budget:
            from ..parallel.store_shard import store_shard_enabled
            sh_ok = (self.mesh is not None and store_shard_enabled())
            d = max(len(self.mesh.devices.flat), 1) if sh_ok else 1
            if not sh_ok or cap > budget * d:
                return False
            self._activate_store_shards(f"recover capacity={cap}")
        self.host_pinned = False
        self.n_oom_recovered += 1
        self._fault_event("oom.recover",
                          f"capacity={cap} budget={budget}")
        return True

    def _compact_below_floor(self) -> int:
        """Floor-driven compaction: free every live slot whose TxnId sits
        below the RedundantBefore floor over EVERY interval of its own
        footprint.  Safe by the same contract as free(): the attributed
        scan drops a dep below the floor of every token it could emit at,
        on every route — its effect is covered by the watermark.  A Python
        sweep: this is the rare emergency path (budget breach / OOM), not
        a hot path."""
        rb = getattr(self.store, "redundant_before", None)
        if rb is None:
            return 0
        d = self.deps
        freed = 0
        for s in np.nonzero(d.status != dk.SLOT_FREE)[0].tolist():
            tid = d.id_of.get(s)
            if tid is None:
                continue
            row_lo, row_hi = d.lo[s], d.hi[s]
            covered = False
            for m in range(d.max_intervals):
                lo_v, hi_v = int(row_lo[m]), int(row_hi[m])
                if lo_v > hi_v:
                    continue
                if tid < rb.min_floor_over(lo_v, hi_v):
                    covered = True
                else:
                    covered = False
                    break
            if covered:
                d.free(tid)
                freed += 1
        return freed

    def _host_ready_slots(self) -> np.ndarray:
        """Host replacement of the drain frontier sweep (the bottom rung of
        the degradation ladder) — EXACTLY drain_kernel.ready_frontier's
        rule over the drain mirror's sparse adjacency: a Stable row is
        ready unless some dep is live, non-applied, and gating (undecided,
        executing earlier, or the row awaits all deps).  Python-loop over
        the in-flight set: this path runs only quarantined/degraded."""
        dr = self.drain
        m64 = (1 << 64) - 1
        out = []
        for i in np.nonzero((dr.status == dk.SLOT_STABLE) & dr.active)[0]:
            i = int(i)
            ei = (int(dr.exec_msb[i]) & m64, int(dr.exec_lsb[i]) & m64,
                  int(dr.exec_node[i]))
            awaits = bool(dr.awaits_all[i])
            blocked = False
            for j in dr.deps_of[i]:
                stj = int(dr.status[j])
                if stj in (dk.SLOT_FREE, dk.SLOT_INVALIDATED,
                           dk.SLOT_APPLIED):
                    continue
                if stj < dk.SLOT_COMMITTED or awaits:
                    blocked = True      # undecided always gates
                    break
                ej = (int(dr.exec_msb[j]) & m64, int(dr.exec_lsb[j]) & m64,
                      int(dr.exec_node[j]))
                if ej < ei:             # executes before i: gates
                    blocked = True
                    break
            if not blocked:
                out.append(i)
        return np.array(out, np.int64)

    # ------------------------------------------------------------------
    # the deps query (device replacement of map_reduce_active fold)
    # ------------------------------------------------------------------
    def deps_query(self, safe, txn_id: TxnId, keys, started_before: Timestamp,
                   witnesses: Kinds, builder) -> None:
        """Run the PreAccept/Accept/Recover dependency scan on device and
        fold the result into ``builder`` with the same per-key semantics as
        the host CommandsForKey path (full ownership history, matching
        SafeCommandStore.map_reduce_active — a dual-quorum scan at a
        dropped prior-epoch owner must still see its old-range witnesses).

        This is the batch path with B=1: the per-message and batched code
        are ONE path (same kernel dispatch, same floors/elision/attribution)
        so the benched path is exactly the path the protocol runs."""
        query = self.build_query(safe, txn_id, keys, started_before,
                                 witnesses)
        if query is None:
            return
        handle = self.deps_query_batch_begin([query], immediate=True,
                                             prune_floors=True,
                                             attributed=True)
        self.deps_query_batch_end_attributed(safe, handle, [builder])

    def build_query(self, safe, txn_id: TxnId, keys,
                    started_before: Timestamp, witnesses: Kinds):
        """Slice a scan's keys to the store's full ownership history and
        package them as one batch-query tuple (None if nothing owned)."""
        owned = safe.store.ranges_for_epoch.all()
        if isinstance(keys, Ranges):
            q_toks: List[int] = []
            q_rngs = list(keys.slice(owned))
        else:
            q_toks = [k.token() for k in keys
                      if owned.contains_token(k.token())]
            q_rngs = []
        if not q_toks and not q_rngs:
            return None
        return (txn_id, started_before, witnesses, q_toks, q_rngs)

    def _attribute_batch(self, safe, b_idx, j_idx, pmq, ids, ivs, qnp,
                         queries, builders) -> None:
        """Fold a whole batch's kernel answer into the builders with the
        floors, elision and key/range attribution of the host path: the
        kernel answers "who", the mirror snapshot answers "where",
        RedundantBefore floors and the CFK elision rule decide "whether".

        The geometry runs ONCE, vectorized over all (pair, dep-interval,
        query-interval) triples — no per-query Python overhead.  The
        unification that makes this possible: a key-domain dep's footprint
        is a point, so its emitted key is its own token whether the query
        interval was a key or a range; a range-domain dep emits the
        dep∩query interval clip, which for a point query degenerates to the
        width-1 range.  Python touches only the deduplicated surviving
        emits."""
        if len(j_idx) == 0:
            return
        lo, hi, dom = ivs
        rb = safe.redundant_before()
        _MISSING = object()
        cfks: Dict[int, object] = {}

        def elide_ctx(t: int, bound):
            """(cfk, pivot) when elision is possible on this key for this
            bound, else None — ONE lookup per (token, bound) instead of one
            per (dep, token) pair (the common key has nothing elidable)."""
            key = (t, bound)
            ctx = cfks.get(key, _MISSING)
            if ctx is not _MISSING:
                return ctx
            cfk = self.store.commands_for_key.get(t)
            ctx = None
            if cfk is not None:
                pivot = cfk.can_elide(bound)
                if pivot is not None:
                    ctx = (cfk, pivot)
            cfks[key] = ctx
            return ctx

        q_m = (qnp.shape[1] - 7) // 2
        # the exact (pair row, dep-interval col, query-interval col) emit
        # triples arrive precomputed from the collect pass (host probes or
        # np.nonzero over the kernel parts' overlap geometry)
        p_i, m_i, q_i = pmq
        key_dep = (dom[j_idx] == int(Domain.Key))[p_i]

        # key-domain deps: emitted at the dep's own footprint point,
        # deduped per (pair, token); floors + elision decide survival.
        # Emits reach the builders through the batch finalize (whole-batch
        # vectorized dedupe/CSR, set_prebuilt per builder) — per-emit
        # Python runs only for the rare keys with elidable state
        kp, km = p_i[key_dep], m_i[key_dep]
        (msb_a, lsb_a, node_a, obj_a, status_a, xm_a, xl_a, xn_a,
         xk_a) = ids
        if len(kp):
            jj, bb = j_idx[kp], b_idx[kp]
            tt = lo[jj, km]                   # key-domain footprint = point
            # vectorized RedundantBefore floor: dep >= floor(token),
            # lexicographic over the packed (msb, lsb, node) triples (the
            # same int64 ordering the kernel's ts_lt assumes)
            fmsb, flsb, fnode = rb.deps_floor_batch(tt)
            dmsb, dlsb, dnode = msb_a[jj], lsb_a[jj], node_a[jj]
            keep = ((dmsb > fmsb)
                    | ((dmsb == fmsb)
                       & ((dlsb > flsb)
                          | ((dlsb == flsb) & (dnode >= fnode)))))
            jj_k, bb_k, tt_k = jj[keep], bb[keep], tt[keep]
            # object resolution: pure take from the snapshot object column
            deps_k = obj_a[jj_k]
            # VECTORIZED transitive elision (the per-key skip rule,
            # CommandsForKey.is_elided): transitively-known deps never
            # emit; decided deps executing below the key's latest
            # committed-write pivot (for this query's bound) are reached
            # through that write's stable deps.  The pivot is looked up
            # once per unique (token, query) on keys with anything
            # elidable; the per-emit judgement is pure array compares over
            # the mirror's status/executeAt snapshot — no per-emit Python
            uniq_t2, inv_t2 = np.unique(tt_k, return_inverse=True)
            tok_maybe = np.zeros(len(uniq_t2), bool)
            cfk_map = self.store.commands_for_key
            ecache = self._elidable_cache
            for i, t in enumerate(uniq_t2.tolist()):
                cfk = cfk_map.get(t)
                if cfk is None:
                    continue
                # version-keyed memo: may_elide_any flips only when a
                # committed write or unwitnessable lands on the key, both
                # monotone counters — the common spread key resolves to a
                # single dict hit instead of the CFK probe
                ver = (len(cfk._committed_write_execs),
                       cfk._n_unwitnessable)
                hit = ecache.get(t)
                if hit is not None and hit[0] == ver:
                    tok_maybe[i] = hit[1]
                else:
                    m = cfk.may_elide_any()
                    ecache[t] = (ver, m)
                    tok_maybe[i] = m
            status_k = status_a[jj_k]
            elide = status_k == dk.SLOT_TRANSITIVE
            flagged = tok_maybe[inv_t2]
            if flagged.any():
                f_idx = np.nonzero(flagged)[0]
                # (builder, token) pairs as ONE int64 composite key over
                # the token RANKS (np.unique(axis=0) on the raw 2-column
                # stack cost ~250ms/1k queries in the hot regime — the
                # void-dtype argsort dominated attribution)
                ntok2 = len(uniq_t2)
                key_bt = bb_k[f_idx] * np.int64(ntok2) + inv_t2[f_idx]
                ubt_key, inv_bt = np.unique(key_bt, return_inverse=True)
                pv = np.zeros((len(ubt_key), 3), np.int64)
                pv_ok = np.zeros(len(ubt_key), bool)
                ub_list = (ubt_key // ntok2).tolist()
                ut_list = uniq_t2[ubt_key % ntok2].tolist()
                for i, (b, t) in enumerate(zip(ub_list, ut_list)):
                    ctx = elide_ctx(int(t), queries[b][1])
                    if ctx is not None and ctx[1] is not Timestamp.NONE \
                            and ctx[1] is not None:
                        pv[i] = (to_i64(ctx[1].msb), to_i64(ctx[1].lsb),
                                 ctx[1].node)
                        pv_ok[i] = True
                pm, pl, pn = (pv[inv_bt, 0], pv[inv_bt, 1], pv[inv_bt, 2])
                jf = jj_k[f_idx]
                sf = status_k[f_idx]
                xm, xl, xn = xm_a[jf], xl_a[jf], xn_a[jf]
                below = ((xm < pm) | ((xm == pm)
                                      & ((xl < pl)
                                         | ((xl == pl) & (xn < pn)))))
                decided = ((sf >= dk.SLOT_COMMITTED)
                           & (sf <= dk.SLOT_APPLIED) & xk_a[jf])
                elide[f_idx] |= pv_ok[inv_bt] & decided & below
            keep2 = ~elide
            if keep2.any():
                jj_f = jj_k[keep2]
                # dense dep ranks over the batch's unique slots, ordered by
                # the packed id (same signed lexicographic order the old
                # 5-column lexsort used) — the finalize sorts become single
                # int64 argsorts
                u_slots, slot_inv = np.unique(jj_f, return_inverse=True)
                ordr = np.lexsort((node_a[u_slots], lsb_a[u_slots],
                                   msb_a[u_slots]))
                rank = np.empty(len(u_slots), np.int64)
                rank[ordr] = np.arange(len(u_slots))
                _finalize_key_batch(builders, bb_k[keep2], tt_k[keep2],
                                    inv_t2[keep2], len(uniq_t2),
                                    rank[slot_inv], len(u_slots),
                                    deps_k[keep2])

        # range-domain deps: emit the dep∩query interval clip per pair —
        # batch-finalized (dedupe/sort/CSR in one vectorized pass; Range
        # objects materialize once per unique clip)
        rp, rm, rq = p_i[~key_dep], m_i[~key_dep], q_i[~key_dep]
        if len(rp):
            jj_r = j_idx[rp]
            bb_r = b_idx[rp]
            ilo = np.maximum(lo[jj_r, rm], qnp[bb_r, 7 + rq])
            ihi = np.minimum(hi[jj_r, rm], qnp[bb_r, 7 + q_m + rq]) + 1
            dmsb_r, dlsb_r, dnode_r = msb_a[jj_r], lsb_a[jj_r], node_a[jj_r]
            # batch-global RedundantBefore floor on range-domain deps (the
            # host analogue of the device prune, applied on EVERY attributed
            # path so pruned and unpruned kernels agree; the pruned history
            # is covered by the boundary fence dep, messages/preaccept.py:
            # add_boundary_deps)
            m_all = qnp[:, 7:7 + q_m]
            h_all = qnp[:, 7 + q_m:7 + 2 * q_m]
            u_all = m_all <= h_all
            if u_all.any():
                fl = rb.min_floor_over(int(m_all[u_all].min()),
                                       int(h_all[u_all].max()))
                if fl > TxnId.NONE:
                    fm, fls, fn = (to_i64(fl.msb), to_i64(fl.lsb), fl.node)
                    keep_r = ((dmsb_r > fm)
                              | ((dmsb_r == fm)
                                 & ((dlsb_r > fls)
                                    | ((dlsb_r == fls) & (dnode_r >= fn)))))
                    rp, ilo, ihi, jj_r = (rp[keep_r], ilo[keep_r],
                                          ihi[keep_r], jj_r[keep_r])
                    dmsb_r, dlsb_r, dnode_r = (dmsb_r[keep_r],
                                               dlsb_r[keep_r],
                                               dnode_r[keep_r])
            if len(rp):
                _finalize_range_batch(builders, b_idx[rp], ilo, ihi,
                                      dmsb_r, dlsb_r, dnode_r, obj_a[jj_r])

    # ------------------------------------------------------------------
    # store-level coalescing (the lived batched path): queries arriving
    # within one scheduler quantum fold into ONE kernel dispatch
    # ------------------------------------------------------------------
    def enqueue_query(self, query, builder, done) -> None:
        """Queue one deps query for the next flush; ``done(failure, safe)``
        fires after the builder is filled (``safe`` is the flush task's
        exclusive store handle, live only within the callback).  All queries enqueued before the flush
        task runs (i.e. during the same scheduler quantum — message bursts
        land as same-timestamp tasks) share one kernel dispatch, so the
        benched batched shape IS the lived shape (mean batch size =
        n_queries / n_dispatches)."""
        self._q_pending.append((query, builder, done))
        if len(self._q_pending) == 1:
            node = self.store.node
            # node-level dispatch scheduler (r08): all stores of this node
            # whose flushes become runnable in the same event-loop step
            # register with ONE dispatcher event, which coalesces their
            # device launches when the cost model says fusion wins
            disp = getattr(node, "dispatcher", None)
            if disp is not None:
                disp.register_flush(self)
                return
            from .command_store import PreLoadContext
            # one scheduler hop (zero sim-time) so every same-instant
            # message's store task enqueues BEFORE the flush runs
            node.scheduler.now(lambda: self.store.execute(
                PreLoadContext.empty(), self._flush_queries))

    def _flush_queries(self, safe) -> None:
        batch = self._q_pending
        self._q_pending = []
        self._flush_batch(safe, batch)

    def _flush_batch(self, safe, batch) -> None:
        """Serve one claimed batch of enqueued queries solo: the classic
        atomic begin+collect+attribute within this store task (the
        dispatcher routes a store here when fusion does not pay)."""
        if not batch:
            return
        try:
            handle = self.deps_query_batch_begin(
                [q for q, _b, _d in batch], immediate=True,
                prune_floors=True, attributed=True)
            self.deps_query_batch_end_attributed(
                safe, handle, [b for _q, b, _d in batch])
        except BaseException as e:  # noqa: BLE001
            for _q, _b, d in batch:
                d(e, None)
            return
        for _q, _b, d in batch:
            d(None, safe)

    def deps_query_batch(self, queries):
        """Batched deps scan: ONE kernel call for B concurrent queries (the
        server-side batching a pipelined deployment uses).

        ``queries`` = [(txn_id, started_before, witnesses, tokens, ranges)].
        Returns the dep sets in the device-native packed-CSR layout —
        ``(row_ptr int64[B+1], msb int64[D], lsb int64[D], node int32[D])``
        — the same encoding KeyDeps/RangeDeps use (ref: KeyDeps.java:150-156
        CSR layout); consumers materialise TxnId objects lazily."""
        if not queries:
            return (np.zeros(1, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int64), np.zeros(0, np.int32))
        return self.deps_query_batch_end(self.deps_query_batch_begin(queries))

    def deps_query_batch_attributed(self, safe, queries, builders):
        """The correctness-complete batched scan: one kernel dispatch for B
        queries, then the full host-path semantics (floors, elision,
        key/range attribution) folded into each query's builder.  This is
        the exact code deps_query runs (B=1) — and what the bench times."""
        if not queries:
            return
        handle = self.deps_query_batch_begin(queries, prune_floors=True,
                                             attributed=True)
        self.deps_query_batch_end_attributed(safe, handle, builders)

    # below this many stragglers the bucketed path is used for narrow
    # queries on a single device; above it (hot/adversarial footprints) the
    # dense scan is the better kernel anyway
    BUCKETED = True

    # test knob: force the global triple-dedupe pass even for single-part
    # exact kernels (whose CSRs are unique by construction, so the pass is
    # skipped in production) — test_routing asserts results are
    # byte-identical either way
    FORCE_TRIPLE_DEDUPE = False

    # process-wide route calibration: {"rtt": s, "c_dev": s/elem,
    # "c_host": s/elem}, measured once by a micro-probe (or injected by
    # tests via set_route_calibration)
    _CALIB = None

    @classmethod
    def set_route_calibration(cls, rtt: float, c_host: float,
                              c_dev: float,
                              rtt_mesh: Optional[float] = None,
                              c_xfer: float = 0.0,
                              c_attr: float = 0.0,
                              c_shard: float = 0.0) -> None:
        cls._CALIB = {"rtt": rtt, "c_host": c_host, "c_dev": c_dev,
                      "rtt_mesh": rtt_mesh if rtt_mesh is not None else rtt,
                      "c_xfer": c_xfer, "c_attr": c_attr,
                      "c_shard": c_shard}

    @staticmethod
    def _measure_route_calibration():
        """The once-per-process micro-probe behind the routing crossover:
        measures (a) the device round-trip cost (tiny dispatch + download —
        on a tunneled TPU this is the term that dominates small scans),
        (b) the device per-element kernel cost (a mid-size dense scan minus
        the round trip), (c) the host per-element cost of the vectorized
        numpy predicate the host route runs.  No hard-coded thresholds:
        the crossover IS these three numbers."""
        import statistics as _st
        import time as _time
        x = jnp.arange(256, dtype=jnp.int64)
        tiny = jax.jit(lambda a: a + 1)
        np.asarray(tiny(x))                      # warm + compile
        rtts = []
        for _ in range(5):
            t0 = _time.perf_counter()
            np.asarray(tiny(x))
            rtts.append(_time.perf_counter() - t0)
        rtt = _st.median(rtts)
        # device per-element: dense flat kernel over a 8192x4 table, B=16
        cap, b, m = 8192, 16, 4
        table = dk.empty_table(cap, m)
        qmat = jnp.asarray(np.zeros((b, 7 + 2 * m), np.int64))
        jax.block_until_ready(dk.calculate_deps_flat(table, qmat, m,
                                                     256, 64))
        runs = []
        for _ in range(3):
            t0 = _time.perf_counter()
            jax.block_until_ready(dk.calculate_deps_flat(table, qmat, m,
                                                         256, 64))
            runs.append(_time.perf_counter() - t0)
        elems = b * cap * m * m
        c_dev = max(_st.median(runs) - rtt, 1e-9) / elems
        # host per-element: the predicate compare chain over 64k entries
        n = 1 << 16
        a = np.arange(n, dtype=np.int64)
        c = a[::-1].copy()
        _ = ((a < c) | ((a == c) & (c < a))).sum()   # warm
        t0 = _time.perf_counter()
        reps = 4
        for _ in range(reps):
            _ = ((a < c) | ((a == c) & (c < a))).sum()
        c_host = max((_time.perf_counter() - t0) / (reps * n), 1e-11)
        # host per-element column-copy cost (the deferred-harvest mirror
        # snapshot the fused pricing charges) — memcpy, ~20x cheaper per
        # element than the compare chain
        _ = a.copy()
        t0 = _time.perf_counter()
        for _ in range(8):
            _ = a.copy()
        c_copy = max((_time.perf_counter() - t0) / (8 * n), 1e-12)
        # device->host transfer cost per BYTE (the r10 prefix-fetch model:
        # an immediate flush slices the entry buffer only when the bytes
        # it saves cost more than the extra slice dispatch ~ one rtt; on
        # a local CPU device bytes are ~free and the full fetch wins, on
        # a tunneled MB/s-scale link the prefix wins from ~100KB saved)
        # each timed conversion must see a FRESH device buffer: jax.Array
        # caches its host copy after the first np.asarray, so re-converting
        # one array times a cache hit (~ns) and c_xfer would collapse to
        # the floor, pricing the prefix fetch off on exactly the tunneled
        # link it exists for
        mk = jax.jit(lambda i: jnp.zeros(1 << 16, jnp.int64) + i)
        bufs = [jax.block_until_ready(mk(i)) for i in range(4)]   # 512KB ea
        np.asarray(bufs[0])                      # warm the conversion path
        xfers = []
        for buf in bufs[1:]:
            t0 = _time.perf_counter()
            np.asarray(buf)
            xfers.append(_time.perf_counter() - t0)
        c_xfer = max((_st.median(xfers) - rtt) / float(8 << 16), 1e-13)
        # r15: the attributed kernels run the post-compaction attribution
        # stage over the [s]-long entry buffer — price its per-entry-slot
        # cost from a direct A/B of the attributed vs raw dense kernel at
        # a wide s (the stage is O(s), so the slope IS the coefficient)
        s_probe = 4096
        zeros3 = (jnp.asarray(np.int64(0)), jnp.asarray(np.int64(0)),
                  jnp.asarray(np.int32(0)))
        attr = dk.AttrCols(jnp.zeros(cap, jnp.int32),
                           jnp.full(cap, dk.SLOT_FREE, jnp.int32),
                           jnp.zeros(cap, jnp.int64),
                           jnp.zeros(cap, jnp.int64),
                           jnp.zeros(cap, jnp.int32),
                           jnp.zeros(cap, jnp.int64),
                           jnp.zeros(cap, jnp.int64),
                           jnp.zeros(cap, jnp.int32),
                           jnp.zeros(cap, bool))
        inf64 = np.int64(np.iinfo(np.int64).max)
        aidx = dk.AttrIndex(jnp.full(1, inf64), jnp.zeros(2, jnp.int64),
                            jnp.zeros(2, jnp.int64), jnp.zeros(2, jnp.int32),
                            jnp.full(1, inf64), jnp.zeros(2, jnp.int32),
                            jnp.full(1, inf64), jnp.zeros(1, jnp.int64),
                            jnp.zeros(1, jnp.int64), jnp.zeros(1, jnp.int32),
                            jnp.asarray(np.int64(1)))
        rb0 = jnp.zeros(b, jnp.int64)
        jax.block_until_ready(dk.calculate_deps_flat(table, qmat, m,
                                                     s_probe, 64))
        jax.block_until_ready(dk.calculate_deps_flat_attr(
            table, attr, aidx, qmat, rb0, *zeros3, m, s_probe, 64))
        t0 = _time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(dk.calculate_deps_flat(table, qmat, m,
                                                         s_probe, 64))
        t_raw = (_time.perf_counter() - t0) / 3
        t0 = _time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(dk.calculate_deps_flat_attr(
                table, attr, aidx, qmat, rb0, *zeros3, m, s_probe, 64))
        t_attr = (_time.perf_counter() - t0) / 3
        c_attr = max(t_attr - t_raw, 0.0) / s_probe
        return {"rtt": rtt, "c_dev": c_dev, "c_host": c_host,
                "c_copy": c_copy, "c_xfer": c_xfer, "c_attr": c_attr}

    @staticmethod
    def _measure_mesh_rtt(mesh) -> float:
        """Round-trip cost of ONE tiny shard_map dispatch over ``mesh`` —
        the mesh analogue of the single-device rtt probe.  A shard_map
        launch costs far more than a plain dispatch (per-device program
        launches + collectives plumbing; on the virtual CPU test mesh it is
        100x+ a single-device call), so pricing mesh routes with the
        single-device rtt would send tiny sim scans to the mesh the model
        claims is cheap."""
        import statistics as _st
        import time as _time
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        from ..parallel.sharded import STORE_AXIS, _shard_map
        d = int(np.prod(list(mesh.shape.values())))
        arr = jax.device_put(np.zeros(8 * d, np.int64),
                             NamedSharding(mesh, P(STORE_AXIS)))
        fn = jax.jit(_shard_map(lambda a: a + 1, mesh,
                                (P(STORE_AXIS),), P(STORE_AXIS)))
        np.asarray(fn(arr))                      # warm + compile
        rtts = []
        for _ in range(3):
            t0 = _time.perf_counter()
            np.asarray(fn(arr))
            rtts.append(_time.perf_counter() - t0)
        return _st.median(rtts)

    @staticmethod
    def _measure_shard_coeff(mesh) -> float:
        """Per-element cost of the cross-slice merge collective the
        sharded-store route adds (all-gather + replicated-block shuffle):
        an A/B slope over two buffer sizes, so the fixed launch overhead
        cancels and what remains is the collective's marginal cost.  A
        COEFFICIENT, never a device-count threshold — the router prices
        the sharded route with it like every other term."""
        import statistics as _st
        import time as _time
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        from ..parallel.sharded import STORE_AXIS, _shard_map
        d = int(np.prod(list(mesh.shape.values())))

        def timed(n):
            arr = jax.device_put(np.zeros(n * d, np.int64),
                                 NamedSharding(mesh, P(STORE_AXIS)))

            def body(a):
                g = jax.lax.all_gather(a, STORE_AXIS, tiled=True)
                return jnp.sort(g)

            fn = jax.jit(_shard_map(body, mesh, (P(STORE_AXIS),),
                                    P(STORE_AXIS)))
            np.asarray(fn(arr))                  # warm + compile
            runs = []
            for _ in range(3):
                t0 = _time.perf_counter()
                np.asarray(fn(arr))
                runs.append(_time.perf_counter() - t0)
            return _st.median(runs)

        n1, n2 = 1024, 8192
        t1, t2 = timed(n1), timed(n2)
        return max(t2 - t1, 0.0) / ((n2 - n1) * d) + 1e-12

    def _calibration(self):
        if DeviceState._CALIB is None:
            DeviceState._CALIB = self._measure_route_calibration()
        calib = DeviceState._CALIB
        if self.mesh is not None and "rtt_mesh" not in calib:
            calib["rtt_mesh"] = self._measure_mesh_rtt(self.mesh)
        if self.mesh is not None and "c_shard" not in calib:
            calib["c_shard"] = self._measure_shard_coeff(self.mesh)
        return calib

    def _choose_route(self, qnp: np.ndarray, q_m: int, floor_id) -> str:
        """Pick "host" or "device" for this flush by comparing the modeled
        host-scan cost (live-above-floor working set from the mirror's
        incremental stats) against the modeled device cost (round trips +
        the cheaper kernel's element count).  Models, not thresholds: both
        sides are priced in seconds from the calibration probe."""
        calib = self._calibration()
        st = self.deps.floor_stats(floor_id)
        lo = qnp[:, 7:7 + q_m]
        hi = qnp[:, 7 + q_m:7 + 2 * q_m]
        used = lo <= hi
        n_iv = int(used.sum())
        nq = qnp.shape[0]
        # host model: point candidates ~ covered-token-width x density,
        # plus the [query-interval x range-entry] stab broadcast
        span = max(st["tok_hi"] - st["tok_lo"] + 1, 1)
        density = st["n_pt"] / span
        w = np.where(used,
                     np.minimum(hi, st["tok_hi"])
                     - np.maximum(lo, st["tok_lo"]) + 1, 0)
        est_pt = float(np.clip(w, 0, None).sum()) * density + n_iv * 8.0
        est_host = est_pt + float(n_iv) * st["n_rng"]
        # ~5 vectorized passes per candidate (probe + attr filter + the
        # thin finalize — r15 replaced the attribute re-sort), plus a
        # fixed per-flush overhead (probe setup, index builds, snapshots)
        host_cost = calib["c_host"] * (5.0 * est_host + 40_000.0)
        if self.deps._hidx_key != ((floor_id if floor_id is not None
                                    and floor_id > TxnId.NONE else None),
                                   self.deps.version):
            # index rebuild: one vectorized pass over the live tail
            host_cost += calib["c_host"] * 4.0 * (st["n_above"]
                                                  + st["n_pt"] + st["n_rng"])
        # device model: the cheaper kernel's PER-SHARD element count (wall
        # clock of a parallel launch = the per-shard work).  The slot table
        # row-shards, so dense work divides by d; the bucket probe matrix
        # does NOT — every shard evaluates all nq x (q_m*span*K) bucket
        # candidates against its row slice, only the wide list splits
        rtt = calib["rtt"]
        d = 1
        if self.mesh is not None:
            d = max(len(self.mesh.devices.flat), 1)
            rtt = calib.get("rtt_mesh", rtt)
        dense_elems = nq * self.deps.capacity * q_m \
            * self.deps.max_intervals // d
        if self.BUCKETED and \
                len(self.deps.wide_entries) <= self.deps.WIDE_MAX:
            # the candidate matrix is sliced to the live bucket-occupancy
            # high-water (not BUCKET_K) and the wide list crosses every
            # query interval (exact triples) — price what actually runs
            buck_elems = nq * (q_m * self.deps.SPAN
                               * self.deps.bucket_keff()
                               + q_m * len(self.deps.wide_entries) // d)
            dev_elems = min(dense_elems, buck_elems)
        else:
            dev_elems = dense_elems
        # the attributed launch additionally runs the post-compaction
        # attribution stage over the learned [s] entry buffer — collect
        # got cheaper (pre-attributed prefix), launch slightly heavier;
        # both priced, never thresholded
        s_attr = min(self._batch_flat, dev_elems)
        dev_cost = 2.0 * rtt + calib["c_dev"] * dev_elems \
            + calib.get("c_attr", 0.0) * s_attr
        if d > 1:
            # the mesh routes pay the cross-slice merge collective over
            # the (up to) d x s merged entry block — priced from its own
            # A/B micro-probe slope (r21), never a device-count threshold
            dev_cost += calib.get("c_shard", 0.0) * d * s_attr
        return "host" if host_cost < dev_cost else "device"

    def _batch_floor(self, qnp: np.ndarray, q_m: int):
        """(floor_id, np prune triple) for a batch: the conservative
        batch-global RedundantBefore floor with the (rb.version, window)
        memo — shared by the solo begin path and the fused dispatcher
        prep.  (None, None) when no floor applies."""
        rb = getattr(self.store, "redundant_before", None)
        if rb is None:
            return None, None
        lo_cols = qnp[:, 7:7 + q_m]
        hi_cols = qnp[:, 7 + q_m:7 + 2 * q_m]
        used = lo_cols <= hi_cols
        if not used.any():
            return None, None
        window = (rb.version, int(lo_cols[used].min()),
                  int(hi_cols[used].max()))
        if self._floor_memo is not None and self._floor_memo[0] == window:
            f = self._floor_memo[1]
        else:
            f = rb.min_floor_over(window[1], window[2])
            self._floor_memo = (window, f)
        if f > TxnId.NONE:
            return f, (to_i64(f.msb), to_i64(f.lsb), np.int32(f.node))
        return None, None

    # ------------------------------------------------------------------
    # device-resident attribution (r15): the per-store floor + elision
    # index every attributed route (kernels AND host) applies
    # ------------------------------------------------------------------
    def _attr_index(self) -> _AttrIndexHost:
        """Build (or reuse) the store's attribution index: the packed
        RedundantBefore segment floors plus, per elision-registry token,
        the CFK committed-write pivot list.  The signature folds the
        RedundantBefore version, the registry size and the SUM of the
        touched CFKs' monotone _elide_versions — any pivot mutation moves
        the sum, so staleness detection is one pass of dict hits, no
        content hashing."""
        d = self.deps
        if self._elide_pending:
            new = np.fromiter(self._elide_pending, np.int64,
                              len(self._elide_pending))
            self._elide_pending.clear()
            self._elide_tokens = np.union1d(self._elide_tokens, new)
        rb = getattr(self.store, "redundant_before", None)
        cfk_map = getattr(self.store, "commands_for_key", None) or {}
        toks = self._elide_tokens
        cfks = [cfk_map.get(int(t)) for t in toks]
        vsum = 0
        for c in cfks:
            if c is not None:
                vsum += c._elide_version
        sig = (rb.version if rb is not None else -1, len(toks), vsum)
        if self._aidx_cache is not None and self._aidx_cache[0] == sig:
            return self._aidx_cache[1]
        if rb is not None:
            floors = rb.packed_floor_index()
        else:
            floors = (np.zeros(0, np.int64), np.zeros(1, np.int64),
                      np.zeros(1, np.int64), np.zeros(1, np.int32))
        packs = []
        keep_toks = []
        for t, c in zip(toks.tolist(), cfks):
            if c is None:
                continue
            p = c.packed_committed_execs()
            if len(p[0]):
                packs.append(p)
                keep_toks.append(t)
        if packs:
            etok = np.asarray(keep_toks, np.int64)
            lens = np.array([len(p[0]) for p in packs], np.int64)
            eptr = np.zeros(len(packs) + 1, np.int32)
            np.cumsum(lens, out=eptr[1:])
            exm = np.concatenate([p[0] for p in packs])
            exl = np.concatenate([p[1] for p in packs])
            exn = np.concatenate([p[2] for p in packs])
        else:
            etok = np.zeros(0, np.int64)
            eptr = np.zeros(1, np.int32)
            exm = np.zeros(0, np.int64)
            exl = np.zeros(0, np.int64)
            exn = np.zeros(0, np.int32)
        aidx = _AttrIndexHost(floors, etok, eptr, exm, exl, exn)
        self._aidx_cache = (sig, aidx)
        return aidx

    def _attr_filter_entries(self, tb, tj, tm, tq, ids, ivs, aidx,
                             rankb, floor_skip: bool = False) -> tuple:
        """Apply the attributed kernels' in-kernel drops to a HOST-derived
        entry set (host route, fault fallback, shadow verify): per-token
        floors + elision on key-domain entries, over the flush's snapshot
        columns.  Duplicate (row, token, dep) emits survive — the shared
        finalize dedupes, so bytes match the kernel routes that dropped
        them in-kernel.  ``floor_skip`` (precomputed per flush by
        floors_match) elides the whole floor leg when the exact per-token
        floors equal the structurally-applied batch floor; the decided-
        elision pivot search runs only over the decided subset."""
        if len(tj) == 0:
            return tb, tj, tm, tq, 0, 0
        (msb_a, lsb_a, node_a, _obj, status_a, xm_a, xl_a, xn_a,
         xk_a) = ids
        lo, _hi, dom = ivs
        key_dep = dom[tj] == int(Domain.Key)
        if not key_dep.any():
            return tb, tj, tm, tq, 0, 0
        status = status_a[tj]
        el_trans = key_dep & (status == dk.SLOT_TRANSITIVE)
        tok = None
        keep_floor = None
        if not floor_skip:
            tok = lo[tj, tm]
            keep_floor = aidx.keep_floor(tok, msb_a[tj], lsb_a[tj],
                                         node_a[tj])
        el_dec = np.zeros(len(tj), bool)
        if aidx.u:
            dec = (key_dep & (status >= dk.SLOT_COMMITTED)
                   & (status <= dk.SLOT_APPLIED) & xk_a[tj])
            di = np.nonzero(dec)[0]
            if len(di):
                tji = tj[di]
                tok_d = tok[di] if tok is not None else lo[tji, tm[di]]
                el_dec[di] = aidx.elide_decided(
                    tok_d, xm_a[tji], xl_a[tji], xn_a[tji], rankb[tb[di]])
        if keep_floor is None:
            keep = ~(el_trans | el_dec)
            n_trans = int(el_trans.sum())
            n_dec = int(el_dec.sum())
        else:
            keep = ~key_dep | (keep_floor & ~el_trans & ~el_dec)
            n_trans = int(np.sum(keep_floor & el_trans))
            n_dec = int(np.sum(keep_floor & ~el_trans & el_dec))
        if keep.all():
            return tb, tj, tm, tq, n_trans, n_dec
        return tb[keep], tj[keep], tm[keep], tq[keep], n_trans, n_dec

    def deps_query_batch_begin(self, queries, immediate: bool = False,
                               prune_floors: bool = False,
                               attributed: bool = False):
        """Dispatch a batched deps scan WITHOUT waiting: one fused query
        upload per kernel part + enqueue; returns an opaque handle for
        deps_query_batch_end.

        ``attributed=True`` (every protocol path) dispatches the r15
        ATTRIBUTED kernels: per-token RedundantBefore floors, elision and
        the key dedupe run in-kernel against the device-resident
        attribution columns + the packed floor/elision index, and the CSR
        that comes back holds exactly the entries the builders keep — the
        host side is a pure decode + finalize.  Mesh routes additionally
        merge their shard blocks ON DEVICE (one replicated download).  Callers overlap the next batch's dispatch
        with the previous batch's result download (double-buffering) — on a
        tunneled accelerator the round trips dominate the kernel, so the
        pipeline nearly doubles sustained throughput.

        Dispatch is adaptive: under a mesh the scan fans over the sharded
        dense kernel; on a single device queries whose intervals are narrow
        probe the bucketed index (O(candidates) instead of O(N)), wide
        queries — and everything, when the straggler list says the
        footprint distribution defeats bucketing — take the dense kernel.
        All parts share one mirror snapshot and one geometry/attribution
        pass, so every path yields identical protocol results."""
        q_m = _pow2_at_least(max(len(t[3]) + len(t[4]) for t in queries))
        packed = [(sb, wit, toks, rngs, tid)
                  for (tid, sb, wit, toks, rngs) in queries]
        nq = len(queries)
        qnp = dk.pack_query_matrix(packed, q_m)
        parts: List[Dict[str, object]] = []
        # conservative batch-global RedundantBefore floor, applied ON
        # DEVICE (the exact floors still run in attribution): in durable-
        # prefix-dominated stores this keeps the CSR to the live tail
        # instead of shipping redundant history — on EVERY device route,
        # sharded included (the r05 mesh path hard-disabled this).  Opt-in:
        # the attributed (protocol) paths enable it; the raw-CSR path
        # documents no floors and never prunes
        prune = None
        floor_id = None
        if prune_floors:
            floor_id, prune_np = self._batch_floor(qnp, q_m)
            if floor_id is not None:
                prune = (jnp.asarray(prune_np[0]), jnp.asarray(prune_np[1]),
                         jnp.asarray(prune_np[2]))
        aidx = rankb_np = None
        floor_skip = False
        if attributed:
            aidx = self._attr_index()
            rankb_np = aidx.rank_bounds(qnp)
            # when the exact per-token floors equal the structurally
            # applied batch floor everywhere the batch reaches, the
            # per-entry floor leg is provably a no-op — on the host route
            # AND in the kernels (the mask's batch-global prune is that
            # same floor); an empty elision index likewise drops the
            # whole pivot leg from the traced program (static flags)
            floor_skip = aidx.floors_match(qnp, q_m, floor_id)
            k_floors = not floor_skip
            k_elide = aidx.u > 0

        def dispatch(kind, rows, qcols=None):
            """rows: np int64 array of query indices for this part, padded
            to a pow2 batch by repeating the last row (pads map to -1).
            Under ``attributed`` every device kind launches its r15
            ATTRIBUTED kernel variant (suffix ``attr_`` in the devprof
            slices); mesh kinds come back as ONE merged replicated block
            (d=1, entry buffer d_mesh * s)."""
            import time as _time
            _t0 = _time.perf_counter()
            kname = ("attr_" + kind) if attributed else kind
            if kind == "host":
                # the host route computes its (query, slot) pairs AND the
                # exact emit triples right here — no device box, no
                # download thread; under ``attributed`` the floor/elision
                # drops run at collect over the same snapshot the
                # builders read
                if attributed:
                    ent4 = self.deps.host_pairs(qnp, q_m, floor_id,
                                                entries=True)
                    parts.append({"kind": "host", "ent": ent4})
                else:
                    b_h, j_h, pmq = self.deps.host_pairs(qnp, q_m,
                                                         floor_id)
                    parts.append({"kind": "host", "b": b_h, "j": j_h,
                                  "pmq": pmq})
                self.n_host_queries += len(rows)
                self.n_dispatches += 1
                self._ktime("dispatch_host", _t0)
                return
            if kind == "host_slice":
                # r21 hybrid twin part: while slices are quarantined the
                # assembled sharded table masks their slots to SLOT_FREE,
                # and this part answers for EXACTLY those slots from the
                # host mirror — disjoint from the device part's slot set
                # by construction, so the concatenated entries finalize
                # byte-identically to an all-device answer
                cb, cj, cm, cq = self.deps.host_pairs(qnp, q_m, floor_id,
                                                      entries=True)
                keep = self.store_shards.quarantined_slot_mask(cj)
                parts.append({"kind": "host_slice",
                              "ent": (cb[keep], cj[keep], cm[keep],
                                      cq[keep])})
                self.n_dispatches += 1
                self._ktime("dispatch_host_slice", _t0)
                return
            dk.launch_check(kind)
            b_pad = _pow2_at_least(len(rows), 1)
            rows_p = np.concatenate(
                [rows, np.full(b_pad - len(rows), rows[-1], np.int64)])
            gmap = np.concatenate(
                [rows, np.full(b_pad - len(rows), -1, np.int64)])
            m_t = self.deps.max_intervals
            part: Dict[str, object] = {"kind": kname, "gmap": gmap,
                                       "nq": b_pad, "q_m": q_m,
                                       "mq": m_t * q_m, "hoff": 2,
                                       "d_ent": 1,
                                       "immediate": immediate}
            rankb = jnp.asarray(rankb_np[rows_p]) if attributed else None
            if kind == "sharded":
                table = self.deps.device_table_sharded(self.mesh)
                d = int(np.prod(list(self.mesh.shape.values())))
                n = table.capacity
                s = min(self._batch_flat, b_pad * (n // d) * m_t * q_m)
                k = min(self._batch_k, (n // d) * m_t * q_m)
                qmat = jnp.asarray(qnp[rows_p])
                mesh = self.mesh
                if attributed:
                    # merged replicated block with GLOBAL slot codes: the
                    # cross-shard Deps.merge happens on device
                    wide = dk.wide_codes(n, m_t, q_m)
                    from ..parallel.sharded import sharded_flat_attr
                    acols = self.deps.device_attr_cols_sharded(mesh)
                    ai = aidx.device_replicated(mesh)
                    pz = prune if prune is not None else _prune_zeros()

                    def relaunch(s2, k2, _m=mesh, _t=table, _q=qmat,
                                 _a=acols, _i=ai, _r=rankb, _p=pz):
                        return sharded_flat_attr(
                            _m, q_m, s2, k2, wide, k_floors,
                            k_elide)(_t, _a, _i, _q, _r, *_p)

                    part.update(d=1, d_ent=d, shard_n=n, s=s, k=k,
                                wide=wide, hoff=5, global_ids=True,
                                s_cap=b_pad * (n // d) * m_t * q_m,
                                k_cap=(n // d) * m_t * q_m)
                else:
                    wide = dk.wide_codes(n // d, m_t, q_m)
                    from ..parallel.sharded import (
                        sharded_calculate_deps_flat,
                        sharded_calculate_deps_flat_pruned)

                    def relaunch(s2, k2, _m=mesh, _t=table, _q=qmat,
                                 _p=prune):
                        if _p is not None:
                            return sharded_calculate_deps_flat_pruned(
                                _m, q_m, s2, k2, wide)(_t, _q, *_p)
                        return sharded_calculate_deps_flat(
                            _m, q_m, s2, k2, wide)(_t, _q)

                    part.update(d=d, shard_n=n // d, s=s, k=k, wide=wide,
                                s_cap=b_pad * (n // d) * m_t * q_m,
                                k_cap=(n // d) * m_t * q_m)
                self.n_mesh_queries += len(rows)
            elif kind == "sharded_bucketed":
                btable = self.deps.bucket_device_sharded(self.mesh)
                d = int(np.prod(list(self.mesh.shape.values())))
                span = self.deps.SPAN
                keff = self.deps.bucket_keff()
                wide = dk.wide_codes(self.deps.capacity, m_t, q_m)
                # per-shard candidate ceiling: every touched bucket's live
                # entry slice plus this shard's slice of the wide list
                # crossed with the query intervals (exact triples)
                c = (q_m * span * keff
                     + q_m * (btable.wlo.shape[0] // d))
                s = min(self._batch_flat, b_pad * c)
                k = min(self._batch_k, c)
                qb = qcols[rows_p].reshape(b_pad, q_m * span)
                qmat = jnp.asarray(np.concatenate(
                    [qnp[rows_p], qb], axis=1))
                pz = prune if prune is not None else _prune_zeros()
                mesh = self.mesh
                if attributed:
                    from ..parallel.sharded import sharded_bucketed_attr
                    acols = self.deps.device_attr_cols_replicated(mesh)
                    ai = aidx.device_replicated(mesh)
                    tsh = self.deps.device_table_sharded(mesh)

                    def relaunch(s2, k2, _m=mesh, _b=btable, _t=tsh,
                                 _q=qmat, _a=acols, _i=ai, _r=rankb,
                                 _p=pz):
                        return sharded_bucketed_attr(
                            _m, q_m, span, s2, k2, m_t, keff, wide,
                            k_floors, k_elide)(_b, _t, _a, _i, _q, _r,
                                               *_p)

                    part.update(d=1, d_ent=d, shard_n=c, s=s, k=k, c=c,
                                wide=wide, hoff=5, global_ids=True,
                                s_cap=b_pad * c, k_cap=c)
                else:
                    from ..parallel.sharded import sharded_bucketed_flat

                    def relaunch(s2, k2, _m=mesh, _b=btable, _q=qmat,
                                 _p=pz):
                        return sharded_bucketed_flat(
                            _m, q_m, span, s2, k2, m_t, keff,
                            wide)(_b, _q, *_p)

                    part.update(d=d, shard_n=c, s=s, k=k, c=c, wide=wide,
                                global_ids=True, s_cap=b_pad * c, k_cap=c)
                self.n_mesh_queries += len(rows)
                self.n_mesh_bucketed_queries += len(rows)
            elif kind == "dense":
                table = self.deps.device_table()
                n = table.capacity
                wide = dk.wide_codes(n, m_t, q_m)
                s = min(self._batch_flat, b_pad * n * m_t * q_m)
                k = min(self._batch_k, n * m_t * q_m)
                qmat = jnp.asarray(qnp[rows_p])
                if attributed:
                    acols = self.deps.device_attr_cols()
                    ai = aidx.device()
                    pz = prune if prune is not None else _prune_zeros()

                    def relaunch(s2, k2, _t=table, _q=qmat, _a=acols,
                                 _i=ai, _r=rankb, _p=pz):
                        return dk.calculate_deps_flat_attr(
                            _t, _a, _i, _q, _r, *_p, q_m, s2, k2, wide,
                            k_floors, k_elide)

                    part.update(hoff=5)
                else:
                    def relaunch(s2, k2, _t=table, _q=qmat, _p=prune):
                        if _p is not None:
                            return dk.calculate_deps_flat_pruned(
                                _t, _q, *_p, q_m, s2, k2, wide)
                        return dk.calculate_deps_flat(_t, _q, q_m, s2,
                                                      k2, wide)

                self.n_dense_queries += len(rows)
                part.update(d=1, shard_n=n, s=s, k=k, wide=wide,
                            s_cap=b_pad * n * m_t * q_m,
                            k_cap=n * m_t * q_m)
            else:   # bucketed
                table = self.deps.device_table()
                btable = self.deps.bucket_device()
                span = self.deps.SPAN
                keff = self.deps.bucket_keff()
                wide = dk.wide_codes(table.capacity, m_t, q_m)
                c = (q_m * span * keff + q_m * btable.wlo.shape[0])
                s = min(self._batch_flat, b_pad * c)
                k = min(self._batch_k, c)
                qb = qcols[rows_p].reshape(b_pad, q_m * span)
                qmat = jnp.asarray(np.concatenate(
                    [qnp[rows_p], qb], axis=1))
                if attributed:
                    acols = self.deps.device_attr_cols()
                    ai = aidx.device()
                    pz = prune if prune is not None else _prune_zeros()

                    def relaunch(s2, k2, _t=table, _b=btable, _q=qmat,
                                 _a=acols, _i=ai, _r=rankb, _p=pz):
                        return dk.bucketed_attr_jit(
                            _t, _a, _i, _b, _q, _r, q_m, span, s2, k2,
                            _p, keff=keff, wide=wide, floors=k_floors,
                            elide=k_elide)

                    part.update(hoff=5)
                else:
                    def relaunch(s2, k2, _t=table, _b=btable, _q=qmat,
                                 _p=prune):
                        if _p is not None:
                            return dk.bucketed_flat_pruned(
                                _t, _b, _q, q_m, span, s2, k2, *_p,
                                keff=keff, wide=wide)
                        return dk.bucketed_flat_jit(_t, _b, _q, q_m, span,
                                                    s2, k2, keff=keff,
                                                    wide=wide)

                self.n_bucketed_queries += len(rows)
                part.update(d=1, shard_n=table.capacity, s=s, k=k, c=c,
                            wide=wide, global_ids=True, s_cap=b_pad * c,
                            k_cap=c)
            hdr_dev, ent_dev = relaunch(s, k)
            part["relaunch"] = relaunch
            self.n_dispatches += 1
            self._ktime("dispatch_" + kname, _t0)
            box: Dict[str, object] = {"hdr": hdr_dev, "ent": ent_dev}
            part["box"] = box
            if not immediate:
                # two-stage prefetch on a worker thread: the header join
                # blocks on the kernel (GIL released), then ONLY the live
                # entry prefix crosses the wire — a pipelined caller
                # attributes batch i while batch i+1 computes AND
                # downloads.  No faults.check here: injection draws stay
                # on the deterministic store-task thread (_collect_part
                # re-checks before consuming each stage)
                d_, nq_, s_, k_ = part["d"], b_pad, s, k
                hoff_, de_ = part["hoff"], part["d_ent"]

                def _fetch():
                    import time as _time
                    try:
                        t0 = _time.perf_counter()
                        hdr = np.asarray(hdr_dev).reshape(d_, hoff_ + nq_)
                        box["hdr_np"] = hdr
                        box["t_hdr"] = (t0, _time.perf_counter())
                        ovf_s = int(hdr[:, 1 if hoff_ == 5 else 0].max())
                        ovf_k = int(hdr[:, 2 if hoff_ == 5 else 1].max())
                        if ovf_s > s_ or ovf_k > k_:
                            return    # overflowed: collector re-runs
                        t1 = _time.perf_counter()
                        box["ent_np"] = _fetch_entry_prefix(
                            ent_dev, d_, de_ * s_, int(hdr[:, 0].max()))
                        box["t_ent"] = (t1, _time.perf_counter())
                    except BaseException as e:     # surfaced after join
                        box["err"] = e

                part["th"] = _fetch_pool().submit(_fetch)
            parts.append(part)

        all_rows = np.arange(nq, dtype=np.int64)
        # -- route health gating (module docstring: degradation ladder) --
        # while OOM-degraded or quarantined, every flush is pinned to the
        # host route (the route choice isn't even priced); when a
        # quarantine expires, the next device-bound flush is the PROBE —
        # its success restores the device routes, its failure re-
        # quarantines deeper
        probing = False
        forced, may_probe = self._flush_gate(nq)
        if forced is not None:
            route = "host"
        else:
            route = self.route_override
            if route is None:
                route = self._choose_route(qnp, q_m,
                                           floor_id if prune_floors
                                           else None)
            if route != "host" and may_probe:
                probing = True
                self.n_reprobes += 1
                self._fault_event("reprobe", f"route={route}")
        # -- r21 store-sharded residency gating --
        sh = self.store_shards
        hybrid = False
        if (sh is not None and sh.active and self.mesh is not None
                and forced is None and route != "host"):
            sh.tick_flush()
            if sh.any_quarantined():
                if attributed:
                    # hybrid: healthy slices answer on device, the sick
                    # slices' slots from the host twin (a host_slice part)
                    hybrid = True
                else:
                    # the raw-CSR path consumes whole per-part CSRs (no
                    # per-entry merge point for a twin to join at): serve
                    # the whole flush from host while any slice is sick
                    route = "host"
                    self.n_fallback_queries += nq
                    probing = False
            if route != "host":
                self.n_store_sharded_flushes += 1
        observed = forced or route
        if self.on_route is not None:
            self.on_route(observed, nq)
        else:
            obs = getattr(self.store.node, "route_observer", None)
            if obs is not None:
                # the query txn-ids ride along so the observer can stamp
                # the route onto each txn's span tree (obs.spans)
                obs(self.store, observed, nq, [q[0] for q in queries])
        degenerate = not self.BUCKETED or \
            len(self.deps.wide_entries) > self.deps.WIDE_MAX
        try:
            if route == "host":
                dispatch("host", all_rows)
            elif self.mesh is not None:
                if hybrid:
                    # quarantined slices pin the flush to the DENSE
                    # sharded kind: the bucketed kernels read entries
                    # structurally (no status column), so only the dense
                    # mask can exclude a masked slice
                    dispatch("sharded", all_rows)
                    dispatch("host_slice", all_rows)
                elif route == "dense" or degenerate:
                    dispatch("sharded", all_rows)
                else:
                    qcols, wide_q = self._bucket_query_cols(qnp, q_m)
                    narrow = np.nonzero(~wide_q)[0].astype(np.int64)
                    wide = np.nonzero(wide_q)[0].astype(np.int64)
                    if len(narrow):
                        dispatch("sharded_bucketed", narrow, qcols)
                    if len(wide):
                        dispatch("sharded", wide)
            elif route == "dense" or degenerate:
                dispatch("dense", all_rows)
            else:
                qcols, wide_q = self._bucket_query_cols(qnp, q_m)
                narrow = np.nonzero(~wide_q)[0].astype(np.int64)
                wide = np.nonzero(wide_q)[0].astype(np.int64)
                if len(narrow):
                    dispatch("bucketed", narrow, qcols)
                if len(wide):
                    dispatch("dense", wide)
        except faults.DEVICE_EXCEPTIONS as e:
            # device-boundary failure at dispatch: quarantine (the slice
            # it touched, under store-shards; else the device) and fail
            # the WHOLE flush over to the always-correct host route
            parts.clear()
            self._device_fault(e, f"dispatch: {e}", sliced=True)
            self.n_fallback_queries += nq
            probing = False
            dispatch("host", all_rows)
        if immediate:
            # synchronous caller (deps_query, B=1): collect follows on the
            # next line with no interleaved mutation, so skip the snapshot
            # copies and the prefetch thread — the live mirror IS the
            # snapshot
            ids = (self.deps.msb, self.deps.lsb, self.deps.node,
                   self.deps.obj, self.deps.status, self.deps.emsb,
                   self.deps.elsb, self.deps.enode, self.deps.eknown)
            ivs = (self.deps.lo, self.deps.hi, self.deps.domain)
        elif len(parts) == 1 and parts[0]["kind"] == "host":
            # host route: the pairs are already known, so snapshot ONLY the
            # referenced slots (a gather of ~live-tail rows instead of a
            # full-capacity copy) and remap the pair/slot indices onto the
            # compact snapshot.  np.unique is sorted, so the remap is
            # monotonic and the CSR's ascending-slot order — and therefore
            # every downstream byte — is unchanged
            part = parts[0]
            d = self.deps
            if "ent" in part:
                cb, cj, cm, cq = part["ent"]
                flag = np.zeros(d.capacity, bool)
                flag[cj] = True
                u = np.nonzero(flag)[0]
                remap = np.empty(d.capacity, np.int64)
                remap[u] = np.arange(len(u), dtype=np.int64)
                part["ent"] = (cb, remap[cj], cm, cq)
            else:
                u = np.unique(part["j"])
                part["j"] = np.searchsorted(u, part["j"])
            ids = (d.msb[u], d.lsb[u], d.node[u], d.obj[u], d.status[u],
                   d.emsb[u], d.elsb[u], d.enode[u], d.eknown[u])
            ivs = (d.lo[u], d.hi[u], d.domain[u])
        else:
            # snapshot the mirror's id + interval columns: the mirror
            # mutates in place, and a slot freed+reallocated between begin
            # and end would otherwise resolve this batch's indices to the
            # WRONG TxnId (or footprint).  The copy is version-cached:
            # pipelined batches over an unmutated mirror share one
            ids, ivs, _kind = self.deps.snapshot_cols()
        fmeta = {"floor_id": floor_id, "probing": probing,
                 "immediate": immediate, "attributed": attributed,
                 "aidx": aidx, "rankb": rankb_np,
                 "floor_skip": floor_skip}
        return (parts, ids, ivs, qnp, q_m, list(queries), fmeta)

    def _bucket_query_cols(self, qnp: np.ndarray, q_m: int):
        """Vectorized query->bucket-row mapping: int64[NQ, q_m, SPAN] dense
        rows (-1 = no/empty bucket) and the wide-query mask (any interval
        spanning more than SPAN buckets — those take the dense kernel)."""
        shift = self.deps.BSHIFT
        span = self.deps.SPAN
        lo = qnp[:, 7:7 + q_m]
        hi = qnp[:, 7 + q_m:7 + 2 * q_m]
        used = lo <= hi
        blo = lo >> shift
        bhi = hi >> shift
        wide_q = np.any(used & (bhi - blo + 1 > span), axis=1)
        sorted_bids, row_of = self.deps.bid_rows()
        cols = np.full((qnp.shape[0], q_m, span), -1, np.int64)
        if len(sorted_bids):
            for off in range(span):
                bid = blo + off
                ok = used & (bid <= bhi)
                idx = np.searchsorted(sorted_bids, bid)
                idxc = np.minimum(idx, len(sorted_bids) - 1)
                found = ok & (sorted_bids[idxc] == bid)
                cols[:, :, off] = np.where(found, row_of[idxc], -1)
        return cols, wide_q

    def _ktime(self, kind: str, t0: float) -> None:
        import time as _time
        self._ktime_span(kind, t0, _time.perf_counter())

    def _ktime_span(self, kind: str, t0: float, t1: float) -> None:
        """One finished launch-boundary slice with explicit endpoints —
        the two-stage downloads measure their header/entry fetches where
        they actually happened (possibly on the prefetch thread) and
        report them here (dispatch_* = host pack + upload + enqueue,
        wait_header_* = header join, wait_entries_* = entry-prefix
        transfer, host_* = host passes)."""
        cell = self.kernel_times.get(kind)
        if cell is None:
            cell = self.kernel_times[kind] = [0, 0.0]
        cell[0] += 1
        cell[1] += t1 - t0
        prof = devprof.PROFILER
        if prof is not None:
            # every launch boundary timed here becomes a Chrome-trace
            # slice: pid = node, tid = store — the launch timeline, not
            # just a counter
            prof.complete(
                kind, t0, t1,
                pid=getattr(getattr(self.store, "node", None),
                            "node_id", 0) or 0,
                tid=getattr(self.store, "store_id", 0) or 0)

    def _overflow_resize(self, total: int, maxc: int, s: int, k: int,
                         s_cap: int, k_cap: int, runs: int):
        """ONE overflow re-sizing policy for the solo and fused re-run
        loops: size the flat capacity to the exact observed total (+25%
        headroom, 16k granularity) and the row width with 2x headroom
        (every distinct (s, k) is a fresh jit compilation; a mid-run
        recompile costs seconds on TPU); after the first re-run escalate
        geometrically — a truncated-past-k dense row under-counts its
        triples in the header (flat_csr_local docstring) — so the loop
        terminates at the caps; sticky-learn the result so subsequent
        batches dispatch right-sized."""
        s2 = -(-int(total * 1.25) // 16384) * 16384
        k2 = _pow2_at_least(2 * maxc)
        if runs:
            s2, k2 = max(s2, 2 * s), max(k2, 2 * k)
        s = min(max(s2, s), s_cap)
        k = min(max(k2, k), k_cap)
        self._batch_flat = max(self._batch_flat, s)
        self._batch_k = max(self._batch_k, k)
        return s, k

    def _prefix_pays(self, d: int, s: int, maxtot: int,
                     itemsize: int) -> bool:
        """Stage-2 transfer model for a SYNCHRONOUS fetch: slicing the
        live prefix costs one extra device dispatch (~an rtt) and saves
        the padded tail's bytes — a model over the calibrated per-byte
        transfer cost, not a threshold.  On a local CPU device bytes are
        ~free and the single full fetch wins; on a tunneled MB/s link the
        prefix wins from ~100KB of tail."""
        saved = d * (s - _prefix_len(maxtot, s)) * itemsize
        if saved <= 0:
            return False
        calib = self._calibration()
        return saved * calib.get("c_xfer", 0.0) > calib["rtt"]

    def _collect_part(self, part):
        """Two-stage download + decode of one kernel part's exact CSR.
        Stage 1 fetches the scalar header (totals / max row width /
        row_end) — a few hundred int32s whose join also absorbs the kernel
        wait; stage 2 transfers ONLY the live prefix of the entry buffer.
        When the learned flat capacity or row width overflowed, the re-run
        is sized from the exact header already downloaded and rides the
        same compacted transfer — the full pow2-padded buffer is never
        materialized on the host.  Returns per-triple (b, j, m, q) global
        arrays (codes decoded, pad rows dropped)."""
        import time as _time
        box = part["box"]
        th = part.get("th")
        nq, d = part["nq"], part["d"]
        s, k = part["s"], part["k"]
        hoff, d_ent = part.get("hoff", 2), part.get("d_ent", 1)
        attr = hoff == 5
        itemsize = 8 if part["wide"] else 4
        faults.check("transfer", "header download")
        _t0 = _time.perf_counter()
        if th is not None:
            th.result()
            err = box.get("err")
            if err is not None:
                raise err           # the real device/transfer failure
            hdr = box["hdr_np"]
            t_h = box.get("t_hdr")
        else:
            hdr = np.asarray(box["hdr"]).reshape(d, hoff + nq)
            t_h = None
        self._ktime_span("wait_header_" + part["kind"],
                         *(t_h or (_t0, _time.perf_counter())))
        self.download_bytes += hdr.nbytes
        self.download_bytes_padded += hdr.nbytes + d * d_ent * s * itemsize
        runs = 0
        while int(hdr[:, 1 if attr else 0].max()) > s \
                or int(hdr[:, 2 if attr else 1].max()) > k:
            # overflow: re-size from the exact header (shared policy,
            # _overflow_resize), then re-dispatch against the same
            # snapshot tables via the part's relaunch closure —
            # registrations interleaved between begin and end must not
            # shift the queried snapshot
            s, k = self._overflow_resize(
                int(hdr[:, 1 if attr else 0].max()),
                int(hdr[:, 2 if attr else 1].max()), s, k,
                part["s_cap"], part["k_cap"], runs)
            dk.launch_check(part["kind"])
            hdr_dev, ent_dev = part["relaunch"](s, k)
            box = {"hdr": hdr_dev, "ent": ent_dev}
            th = None
            faults.check("transfer", "header download")
            _t0 = _time.perf_counter()
            hdr = np.asarray(hdr_dev).reshape(d, hoff + nq)
            self._ktime("wait_header_" + part["kind"], _t0)
            self.download_bytes += hdr.nbytes
            self.download_bytes_padded += hdr.nbytes \
                + d * d_ent * s * itemsize
            runs += 1
        faults.check("transfer", "entry download")
        _t1 = _time.perf_counter()
        if th is not None and "ent_np" in box:
            ent = box["ent_np"]
            t_e = box.get("t_ent")
        else:
            # synchronous fetch (immediate flush or post-overflow): slice
            # the live prefix only when the modeled byte saving beats the
            # extra slice dispatch — on the pipelined path the prefix
            # fetch rides the prefetch thread and overlaps compute, so it
            # never asks
            maxtot = int(hdr[:, 0].max())
            if self._prefix_pays(d, d_ent * s, maxtot, itemsize):
                ent = _fetch_entry_prefix(box["ent"], d, d_ent * s, maxtot)
            else:
                ent = np.asarray(box["ent"]).reshape(d, d_ent * s)
            t_e = None
        self._ktime_span("wait_entries_" + part["kind"],
                         *(t_e or (_t1, _time.perf_counter())))
        self.download_bytes += ent.nbytes
        if self.store_shards is not None and self.store_shards.active \
                and "sharded" in part["kind"]:
            # bytes the sharded-store merge shipped home (header + merged
            # entry block) — the ``shard_merge_bytes`` index counter
            self.n_shard_merge_bytes += hdr.nbytes + ent.nbytes
        if attr:
            # the attributed header carries the in-kernel elision tallies
            # (eknown-graded transitive rows vs decided-below-pivot rows)
            # and the download is the post-attribution entry set
            self.n_elided_transitive += int(hdr[:, 3].sum())
            self.n_elided_decided += int(hdr[:, 4].sum())
            self.attr_download_bytes += hdr.nbytes + ent.nbytes
        tb, tj, tm, tq = _decode_triples(hdr, ent, nq, part["shard_n"],
                                         bool(part.get("global_ids")),
                                         part["mq"], part["q_m"],
                                         hoff=hoff)
        # stale/corrupted-result injection: perturb the slot indices the
        # kernel answered with.  Only where the detector actually runs —
        # paranoia shadow-verify on an IMMEDIATE flush (the protocol path);
        # injecting silent corruption with no detector would just be
        # breaking the program, not testing it.
        if part.get("immediate") and self._paranoid() and len(tj) \
                and faults.should_fire("stale_result"):
            tj = (tj + np.int64(1)) % np.int64(self.deps.capacity)
        gmap = part["gmap"]
        b_global = gmap[tb]
        keep = b_global >= 0                      # drop pad rows
        return b_global[keep], tj[keep], tm[keep], tq[keep]

    def _batch_collect(self, handle):
        """Collect a dispatched batch: one two-stage compacted download per
        part (plus an exact-header-sized re-run on overflow), then a pure
        DECODE — the kernels answer with exact overlap triples, so no
        false-positive pair exists to re-filter and the old host geometry
        pass (``_exact_geometry``) has nothing to do on any device route.
        The host route's probes were always exact, so its pairs and
        triples arrive precomputed either way.  Re-runs use the table
        snapshot captured at begin — registrations interleaved between
        begin and end must not shift the queried snapshot.

        Device-boundary failures here (transfer/download, injected or real)
        quarantine the device routes and fail the flush over to the host
        route; in paranoia mode the surviving device answer is additionally
        shadow-verified against the host route and any mismatch is treated
        as a device fault (both correctness-preserving: all routes are
        bit-identical by construction).  The host fallback/shadow scan runs
        against the live mirror — exact under the immediate (protocol)
        path, where no mutation can interleave between begin and end."""
        (parts, ids, ivs, qnp, q_m, queries, fmeta) = handle
        import time as _time
        nq = len(queries)
        if len(parts) == 1 and parts[0]["kind"] == "host":
            part = parts[0]
            b_idx, j_idx = part["b"], part["j"]
            self.n_queries += nq
            self.n_kernel_deps += len(j_idx)
            return b_idx, j_idx, part["pmq"], ids, ivs, qnp, queries
        try:
            outs = [self._collect_part(p) for p in parts]
        except faults.DEVICE_EXCEPTIONS as e:
            self._device_fault(e, f"collect: {e}", sliced=True)
            return self._host_fallback_collect(handle)
        _tg = _time.perf_counter()
        if len(outs) == 1:
            tb, tj, tm, tq = outs[0]
        else:
            tb = np.concatenate([o[0] for o in outs])
            tj = np.concatenate([o[1] for o in outs])
            tm = np.concatenate([o[2] for o in outs])
            tq = np.concatenate([o[3] for o in outs])
        # global triple dedupe: the in-kernel dedupe is per-part only —
        # under the row-sharded bucket index one triple can surface from
        # several shards.  The (b-major, code-ascending) dedupe order
        # matches the per-part CSR order, so results are byte-identical
        # with or without this pass; single-part exact kernels skip it
        # (slot-sharded and single-device CSRs are unique by construction)
        if len(tj) and (self.FORCE_TRIPLE_DEDUPE or len(parts) > 1
                        or parts[0]["kind"] == "sharded_bucketed"):
            order, first = _group_dedupe((tq, tm, tj, tb))
            order = order[first]
            tb, tj, tm, tq = tb[order], tj[order], tm[order], tq[order]
        b_idx, j_idx, p_i = _tri_pairs(tb, tj)
        if self._paranoid() and fmeta["immediate"]:
            # shadow-verify: the exact (query, slot) pair set must match
            # the host route's byte-for-byte; a mismatch means the device
            # answered wrong (stale/corrupted result) — quarantine it and
            # serve the host answer
            self.n_shadow_checks += 1
            b_h, j_h, pmq_h = self.deps.host_pairs(qnp, q_m,
                                                   fmeta["floor_id"])
            cap = np.int64(self.deps.capacity)
            if not np.array_equal(np.unique(b_idx * cap + j_idx),
                                  np.unique(b_h * cap + j_h)):
                self.n_shadow_mismatches += 1
                self._device_fault("stale_result", "shadow mismatch",
                                   sliced=True)
                self.n_fallback_queries += nq
                self.n_queries += nq
                self.n_kernel_deps += len(j_h)
                return b_h, j_h, pmq_h, ids, ivs, qnp, queries
        sh = self.store_shards
        if sh is not None and sh.active:
            sh.note_success()   # probing suspect slices are healthy again
        if fmeta["probing"]:
            self._restore_device()   # the probe flush succeeded end-to-end
        self.n_queries += nq
        self.n_kernel_deps += len(j_idx)
        self._ktime("host_decode", _tg)
        return b_idx, j_idx, (p_i, tm, tq), ids, ivs, qnp, queries

    def _exact_geometry(self, b_idx, j_idx, ivs, qnp, q_m):
        """REFERENCE implementation of the exact overlap geometry over a
        (query, slot) pair list, yielding the (pair, dep-interval,
        query-interval) emit triples.  r10 pushed this into every device
        kernel (the CSR entries ARE the triples, as sorted composite
        codes), so no production route calls it anymore — it remains as
        the oracle the exact-kernel property tests compare against
        (tests/test_exact_collect.py) and as the executable spec of the
        emit-triple order (np.nonzero over [P, M, Q] = pair-major,
        dep-column, query-column — exactly the kernels' code sort)."""
        lo, hi, _dom = ivs
        lo_p, hi_p = lo[j_idx], hi[j_idx]                       # [P, M]
        used = lo_p <= hi_p
        qlo_p = qnp[b_idx, 7:7 + q_m]                           # [P, Q]
        qhi_p = qnp[b_idx, 7 + q_m:7 + 2 * q_m]
        overlap = (used[:, :, None]
                   & (lo_p[:, :, None] <= qhi_p[:, None, :])
                   & (qlo_p[:, None, :] <= hi_p[:, :, None]))   # [P, M, Q]
        p_i, m_i, q_i = np.nonzero(overlap)
        # drop pairs with no exact overlap (bounding-box false positives)
        present = np.zeros(len(j_idx), bool)
        present[p_i] = True
        if not present.all():
            new_pos = np.cumsum(present) - 1
            b_idx, j_idx = b_idx[present], j_idx[present]
            p_i = new_pos[p_i]
        return b_idx, j_idx, (p_i, m_i, q_i)

    def _host_fallback_collect(self, handle):
        """Serve a flush whose device parts failed mid-collect from the
        host route (identical bytes by the routing invariant)."""
        (_parts, ids, ivs, qnp, q_m, queries, fmeta) = handle
        nq = len(queries)
        b_h, j_h, pmq_h = self.deps.host_pairs(qnp, q_m, fmeta["floor_id"])
        self.n_host_queries += nq
        self.n_fallback_queries += nq
        self.n_dispatches += 1
        self.n_queries += nq
        self.n_kernel_deps += len(j_h)
        return b_h, j_h, pmq_h, ids, ivs, qnp, queries

    def deps_query_batch_end(self, handle):
        """Raw packed-CSR collection (no floors/attribution) — the transport
        layout replicas exchange; deps_query_batch_end_attributed is the
        protocol-complete variant."""
        b_idx, j_idx, _ov, ids, _ivs, _qnp, queries = \
            self._batch_collect(handle)
        order = np.argsort(b_idx, kind="stable")
        b_idx, j_idx = b_idx[order], j_idx[order]
        counts = np.bincount(b_idx, minlength=len(queries))
        row_ptr = np.zeros(len(queries) + 1, np.int64)
        np.cumsum(counts, out=row_ptr[1:])
        msb, lsb, node = ids[0], ids[1], ids[2]
        return (row_ptr, msb[j_idx], lsb[j_idx], node[j_idx])

    def _host_attr_triples(self, handle, part=None, snapshot=None):
        """Entry-level host answer for an ATTRIBUTED flush: the host
        route's exact probes + the same floor/elision drops the kernels
        fold in, over the flush's snapshot columns.  Serves the host
        route itself, the device-fault failover and the paranoia shadow.
        Returns (tb, tj, tm, tq)."""
        (_parts, ids, ivs, qnp, q_m, _queries, fmeta) = handle
        if part is not None:
            tb, tj, cm, cq = part["ent"]
        else:
            tb, tj, cm, cq = self.deps.host_pairs(
                qnp, q_m, fmeta["floor_id"], snapshot=snapshot,
                entries=True)
        tb, tj, tm, tq, n_t, n_d = self._attr_filter_entries(
            tb, tj, cm, cq, ids, ivs, fmeta["aidx"], fmeta["rankb"],
            fmeta["floor_skip"])
        self.n_elided_transitive += n_t
        self.n_elided_decided += n_d
        return tb, tj, tm, tq

    def _batch_collect_attr(self, handle):
        """Collect an ATTRIBUTED dispatched batch: the kernels already
        applied floors/elision/dedupe, so the download IS the final entry
        set and this is a pure decode.  The host route (and any device
        failover / paranoia shadow) applies the identical drops through
        _attr_filter_entries over the same snapshot — every route hands
        the shared finalize the same entries.  Returns (tb, tj, tm, tq,
        ids, ivs, qnp, q_m, queries)."""
        (parts, ids, ivs, qnp, q_m, queries, fmeta) = handle
        import time as _time
        nq = len(queries)
        if len(parts) == 1 and parts[0]["kind"] == "host":
            _th = _time.perf_counter()
            tb, tj, tm, tq = self._host_attr_triples(handle,
                                                     part=parts[0])
            self.n_queries += nq
            self.n_kernel_deps += len(tj)
            self._ktime("host_attr_filter", _th)
            return tb, tj, tm, tq, ids, ivs, qnp, q_m, queries
        try:
            # host_slice twin parts (the r21 hybrid) answer from the host
            # mirror through the same attr filter the host route uses;
            # device parts download as usual
            outs = [self._host_attr_triples(handle, part=p)
                    if p["kind"] == "host_slice" else self._collect_part(p)
                    for p in parts]
        except faults.DEVICE_EXCEPTIONS as e:
            self._device_fault(e, f"collect: {e}", sliced=True)
            self.n_host_queries += nq
            self.n_fallback_queries += nq
            self.n_dispatches += 1
            self.n_queries += nq
            tb, tj, tm, tq = self._host_attr_triples(handle)
            self.n_kernel_deps += len(tj)
            return tb, tj, tm, tq, ids, ivs, qnp, q_m, queries
        _tg = _time.perf_counter()
        if len(outs) == 1:
            tb, tj, tm, tq = outs[0]
        else:
            tb = np.concatenate([o[0] for o in outs])
            tj = np.concatenate([o[1] for o in outs])
            tm = np.concatenate([o[2] for o in outs])
            tq = np.concatenate([o[3] for o in outs])
        if self._paranoid() and fmeta["immediate"]:
            # shadow-verify the ATTRIBUTED answer: the surviving
            # (query, slot) pair set must equal the host route's answer
            # run through the same floor/elision drops
            self.n_shadow_checks += 1
            hb, hj, hm, hq = self._host_attr_triples(handle)
            cap = np.int64(max(self.deps.capacity, 1))
            if not np.array_equal(np.unique(tb * cap + tj),
                                  np.unique(hb * cap + hj)):
                self.n_shadow_mismatches += 1
                self._device_fault("stale_result", "attr shadow mismatch",
                                   sliced=True)
                self.n_fallback_queries += nq
                self.n_queries += nq
                self.n_kernel_deps += len(hj)
                return hb, hj, hm, hq, ids, ivs, qnp, q_m, queries
        sh = self.store_shards
        if sh is not None and sh.active:
            sh.note_success()   # probing suspect slices are healthy again
        if fmeta["probing"]:
            self._restore_device()   # the probe flush succeeded end-to-end
        self.n_queries += nq
        self.n_kernel_deps += len(tj)
        self._ktime("host_decode", _tg)
        return tb, tj, tm, tq, ids, ivs, qnp, q_m, queries

    def _finalize_attr_entries(self, tb, tj, tm, tq, ids, ivs, qnp, q_m,
                               builders) -> None:
        """The thin shared finalize: attributed entries -> builder CSRs.
        Every floor/elision decision already happened (in-kernel on device
        routes, _attr_filter_entries on the host route), so what remains
        is pure shaping: token gathers, dense id ranks, and the two
        columnar batch finalizes.  The (query, token, dep) dedupe built
        into _finalize_key_batch covers the duplicate emits host probes
        keep (the kernels drop them in-kernel only to shrink the wire)."""
        (msb_a, lsb_a, node_a, obj_a, _status, _xm, _xl, _xn, _xk) = ids
        lo, hi, dom = ivs
        if len(tj) == 0:
            return
        key_dep = dom[tj] == int(Domain.Key)
        all_key = key_dep.all()              # the hot-key regime: skip the
        if all_key:                          # split gathers wholesale
            kp = None
        else:
            kp = np.nonzero(key_dep)[0]
        if all_key or len(kp):
            if all_key:
                bb, jj, km = tb, tj, tm
            else:
                bb, jj, km = tb[kp], tj[kp], tm[kp]
            tt = lo[jj, km]                  # key-domain footprint = point
            # token ranks: when every used query interval is a POINT the
            # emitted tokens are a subset of the query tokens — rank
            # against that tiny sorted set instead of sorting the emits
            # (extra never-emitted ranks only stretch the composite)
            q_lo = qnp[:, 7:7 + q_m]
            q_hi = qnp[:, 7 + q_m:7 + 2 * q_m]
            used = q_lo <= q_hi
            if (q_lo[used] == q_hi[used]).all():
                uniq_t2 = np.unique(q_lo[used])
                inv_t2 = _exact_ranks(uniq_t2, tt)
            else:
                uniq_t2, inv_t2 = np.unique(tt, return_inverse=True)
            # unique dep slots: presence flags + an inverse-map gather
            # beat a sort once the emit set outgrows the snapshot's slot
            # space (slot ids are dense by construction)
            cap_s = len(msb_a)
            if len(jj) > cap_s // 4:
                flag = np.zeros(cap_s, bool)
                flag[jj] = True
                u_slots = np.nonzero(flag)[0]
                remap = np.empty(cap_s, np.int64)
                remap[u_slots] = np.arange(len(u_slots), dtype=np.int64)
                slot_inv = remap[jj]
            else:
                u_slots, slot_inv = np.unique(jj, return_inverse=True)
            ordr = np.lexsort((node_a[u_slots],
                               lsb_a[u_slots].astype(np.uint64),
                               msb_a[u_slots].astype(np.uint64)))
            rank = np.empty(len(u_slots), np.int64)
            rank[ordr] = np.arange(len(u_slots))
            _finalize_key_batch(builders, bb, tt, inv_t2, len(uniq_t2),
                                rank[slot_inv], len(u_slots), obj_a[jj])
        rp = np.zeros(0, np.int64) if all_key else np.nonzero(~key_dep)[0]
        if len(rp):
            jj_r, bb_r, rm, rq = tj[rp], tb[rp], tm[rp], tq[rp]
            ilo = np.maximum(lo[jj_r, rm], qnp[bb_r, 7 + rq])
            ihi = np.minimum(hi[jj_r, rm], qnp[bb_r, 7 + q_m + rq]) + 1
            _finalize_range_batch(builders, bb_r, ilo, ihi,
                                  msb_a[jj_r], lsb_a[jj_r],
                                  node_a[jj_r], obj_a[jj_r])

    def deps_query_batch_end_attributed(self, safe, handle, builders) -> None:
        """Collect a dispatched batch and fold each query's deps into its
        builder with full host-path semantics.  Attributed handles (every
        protocol path since r15) arrive pre-floored/pre-elided from the
        kernels and take the thin shared finalize; raw handles keep the
        legacy host _attribute_batch pass (the property-test oracle)."""
        import time as _time
        if handle[6].get("attributed"):
            tb, tj, tm, tq, ids, ivs, qnp, q_m, _queries = \
                self._batch_collect_attr(handle)
            _ta = _time.perf_counter()
            self._finalize_attr_entries(tb, tj, tm, tq, ids, ivs, qnp,
                                        q_m, builders)
            self._ktime("host_attr_finalize", _ta)
            return
        b_idx, j_idx, overlap, ids, ivs, qnp, queries = \
            self._batch_collect(handle)
        _ta = _time.perf_counter()
        self._attribute_batch(safe, b_idx, j_idx, overlap, ids, ivs, qnp,
                              queries, builders)
        self._ktime("host_attribute", _ta)

    # ------------------------------------------------------------------
    # fused cross-store dispatch (r08; driven by local.dispatch's
    # per-node DeviceDispatcher)
    # ------------------------------------------------------------------
    def fused_eligible(self, queries):
        """Dispatcher phase A (PURE — mutates nothing): can this store's
        pending flush join a fused device launch?  None when the flush
        must (or would) run the host route — a host flush has no device
        launch to coalesce; else a hint dict carrying the packed queries
        and the modeled solo device element count the dispatcher's
        fused-vs-solo pricing consumes.  A store that ends up NOT fused
        runs the classic solo flush, which applies the gate/probe/route
        bookkeeping itself."""
        if self.host_pinned or self._dev_quar_flushes > 0 \
                or self.route_override == "host":
            return None
        sh = self.store_shards
        if sh is not None and sh.active and sh.any_quarantined():
            # hybrid (device + host-twin) flushes run solo: a fused
            # member's block is all-device, with no twin part to graft
            return None
        q_m = _pow2_at_least(max(len(t[3]) + len(t[4]) for t in queries))
        packed = [(sb, wit, toks, rngs, tid)
                  for (tid, sb, wit, toks, rngs) in queries]
        qnp = dk.pack_query_matrix(packed, q_m)
        floor_id, prune_np = self._batch_floor(qnp, q_m)
        route = self.route_override
        if route is None:
            route = self._choose_route(qnp, q_m, floor_id)
        if route == "host":
            return None
        nq = qnp.shape[0]
        b_pad = _pow2_at_least(nq, 1)
        cap = self.deps.capacity
        d = 1 if self.mesh is None else max(len(self.mesh.devices.flat), 1)
        solo_elems = b_pad * cap * q_m * self.deps.max_intervals // d
        degenerate = not self.BUCKETED or \
            len(self.deps.wide_entries) > self.deps.WIDE_MAX
        if route != "dense" and not degenerate:
            # the adaptive solo dispatch would probe the bucket index for
            # narrow queries — price solo with the cheaper kernel
            buck = b_pad * (q_m * self.deps.SPAN * self.deps.bucket_keff()
                            + q_m * len(self.deps.wide_entries) // d)
            solo_elems = min(solo_elems, buck)
        # snapshot cost the fused pricing charges: zero when the cached
        # copy is still fresh, one full-column memcpy's worth otherwise
        dm = self.deps
        snap_stale = dm._snap is None or dm._snap[0] != dm.mut_version
        snap_elems = cap * (2 * dm.max_intervals + 10) if snap_stale else 0
        # r15: fused launches run the ATTRIBUTED kernels — build (or
        # reuse) this store's floor/elision index and the per-query bound
        # ranks now, while the mirror is the begin-time state
        aidx = self._attr_index()
        return {"dev": self, "queries": list(queries), "qnp": qnp,
                "q_m": q_m, "floor_id": floor_id, "prune": prune_np,
                "nq": nq, "b_pad": b_pad, "cap": cap,
                "m_iv": self.deps.max_intervals, "solo_elems": solo_elems,
                "snap_elems": snap_elems, "aidx": aidx,
                "rankb_np": aidx.rank_bounds(qnp),
                "floor_skip": aidx.floors_match(qnp, q_m, floor_id)}

    def fused_table(self):
        """The (cached, device-resident) table the fused launch consumes —
        mesh-sharded under a mesh, single-device otherwise."""
        if self.mesh is not None:
            return self.deps.device_table_sharded(self.mesh)
        return self.deps.device_table()

    def fused_commit(self, hint) -> None:
        """Dispatcher phase B for a chosen fused member: apply the
        flush-gate bookkeeping the solo path would have applied (probe
        accounting), snapshot the mirror columns the deferred harvest
        needs (mutations may land between dispatch and the harvest task),
        and surface the routing decision."""
        probing = False
        if self._dev_backoff > 0:
            probing = True
            self.n_reprobes += 1
            self._fault_event("reprobe", "route=fused")
        hint["ids"], hint["ivs"], hint["kind_col"] = \
            self.deps.snapshot_cols()
        hint["probing"] = probing
        if self.on_route is not None:
            self.on_route("fused", hint["nq"])
        else:
            obs = getattr(self.store.node, "route_observer", None)
            if obs is not None:
                batch = hint.get("batch") or ()
                obs(self.store, "fused", hint["nq"],
                    [q[0] for q, _b, _d in batch])

    def fused_fail_to_host(self, hint, exc) -> None:
        """A device fault inside the fused LAUNCH fails the whole batch
        over to the host route: quarantine this member and compute its
        host pairs right now (still inside the dispatcher event, so the
        live mirror IS the prep-time state)."""
        self._device_fault(exc, f"fused dispatch: {exc}", sliced=True)
        self.n_fallback_queries += hint["nq"]
        hint["probing"] = False
        hint["host"] = self.deps.host_pairs(hint["qnp"], hint["q_m"],
                                            hint["floor_id"], entries=True)

    def _hint_attr_entries(self, hint, ent4) -> tuple:
        """Turn a fused hint's host-route per-entry answer into the
        attributed entry set: the same floor/elision drops the fused
        kernel applies, over the hint's begin-time snapshot columns."""
        cb, cj, cm, cq = ent4
        tb, tj, tm, tq, n_t, n_d = self._attr_filter_entries(
            cb, cj, cm, cq, hint["ids"], hint["ivs"],
            hint["aidx"], hint["rankb_np"], hint.get("floor_skip", False))
        self.n_elided_transitive += n_t
        self.n_elided_decided += n_d
        return tb, tj, tm, tq

    def _fused_snapshot(self, hint):
        return (hint["ids"][0], hint["ids"][1], hint["ids"][2],
                hint["kind_col"], hint["ids"][4], hint["ivs"][0],
                hint["ivs"][1])

    def _fused_collect(self, hint, launch):
        """Download + decode of this store's block of the fused ATTRIBUTED
        result, with the solo path's full semantics: overflow re-run
        (solo attributed, escalated s/k from the exact header, same
        snapshot table + attr inputs), stale-result injection point,
        paranoia shadow-verify against the attr-filtered SNAPSHOT host
        scan, probe restore, and whole-batch host failover on any
        device-boundary failure.  Returns attributed ENTRY arrays
        (tb, tj, tm, tq)."""
        import time as _time
        _t0 = _time.perf_counter()
        nq = hint["nq"]
        if "host" in hint:           # launch already failed over to host
            self.n_host_queries += nq
            self.n_dispatches += 1
            return self._hint_attr_entries(hint, hint["host"])
        qnp, q_m = hint["qnp"], hint["q_m"]
        shard_n = hint["shard_n"]
        b_pad = hint["b_pad_c"]
        mq, qmc = hint["mq"], hint["q_m_c"]
        pad_stride = hint.get("pad_shard_n")   # mesh: padded shard stride
        try:
            hdr_all, ent_all = launch.materialize()
            hdr = hdr_all[hint["row"]].reshape(1, 5 + b_pad)
            ent = ent_all[hint["row"]]
            s_, k_ = launch.s, launch.k
            runs = 0
            while int(hdr[:, 1].max()) > s_ or int(hdr[:, 2].max()) > k_:
                # overflow: escalate EXACTLY like the solo path — re-run
                # this store alone against the same cached table + attr
                # inputs, sized from the exact header
                cap_k = shard_n * hint["m_iv"] * qmc
                s_, k_ = self._overflow_resize(
                    int(hdr[:, 1].max()), int(hdr[:, 2].max()), s_, k_,
                    b_pad * cap_k, cap_k, runs)
                qmat = jnp.asarray(hint["qmat_np"])
                rankb = jnp.asarray(hint["rankb_pad"])
                pnp = hint["prune"]
                pz = _prune_zeros() if pnp is None else \
                    (jnp.asarray(pnp[0]), jnp.asarray(pnp[1]),
                     jnp.asarray(pnp[2]))
                wide = hint["wide"]
                fl_, el_ = (not hint.get("floor_skip", False),
                            hint["aidx"].u > 0)
                if self.mesh is not None:
                    from ..parallel.sharded import sharded_flat_attr
                    hdr_dev, ent_dev = sharded_flat_attr(
                        self.mesh, qmc, s_, k_, wide, fl_, el_)(
                        hint["table"],
                        self.deps.device_attr_cols_sharded(self.mesh),
                        hint["aidx"].device_replicated(self.mesh),
                        qmat, rankb, *pz)
                    d_ent = len(self.mesh.devices.flat)
                else:
                    hdr_dev, ent_dev = dk.calculate_deps_flat_attr(
                        hint["table"], self.deps.device_attr_cols(),
                        hint["aidx"].device(), qmat, rankb, *pz,
                        qmc, s_, k_, wide, fl_, el_)
                    d_ent = 1
                faults.check("transfer", "header download")
                hdr = np.asarray(hdr_dev).reshape(1, 5 + b_pad)
                itemsize = 8 if wide else 4
                self.download_bytes += hdr.nbytes
                self.download_bytes_padded += hdr.nbytes \
                    + d_ent * s_ * itemsize
                if int(hdr[:, 1].max()) <= s_ \
                        and int(hdr[:, 2].max()) <= k_:
                    faults.check("transfer", "entry download")
                    ent = _fetch_entry_prefix(ent_dev, 1, d_ent * s_,
                                              int(hdr[:, 0].max()))
                    self.download_bytes += ent.nbytes
                runs += 1
            if runs:
                # the re-run scanned the store's OWN table solo, so its
                # codes scale on the store's interval width and its slot
                # ids are contiguous-global (no fused pad stride)
                mq = hint["m_iv"] * qmc
                pad_stride = None
            if ent.ndim == 1:
                ent = ent.reshape(1, -1)
        except faults.DEVICE_EXCEPTIONS as e:
            # whole-batch failover: quarantine every member, serve this
            # flush from the SNAPSHOT host scan (begin-time bytes)
            launch.poison(e)
            self.n_fallback_queries += nq
            self.n_host_queries += nq
            self.n_dispatches += 1
            return self._hint_attr_entries(
                hint, self.deps.host_pairs(
                    qnp, q_m, hint["floor_id"],
                    snapshot=self._fused_snapshot(hint), entries=True))
        self.n_elided_transitive += int(hdr[:, 3].sum())
        self.n_elided_decided += int(hdr[:, 4].sum())
        self.attr_download_bytes += hdr.nbytes + ent.nbytes
        tb, tj, tm, tq = _decode_triples(hdr, ent, b_pad, shard_n,
                                         True, mq, qmc, hoff=5)
        if pad_stride is not None:
            # mesh fused codes number slots on the PADDED per-shard
            # stride (every member padded to the group's largest slice):
            # fold back onto this store's contiguous slot ids
            tj = (tj // pad_stride) * np.int64(hint["cap"]
                                               // hint["d_mesh"]) \
                + tj % pad_stride
        if self._paranoid() and len(tj) \
                and faults.should_fire("stale_result"):
            tj = (tj + np.int64(1)) % np.int64(len(hint["ids"][0]))
        gmap = hint["gmap"]
        b_global = gmap[tb]
        keep = b_global >= 0
        tb, tj, tm, tq = b_global[keep], tj[keep], tm[keep], tq[keep]
        if self._paranoid():
            self.n_shadow_checks += 1
            hb, hj, hm, hq = self._hint_attr_entries(
                hint, self.deps.host_pairs(
                    qnp, q_m, hint["floor_id"],
                    snapshot=self._fused_snapshot(hint), entries=True))
            cap = np.int64(len(hint["ids"][0]))
            if not np.array_equal(np.unique(tb * cap + tj),
                                  np.unique(hb * cap + hj)):
                self.n_shadow_mismatches += 1
                self._device_fault("stale_result", "fused shadow mismatch")
                self.n_fallback_queries += nq
                self.n_dispatches += 1
                return hb, hj, hm, hq
        sh = self.store_shards
        if sh is not None and sh.active:
            sh.note_success()
        if hint.get("probing"):
            self._restore_device()
        self.n_dispatches += 1
        self.n_fused_flushes += 1
        self.n_fused_queries += nq
        if self.mesh is not None:
            self.n_mesh_queries += nq
        else:
            self.n_dense_queries += nq
        self._ktime("wait_attr_fused", _t0)
        return tb, tj, tm, tq

    def fused_harvest(self, safe, hint, launch) -> None:
        """Store-task leg of a fused flush: parse this store's block of
        the fused ATTRIBUTED result (the shared download happens at the
        first member's harvest — jax's async dispatch overlapped the
        device work with whatever host processing ran since the launch)
        and hand the pre-attributed entries straight to the shared
        finalize over the prep-time snapshot — the same bytes the solo
        launch would have produced, harvested at the next event-loop
        boundary in deterministic store order."""
        batch = hint["batch"]
        try:
            tb, tj, tm, tq = self._fused_collect(hint, launch)
            self.n_queries += hint["nq"]
            self.n_kernel_deps += len(tj)
            self._finalize_attr_entries(tb, tj, tm, tq, hint["ids"],
                                        hint["ivs"], hint["qnp"],
                                        hint["q_m"],
                                        [b for _q, b, _d in batch])
        except BaseException as e:  # noqa: BLE001
            for _q, _b, done in batch:
                done(e, None)
            return
        for _q, _b, done in batch:
            done(None, safe)

    # ------------------------------------------------------------------
    # the drain (device replacement of listener fan-out)
    # ------------------------------------------------------------------
    def arm(self, safe, txn_id: TxnId) -> None:
        """Register a Stable/PreApplied txn's remaining waiting set as a
        drain row; the next tick will re-evaluate it."""
        cmd = safe.if_present(txn_id)
        if cmd is None or cmd.waiting_on is None:
            return
        slot = self.drain.alloc(txn_id)
        self.drain.set_status(slot, dk.SLOT_STABLE, cmd.execute_at)
        self.drain.clear_deps(slot)
        for dep in cmd.waiting_on.waiting_ids():
            dslot = self._dep_drain_slot(safe, dep)
            self.drain.add_edge(slot, dslot)
        self.drain.active[slot] = True
        self.schedule_tick()

    def _dep_drain_slot(self, safe, dep: TxnId) -> int:
        slot = self.drain.slot_of.get(dep)
        if slot is not None:
            return slot
        slot = self.drain.alloc(dep)
        cmd = safe.if_present(dep)
        status, exec_at = _drain_status_of(cmd)
        self.drain.set_status(slot, status, exec_at)
        return slot

    def on_terminal(self, txn_id: TxnId) -> None:
        """Truncation/erasure: the txn can never gate execution again
        (ref: _dep_clearance treats truncated as done).  Mark its drain row
        terminal and re-evaluate waiters — without this, truncating a dep
        whose record Cleanup then drops is a lost wakeup in device mode
        (no listeners exist to carry the erase notification)."""
        dslot = self.drain.slot_of.get(txn_id)
        if dslot is not None:
            self.drain.set_status(dslot, dk.SLOT_INVALIDATED, None)
            if self.drain.active.any():
                self.schedule_tick()

    def on_driven(self, txn_id: TxnId) -> None:
        """The txn reached ReadyToExecute/Applying — stop driving it (its
        slot lives on as a dependency of others until terminal + unreferenced)."""
        slot = self.drain.slot_of.get(txn_id)
        if slot is not None:
            self.drain.active[slot] = False
            self.drain.clear_deps(slot)

    def _mesh_tick_pays(self, n: int) -> bool:
        """Regime-adaptive drain tick: row-shard the frontier sweep only
        when the modeled per-shard matvec saving (n^2 work split d ways)
        beats the extra shard_map launch cost — the same calibration the
        deps router uses.  Tiny in-flight sets (the common sim/tick shape)
        otherwise pay a 100x launch premium per tick on the virtual CPU
        mesh; at-scale dense drains still shard."""
        calib = self._calibration()
        d = max(len(self.mesh.devices.flat), 1)
        single = 2.0 * calib["rtt"] + calib["c_dev"] * float(n) * n
        mesh = 2.0 * calib.get("rtt_mesh", calib["rtt"]) \
            + calib["c_dev"] * float(n) * n / d
        return mesh < single

    # Coalescing quantum for drain ticks (simulated/real micros): many dep
    # transitions land per tick, so the per-tick adjacency upload + kernel
    # sweep amortizes across a whole antichain instead of firing per event.
    TICK_DELAY_MICROS = 2_000

    def schedule_tick(self) -> None:
        if self._tick_scheduled:
            return
        self._tick_scheduled = True
        disp = getattr(self.store.node, "dispatcher", None)
        if disp is not None:
            # node-level coalescing (r08): ticks landing in the same
            # window share one dispatcher event — and, when the cost model
            # says it pays, one fused frontier launch
            disp.register_tick(self)
            return
        from .command_store import PreLoadContext

        def run():
            self.store.execute(PreLoadContext.empty(), self._tick)

        self.store.node.scheduler.once(self.TICK_DELAY_MICROS, run)

    def _tick(self, safe, fused=None) -> None:
        from . import commands
        self._tick_scheduled = False
        self.n_ticks += 1
        sweep_due = self.n_ticks % 8 == 0
        if not self.drain.active.any():
            if sweep_due:
                self.drain.sweep_free()
            return
        # the drain is a device boundary too: while quarantined/degraded
        # the frontier sweeps on host, and a device failure mid-tick
        # quarantines + falls back to the host sweep (same rule, same
        # candidates — the per-candidate WaitingOn re-validation below
        # makes any residual divergence a no-op, never a wrong execution).
        # A fused sweep (dispatcher-precomputed, shared with sibling
        # stores) serves the same candidates; a device failure harvesting
        # it quarantines the WHOLE fused batch, and every member's sweep
        # fails over to host.
        cand_slots = None
        used_fused = False
        mode = None
        if not (self.host_pinned or self._dev_quar_flushes > 0):
            if fused is not None and fused.serves(self):
                try:
                    cand_slots = fused.result_for(self)
                    self.n_fused_ticks += 1
                    used_fused = True
                    mode = "fused"
                except faults.DEVICE_EXCEPTIONS as e:
                    fused.poison(e)
            else:
                try:
                    import time as _time
                    _t0 = _time.perf_counter()
                    dk.launch_check("drain")
                    state, live = self.drain.state()
                    faults.check("transfer", "drain download")
                    wave = self._drain_wavefront
                    fut = None
                    if wave > 1 and drk.drain_logdepth_enabled():
                        # widened sweep: the log-depth level pass prices one
                        # launch for the next `wave` executeAt antichains.
                        # Candidates beyond the true frontier are safe — the
                        # per-candidate host re-validation below makes a
                        # not-actually-ready candidate a no-op — and any
                        # that fail to execute reset the wavefront
                        try:
                            if isinstance(state, drk.EllDrainState):
                                mode = "ell-wave"
                                lv, _r = drk.level_assign_ell(state)
                            else:
                                mode = "wave"
                                lv, _r = drk.level_assign_dense(state)
                            fut = (lv >= 1) & (lv <= wave)
                            self.n_wavefront_ticks += 1
                        except faults.DEVICE_EXCEPTIONS:
                            # fail the widened launch over to the plain
                            # frontier route, byte-identically (the W=1
                            # candidate set); leave the outer handler to
                            # the frontier's own faults
                            self._drain_wavefront = wave = 1
                            mode = None
                            fut = None
                    if wave > 1 and fut is not None:
                        pass
                    elif isinstance(state, drk.EllDrainState):
                        # large in-flight set: sparse gather sweep (no [N, N])
                        mode = "ell"
                        fut = drk.ready_frontier_ell(state)
                    elif self.mesh is not None and \
                            state.status.shape[0] % \
                            len(self.mesh.devices.flat) == 0 \
                            and self._mesh_tick_pays(state.status.shape[0]):
                        # live mesh path: the frontier sweep row-shards across
                        # devices (fixpoint analogue: parallel.sharded.
                        # sharded_drain)
                        from ..parallel.sharded import sharded_ready_frontier
                        mode = "mesh"
                        fut = sharded_ready_frontier(self.mesh)(state)
                    else:
                        mode = "device"
                        fut = drk.ready_frontier(state)
                    # drain forensics: split the sweep at the async-dispatch
                    # boundary — upload+enqueue vs the result join — so a
                    # drain-bound regime shows WHERE the tick pays
                    # (kernel_times rows + devprof drain_tick_* slices)
                    _t1 = _time.perf_counter()
                    ready = np.asarray(fut)[: len(live)]
                    self._ktime_span("drain_tick_dispatch", _t0, _t1)
                    self._ktime("drain_tick_wait", _t1)
                    cand_slots = live[ready & self.drain.active[live]]
                except faults.DEVICE_EXCEPTIONS as e:
                    self._device_fault(e, f"drain tick: {e}")
        if cand_slots is None:
            self.n_host_ticks += 1
            cand_slots = self._host_ready_slots()
            mode = "host"
        obs = getattr(getattr(self.store, "node", None),
                      "drain_observer", None)
        if obs is not None:
            obs(self.store, mode, int(len(cand_slots)))
        if len(cand_slots) != 0:
            cands = sorted(
                (self.drain.id_of[int(s)] for s in cand_slots
                 if int(s) in self.drain.id_of),
                key=_exec_order_key(safe))
            for txn_id in cands:
                commands.refresh_waiting_and_maybe_execute(safe, txn_id)
        # adaptive wavefront control (r19): widen only in the synchronous-
        # cascade regime — every candidate this tick reached Applied before
        # the tick returned (a serial chain drains in O(log depth) ticks
        # instead of one tick per link).  Anything else (async execution,
        # host/fused/mesh route, empty sweep, escape hatch) pins W back to
        # 1, so protocol-flow ticks run the exact pre-r19 frontier sweep.
        if mode in ("device", "ell", "wave", "ell-wave") \
                and len(cand_slots) != 0 and drk.drain_logdepth_enabled() \
                and all(int(self.drain.status[int(s)]) == dk.SLOT_APPLIED
                        for s in cand_slots):
            self._drain_wavefront = min(self._drain_wavefront * 2, 8192)
        else:
            self._drain_wavefront = 1
        if sweep_due:
            self.drain.sweep_free()
        if used_fused and self.drain.version != fused.version_for(self) \
                and self.drain.active.any():
            # the fused sweep was computed at dispatch time; mutations that
            # landed between dispatch and this harvest (earlier tasks in
            # this store's queue) could otherwise be a lost wakeup —
            # re-evaluate with a fresh tick
            self.schedule_tick()


def _exec_order_key(safe):
    def key(txn_id: TxnId):
        cmd = safe.if_present(txn_id)
        exec_at = cmd.execute_at if cmd is not None and cmd.execute_at \
            is not None else txn_id
        return (exec_at, txn_id)
    return key


def _drain_status_of(cmd) -> Tuple[int, Optional[Timestamp]]:
    from .status import Status
    if cmd is None:
        return dk.SLOT_TRANSITIVE, None
    if cmd.is_invalidated():
        return dk.SLOT_INVALIDATED, None
    if cmd.is_truncated():
        # truncated == locally done; never gates execution
        return dk.SLOT_INVALIDATED, None
    exec_at = cmd.execute_at_if_known()
    if cmd.has_been(Status.Applied):
        return dk.SLOT_APPLIED, exec_at
    if cmd.has_been(Status.Stable):
        return dk.SLOT_STABLE, exec_at
    if cmd.has_been(Status.Committed):
        return dk.SLOT_COMMITTED, exec_at
    if cmd.has_been(Status.Accepted):
        return dk.SLOT_ACCEPTED, exec_at
    return dk.SLOT_PREACCEPTED, None
