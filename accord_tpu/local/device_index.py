"""Device-backed conflict index + execution drain for a CommandStore.

This is the live protocol wiring of the two TPU kernels (SURVEY.md §7
stages 3-4): every globally-visible transaction a store witnesses is
registered in a struct-of-arrays DepsTable slot kept incrementally in sync
with the host command state, PreAccept/Accept/BeginRecovery dependency scans
run through ops.deps_kernel.calculate_deps, and the executeAt-gated
execution drain is driven by ops.drain_kernel.ready_frontier over a live
adjacency graph instead of per-dependency listener fan-out.

Ref semantics preserved:
 - deps scan: accord-core/src/main/java/accord/local/CommandsForKey.java:614-650
   (mapReduceActive) + InMemoryCommandStore.java:863-877 (range scan) +
   messages/PreAccept.java:245-265 (calculatePartialDeps)
 - drain: local/Commands.java:656-857 (maybeExecute /
   updateDependencyAndMaybeExecute / NotifyWaitingOn)

Host numpy mirrors are the source of truth (the sim mutates them in place,
deterministically, under the store's single-threaded task queue).  The deps
table's device buffers are refreshed by scatter-updating only dirty rows, so
on TPU the table stays HBM-resident between queries and only deltas cross
the PCIe/ICI boundary; the drain graph is uploaded whole per tick — it is
bounded by the in-flight (stable-but-unapplied) set, which sweep_free keeps
small.  The host command records remain authoritative for execution: the
kernel proposes the ready frontier, and each candidate is re-validated
against its WaitingOn bitset before executing — any mirror divergence
degrades to a no-op, never a wrong execution.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import deps_kernel as dk
from ..ops import drain_kernel as drk
from ..ops.packing import to_i64, unpack_txn_id
from ..primitives.keys import Range, Ranges
from ..primitives.timestamp import Domain, Kinds, Timestamp, TxnId

_MIN_CAPACITY = 64
_MIN_INTERVALS = 4


def _pow2_at_least(n: int, floor: int = _MIN_INTERVALS) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


@jax.jit
def _scatter_rows(table: dk.DepsTable, idx, msb, lsb, node, kind, status,
                  lo, hi) -> dk.DepsTable:
    """One fused dirty-row update for all seven table arrays (a single jit
    dispatch instead of seven eager scatters — the update-in-place path that
    keeps the table device-resident between queries)."""
    return dk.DepsTable(
        table.msb.at[idx].set(msb),
        table.lsb.at[idx].set(lsb),
        table.node.at[idx].set(node),
        table.kind.at[idx].set(kind),
        table.status.at[idx].set(status),
        table.lo.at[idx].set(lo),
        table.hi.at[idx].set(hi))


def _grow(arr: np.ndarray, new_len: int, fill) -> np.ndarray:
    out = np.full((new_len,) + arr.shape[1:], fill, dtype=arr.dtype)
    out[: arr.shape[0]] = arr
    return out


class _DepsMirror:
    """Host mirror of one store's DepsTable, with dirty-row tracking."""

    def __init__(self, capacity: int = _MIN_CAPACITY,
                 max_intervals: int = _MIN_INTERVALS):
        self.capacity = capacity
        self.max_intervals = max_intervals
        self.msb = np.zeros(capacity, np.int64)
        self.lsb = np.zeros(capacity, np.int64)
        self.node = np.zeros(capacity, np.int32)
        self.kind = np.zeros(capacity, np.int32)
        self.domain = np.zeros(capacity, np.int8)   # Domain enum value
        self.status = np.full(capacity, dk.SLOT_FREE, np.int32)
        self.lo = np.full((capacity, max_intervals), dk.PAD_LO, np.int64)
        self.hi = np.full((capacity, max_intervals), dk.PAD_HI, np.int64)
        self.slot_of: Dict[TxnId, int] = {}
        self.id_of: Dict[int, TxnId] = {}
        self.free_slots: List[int] = list(range(capacity - 1, -1, -1))
        self._dirty: Set[int] = set()
        self._device: Optional[dk.DepsTable] = None

    # -- slot management ----------------------------------------------------
    def alloc(self, txn_id: TxnId) -> int:
        slot = self.slot_of.get(txn_id)
        if slot is not None:
            return slot
        if not self.free_slots:
            self._grow_capacity()
        slot = self.free_slots.pop()
        self.slot_of[txn_id] = slot
        self.id_of[slot] = txn_id
        self.msb[slot] = to_i64(txn_id.msb)
        self.lsb[slot] = to_i64(txn_id.lsb)
        self.node[slot] = txn_id.node
        self.kind[slot] = int(txn_id.kind())
        self.domain[slot] = int(txn_id.domain())
        self.status[slot] = dk.SLOT_TRANSITIVE
        self.lo[slot] = dk.PAD_LO
        self.hi[slot] = dk.PAD_HI
        self._dirty.add(slot)
        return slot

    def free(self, txn_id: TxnId) -> None:
        slot = self.slot_of.pop(txn_id, None)
        if slot is None:
            return
        self.id_of.pop(slot, None)
        self.status[slot] = dk.SLOT_FREE
        self.lo[slot] = dk.PAD_LO
        self.hi[slot] = dk.PAD_HI
        self.free_slots.append(slot)
        self._dirty.add(slot)

    def _grow_capacity(self) -> None:
        old = self.capacity
        new = old * 2
        self.msb = _grow(self.msb, new, 0)
        self.lsb = _grow(self.lsb, new, 0)
        self.node = _grow(self.node, new, 0)
        self.kind = _grow(self.kind, new, 0)
        self.domain = _grow(self.domain, new, 0)
        self.status = _grow(self.status, new, dk.SLOT_FREE)
        self.lo = _grow(self.lo, new, dk.PAD_LO)
        self.hi = _grow(self.hi, new, dk.PAD_HI)
        self.free_slots.extend(range(new - 1, old - 1, -1))
        self.capacity = new
        self._device = None  # shape changed: full re-upload

    def _grow_intervals(self) -> None:
        new_m = self.max_intervals * 2
        lo = np.full((self.capacity, new_m), dk.PAD_LO, np.int64)
        hi = np.full((self.capacity, new_m), dk.PAD_HI, np.int64)
        lo[:, : self.max_intervals] = self.lo
        hi[:, : self.max_intervals] = self.hi
        self.lo, self.hi = lo, hi
        self.max_intervals = new_m
        self._device = None

    def add_intervals(self, slot: int, tokens: Sequence[int],
                      ranges: Sequence[Range]) -> None:
        """Union new intervals into the slot's footprint (idempotent)."""
        row_lo, row_hi = self.lo[slot], self.hi[slot]
        used = int(np.sum(row_lo <= row_hi))
        new: List[Tuple[int, int]] = []
        for t in tokens:
            new.append((t, t))
        for r in ranges:
            new.append((r.start, r.end - 1))
        for lo_v, hi_v in new:
            present = False
            for m in range(used):
                if row_lo[m] <= lo_v and hi_v <= row_hi[m]:
                    present = True
                    break
            if present:
                continue
            while used >= self.max_intervals:
                self._grow_intervals()
                row_lo, row_hi = self.lo[slot], self.hi[slot]
            row_lo[used] = lo_v
            row_hi[used] = hi_v
            used += 1
            self._dirty.add(slot)

    def set_status(self, slot: int, status: int) -> None:
        if self.status[slot] != status:
            self.status[slot] = status
            self._dirty.add(slot)

    # -- device sync --------------------------------------------------------
    def device_table_sharded(self, mesh) -> dk.DepsTable:
        """Mesh placement: the slot dimension sharded across the mesh.  Any
        dirt triggers a full sharded re-upload (the incremental scatter path
        is single-device; on the virtual CPU mesh correctness is the point,
        and a real multi-chip deployment would shard the scatter too)."""
        if self._device is None or self._dirty:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            from ..parallel.sharded import STORE_AXIS
            s1 = NamedSharding(mesh, P(STORE_AXIS))
            s2 = NamedSharding(mesh, P(STORE_AXIS, None))
            self._device = dk.DepsTable(
                jax.device_put(self.msb, s1), jax.device_put(self.lsb, s1),
                jax.device_put(self.node, s1), jax.device_put(self.kind, s1),
                jax.device_put(self.status, s1), jax.device_put(self.lo, s2),
                jax.device_put(self.hi, s2))
            self._dirty.clear()
        return self._device

    def device_table(self) -> dk.DepsTable:
        if self._device is None:
            self._device = dk.DepsTable(
                jnp.asarray(self.msb), jnp.asarray(self.lsb),
                jnp.asarray(self.node), jnp.asarray(self.kind),
                jnp.asarray(self.status), jnp.asarray(self.lo),
                jnp.asarray(self.hi))
            self._dirty.clear()
        elif self._dirty:
            rows = np.array(sorted(self._dirty), np.int32)
            if len(rows) * 2 >= self.capacity:
                # mostly dirty: a full upload is cheaper than a scatter
                self._device = None
                return self.device_table()
            # pad to a power-of-two bucket (repeating the last row: scatter
            # of identical values is idempotent) so jit caches one
            # compilation per bucket instead of one per dirty-count
            padded = _pow2_at_least(len(rows), 8)
            rows = np.concatenate([rows, np.full(padded - len(rows),
                                                 rows[-1], np.int32)])
            self._device = _scatter_rows(
                self._device, jnp.asarray(rows),
                self.msb[rows], self.lsb[rows], self.node[rows],
                self.kind[rows], self.status[rows],
                self.lo[rows], self.hi[rows])
            self._dirty.clear()
        return self._device


class _DrainMirror:
    """Host mirror of the execution drain graph: adjacency over the store's
    in-flight (stable-but-unapplied) txns and their direct dependencies."""

    def __init__(self, capacity: int = _MIN_CAPACITY):
        self.capacity = capacity
        self.adj = np.zeros((capacity, capacity), bool)
        self.status = np.full(capacity, dk.SLOT_FREE, np.int32)
        self.exec_msb = np.zeros(capacity, np.int64)
        self.exec_lsb = np.zeros(capacity, np.int64)
        self.exec_node = np.zeros(capacity, np.int32)
        self.awaits_all = np.zeros(capacity, bool)
        self.active = np.zeros(capacity, bool)   # rows being driven to execution
        self.slot_of: Dict[TxnId, int] = {}
        self.id_of: Dict[int, TxnId] = {}
        self.free_slots: List[int] = list(range(capacity - 1, -1, -1))

    def alloc(self, txn_id: TxnId) -> int:
        slot = self.slot_of.get(txn_id)
        if slot is not None:
            return slot
        if not self.free_slots:
            self._grow_capacity()
        slot = self.free_slots.pop()
        self.slot_of[txn_id] = slot
        self.id_of[slot] = txn_id
        self.status[slot] = dk.SLOT_TRANSITIVE
        self.exec_msb[slot] = 0
        self.exec_lsb[slot] = 0
        self.exec_node[slot] = 0
        self.awaits_all[slot] = txn_id.kind().awaits_only_deps()
        self.adj[slot, :] = False
        self.adj[:, slot] = False
        self.active[slot] = False
        return slot

    def free(self, slot: int) -> None:
        txn_id = self.id_of.pop(slot, None)
        if txn_id is not None:
            del self.slot_of[txn_id]
        self.status[slot] = dk.SLOT_FREE
        self.adj[slot, :] = False
        self.adj[:, slot] = False
        self.active[slot] = False
        self.free_slots.append(slot)

    def _grow_capacity(self) -> None:
        old = self.capacity
        new = old * 2
        adj = np.zeros((new, new), bool)
        adj[:old, :old] = self.adj
        self.adj = adj
        self.status = _grow(self.status, new, dk.SLOT_FREE)
        self.exec_msb = _grow(self.exec_msb, new, 0)
        self.exec_lsb = _grow(self.exec_lsb, new, 0)
        self.exec_node = _grow(self.exec_node, new, 0)
        self.awaits_all = _grow(self.awaits_all, new, False)
        self.active = _grow(self.active, new, False)
        self.free_slots.extend(range(new - 1, old - 1, -1))
        self.capacity = new

    def set_status(self, slot: int, status: int,
                   execute_at: Optional[Timestamp]) -> None:
        self.status[slot] = status
        if execute_at is not None:
            self.exec_msb[slot] = to_i64(execute_at.msb)
            self.exec_lsb[slot] = to_i64(execute_at.lsb)
            self.exec_node[slot] = execute_at.node

    def state(self) -> Tuple[drk.DrainState, np.ndarray]:
        """Compacted drain state over LIVE slots only (padded to a power-of-
        two bucket so jit caches per bucket): the kernel cost scales with the
        in-flight set, not the high-water capacity.  Returns (state,
        live_slot_index) for mapping frontier rows back to slots."""
        live = np.nonzero(self.status != dk.SLOT_FREE)[0]
        n = _pow2_at_least(len(live), 16)
        adj = np.zeros((n, n), bool)
        adj[: len(live), : len(live)] = self.adj[np.ix_(live, live)]
        status = np.full(n, dk.SLOT_FREE, np.int32)
        status[: len(live)] = self.status[live]
        ts0 = np.zeros(n, np.int64)
        em, el = ts0.copy(), ts0.copy()
        en = np.zeros(n, np.int32)
        aw = np.zeros(n, bool)
        em[: len(live)] = self.exec_msb[live]
        el[: len(live)] = self.exec_lsb[live]
        en[: len(live)] = self.exec_node[live]
        aw[: len(live)] = self.awaits_all[live]
        state = drk.DrainState(jnp.asarray(adj), jnp.asarray(status),
                               jnp.asarray(em), jnp.asarray(el),
                               jnp.asarray(en), jnp.asarray(aw))
        return state, live

    def sweep_free(self) -> None:
        """Release slots that can no longer gate anything: terminal status,
        not being driven, and no waiter edge pointing at them."""
        terminal = (self.status == dk.SLOT_APPLIED) | \
                   (self.status == dk.SLOT_INVALIDATED)
        referenced = self.adj.any(axis=0)
        for slot in np.nonzero(terminal & ~self.active & ~referenced)[0]:
            if self.id_of.get(int(slot)) is not None:
                self.free(int(slot))


class DeviceState:
    """Per-CommandStore device wiring: the deps index + drain graph, kept in
    sync by the Commands transition functions."""

    def __init__(self, store):
        self.store = store
        self.deps = _DepsMirror()
        self.drain = _DrainMirror()
        self._tick_scheduled = False
        # mesh mode: with >1 jax device (the virtual 8-device CPU test mesh,
        # or a real multi-chip slice), the deps table's slot dimension is
        # sharded across the mesh and every scan runs as a shard_map with
        # per-shard CSR compaction (ref: the CommandStores scatter-gather,
        # CommandStores.java:575-643; cross-shard Deps.merge, Deps.java:256)
        self.mesh = None
        import jax as _jax
        n_dev = len(_jax.devices())
        if n_dev > 1:
            d = 1
            while d * 2 <= n_dev:
                d *= 2
            from ..parallel.sharded import make_mesh
            self.mesh = make_mesh(d)
        # learned compaction width for batched queries (sticky across
        # batches; see deps_query_batch)
        self._batch_k = 64
        # learned flat-compaction capacity (coarse pairs per batch)
        self._batch_flat = 4096
        # counters surfaced through sim stats / bench
        self.n_queries = 0
        self.n_ticks = 0
        self.n_kernel_deps = 0
        self.n_mesh_queries = 0

    # ------------------------------------------------------------------
    # registration hooks (called from local.commands transitions)
    # ------------------------------------------------------------------
    def register(self, txn_id: TxnId, status: int, keys) -> None:
        """Witness/advance a txn in the deps index.  ``keys`` is the txn's
        sliced participation (Keys or Ranges) — its conflict footprint."""
        slot = self.deps.alloc(txn_id)
        if keys is not None:
            if isinstance(keys, Ranges):
                self.deps.add_intervals(slot, (), list(keys))
            else:
                self.deps.add_intervals(slot, [k.token() for k in keys], ())
        self._advance_status(txn_id, slot, status, None)

    def update_status(self, txn_id: TxnId, status: int,
                      execute_at: Optional[Timestamp] = None) -> None:
        slot = self.deps.slot_of.get(txn_id)
        if slot is None:
            slot = self.deps.alloc(txn_id)
        self._advance_status(txn_id, slot, status, execute_at)

    def _advance_status(self, txn_id: TxnId, slot: int, status: int,
                        execute_at: Optional[Timestamp]) -> None:
        cur = int(self.deps.status[slot])
        if status == dk.SLOT_INVALIDATED:
            new = dk.SLOT_INVALIDATED
        else:
            new = max(cur, status)
        self.deps.set_status(slot, new)
        dslot = self.drain.slot_of.get(txn_id)
        if dslot is not None:
            self.drain.set_status(dslot, new, execute_at)
        # a dependency becoming decided (executeAt known) or terminal can
        # unblock waiters: re-evaluate the frontier
        if new >= dk.SLOT_COMMITTED and self.drain.active.any():
            self.schedule_tick()

    def free(self, txn_id: TxnId) -> None:
        """Truncation/erasure: drop the txn from the deps index (its effect
        is covered by the RedundantBefore watermark from now on)."""
        self.deps.free(txn_id)

    def index_size(self) -> int:
        return len(self.deps.slot_of)

    # ------------------------------------------------------------------
    # the deps query (device replacement of map_reduce_active fold)
    # ------------------------------------------------------------------
    def deps_query(self, safe, txn_id: TxnId, keys, started_before: Timestamp,
                   witnesses: Kinds, builder) -> None:
        """Run the PreAccept/Accept/Recover dependency scan on device and
        fold the result into ``builder`` with the same per-key semantics as
        the host CommandsForKey path (full ownership history, matching
        SafeCommandStore.map_reduce_active — a dual-quorum scan at a
        dropped prior-epoch owner must still see its old-range witnesses).

        This is the batch path with B=1: the per-message and batched code
        are ONE path (same kernel dispatch, same floors/elision/attribution)
        so the benched path is exactly the path the protocol runs."""
        query = self.build_query(safe, txn_id, keys, started_before,
                                 witnesses)
        if query is None:
            return
        handle = self.deps_query_batch_begin([query], immediate=True)
        self.deps_query_batch_end_attributed(safe, handle, [builder])

    def build_query(self, safe, txn_id: TxnId, keys,
                    started_before: Timestamp, witnesses: Kinds):
        """Slice a scan's keys to the store's full ownership history and
        package them as one batch-query tuple (None if nothing owned)."""
        owned = safe.store.ranges_for_epoch.all()
        if isinstance(keys, Ranges):
            q_toks: List[int] = []
            q_rngs = list(keys.slice(owned))
        else:
            q_toks = [k.token() for k in keys
                      if owned.contains_token(k.token())]
            q_rngs = []
        if not q_toks and not q_rngs:
            return None
        return (txn_id, started_before, witnesses, q_toks, q_rngs)

    def _resolve_id(self, j: int, ids) -> TxnId:
        """Slot -> TxnId via the live reverse map when it still matches the
        batch snapshot (no object allocation on the hot path); fall back to
        unpacking from the snapshot columns when the slot was recycled
        between begin and end."""
        msb, lsb, node = ids
        cand = self.deps.id_of.get(j)
        if cand is not None and to_i64(cand.msb) == msb[j] \
                and to_i64(cand.lsb) == lsb[j] and cand.node == node[j]:
            return cand
        return unpack_txn_id(msb[j], lsb[j], node[j])

    def _attribute_batch(self, safe, b_idx, j_idx, overlap, ids, ivs, qnp,
                         queries, builders) -> None:
        """Fold a whole batch's kernel answer into the builders with the
        floors, elision and key/range attribution of the host path: the
        kernel answers "who", the mirror snapshot answers "where",
        RedundantBefore floors and the CFK elision rule decide "whether".

        The geometry runs ONCE, vectorized over all (pair, dep-interval,
        query-interval) triples — no per-query Python overhead.  The
        unification that makes this possible: a key-domain dep's footprint
        is a point, so its emitted key is its own token whether the query
        interval was a key or a range; a range-domain dep emits the
        dep∩query interval clip, which for a point query degenerates to the
        width-1 range.  Python touches only the deduplicated surviving
        emits."""
        if len(j_idx) == 0:
            return
        lo, hi, dom = ivs
        rb = safe.redundant_before()
        _MISSING = object()
        floors: Dict[int, TxnId] = {}
        cfks: Dict[int, object] = {}
        id_cache: Dict[int, TxnId] = {}

        def resolve(j: int) -> TxnId:
            d = id_cache.get(j)
            if d is None:
                d = id_cache[j] = self._resolve_id(j, ids)
            return d

        def floor_of(t: int) -> TxnId:
            f = floors.get(t)
            if f is None:
                f = floors[t] = rb.deps_floor(t)
            return f

        def elide_ctx(t: int, bound):
            """(cfk, pivot) when elision is possible on this key for this
            bound, else None — ONE lookup per (token, bound) instead of one
            per (dep, token) pair (the common key has nothing elidable)."""
            key = (t, bound)
            ctx = cfks.get(key, _MISSING)
            if ctx is not _MISSING:
                return ctx
            cfk = self.store.commands_for_key.get(t)
            ctx = None
            if cfk is not None:
                pivot = cfk.can_elide(bound)
                if pivot is not None:
                    ctx = (cfk, pivot)
            cfks[key] = ctx
            return ctx

        q_m = (qnp.shape[1] - 7) // 2
        lo_p = lo[j_idx]                               # [P, M]
        hi_p = hi[j_idx]
        qlo_p = qnp[b_idx, 7:7 + q_m]                  # [P, Q]
        qhi_p = qnp[b_idx, 7 + q_m:7 + 2 * q_m]
        # overlap [P, M, Q] arrives precomputed from the collect pass
        p_i, m_i, q_i = np.nonzero(overlap)
        key_dep = (dom[j_idx] == int(Domain.Key))[p_i]

        # key-domain deps: emitted at the dep's own footprint point,
        # deduped per (pair, token); floors + elision decide survival
        kp, km = p_i[key_dep], m_i[key_dep]
        if len(kp):
            key_pairs = np.unique(
                np.stack([kp, lo_p[kp, km]], axis=1), axis=0)
            pp, tt = key_pairs[:, 0], key_pairs[:, 1]
            jj, bb = j_idx[pp], b_idx[pp]
            # vectorized RedundantBefore floor: dep >= floor(token),
            # lexicographic over the packed (msb, lsb, node) triples (the
            # same int64 ordering the kernel's ts_lt assumes)
            msb_a, lsb_a, node_a = ids
            uniq_t, inv = np.unique(tt, return_inverse=True)
            f_objs = [floor_of(int(t)) for t in uniq_t]
            fmsb = np.array([to_i64(f.msb) for f in f_objs], np.int64)[inv]
            flsb = np.array([to_i64(f.lsb) for f in f_objs], np.int64)[inv]
            fnode = np.array([f.node for f in f_objs], np.int64)[inv]
            dmsb, dlsb, dnode = msb_a[jj], lsb_a[jj], node_a[jj]
            keep = ((dmsb > fmsb)
                    | ((dmsb == fmsb)
                       & ((dlsb > flsb)
                          | ((dlsb == flsb) & (dnode >= fnode)))))
            # object resolution via one unique pass + C-level take
            jj_k = jj[keep]
            uq_j, inv_j = np.unique(jj_k, return_inverse=True)
            objs = np.empty(len(uq_j), object)
            for i, j in enumerate(uq_j.tolist()):
                objs[i] = resolve(j)
            deps_k = objs[inv_j]
            # keys with ANYTHING elidable get the per-dep check; the common
            # key skips it entirely (one can_elide per token+bound)
            for b, t, dep_id in zip(bb[keep].tolist(), tt[keep].tolist(),
                                    deps_k):
                ctx = elide_ctx(t, queries[b][1])
                if ctx is not None:
                    info = ctx[0].get(dep_id)
                    if info is not None and \
                            ctx[0].is_elided(info, queries[b][1], ctx[1]):
                        continue
                builders[b].add_key(t, dep_id)

        # range-domain deps: emit the dep∩query interval clip per pair
        rp, rm, rq = p_i[~key_dep], m_i[~key_dep], q_i[~key_dep]
        if len(rp):
            ilo = np.maximum(lo_p[rp, rm], qlo_p[rp, rq])
            ihi = np.minimum(hi_p[rp, rm], qhi_p[rp, rq]) + 1
            range_pairs = np.unique(
                np.stack([rp, ilo, ihi], axis=1), axis=0)
            rpp = range_pairs[:, 0]
            uq_j, inv_j = np.unique(j_idx[rpp], return_inverse=True)
            objs = np.empty(len(uq_j), object)
            for i, j in enumerate(uq_j.tolist()):
                objs[i] = resolve(j)
            deps_r = objs[inv_j]
            bb_r = b_idx[rpp].tolist()
            for b, lo_v, hi_v, dep_id in zip(
                    bb_r, range_pairs[:, 1].tolist(),
                    range_pairs[:, 2].tolist(), deps_r):
                builders[b].add_range(Range(lo_v, hi_v), dep_id)

    def deps_query_batch(self, queries):
        """Batched deps scan: ONE kernel call for B concurrent queries (the
        server-side batching a pipelined deployment uses).

        ``queries`` = [(txn_id, started_before, witnesses, tokens, ranges)].
        Returns the dep sets in the device-native packed-CSR layout —
        ``(row_ptr int64[B+1], msb int64[D], lsb int64[D], node int32[D])``
        — the same encoding KeyDeps/RangeDeps use (ref: KeyDeps.java:150-156
        CSR layout); consumers materialise TxnId objects lazily."""
        if not queries:
            return (np.zeros(1, np.int64), np.zeros(0, np.int64),
                    np.zeros(0, np.int64), np.zeros(0, np.int32))
        return self.deps_query_batch_end(self.deps_query_batch_begin(queries))

    def deps_query_batch_attributed(self, safe, queries, builders):
        """The correctness-complete batched scan: one kernel dispatch for B
        queries, then the full host-path semantics (floors, elision,
        key/range attribution) folded into each query's builder.  This is
        the exact code deps_query runs (B=1) — and what the bench times."""
        if not queries:
            return
        handle = self.deps_query_batch_begin(queries)
        self.deps_query_batch_end_attributed(safe, handle, builders)

    def deps_query_batch_begin(self, queries, immediate: bool = False):
        """Dispatch a batched deps scan WITHOUT waiting: one fused query
        upload + kernel enqueue; returns an opaque handle for
        deps_query_batch_end.  Callers overlap the next batch's dispatch
        with the previous batch's result download (double-buffering) — on a
        tunneled accelerator the round trips dominate the kernel by ~1000x,
        so the pipeline nearly doubles sustained throughput."""
        q_m = _pow2_at_least(max(len(t[3]) + len(t[4]) for t in queries))
        packed = [(sb, wit, toks, rngs, tid)
                  for (tid, sb, wit, toks, rngs) in queries]
        if self.mesh is not None:
            table = self.deps.device_table_sharded(self.mesh)
        else:
            table = self.deps.device_table()
        n = table.capacity
        qnp = dk.pack_query_matrix(packed, q_m)
        qmat = jnp.asarray(qnp)                               # ONE upload
        # adaptive + STICKY flat-compaction capacity: the coarse pair list
        # is sparse, so the download stays ~100KB; an overflow escalates
        # (the true count rides in the same download, so detection is free)
        # and the learned capacity persists so steady state stays one
        # round trip
        if self.mesh is not None:
            d = int(np.prod(list(self.mesh.shape.values())))
        else:
            d = 1
        # caps are PER SHARD: each shard block holds at most nq * (n/d)
        # entries, and its widest row at most n/d
        s = min(self._batch_flat, len(queries) * (n // d))
        k = min(self._batch_k, n // d)
        if self.mesh is not None:
            from ..parallel.sharded import sharded_calculate_deps_flat
            out_dev = sharded_calculate_deps_flat(
                self.mesh, q_m, s, k)(table, qmat)
            self.n_mesh_queries += len(queries)
        else:
            out_dev = dk.calculate_deps_flat(table, qmat, q_m, s, k)
        box: Dict[str, object] = {"dev": out_dev}
        if immediate:
            # synchronous caller (deps_query, B=1): collect follows on the
            # next line with no interleaved mutation, so skip the snapshot
            # copies and the prefetch thread — the live mirror IS the
            # snapshot
            th = None
            ids = (self.deps.msb, self.deps.lsb, self.deps.node)
            ivs = (self.deps.lo, self.deps.hi, self.deps.domain)
            return (box, th, table, ids, ivs, qnp, qmat, packed, q_m, s, k,
                    n, d, list(queries))
        # prefetch the result on a worker thread: np.asarray blocks on the
        # (tunneled) transfer with the GIL released, so a pipelined caller
        # attributes batch i while batch i+1 computes AND downloads

        def _fetch():
            try:
                box["out"] = np.asarray(out_dev)
            except BaseException as e:     # surfaced after join
                box["err"] = e

        import threading
        th = threading.Thread(target=_fetch, daemon=True)
        th.start()
        # snapshot the mirror's id + interval columns: the mirror mutates in
        # place, and a slot freed+reallocated between begin and end would
        # otherwise resolve this batch's indices to the WRONG TxnId (or
        # footprint)
        ids = (self.deps.msb.copy(), self.deps.lsb.copy(),
               self.deps.node.copy())
        ivs = (self.deps.lo.copy(), self.deps.hi.copy(),
               self.deps.domain.copy())
        return (box, th, table, ids, ivs, qnp, qmat, packed, q_m, s, k, n,
                d, list(queries))

    def _batch_collect(self, handle):
        """Collect a dispatched batch: ONE sparse download (plus a re-run
        when the learned flat capacity overflowed), then the host-side
        EXACT geometry pass over the coarse pairs — the kernel's bounding-
        box mask admits a query sitting inside a slot's interval gap; the
        vectorized overlap here drops those and hands the surviving
        (pair, dep-interval, query-interval) triples to attribution.  The
        re-run uses the table snapshot captured at begin — registrations
        interleaved between begin and end must not shift the queried
        snapshot."""
        (box, th, table, ids, ivs, qnp, qmat, packed, q_m, s, k, n,
         d, queries) = handle
        nq = len(queries)
        shard_n = n // d

        def parse(out, s, k):
            """Per-shard blocks (total, maxc, row_end[B], entries[s]) with
            shard-local slot indices; shard 0 alone when unsharded."""
            blocks = out.reshape(d, 2 + nq + s)
            if int(blocks[:, 0].max()) > s or int(blocks[:, 1].max()) > k:
                return None
            bs, js = [], []
            for i in range(d):
                total = int(blocks[i, 0])
                row_end = blocks[i, 2:2 + nq].astype(np.int64)
                counts = np.diff(row_end, prepend=0)
                bs.append(np.repeat(np.arange(nq), counts))
                js.append(blocks[i, 2 + nq:2 + nq + total].astype(np.int64)
                          + i * shard_n)
            return np.concatenate(bs), np.concatenate(js)

        if th is not None:
            th.join()
            err = box.get("err")
            if err is not None:
                raise err           # the real device/transfer failure
            out = box["out"]
        else:
            out = np.asarray(box["dev"])
        parsed = parse(out, s, k)
        if parsed is None:
            # size the flat capacity to the observed total (+25% headroom,
            # 16k granularity) — pow2 rounding doubled the download
            blocks = out.reshape(d, 2 + nq + s)
            total = int(blocks[:, 0].max())
            s = min(-(-int(total * 1.25) // 16384) * 16384, nq * shard_n)
            k = min(_pow2_at_least(int(blocks[:, 1].max())), shard_n)
            self._batch_flat = max(self._batch_flat, s)
            self._batch_k = max(self._batch_k, k)
            if d > 1:
                from ..parallel.sharded import sharded_calculate_deps_flat
                out = np.asarray(sharded_calculate_deps_flat(
                    self.mesh, q_m, s, k)(table, qmat))
            else:
                out = np.asarray(dk.calculate_deps_flat(table, qmat, q_m,
                                                        s, k))
            parsed = parse(out, s, k)
        b_idx, j_idx = parsed
        # exact geometry on the sparse pair list
        lo, hi, _dom = ivs
        lo_p, hi_p = lo[j_idx], hi[j_idx]                       # [P, M]
        used = lo_p <= hi_p
        qlo_p = qnp[b_idx, 7:7 + q_m]                           # [P, Q]
        qhi_p = qnp[b_idx, 7 + q_m:7 + 2 * q_m]
        overlap = (used[:, :, None]
                   & (lo_p[:, :, None] <= qhi_p[:, None, :])
                   & (qlo_p[:, None, :] <= hi_p[:, :, None]))   # [P, M, Q]
        keep = overlap.any(axis=(1, 2))
        b_idx, j_idx, overlap = b_idx[keep], j_idx[keep], overlap[keep]
        self.n_queries += len(queries)
        self.n_kernel_deps += len(j_idx)
        return b_idx, j_idx, overlap, ids, ivs, qnp, queries

    def deps_query_batch_end(self, handle):
        """Raw packed-CSR collection (no floors/attribution) — the transport
        layout replicas exchange; deps_query_batch_end_attributed is the
        protocol-complete variant."""
        b_idx, j_idx, _ov, ids, _ivs, _qnp, queries = \
            self._batch_collect(handle)
        order = np.argsort(b_idx, kind="stable")
        b_idx, j_idx = b_idx[order], j_idx[order]
        counts = np.bincount(b_idx, minlength=len(queries))
        row_ptr = np.zeros(len(queries) + 1, np.int64)
        np.cumsum(counts, out=row_ptr[1:])
        msb, lsb, node = ids
        return (row_ptr, msb[j_idx], lsb[j_idx], node[j_idx])

    def deps_query_batch_end_attributed(self, safe, handle, builders) -> None:
        """Collect a dispatched batch and fold each query's deps into its
        builder with full host-path semantics (floors/elision/attribution)."""
        b_idx, j_idx, overlap, ids, ivs, qnp, queries = \
            self._batch_collect(handle)
        self._attribute_batch(safe, b_idx, j_idx, overlap, ids, ivs, qnp,
                              queries, builders)

    # ------------------------------------------------------------------
    # the drain (device replacement of listener fan-out)
    # ------------------------------------------------------------------
    def arm(self, safe, txn_id: TxnId) -> None:
        """Register a Stable/PreApplied txn's remaining waiting set as a
        drain row; the next tick will re-evaluate it."""
        cmd = safe.if_present(txn_id)
        if cmd is None or cmd.waiting_on is None:
            return
        slot = self.drain.alloc(txn_id)
        self.drain.set_status(slot, dk.SLOT_STABLE, cmd.execute_at)
        self.drain.adj[slot, :] = False
        for dep in cmd.waiting_on.waiting_ids():
            dslot = self._dep_drain_slot(safe, dep)
            self.drain.adj[slot, dslot] = True
        self.drain.active[slot] = True
        self.schedule_tick()

    def _dep_drain_slot(self, safe, dep: TxnId) -> int:
        slot = self.drain.slot_of.get(dep)
        if slot is not None:
            return slot
        slot = self.drain.alloc(dep)
        cmd = safe.if_present(dep)
        status, exec_at = _drain_status_of(cmd)
        self.drain.set_status(slot, status, exec_at)
        return slot

    def on_terminal(self, txn_id: TxnId) -> None:
        """Truncation/erasure: the txn can never gate execution again
        (ref: _dep_clearance treats truncated as done).  Mark its drain row
        terminal and re-evaluate waiters — without this, truncating a dep
        whose record Cleanup then drops is a lost wakeup in device mode
        (no listeners exist to carry the erase notification)."""
        dslot = self.drain.slot_of.get(txn_id)
        if dslot is not None:
            self.drain.set_status(dslot, dk.SLOT_INVALIDATED, None)
            if self.drain.active.any():
                self.schedule_tick()

    def on_driven(self, txn_id: TxnId) -> None:
        """The txn reached ReadyToExecute/Applying — stop driving it (its
        slot lives on as a dependency of others until terminal + unreferenced)."""
        slot = self.drain.slot_of.get(txn_id)
        if slot is not None:
            self.drain.active[slot] = False
            self.drain.adj[slot, :] = False

    # Coalescing quantum for drain ticks (simulated/real micros): many dep
    # transitions land per tick, so the per-tick adjacency upload + kernel
    # sweep amortizes across a whole antichain instead of firing per event.
    TICK_DELAY_MICROS = 2_000

    def schedule_tick(self) -> None:
        if self._tick_scheduled:
            return
        self._tick_scheduled = True
        from .command_store import PreLoadContext

        def run():
            self.store.execute(PreLoadContext.empty(), self._tick)

        self.store.node.scheduler.once(self.TICK_DELAY_MICROS, run)

    def _tick(self, safe) -> None:
        from . import commands
        self._tick_scheduled = False
        self.n_ticks += 1
        sweep_due = self.n_ticks % 8 == 0
        if not self.drain.active.any():
            if sweep_due:
                self.drain.sweep_free()
            return
        state, live = self.drain.state()
        ready = np.asarray(drk.ready_frontier(state))[: len(live)]
        cand_slots = live[ready & self.drain.active[live]]
        if len(cand_slots) != 0:
            cands = sorted(
                (self.drain.id_of[int(s)] for s in cand_slots
                 if int(s) in self.drain.id_of),
                key=_exec_order_key(safe))
            for txn_id in cands:
                commands.refresh_waiting_and_maybe_execute(safe, txn_id)
        if sweep_due:
            self.drain.sweep_free()


def _exec_order_key(safe):
    def key(txn_id: TxnId):
        cmd = safe.if_present(txn_id)
        exec_at = cmd.execute_at if cmd is not None and cmd.execute_at \
            is not None else txn_id
        return (exec_at, txn_id)
    return key


def _drain_status_of(cmd) -> Tuple[int, Optional[Timestamp]]:
    from .status import Status
    if cmd is None:
        return dk.SLOT_TRANSITIVE, None
    if cmd.is_invalidated():
        return dk.SLOT_INVALIDATED, None
    if cmd.is_truncated():
        # truncated == locally done; never gates execution
        return dk.SLOT_INVALIDATED, None
    exec_at = cmd.execute_at_if_known()
    if cmd.has_been(Status.Applied):
        return dk.SLOT_APPLIED, exec_at
    if cmd.has_been(Status.Stable):
        return dk.SLOT_STABLE, exec_at
    if cmd.has_been(Status.Committed):
        return dk.SLOT_COMMITTED, exec_at
    if cmd.has_been(Status.Accepted):
        return dk.SLOT_ACCEPTED, exec_at
    return dk.SLOT_PREACCEPTED, None
