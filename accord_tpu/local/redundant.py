"""Watermark maps: MaxConflicts, RedundantBefore, DurableBefore.

Rebuild of ref: accord-core/src/main/java/accord/local/MaxConflicts.java:32,
RedundantBefore.java:49, DurableBefore.java:39.  All three are range-keyed
step functions (ReducingRangeMap) — sorted boundary arrays, which is also
their device format for the deps floor in the PreAccept kernel.
"""

from __future__ import annotations

import enum
from typing import Optional

from ..primitives.keys import Range, Ranges, RoutingKeys, Unseekables
from ..primitives.timestamp import Timestamp, TxnId, max_timestamp
from ..utils.interval_map import ReducingRangeMap


class MaxConflicts:
    """range -> max Timestamp witnessed; consulted to propose executeAt
    (ref: local/MaxConflicts.java)."""

    __slots__ = ("_map",)

    def __init__(self):
        self._map: ReducingRangeMap = ReducingRangeMap.empty()

    def get_max(self, keys_or_ranges) -> Timestamp:
        ranges = _as_ranges(keys_or_ranges)
        out = self._map.fold_over_ranges(ranges, lambda v, acc: max_timestamp(acc, v), None)
        return out if out is not None else Timestamp.NONE

    def update(self, keys_or_ranges, ts: Timestamp) -> None:
        ranges = _as_ranges(keys_or_ranges)
        self._map = self._map.add(ranges, ts, lambda a, b: a if a >= b else b)


class RedundantStatus(enum.IntEnum):
    """(ref: local/RedundantStatus.java)."""
    NOT_OWNED = 0
    LIVE = 1
    PARTIALLY_PRE_BOOTSTRAP_OR_STALE = 2
    PRE_BOOTSTRAP_OR_STALE = 3
    PARTIALLY_SHARD_REDUNDANT = 4
    SHARD_REDUNDANT = 5


class RedundantEntry:
    """(ref: RedundantBefore.Entry).  ``redundant_before`` is the SHARD
    watermark (shardAppliedOrInvalidatedBefore: applied at every healthy
    replica — set by SetShardDurable).  The reference's separate
    locallyAppliedOrInvalidatedBefore watermark is a deliberate omission
    until a consumer (finer local Cleanup) exists."""

    __slots__ = ("redundant_before", "bootstrapped_at", "stale_until_at_least")

    def __init__(self, redundant_before: TxnId = TxnId.NONE,
                 bootstrapped_at: TxnId = TxnId.NONE,
                 stale_until_at_least: Optional[Timestamp] = None):
        self.redundant_before = redundant_before
        self.bootstrapped_at = bootstrapped_at
        self.stale_until_at_least = stale_until_at_least

    def merge(self, other: "RedundantEntry") -> "RedundantEntry":
        stale = self.stale_until_at_least
        if other.stale_until_at_least is not None:
            stale = max_timestamp(stale, other.stale_until_at_least)
        boot = max(self.bootstrapped_at, other.bootstrapped_at)
        # a bootstrap fence at/above the stale bound re-covers the data:
        # staleness clears once the re-bootstrap begins (reads still defer
        # behind the bootstrap gate until the snapshot lands — ref:
        # CommandStore.java markShardStale + safeToRead)
        if stale is not None and boot >= stale:
            stale = None
        return RedundantEntry(
            max(self.redundant_before, other.redundant_before),
            boot, stale)

    def status_of(self, txn_id: TxnId) -> RedundantStatus:
        if self.stale_until_at_least is not None or txn_id < self.bootstrapped_at:
            return RedundantStatus.PRE_BOOTSTRAP_OR_STALE
        if txn_id < self.redundant_before:
            return RedundantStatus.SHARD_REDUNDANT
        return RedundantStatus.LIVE

    def __eq__(self, o):
        return (isinstance(o, RedundantEntry)
                and self.redundant_before == o.redundant_before
                and self.bootstrapped_at == o.bootstrapped_at
                and self.stale_until_at_least == o.stale_until_at_least)


class RedundantBefore:
    """Range-keyed redundancy watermarks (ref: local/RedundantBefore.java:49).

    ``version`` increments on every watermark mutation: the deps-scan router
    (local/device_index.py) keys its incremental live-above-floor estimate on
    it, so detecting "the floor moved" is O(1) per dispatch instead of a
    re-derivation of the floor map."""

    __slots__ = ("_map", "version", "_packed_floors")

    def __init__(self):
        self._map: ReducingRangeMap = ReducingRangeMap.empty()
        self.version = 0
        self._packed_floors = None   # (version, (bnd, msb, lsb, node))

    def add_redundant(self, ranges: Ranges, redundant_before: TxnId) -> None:
        """Advance the SHARD-applied watermark (ref: markShardDurable)."""
        self._merge(ranges, RedundantEntry(redundant_before=redundant_before))


    def add_bootstrapped(self, ranges: Ranges, bootstrapped_at: TxnId) -> None:
        self._merge(ranges, RedundantEntry(bootstrapped_at=bootstrapped_at))

    def add_stale(self, ranges: Ranges, stale_until: Timestamp) -> None:
        self._merge(ranges, RedundantEntry(stale_until_at_least=stale_until))

    def _merge(self, ranges: Ranges, entry: RedundantEntry) -> None:
        self._map = self._map.add(ranges, entry, lambda a, b: a.merge(b))
        self.version += 1

    def shard_redundant_ranges(self, txn_id: TxnId,
                               within: Ranges) -> Ranges:
        """The subranges of ``within`` where ``txn_id`` is PROVEN
        SHARD_REDUNDANT (an ExclusiveSyncPoint at or above it applied at
        every replica).  This — not raw ownership — is what a truncation
        claim may advertise as its covering: watermark gaps and
        majority-only segments prove nothing."""
        from ..primitives.keys import Range
        out = []

        def fold(entry, start, end, acc):
            # test redundant_before DIRECTLY: status_of masks it behind
            # pre-bootstrap/stale, but those describe THIS store's data
            # health — the shard-redundancy proof (ESP applied at every
            # replica) holds regardless, and hiding it would silently
            # shrink advertised truncation coverings (a straggler could
            # then never purge)
            if txn_id < entry.redundant_before:
                out.append(Range(start, end))
            return acc

        self._map.fold_with_bounds(fold, None)
        return Ranges.of(*out).intersecting(within)

    def status(self, txn_id: TxnId, participants) -> RedundantStatus:
        ranges = _as_ranges(participants)
        statuses = [e.status_of(txn_id) for e in self._map.values_intersecting(ranges)]
        if not statuses:
            return RedundantStatus.LIVE
        if all(s is RedundantStatus.PRE_BOOTSTRAP_OR_STALE for s in statuses):
            return RedundantStatus.PRE_BOOTSTRAP_OR_STALE
        if any(s is RedundantStatus.PRE_BOOTSTRAP_OR_STALE for s in statuses):
            return RedundantStatus.PARTIALLY_PRE_BOOTSTRAP_OR_STALE
        if all(s is RedundantStatus.SHARD_REDUNDANT for s in statuses):
            return RedundantStatus.SHARD_REDUNDANT
        if any(s is RedundantStatus.SHARD_REDUNDANT for s in statuses):
            return RedundantStatus.PARTIALLY_SHARD_REDUNDANT
        return RedundantStatus.LIVE

    def is_redundant(self, txn_id: TxnId, participants) -> bool:
        return self.status(txn_id, participants) in (
            RedundantStatus.SHARD_REDUNDANT, RedundantStatus.PRE_BOOTSTRAP_OR_STALE)

    def min_redundant_before(self, token: int) -> TxnId:
        e = self._map.get(token)
        return e.redundant_before if e is not None else TxnId.NONE

    def deps_floor(self, token: int) -> TxnId:
        """The floor below which deps need not be collected for this key
        (ref: RedundantBefore.collectDeps usage in PreAccept.java:245-264)."""
        e = self._map.get(token)
        if e is None:
            return TxnId.NONE
        return max(e.redundant_before, e.bootstrapped_at)

    def _segment_ranges(self, pred, within: Ranges) -> Ranges:
        """Subranges of ``within`` whose map segment satisfies ``pred``
        (entry may be None for never-touched segments)."""
        b = self._map.boundaries
        vals = self._map.values
        out = []
        lo_bound = -(1 << 62)
        hi_bound = 1 << 62
        for i, v in enumerate(vals):
            if not pred(v):
                continue
            seg_lo = b[i - 1] if i > 0 else lo_bound
            seg_hi = b[i] if i < len(b) else hi_bound
            out.append(Range(seg_lo, seg_hi))
        if not out:
            return Ranges.empty()
        return Ranges.of(*out).intersecting(within)

    def stale_ranges(self, within: Ranges) -> Ranges:
        """Subranges of ``within`` currently marked stale (reads refuse,
        execution skips) — ref: CommandStore.java safeToRead complement."""
        return self._segment_ranges(
            lambda v: v is not None and v.stale_until_at_least is not None,
            within)

    def live_expect_ranges(self, txn_id: TxnId, within: Ranges) -> Ranges:
        """Subranges of ``within`` where ``txn_id`` is still LIVE — owned,
        not pre-bootstrap, not stale, not shard-redundant: the ranges this
        replica still expects to execute the txn over (ref:
        RedundantBefore.everExpectToExecute / expectToExecute)."""
        return self._segment_ranges(
            lambda v: v is None
            or v.status_of(txn_id) is RedundantStatus.LIVE, within)

    def min_floor_over(self, lo: int, hi: int) -> TxnId:
        """Conservative batch-global deps floor: the MIN deps_floor over
        every map segment overlapping [lo, hi] (TxnId.NONE as soon as any
        overlapped segment has no floor).  Safe to apply ON DEVICE before
        the exact per-token host floors: it never exceeds any token's
        floor inside the window."""
        import bisect
        b = self._map.boundaries
        i0 = bisect.bisect_right(b, lo)
        i1 = bisect.bisect_right(b, hi)
        out = None
        for v in self._map.values[i0:i1 + 1]:
            f = TxnId.NONE if v is None else max(v.redundant_before,
                                                 v.bootstrapped_at)
            if out is None or f < out:
                out = f
            if out == TxnId.NONE:
                break
        return out if out is not None else TxnId.NONE

    def deps_floor_batch(self, tokens):
        """Vectorized deps_floor over a token column: packed (msb, lsb,
        node) int64 arrays aligned with ``tokens``.  One floor is computed
        per distinct map segment (the map has a handful of segments; the
        batch has thousands of tokens)."""
        import numpy as np
        bnd, fm, fl, fn = self.packed_floor_index()
        idx = np.searchsorted(bnd, tokens, side="right")
        return fm[idx], fl[idx], fn[idx]

    def packed_floor_index(self):
        """The whole floor map as four numpy columns: segment boundaries
        (int64[F]) plus the per-segment deps_floor triples (int64[F+1] x 2,
        int32[F+1]; ``searchsorted(bnd, token, side="right")`` selects the
        segment — exactly deps_floor_batch's rule).  This is the host
        source of the DEVICE floor index (ops.deps_kernel.AttrIndex): the
        attributed kernels apply the exact per-token floor in-kernel, so
        the packed form is cached on ``version`` and shared by every flush
        until a watermark moves."""
        import numpy as np

        from ..ops.packing import to_i64
        hit = self._packed_floors
        if hit is not None and hit[0] == self.version:
            return hit[1]
        m = self._map
        bnd = np.asarray(m.boundaries, np.int64)
        fm = np.empty(len(m.values), np.int64)
        fl = np.empty(len(m.values), np.int64)
        fn = np.empty(len(m.values), np.int32)
        for i, v in enumerate(m.values):
            f = TxnId.NONE if v is None else max(v.redundant_before,
                                                 v.bootstrapped_at)
            fm[i], fl[i], fn[i] = to_i64(f.msb), to_i64(f.lsb), f.node
        packed = (bnd, fm, fl, fn)
        self._packed_floors = (self.version, packed)
        return packed

    def boundary_dep(self, token: int) -> Optional[TxnId]:
        """The bootstrap-fence TxnId flooring this key's deps, if any.  A
        PreAccept reply that pruned entries below the floor must include the
        floor itself as a dependency (ref: RedundantBefore.collectDeps):
        the fence is a real coordinated ExclusiveSyncPoint whose own deps
        transitively cover everything pruned, so coordinators merging this
        reply still order after the pruned history."""
        e = self._map.get(token)
        if e is None or not (e.bootstrapped_at > TxnId.NONE):
            return None
        return e.bootstrapped_at

    def boundary_deps_in(self, ranges: Ranges):
        """(range, fence TxnId) pairs intersecting ``ranges`` — the range
        analogue of boundary_dep."""
        def fold(entry, start, end, acc):
            if entry.bootstrapped_at > TxnId.NONE:
                r = Range(start, end)
                for sel in ranges:
                    x = r.intersection(sel)
                    if x is not None:
                        acc.append((x, entry.bootstrapped_at))
            return acc
        return self._map.fold_with_bounds(fold, [])

    def redundant_entries(self):
        """(start, end, redundant_before) segments with a non-trivial shard
        watermark — the journal's persisted form (bootstrapped_at is
        journaled separately at its Bootstrap call sites)."""
        def fold(e, start, end, acc):
            if e.redundant_before > TxnId.NONE:
                acc.append((start, end, e.redundant_before))
            return acc
        return self._map.fold_with_bounds(fold, [])

    def locally_settled(self, txn_id: TxnId, participants,
                        execute_at: Optional[Timestamp] = None) -> bool:
        """Per-entry clearance: True when EVERY watermark entry intersecting
        ``participants`` classifies txn_id as done here — shard-redundant
        (applied at every replica) or pre-bootstrap (the snapshot covers it,
        provided no known executeAt lands past that entry's fence).  The
        aggregate status() collapses mixed coverage into PARTIALLY_* and
        loses exactly this case: a dep redundant on one sub-range and
        pre-bootstrap on the rest is settled on both, yet neither aggregate
        branch fires (ref: RedundantBefore folds per Entry; the WaitingOn
        clearance consumes the per-range answer)."""
        ranges = _as_ranges(participants)
        entries = self._map.values_intersecting(ranges)
        if not entries:
            return False
        for e in entries:
            s = e.status_of(txn_id)
            if s is RedundantStatus.SHARD_REDUNDANT:
                continue
            if s is RedundantStatus.PRE_BOOTSTRAP_OR_STALE:
                if execute_at is None or e.stale_until_at_least is not None \
                        or execute_at < e.bootstrapped_at:
                    continue
            return False
        return True

    def bootstrap_covers(self, execute_at: Timestamp, participants) -> bool:
        """Whether a dep KNOWN to execute at ``execute_at`` is fully covered
        by the bootstrap snapshot over ``participants``.  Callers must not
        pass a guessed executeAt: an undecided dep can still slow-path past
        the fence."""
        ranges = _as_ranges(participants)
        entries = self._map.values_intersecting(ranges)
        if not entries:
            return False
        return all(execute_at < e.bootstrapped_at or
                   e.stale_until_at_least is not None for e in entries)


class DurableBefore:
    """Global durability watermarks per range: {majority, universal}
    (ref: local/DurableBefore.java:39)."""

    __slots__ = ("_map",)

    class Entry:
        __slots__ = ("majority_before", "universal_before")

        def __init__(self, majority_before: TxnId = TxnId.NONE,
                     universal_before: TxnId = TxnId.NONE):
            self.majority_before = majority_before
            self.universal_before = universal_before

        def merge(self, other: "DurableBefore.Entry") -> "DurableBefore.Entry":
            return DurableBefore.Entry(
                max(self.majority_before, other.majority_before),
                max(self.universal_before, other.universal_before))

        def __eq__(self, o):
            return (isinstance(o, DurableBefore.Entry)
                    and self.majority_before == o.majority_before
                    and self.universal_before == o.universal_before)

    def __init__(self):
        self._map: ReducingRangeMap = ReducingRangeMap.empty()

    def add_majority(self, ranges: Ranges, before: TxnId) -> None:
        self._map = self._map.add(ranges, DurableBefore.Entry(majority_before=before),
                                  lambda a, b: a.merge(b))

    def add_universal(self, ranges: Ranges, before: TxnId) -> None:
        self._map = self._map.add(ranges, DurableBefore.Entry(universal_before=before),
                                  lambda a, b: a.merge(b))

    def is_majority_durable(self, txn_id: TxnId, token: int) -> bool:
        e = self._map.get(token)
        return e is not None and txn_id < e.majority_before

    def is_universally_durable(self, txn_id: TxnId, token: int) -> bool:
        e = self._map.get(token)
        return e is not None and txn_id < e.universal_before

    def min_majority_before(self, ranges: Ranges) -> TxnId:
        """Gap-aware min: an uncovered sub-range counts as NONE."""
        return self._map.fold_over_ranges_with_gaps(
            ranges,
            lambda e, acc: min(acc, e.majority_before if e is not None
                               else TxnId.NONE),
            TxnId.MAX)

    def min_universal_before(self, ranges: Ranges) -> TxnId:
        return self._map.fold_over_ranges_with_gaps(
            ranges,
            lambda e, acc: min(acc, e.universal_before if e is not None
                               else TxnId.NONE),
            TxnId.MAX)

    def entries(self):
        """(start, end, majority_before, universal_before) segments — the
        wire form for QueryDurableBefore/SetGloballyDurable gossip."""
        def fold(e, start, end, acc):
            acc.append((start, end, e.majority_before, e.universal_before))
            return acc
        return self._map.fold_with_bounds(fold, [])

    def merge_entries(self, entries) -> None:
        """Max-merge gossiped segments (facts only spread forward)."""
        for start, end, majority, universal in entries:
            rs = Ranges.of(Range(start, end))
            self._map = self._map.add(
                rs, DurableBefore.Entry(majority, universal),
                lambda a, b: a.merge(b))


def participant_slice(owned: Ranges, participants) -> Ranges:
    """``owned`` ∩ the participants' token coverage — the one definition of
    'this store's slice of the txn' shared by the truncation replier
    (CheckStatus) and the purger (Propagate); a drift between the two
    breaks the proof-containment check."""
    if participants is None:
        return owned
    return owned.intersecting(_as_ranges(participants))


def _as_ranges(keys_or_ranges) -> Ranges:
    if isinstance(keys_or_ranges, Ranges):
        return keys_or_ranges
    if hasattr(keys_or_ranges, "to_ranges"):
        return keys_or_ranges.to_ranges()
    # Keys
    return Ranges([Range(k.token(), k.token() + 1) for k in keys_or_ranges])
