"""ShardDistributor: how a node's owned ranges split across command stores.

Rebuild of ref: accord-core/src/main/java/accord/local/
ShardDistributor.java:32-107 — a pluggable policy with the EvenSplit
default: chunk the added ranges into N contiguous pieces of equal token
span.  CommandStores keeps assignment STICKY (ranges never migrate between
sibling stores) and only distributes net-new ranges through the policy.
"""

from __future__ import annotations

from typing import List

from ..primitives.keys import Range, Ranges


class ShardDistributor:
    """Policy seam (ref: local/ShardDistributor.java)."""

    def split(self, ranges: Ranges, n: int) -> List[Ranges]:
        raise NotImplementedError


class EvenSplit(ShardDistributor):
    """Equal token-span chunks (ref: ShardDistributor.EvenSplit over the
    key hash space; our tokens are already uniformly hashed)."""

    def split(self, ranges: Ranges, n: int) -> List[Ranges]:
        if n == 1 or ranges.is_empty():
            return [ranges] + [Ranges.empty()] * (n - 1)
        total = sum(r.end - r.start for r in ranges)
        per = max(1, total // n)
        chunks: List[List[Range]] = [[] for _ in range(n)]
        i, budget = 0, per
        for r in ranges:
            start = r.start
            while start < r.end:
                take = min(budget, r.end - start)
                chunks[i].append(Range(start, start + take))
                start += take
                budget -= take
                if budget == 0:
                    if i < n - 1:
                        i += 1
                        budget = per
                    else:
                        budget = total  # remainder lands in the last chunk
        return [Ranges(c) for c in chunks]
