"""The per-transaction record and its execution frontier.

Rebuild of ref: accord-core/src/main/java/accord/local/Command.java:1741.
Instead of the reference's immutable class ladder
(NotDefined->PreAccepted->Accepted->Committed->Executed->Truncated) this is a
single immutable record whose populated fields are governed by SaveStatus —
the idiomatic form for a system whose data plane is a struct-of-arrays: each
field maps 1:1 onto a device array column in the TPU store.

WaitingOn (ref: Command.java:1295-1332) is the per-txn execution frontier:
the sorted dep TxnId vector plus two bitsets (waiting, appliedOrInvalidated)
whose word-views feed the drain kernel (accord_tpu.ops.drain).
"""

from __future__ import annotations

import bisect
from typing import FrozenSet, List, Optional, Tuple

from ..primitives.deps import PartialDeps
from ..primitives.keys import Range, Ranges, Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId
from ..primitives.txn import PartialTxn
from ..primitives.writes import Writes
from ..utils import invariants
from ..utils.bitset import ImmutableBitSet, SimpleBitSet
from .fastpath import proto_fastpath_enabled
from .status import Durability, Known, SaveStatus, Status

_FASTPATH = proto_fastpath_enabled()


class WaitingOn:
    """(ref: Command.java:1295-1332)."""

    __slots__ = ("txn_ids", "waiting", "applied_or_invalidated")

    def __init__(self, txn_ids: List[TxnId], waiting: ImmutableBitSet,
                 applied_or_invalidated: ImmutableBitSet):
        self.txn_ids = txn_ids  # sorted unique
        self.waiting = waiting
        self.applied_or_invalidated = applied_or_invalidated

    @classmethod
    def none(cls) -> "WaitingOn":
        return cls([], ImmutableBitSet(0), ImmutableBitSet(0))

    @classmethod
    def all_of(cls, txn_ids: List[TxnId]) -> "WaitingOn":
        n = len(txn_ids)
        return cls(txn_ids, SimpleBitSet.full(n).freeze(), ImmutableBitSet(n))

    def is_waiting(self) -> bool:
        return not self.waiting.is_empty()

    def is_waiting_on(self, txn_id: TxnId) -> bool:
        i = self._index_of(txn_id)
        return i >= 0 and self.waiting.get(i)

    def _index_of(self, txn_id: TxnId) -> int:
        i = bisect.bisect_left(self.txn_ids, txn_id)
        if i < len(self.txn_ids) and self.txn_ids[i] == txn_id:
            return i
        return -1

    def waiting_ids(self) -> List[TxnId]:
        return [self.txn_ids[i] for i in self.waiting]

    def next_waiting(self) -> Optional[TxnId]:
        i = self.waiting.last_set()
        return self.txn_ids[i] if i >= 0 else None

    def with_done(self, txn_id: TxnId, applied_or_invalidated: bool) -> "WaitingOn":
        """Clear the bit for a completed dependency; optionally record it as
        applied/invalidated (vs merely executes-after)."""
        i = self._index_of(txn_id)
        if i < 0 or not self.waiting.get(i):
            return self
        w = self.waiting.with_unset(i)
        a = (self.applied_or_invalidated.with_set(i)
             if applied_or_invalidated else self.applied_or_invalidated)
        return WaitingOn(self.txn_ids, w, a)

    def __eq__(self, o):
        return (isinstance(o, WaitingOn) and self.txn_ids == o.txn_ids
                and self.waiting == o.waiting
                and self.applied_or_invalidated == o.applied_or_invalidated)

    def __repr__(self):
        return f"WaitingOn({self.waiting_ids()})"


class Command:
    """Immutable per-transaction record (ref: Command.java)."""

    __slots__ = ("txn_id", "save_status", "durability", "route", "progress_key",
                 "promised", "accepted", "partial_txn", "partial_deps",
                 "execute_at", "executes_at_least", "waiting_on", "writes",
                 "result", "listeners")

    def __init__(self, txn_id: TxnId,
                 save_status: SaveStatus = SaveStatus.Uninitialised,
                 durability: Durability = Durability.NotDurable,
                 route: Optional[Route] = None,
                 progress_key: Optional[int] = None,
                 promised: Ballot = Ballot.ZERO,
                 accepted: Ballot = Ballot.ZERO,
                 partial_txn: Optional[PartialTxn] = None,
                 partial_deps: Optional[PartialDeps] = None,
                 execute_at: Optional[Timestamp] = None,
                 executes_at_least: Optional[Timestamp] = None,
                 waiting_on: Optional[WaitingOn] = None,
                 writes: Optional[Writes] = None,
                 result=None,
                 listeners: FrozenSet[TxnId] = frozenset()):
        self.txn_id = txn_id
        self.save_status = save_status
        self.durability = durability
        self.route = route
        self.progress_key = progress_key
        self.promised = promised
        self.accepted = accepted          # acceptedOrCommitted ballot
        self.partial_txn = partial_txn
        self.partial_deps = partial_deps
        self.execute_at = execute_at
        self.executes_at_least = executes_at_least
        self.waiting_on = waiting_on
        self.writes = writes
        self.result = result
        self.listeners = listeners

    # -- derived ------------------------------------------------------------
    def participants(self):
        """Where this command participates, from the best local knowledge:
        the sliced definition if present, else the route, else None.  The
        one shared resolution used by the drain clearing rules and Cleanup
        (keep in sync — divergent copies silently skew cleanup vs drain)."""
        if self.partial_txn is not None:
            return self.partial_txn.keys
        if self.route is not None:
            return self.route.participants
        return None

    @property
    def status(self) -> Status:
        return self.save_status.status

    def known(self) -> Known:
        return self.save_status.known

    def is_defined(self) -> bool:
        return self.save_status.known.is_definition_known()

    def has_been(self, status: Status) -> bool:
        return self.status >= status

    def is_stable(self) -> bool:
        return (self.save_status >= SaveStatus.Stable
                and not self.save_status.is_truncated()
                and self.save_status is not SaveStatus.Invalidated)

    def is_truncated(self) -> bool:
        return self.save_status.is_truncated()

    def is_invalidated(self) -> bool:
        return self.save_status is SaveStatus.Invalidated

    def is_applied(self) -> bool:
        return self.save_status in (SaveStatus.Applied,) or (
            self.save_status.is_truncated()
            and self.save_status is not SaveStatus.ErasedOrInvalidated)

    def is_at_least_applying(self) -> bool:
        return self.save_status >= SaveStatus.Applying

    def execute_at_if_known(self) -> Optional[Timestamp]:
        if self.known().execute_at.is_decided_and_known_to_execute():
            return self.execute_at
        return None

    def is_waiting(self) -> bool:
        return self.waiting_on is not None and self.waiting_on.is_waiting()

    # -- evolution ----------------------------------------------------------
    def updated(self, **kwargs) -> "Command":
        if _FASTPATH:
            # slot-copy transition: the per-op hot loop runs this for
            # every state change, so skip the dict rebuild + __init__
            # re-entry; an unknown kwarg still raises (no spare slots)
            new = Command.__new__(Command)
            for s in Command.__slots__:
                setattr(new, s, getattr(self, s))
            for k, v in kwargs.items():
                setattr(new, k, v)
            return new
        fields = {s: getattr(self, s) for s in Command.__slots__}
        fields.update(kwargs)
        return Command(**fields)

    def with_listener(self, txn_id: TxnId) -> "Command":
        if txn_id in self.listeners:
            return self
        return self.updated(listeners=self.listeners | {txn_id})

    def without_listener(self, txn_id: TxnId) -> "Command":
        if txn_id not in self.listeners:
            return self
        return self.updated(listeners=self.listeners - {txn_id})

    def __repr__(self):
        return (f"Command({self.txn_id}, {self.save_status.name}"
                + (f", executeAt={self.execute_at}" if self.execute_at else "")
                + ")")
