"""The protocol knowledge lattice: Status, SaveStatus, Durability, Known.

Rebuild of ref: accord-core/src/main/java/accord/local/Status.java:47-806 and
SaveStatus.java:55-175.  Status is the consensus progress ladder; the Known
sub-lattice {KnownRoute, Definition, KnownExecuteAt, KnownDeps, Outcome} is
what CheckStatus replies merge through; SaveStatus refines Status with local
execution / truncation states.  Ordinals of each enum form its join order —
``at_least`` is pointwise max.
"""

from __future__ import annotations

import enum
from typing import NamedTuple, Optional


class Phase(enum.IntEnum):
    """(ref: Status.java:99-115)."""
    NONE = 0
    PreAccept = 1
    Accept = 2
    Commit = 3
    Execute = 4
    Persist = 5
    Cleanup = 6

    @property
    def tie_break_with_ballot(self) -> bool:
        return self in (Phase.Accept, Phase.Commit)


class KnownRoute(enum.IntEnum):
    """(ref: Status.java:427-470)."""
    Maybe = 0
    Covering = 1
    Full = 2

    def has_full(self) -> bool:
        return self is KnownRoute.Full

    def at_least(self, that: "KnownRoute") -> "KnownRoute":
        return self if self >= that else that

    def reduce(self, that: "KnownRoute") -> "KnownRoute":
        if self == that:
            return self
        if KnownRoute.Full in (self, that):
            return KnownRoute.Full
        return KnownRoute.Maybe

    def valid_for_all(self) -> "KnownRoute":
        return KnownRoute.Maybe if self is KnownRoute.Covering else self


class Definition(enum.IntEnum):
    """(ref: Status.java:641-694)."""
    DefinitionUnknown = 0
    DefinitionErased = 1
    NoOp = 2
    DefinitionKnown = 3

    def is_known(self) -> bool:
        return self is Definition.DefinitionKnown

    def is_or_was_known(self) -> bool:
        return self is not Definition.DefinitionUnknown

    def at_least(self, that: "Definition") -> "Definition":
        return self if self >= that else that

    def reduce(self, that: "Definition") -> "Definition":
        return self if self <= that else that

    def valid_for_all(self) -> "Definition":
        return Definition.DefinitionUnknown if self is Definition.DefinitionKnown else self


class KnownExecuteAt(enum.IntEnum):
    """(ref: Status.java:473-537)."""
    ExecuteAtUnknown = 0
    ExecuteAtProposed = 1
    ExecuteAtErased = 2
    ExecuteAtKnown = 3
    NoExecuteAt = 4

    def is_decided(self) -> bool:
        return self >= KnownExecuteAt.ExecuteAtErased

    def is_decided_and_known_to_execute(self) -> bool:
        return self is KnownExecuteAt.ExecuteAtKnown

    def at_least(self, that: "KnownExecuteAt") -> "KnownExecuteAt":
        return self if self >= that else that

    def reduce(self, that: "KnownExecuteAt") -> "KnownExecuteAt":
        return self.at_least(that)

    def valid_for_all(self) -> "KnownExecuteAt":
        return (KnownExecuteAt.ExecuteAtUnknown
                if self <= KnownExecuteAt.ExecuteAtErased else self)

    def can_propose_invalidation(self) -> bool:
        return self is KnownExecuteAt.ExecuteAtUnknown


class KnownDeps(enum.IntEnum):
    """(ref: Status.java:539-640)."""
    DepsUnknown = 0
    DepsProposed = 1
    DepsCommitted = 2
    DepsErased = 3
    DepsKnown = 4
    NoDeps = 5

    @property
    def phase(self) -> Phase:
        return {KnownDeps.DepsUnknown: Phase.PreAccept,
                KnownDeps.DepsProposed: Phase.Accept,
                KnownDeps.DepsCommitted: Phase.Commit,
                KnownDeps.DepsErased: Phase.Cleanup,
                KnownDeps.DepsKnown: Phase.Execute,
                KnownDeps.NoDeps: Phase.Persist}[self]

    def has_proposed_deps(self) -> bool:
        return self is KnownDeps.DepsProposed

    def has_decided_deps(self) -> bool:
        return self is KnownDeps.DepsKnown

    def can_propose_invalidation(self) -> bool:
        return self is KnownDeps.DepsUnknown

    def at_least(self, that: "KnownDeps") -> "KnownDeps":
        return self if self >= that else that

    def reduce(self, that: "KnownDeps") -> "KnownDeps":
        return self if self <= that else that

    def valid_for_all(self) -> "KnownDeps":
        return KnownDeps.DepsUnknown if self is not KnownDeps.NoDeps else self


class Outcome(enum.IntEnum):
    """(ref: Status.java:695-806)."""
    Unknown = 0
    Erased = 1
    WasApply = 2
    Apply = 3
    Invalidated = 4

    def is_or_was_apply(self) -> bool:
        return self in (Outcome.Apply, Outcome.WasApply)

    def is_satisfied_by(self, other: "Outcome") -> bool:
        if self is Outcome.Unknown:
            return True
        if self is Outcome.WasApply and other is Outcome.Apply:
            return True
        return other is self

    def can_propose_invalidation(self) -> bool:
        return self is Outcome.Unknown

    def is_invalidated(self) -> bool:
        return self is Outcome.Invalidated

    def at_least(self, that: "Outcome") -> "Outcome":
        return self if self >= that else that

    def reduce(self, that: "Outcome") -> "Outcome":
        return self.at_least(that)

    def valid_for_all(self) -> "Outcome":
        return Outcome.Unknown if self is Outcome.Erased else self


class Known(NamedTuple):
    """What a replica knows about a transaction
    (ref: Status.java:124-420 Known)."""

    route: KnownRoute = KnownRoute.Maybe
    definition: Definition = Definition.DefinitionUnknown
    execute_at: KnownExecuteAt = KnownExecuteAt.ExecuteAtUnknown
    deps: KnownDeps = KnownDeps.DepsUnknown
    outcome: Outcome = Outcome.Unknown

    def at_least(self, that: "Known") -> "Known":
        return Known(self.route.at_least(that.route),
                     self.definition.at_least(that.definition),
                     self.execute_at.at_least(that.execute_at),
                     self.deps.at_least(that.deps),
                     self.outcome.at_least(that.outcome))

    def reduce(self, that: "Known") -> "Known":
        return Known(self.route.reduce(that.route),
                     self.definition.reduce(that.definition),
                     self.execute_at.reduce(that.execute_at),
                     self.deps.reduce(that.deps),
                     self.outcome.reduce(that.outcome))

    def valid_for_all(self) -> "Known":
        return Known(self.route.valid_for_all(),
                     self.definition.valid_for_all(),
                     self.execute_at.valid_for_all(),
                     self.deps.valid_for_all(),
                     self.outcome.valid_for_all())

    def is_satisfied_by(self, that: "Known") -> bool:
        return (self.definition <= that.definition
                and self.execute_at <= that.execute_at
                and self.deps <= that.deps
                and self.outcome.is_satisfied_by(that.outcome))

    def is_definition_known(self) -> bool:
        return self.definition.is_known()

    def is_invalidated(self) -> bool:
        return self.outcome.is_invalidated()

    def can_propose_invalidation(self) -> bool:
        return (self.execute_at.can_propose_invalidation()
                and self.deps.can_propose_invalidation()
                and self.outcome.can_propose_invalidation())

    def fetch_epoch(self, txn_id, execute_at) -> int:
        from ..primitives.timestamp import Timestamp
        if execute_at is None:
            return txn_id.epoch()
        if self.outcome.is_or_was_apply() and execute_at != Timestamp.NONE:
            return execute_at.epoch()
        return txn_id.epoch()


Known.Nothing = Known()
Known.DefinitionOnly = Known(KnownRoute.Maybe, Definition.DefinitionKnown)
Known.DefinitionAndRoute = Known(KnownRoute.Full, Definition.DefinitionKnown)
Known.ExecuteAtOnly = Known(execute_at=KnownExecuteAt.ExecuteAtKnown)
Known.Decision = Known(KnownRoute.Full, Definition.DefinitionKnown,
                       KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsKnown)
Known.Apply = Known(KnownRoute.Full, Definition.DefinitionUnknown,
                    KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsKnown,
                    Outcome.Apply)
Known.Invalidated = Known(outcome=Outcome.Invalidated)


class Durability(enum.IntEnum):
    """Global durability knowledge (ref: Status.java:807-850)."""
    NotDurable = 0
    Local = 1
    ShardUniversal = 2
    MajorityOrInvalidated = 3
    Majority = 4
    UniversalOrInvalidated = 5
    Universal = 6

    def is_durable(self) -> bool:
        return self in (Durability.Majority, Durability.Universal)

    def is_durable_or_invalidated(self) -> bool:
        return self >= Durability.MajorityOrInvalidated

    def merge(self, that: "Durability") -> "Durability":
        return self if self >= that else that


class Status(enum.IntEnum):
    """Consensus progress ladder (ref: Status.java:49-86)."""
    NotDefined = 0
    PreAccepted = 1
    AcceptedInvalidate = 2
    Accepted = 3
    PreCommitted = 4
    Committed = 5
    Stable = 6
    PreApplied = 7
    Applied = 8
    Truncated = 9
    Invalidated = 10

    @property
    def phase(self) -> Phase:
        return _STATUS_PHASE[self]

    @property
    def min_known(self) -> Known:
        return _STATUS_KNOWN[self]

    def has_been(self, status: "Status") -> bool:
        return self >= status

    def is_committed(self) -> bool:
        return self in (Status.Committed, Status.Stable, Status.PreApplied,
                        Status.Applied)


_STATUS_PHASE = {
    Status.NotDefined: Phase.NONE,
    Status.PreAccepted: Phase.PreAccept,
    Status.AcceptedInvalidate: Phase.Accept,
    Status.Accepted: Phase.Accept,
    Status.PreCommitted: Phase.Accept,
    Status.Committed: Phase.Commit,
    Status.Stable: Phase.Execute,
    Status.PreApplied: Phase.Persist,
    Status.Applied: Phase.Persist,
    Status.Truncated: Phase.Cleanup,
    Status.Invalidated: Phase.Persist,
}

_STATUS_KNOWN = {
    Status.NotDefined: Known.Nothing,
    Status.PreAccepted: Known.DefinitionAndRoute,
    Status.AcceptedInvalidate: Known.Nothing,
    Status.Accepted: Known(KnownRoute.Covering, Definition.DefinitionUnknown,
                           KnownExecuteAt.ExecuteAtProposed, KnownDeps.DepsProposed),
    Status.PreCommitted: Known(KnownRoute.Maybe, Definition.DefinitionUnknown,
                               KnownExecuteAt.ExecuteAtKnown),
    Status.Committed: Known(KnownRoute.Full, Definition.DefinitionKnown,
                            KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsCommitted),
    Status.Stable: Known(KnownRoute.Full, Definition.DefinitionKnown,
                         KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsKnown),
    Status.PreApplied: Known(KnownRoute.Full, Definition.DefinitionKnown,
                             KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsKnown,
                             Outcome.Apply),
    Status.Applied: Known(KnownRoute.Full, Definition.DefinitionKnown,
                          KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsKnown,
                          Outcome.Apply),
    Status.Truncated: Known(KnownRoute.Maybe, Definition.DefinitionErased,
                            KnownExecuteAt.ExecuteAtErased, KnownDeps.DepsErased,
                            Outcome.Erased),
    Status.Invalidated: Known(KnownRoute.Maybe, Definition.NoOp,
                              KnownExecuteAt.NoExecuteAt, KnownDeps.NoDeps,
                              Outcome.Invalidated),
}


def recovery_rank(status: "Status", accepted) -> tuple:
    """Knowledge ordering for recovery replies (ref: Status.java:871
    Status.max): higher phase wins; within a ballot-tie-broken phase
    (Accept/Commit) the higher ballot wins even over a higher status —
    AcceptedInvalidate@b1 beats Accepted@ZERO."""
    from ..primitives.timestamp import Ballot
    phase = status.phase
    ballot = accepted if phase.tie_break_with_ballot else Ballot.ZERO
    return (phase, ballot, status)


class LocalExecution(enum.IntEnum):
    """Local progress refinement (ref: SaveStatus.java LocalExecution)."""
    NotReady = 0
    ReadyToExclude = 1
    WaitingToExecute = 2
    ReadyToExecute = 3
    WaitingToApply = 4
    Applying = 5
    Applied = 6
    CleaningUp = 7


class SaveStatus(enum.IntEnum):
    """Disk/local-oriented refinement of Status (ref: SaveStatus.java:55-90)."""
    Uninitialised = 0
    NotDefined = 1
    PreAccepted = 2
    AcceptedInvalidate = 3
    AcceptedInvalidateWithDefinition = 4
    Accepted = 5
    AcceptedWithDefinition = 6
    PreCommitted = 7
    PreCommittedWithAcceptedDeps = 8
    PreCommittedWithDefinition = 9
    PreCommittedWithDefinitionAndAcceptedDeps = 10
    Committed = 11
    Stable = 12
    ReadyToExecute = 13
    PreApplied = 14
    Applying = 15
    Applied = 16
    TruncatedApplyWithDeps = 17
    TruncatedApplyWithOutcome = 18
    TruncatedApply = 19
    ErasedOrInvalidated = 20
    Erased = 21
    Invalidated = 22

    @property
    def status(self) -> Status:
        return _SAVE_STATUS[self][0]

    @property
    def phase(self) -> Phase:
        return self.status.phase

    @property
    def known(self) -> Known:
        return _SAVE_STATUS[self][1]

    @property
    def execution(self) -> LocalExecution:
        return _SAVE_STATUS[self][2]

    def has_been(self, status: Status) -> bool:
        return self.status >= status

    def is_uninitialised(self) -> bool:
        return self is SaveStatus.Uninitialised

    def is_complete(self) -> bool:
        return self in (SaveStatus.Applied, SaveStatus.Invalidated)

    def is_truncated(self) -> bool:
        return self.status is Status.Truncated

    def compare_knowledge(self, that: "SaveStatus") -> int:
        """Which SaveStatus carries more knowledge (for merge)."""
        return -1 if self < that else (0 if self == that else 1)

    @staticmethod
    def merge(a: "SaveStatus", b: "SaveStatus") -> "SaveStatus":
        return a if a >= b else b


def _sk(status: Status, known: Optional[Known] = None,
        execution: LocalExecution = LocalExecution.NotReady):
    return (status, known if known is not None else status.min_known, execution)


_SAVE_STATUS = {
    SaveStatus.Uninitialised: _sk(Status.NotDefined),
    SaveStatus.NotDefined: _sk(Status.NotDefined),
    SaveStatus.PreAccepted: _sk(Status.PreAccepted),
    SaveStatus.AcceptedInvalidate: _sk(Status.AcceptedInvalidate),
    SaveStatus.AcceptedInvalidateWithDefinition: _sk(
        Status.AcceptedInvalidate,
        Known(KnownRoute.Full, Definition.DefinitionKnown)),
    SaveStatus.Accepted: _sk(Status.Accepted),
    SaveStatus.AcceptedWithDefinition: _sk(
        Status.Accepted,
        Known(KnownRoute.Full, Definition.DefinitionKnown,
              KnownExecuteAt.ExecuteAtProposed, KnownDeps.DepsProposed)),
    SaveStatus.PreCommitted: _sk(Status.PreCommitted, None,
                                 LocalExecution.ReadyToExclude),
    SaveStatus.PreCommittedWithAcceptedDeps: _sk(
        Status.PreCommitted,
        Known(KnownRoute.Covering, Definition.DefinitionUnknown,
              KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsProposed),
        LocalExecution.ReadyToExclude),
    SaveStatus.PreCommittedWithDefinition: _sk(
        Status.PreCommitted,
        Known(KnownRoute.Full, Definition.DefinitionKnown,
              KnownExecuteAt.ExecuteAtKnown),
        LocalExecution.ReadyToExclude),
    SaveStatus.PreCommittedWithDefinitionAndAcceptedDeps: _sk(
        Status.PreCommitted,
        Known(KnownRoute.Full, Definition.DefinitionKnown,
              KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsProposed),
        LocalExecution.ReadyToExclude),
    SaveStatus.Committed: _sk(Status.Committed, None, LocalExecution.ReadyToExclude),
    SaveStatus.Stable: _sk(Status.Stable, None, LocalExecution.WaitingToExecute),
    SaveStatus.ReadyToExecute: _sk(Status.Stable, None, LocalExecution.ReadyToExecute),
    SaveStatus.PreApplied: _sk(Status.PreApplied, None, LocalExecution.WaitingToApply),
    SaveStatus.Applying: _sk(Status.PreApplied, None, LocalExecution.Applying),
    SaveStatus.Applied: _sk(Status.Applied, None, LocalExecution.Applied),
    SaveStatus.TruncatedApplyWithDeps: _sk(
        Status.Truncated,
        Known(KnownRoute.Full, Definition.DefinitionErased,
              KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsKnown, Outcome.Apply),
        LocalExecution.CleaningUp),
    SaveStatus.TruncatedApplyWithOutcome: _sk(
        Status.Truncated,
        Known(KnownRoute.Full, Definition.DefinitionErased,
              KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsErased, Outcome.Apply),
        LocalExecution.CleaningUp),
    SaveStatus.TruncatedApply: _sk(
        Status.Truncated,
        Known(KnownRoute.Full, Definition.DefinitionErased,
              KnownExecuteAt.ExecuteAtKnown, KnownDeps.DepsErased, Outcome.WasApply),
        LocalExecution.CleaningUp),
    SaveStatus.ErasedOrInvalidated: _sk(
        Status.Truncated,
        Known(KnownRoute.Maybe, Definition.DefinitionUnknown,
              KnownExecuteAt.ExecuteAtUnknown, KnownDeps.DepsUnknown,
              Outcome.Unknown),
        LocalExecution.CleaningUp),
    SaveStatus.Erased: _sk(
        Status.Truncated,
        Known(KnownRoute.Maybe, Definition.DefinitionErased,
              KnownExecuteAt.ExecuteAtErased, KnownDeps.DepsErased, Outcome.Erased),
        LocalExecution.CleaningUp),
    SaveStatus.Invalidated: _sk(Status.Invalidated, None, LocalExecution.CleaningUp),
}


def save_status_for(status: Status, known: Optional[Known] = None) -> SaveStatus:
    """Pick the SaveStatus that encodes (status, known)
    (ref: SaveStatus.get/enrich)."""
    base = {
        Status.NotDefined: SaveStatus.NotDefined,
        Status.PreAccepted: SaveStatus.PreAccepted,
        Status.AcceptedInvalidate: SaveStatus.AcceptedInvalidate,
        Status.Accepted: SaveStatus.Accepted,
        Status.PreCommitted: SaveStatus.PreCommitted,
        Status.Committed: SaveStatus.Committed,
        Status.Stable: SaveStatus.Stable,
        Status.PreApplied: SaveStatus.PreApplied,
        Status.Applied: SaveStatus.Applied,
        Status.Truncated: SaveStatus.Erased,
        Status.Invalidated: SaveStatus.Invalidated,
    }[status]
    if known is None:
        return base
    if status is Status.AcceptedInvalidate and known.is_definition_known():
        return SaveStatus.AcceptedInvalidateWithDefinition
    if status is Status.Accepted and known.is_definition_known():
        return SaveStatus.AcceptedWithDefinition
    if status is Status.PreCommitted:
        if known.is_definition_known():
            return (SaveStatus.PreCommittedWithDefinitionAndAcceptedDeps
                    if known.deps.has_proposed_deps()
                    else SaveStatus.PreCommittedWithDefinition)
        return (SaveStatus.PreCommittedWithAcceptedDeps
                if known.deps.has_proposed_deps() else SaveStatus.PreCommitted)
    return base
