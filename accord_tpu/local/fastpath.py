"""The r18 protocol fast-path escape hatch.

Every hot-loop cache the r18 pass added to the per-op protocol path
(slot-copy command transitions, memoized epoch-range lookups, cached
owned-shard topology views, precomputed message dispatch tables) is
gated on this ONE knob:

    ACCORD_TPU_PROTO_FASTPATH=off   # also: 0 / false / no

Same contract as ``ACCORD_TPU_FUSION=off``: with the knob off, every
fast path falls back to the original straight-line code, and tier-1
must stay green — no optimization may become load-bearing for
correctness.  ``tests/conftest.py`` carries the canary that asserts the
env var actually reaches this function, and
``tools/run_fault_matrix.sh`` runs the net + recovery legs under both
settings, byte-compared.

Hot consumers capture ``_FASTPATH = proto_fastpath_enabled()`` at
module import (an env probe per Command transition would cost more than
the cache saves); the knob is therefore set in the ENVIRONMENT of the
process under test — exactly how the tier-1 sweep and the fault-matrix
legs run it — not flipped mid-process.
"""

import os


def proto_fastpath_enabled() -> bool:
    """True unless ``ACCORD_TPU_PROTO_FASTPATH`` is off/0/false/no."""
    return os.environ.get("ACCORD_TPU_PROTO_FASTPATH", "").lower() \
        not in ("off", "0", "false", "no")


def store_group_enabled() -> bool:
    """True unless ``ACCORD_TPU_STORE_GROUP`` is off/0/false/no.

    The r20 store-grouped execution escape hatch: with the knob on, an
    ``accord_batch`` envelope's protocol sub-bodies decode in one pass
    and all ops targeting the same CommandStore execute under ONE
    scheduled task with ONE SafeCommandStore acquisition (merged
    PreLoadContext, one page-in pass).  With the knob off, every
    envelope unbatches into the per-op path exactly as r16 shipped it.
    Same contract as ``proto_fastpath_enabled``: consumers capture the
    value at module import; ``tests/conftest.py`` carries the canary;
    ``tools/run_fault_matrix.sh`` dual-runs both settings.
    """
    return os.environ.get("ACCORD_TPU_STORE_GROUP", "").lower() \
        not in ("off", "0", "false", "no")
