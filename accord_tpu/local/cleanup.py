"""Cleanup: watermark-driven truncation and erasure of command state.

Rebuild of ref: accord-core/src/main/java/accord/local/Cleanup.java (the
NO/TRUNCATE/ERASE decision), CommandStore.java:516-532
(markExclusiveSyncPointLocallyApplied / markShardDurable), and the
truncation entry points Commands.java:879-975.

The lifecycle that makes state bounded:

 1. An ExclusiveSyncPoint S applies at EVERY replica of a shard (its kind
    awaits_only_deps, so S applied somewhere proves every TxnId < S applied
    there); CoordinateShardDurable observes this and broadcasts
    SetShardDurable(S) -> mark_shard_durable: advance
    RedundantBefore.redundant_before (the shard watermark), DurableBefore
    majority+universal, prune CommandsForKey below S, free device deps-index
    slots, and truncate/erase eligible commands.
 2. CoordinateGloballyDurable gossips merged DurableBefore maps so replicas
    that missed a SetShardDurable catch up.

After step 1 the deps floor (RedundantBefore.deps_floor) has risen, so
PreAccept dep sets stay O(live txns) and the conflict indexes stay bounded.
"""

from __future__ import annotations

import enum
from typing import TYPE_CHECKING

from ..primitives.keys import Ranges
from ..primitives.timestamp import TxnId
from .status import Durability, SaveStatus, Status

if TYPE_CHECKING:
    from .command_store import SafeCommandStore


class Cleanup(enum.IntEnum):
    """(ref: local/Cleanup.java)."""
    NO = 0
    TRUNCATE = 1   # drop txn/deps/writes, keep the Applied marker
    ERASE = 2      # drop the record entirely


def mark_shard_durable(safe: "SafeCommandStore", sync_id: TxnId,
                       ranges: Ranges) -> None:
    """(ref: CommandStore.markShardDurable :524-532).  ``sync_id`` is an
    ExclusiveSyncPoint applied at EVERY replica of these ranges."""
    store = safe.store
    owned = store.ranges_for_epoch.all().intersecting(ranges)
    if owned.is_empty():
        return
    safe.redundant_before().add_redundant(owned, sync_id)
    # applied at every replica => majority AND universal within the shard
    store.durable_before.add_majority(owned, sync_id)
    store.durable_before.add_universal(owned, sync_id)
    # the deps floor rose: prune per-key conflict indexes below it
    for token, cfk in store.commands_for_key.items():
        if owned.contains_token(token):
            cfk.set_prune_before(sync_id)
    cleanup_store(safe)


def mark_shard_stale(safe: "SafeCommandStore", stale_since, ranges: Ranges,
                     precise: bool) -> None:
    """The staleness escape hatch (ref: CommandStore.markShardStale
    :539-560 + api/Agent.java:65): this replica can no longer procure the
    history it needs for ``ranges`` — peers durably truncated it.  Mark the
    ranges stale (reads refuse, RedundantBefore treats ids below as
    pre-bootstrap-or-stale), tell the Agent, and re-bootstrap: the fence +
    snapshot fetch re-covers the data, and the bootstrap watermark rising
    to the fence clears the staleness (RedundantEntry.merge).

    ``precise``: stale_since is the known executeAt bound of the lost
    history (True) or just the txn's id when even the executeAt is gone
    (False, the conservative bound)."""
    store = safe.store
    owned = store.ranges_for_epoch.all().intersecting(ranges)
    # new staleness only: re-marking already-stale (or already
    # re-bootstrapping — the fence watermark clears the stale flag the
    # instant the bootstrap starts) ranges would re-trigger bootstraps on
    # every fetch of every lost txn
    already = store.redundant_before.stale_ranges(owned) \
        .with_(store.bootstrapping)
    fresh = owned.without(already)
    if fresh.is_empty():
        return
    store.n_stale_marks += 1
    store.redundant_before.add_stale(fresh, stale_since)
    node = store.node
    node.agent.on_stale(stale_since, fresh)
    # the escape: re-bootstrap the stale ranges (ref: Agent.onStale's
    # documented contract — the integrator re-bootstraps; here the store
    # drives it directly, like the journal's restart gap fill)
    from .bootstrap import Bootstrap
    Bootstrap(store, fresh, max(2, node.epoch())).start()


def on_durable_before_advance(safe: "SafeCommandStore") -> None:
    """A gossiped DurableBefore advance (SetGloballyDurable) may newly
    qualify commands for erasure."""
    cleanup_store(safe)


def decide(safe: "SafeCommandStore", cmd) -> Cleanup:
    """The Cleanup decision for one command (ref: local/Cleanup.java).
    Conservative: requires the shard watermark (everything below it applied
    at every replica) plus the matching durability tier."""
    txn_id = cmd.txn_id
    if cmd.save_status is SaveStatus.Uninitialised:
        return Cleanup.NO
    participants = cmd.participants()
    from .redundant import RedundantStatus
    if participants is None or participants.is_empty():
        # placeholder record (dep never witnessed with a definition): erase
        # once the watermarks over everything we own have passed it
        owned = safe.store.ranges_for_epoch.all()
        if not owned.is_empty() \
                and txn_id < safe.store.durable_before.min_universal_before(owned) \
                and safe.redundant_before().status(txn_id, owned) is \
                RedundantStatus.SHARD_REDUNDANT:
            return Cleanup.ERASE
        return Cleanup.NO
    if safe.redundant_before().status(txn_id, participants) is not \
            RedundantStatus.SHARD_REDUNDANT:
        return Cleanup.NO
    # never truncate an undrained local record: a committed-but-unapplied
    # command still owes its writes here (witnessed via a dual-quorum window
    # but applying elsewhere); erasing it is how writes get lost
    if cmd.has_been(Status.Committed) and not cmd.has_been(Status.Applied) \
            and not cmd.is_invalidated():
        return Cleanup.NO
    db = safe.store.durable_before
    from .redundant import _as_ranges
    ranges = _as_ranges(participants)
    if txn_id < db.min_universal_before(ranges):
        return Cleanup.ERASE
    if txn_id < db.min_majority_before(ranges):
        return Cleanup.TRUNCATE
    return Cleanup.NO


def cleanup_store(safe: "SafeCommandStore") -> int:
    """Sweep every command against the watermarks; truncate/erase the
    eligible ones and release their index state.  Returns #commands
    released (ref: the Cleanup hook in SafeCommandStore.get + the journal
    purger; ours sweeps eagerly at watermark advances)."""
    from . import commands as commands_mod
    store = safe.store
    journal = store.node.journal
    if journal is not None:
        # persist the advanced watermarks (latest-wins snapshot — the
        # journal's bounded substitute for replaying every durability verb)
        journal.record_watermarks(store.store_id,
                                  store.durable_before.entries(),
                                  store.redundant_before.redundant_entries())
    if store.paged_limit is not None and journal is not None:
        # paged-out commands must not escape erasure (their journal
        # registers/bodies and device slots would grow forever): page the
        # erasure-eligible ones — below the universal watermark — back in
        # so the sweep below retires them, dropping their registers too.
        # Only when the floor ADVANCED since the last attempt: candidates
        # decide() refuses (e.g. truncated cross-shard routes whose non-
        # owned ranges gap the watermark) must not be reconstructed again
        # on every durability round.
        owned = store.ranges_for_epoch.all()
        if not owned.is_empty():
            floor = store.durable_before.min_universal_before(owned)
            if floor != getattr(store, "_cleanup_paged_floor", None):
                store._cleanup_paged_floor = floor
                for tid in journal.registered_txns(store.store_id):
                    if tid < floor and tid not in store.commands:
                        store.page_in(tid)
    released = 0
    for txn_id in list(store.commands.keys()):
        cmd = store.commands.get(txn_id)
        if cmd is None:
            continue
        decision = decide(safe, cmd)
        if decision is Cleanup.NO:
            continue
        _release_indexes(store, cmd)
        if decision is Cleanup.ERASE:
            # drop the record entirely; RedundantBefore answers for it now
            commands_mod.set_erased(safe, txn_id)
            del store.commands[txn_id]
            store.transient_listeners.pop(txn_id, None)
        else:
            # decide() required SHARD_REDUNDANT — an ExclusiveSyncPoint at
            # or above this id applied at EVERY replica — so record the
            # UNIVERSAL durability tier the truncation proves: a straggler
            # fetching this record must be able to conclude "settled
            # everywhere" (Propagate's purge gate), which mere Majority
            # (set by InformDurable) does not license.  Pure Universal only
            # for genuinely APPLIED commands: has_been(Applied) alone is
            # also true for Invalidated (it ranks above Applied), which
            # never applied writes anywhere.
            applied = cmd.has_been(Status.Applied) \
                and not cmd.is_invalidated() and not cmd.is_truncated()
            commands_mod.set_durability(
                safe, txn_id,
                Durability.Universal if applied
                else Durability.UniversalOrInvalidated)
            commands_mod.set_truncated_apply(safe, txn_id)
        released += 1
    _prune_cfks(store)
    # the watermark rose: frontiers built before the rise may hold bits for
    # deps it now answers for — re-evaluate them (refresh applies the
    # watermark clearance; host mode re-checks via erase notifications)
    if store.device is not None:
        store.device.schedule_tick()
    return released


def _release_indexes(store, cmd) -> None:
    txn_id = cmd.txn_id
    store.drop_range_command(txn_id)
    if store.device is not None:
        store.device.free(txn_id)
    if cmd.partial_txn is not None and not isinstance(cmd.partial_txn.keys,
                                                     Ranges):
        for key in cmd.partial_txn.keys:
            cfk = store.commands_for_key.get(key.token())
            if cfk is not None:
                cfk.remove(txn_id)


def _prune_cfks(store) -> None:
    """Physically drop per-key entries below each CFK's prune watermark —
    everything below it has applied (or been invalidated) at every replica
    of the shard, so no dep set or recovery query needs it again."""
    for cfk in store.commands_for_key.values():
        cfk.prune()


