"""Per-node metadata shards: CommandStore / SafeCommandStore / CommandStores.

Rebuild of ref: accord-core/src/main/java/accord/local/CommandStore.java:80,
SafeCommandStore.java:56, CommandStores.java:78, PreLoadContext.java:42.

A CommandStore is one single-threaded metadata shard owning a set of token
ranges: all commands, per-key conflict indexes (CommandsForKey), and the
watermark maps.  Tasks are submitted with a PreLoadContext and run with an
exclusive SafeCommandStore view; in this build the "thread" is a deterministic
task queue drained through the node's Scheduler, so the whole node group is
simulator-controlled (and the store's array state can be shipped to the TPU
between tasks without synchronisation).

CommandStores is the shard group: it splits the node's owned ranges over a
fixed number of stores (ShardDistributor.EvenSplit analogue) and scatter-
gathers map-reduce-consume tasks across intersecting stores
(ref: CommandStores.java:575-643).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ..primitives.deps import PartialDeps
from ..primitives.keys import Range, Ranges, RoutingKeys, Unseekables
from ..primitives.timestamp import Kinds, Timestamp, TxnId
from ..utils import async_chain, invariants
from ..utils.interval_map import ReducingRangeMap
from .command import Command
from .commands_for_key import CommandsForKey, InternalStatus
from .fastpath import proto_fastpath_enabled, store_group_enabled
from .redundant import DurableBefore, MaxConflicts, RedundantBefore
from .status import SaveStatus

_FASTPATH = proto_fastpath_enabled()
# r20 store-grouped execution: every task that shares a drain tick shares
# ONE SafeCommandStore acquisition (merged PreLoadContext, one page-in
# pass, op-boundary notification flushes).  Captured at import like
# _FASTPATH; ACCORD_TPU_STORE_GROUP=off restores the per-task path.
_STORE_GROUP = store_group_enabled()


class PreLoadContext:
    """Declares what a task needs in memory before running
    (ref: local/PreLoadContext.java:42-90).  In-memory stores satisfy any
    context immediately; a paging/journal store uses it to schedule loads."""

    __slots__ = ("primary_txn_id", "additional_txn_ids", "keys")

    def __init__(self, primary_txn_id: Optional[TxnId] = None,
                 additional_txn_ids: Sequence[TxnId] = (),
                 keys: Optional[Unseekables] = None):
        self.primary_txn_id = primary_txn_id
        self.additional_txn_ids = tuple(additional_txn_ids)
        self.keys = keys

    @classmethod
    def empty(cls) -> "PreLoadContext":
        return _EMPTY_CONTEXT

    @classmethod
    def for_txn(cls, txn_id: TxnId, keys: Optional[Unseekables] = None) -> "PreLoadContext":
        return cls(txn_id, (), keys)


_EMPTY_CONTEXT = PreLoadContext()


def _merge_contexts(batch) -> PreLoadContext:
    """Union of a grouped batch's declared contexts (r20): one merged
    PreLoadContext covering every sub-op's txn ids — the single page-in
    pass / context load the grouped drain performs up front.  Keys are
    not merged (no consumer loads by key; in-memory stores satisfy any
    context immediately)."""
    if len(batch) == 1:
        return batch[0][0]
    primary = None
    additional: List[TxnId] = []
    seen: Set[TxnId] = set()
    for context, _fn, _out in batch:
        for tid in (context.primary_txn_id, *context.additional_txn_ids):
            if tid is not None and tid not in seen:
                seen.add(tid)
                if primary is None:
                    primary = tid
                else:
                    additional.append(tid)
    if primary is None:
        return _EMPTY_CONTEXT
    return PreLoadContext(primary, additional)


class RangesForEpoch:
    """Per-store epoch -> owned-ranges history
    (ref: CommandStores.java:142-336).

    ``at``/``all_between`` run once per (message, store) on the serving
    hot path — the r18 profile showed them as a top frame — so both are
    memoized behind the PROTO_FASTPATH knob.  ``snapshot`` is the ONLY
    mutation point, so clearing the memo there keeps every cached answer
    bit-identical to the straight-line recompute."""

    __slots__ = ("_by_epoch", "_at_memo", "_between_memo")

    def __init__(self):
        self._by_epoch: Dict[int, Ranges] = {}
        self._at_memo: Dict[int, Ranges] = {}
        self._between_memo: Dict[Tuple[int, int], Ranges] = {}

    def snapshot(self, epoch: int, ranges: Ranges) -> None:
        self._by_epoch[epoch] = ranges
        self._at_memo.clear()
        self._between_memo.clear()

    def at(self, epoch: int) -> Ranges:
        if _FASTPATH:
            hit = self._at_memo.get(epoch)
            if hit is not None:
                return hit
        if not self._by_epoch:
            return Ranges.empty()
        best = None
        for e in sorted(self._by_epoch):
            if e <= epoch:
                best = e
        if best is None:
            best = min(self._by_epoch)
        out = self._by_epoch[best]
        if _FASTPATH:
            self._at_memo[epoch] = out
        return out

    def current(self) -> Ranges:
        if not self._by_epoch:
            return Ranges.empty()
        return self._by_epoch[max(self._by_epoch)]

    def earliest(self) -> Ranges:
        """The store's first-epoch snapshot — the ranges it has held since
        its node joined (data present without any bootstrap)."""
        if not self._by_epoch:
            return Ranges.empty()
        return self._by_epoch[min(self._by_epoch)]

    def all_between(self, min_epoch: int, max_epoch: int) -> Ranges:
        """Union of every snapshot in effect during [min_epoch, max_epoch]:
        the snapshots declared inside the window plus the one already active
        at min_epoch."""
        if _FASTPATH:
            hit = self._between_memo.get((min_epoch, max_epoch))
            if hit is not None:
                return hit
        out = self.at(min_epoch)
        for e, r in self._by_epoch.items():
            if min_epoch <= e <= max_epoch:
                out = out.with_(r)
        if _FASTPATH:
            self._between_memo[(min_epoch, max_epoch)] = out
        return out

    def all(self) -> Ranges:
        out = Ranges.empty()
        for r in self._by_epoch.values():
            out = out.with_(r)
        return out


class CommandStore:
    """One single-threaded metadata shard (ref: local/CommandStore.java:80)."""

    def __init__(self, store_id: int, node, paged_limit: Optional[int] = None):
        self.store_id = store_id
        self.node = node                      # local.node.Node
        # paged mode (ref: the cache-limited DelayedCommandStores): above
        # this many command records, terminal commands are paged out to the
        # journal and reloaded on demand via PreLoadContext / page_in
        self.paged_limit = paged_limit
        self.ranges_for_epoch = RangesForEpoch()
        self.commands: Dict[TxnId, Command] = {}
        self.commands_for_key: Dict[int, CommandsForKey] = {}
        # Range-domain txns indexed for the range scan path
        # (ref: InMemoryCommandStore.rangeCommands TreeMap scan :524).
        # Mutate ONLY via put_range_command/drop_range_command: the interval
        # index below is rebuilt lazily on version change.
        self.range_commands: Dict[TxnId, Ranges] = {}
        self._range_index = None
        self._range_index_version = -1
        self._range_version = 0
        self.max_conflicts = MaxConflicts()
        self.redundant_before = RedundantBefore()
        self.durable_before = DurableBefore()
        from ..impl.timestamps_for_key import TimestampsForKeys
        self.timestamps_for_key = TimestampsForKeys()
        # ranges adopted this epoch whose snapshot has not yet arrived —
        # reads are Nacked until clear (ref: safeToRead,
        # local/CommandStore.java:159-176), and writes landing on them are
        # deferred so the snapshot's earlier appends install first
        self.bootstrapping: Ranges = Ranges.empty()
        self._bootstrap_waiters: List[Callable[[], None]] = []
        self.n_stale_marks = 0      # diagnostics: staleness escape hatches
        self.reject_before: Optional[ReducingRangeMap] = None
        # under _STORE_GROUP the queue holds (context, fn, out) entries;
        # otherwise opaque task closures (the original per-task path)
        self._queue: List = []
        self._draining = False
        # r20 grouped-execution census: ops per merged SafeCommandStore
        # acquisition (1 = no sharing; mirrors the outbound batch census)
        self.group_sizes: Dict[int, int] = {}
        # transient (non-durable) listeners: txn_id -> [fn(safe, command)]
        # (ref: Command.TransientListener / ReadData registration)
        self.transient_listeners: Dict[TxnId, List[Callable]] = {}
        self.progress_log = node.progress_log_factory(self)
        # device-backed conflict index + drain graph (the TPU protocol path);
        # None = pure host mode (listener-driven drain, CFK fold scans)
        if getattr(node, "device_mode", False):
            from .device_index import DeviceState
            self.device: Optional["DeviceState"] = DeviceState(self)
        else:
            self.device = None

    def defer_until_bootstrap(self, fn: Callable[[], None]) -> None:
        self._bootstrap_waiters.append(fn)

    def bootstrap_complete(self) -> None:
        waiters, self._bootstrap_waiters = self._bootstrap_waiters, []
        for fn in waiters:   # replay in defer order == executeAt drain order
            fn()

    # -- executor contract (ref: CommandStore submit/execute) ---------------
    def execute(self, context: PreLoadContext,
                fn: Callable[["SafeCommandStore"], "object"]) -> async_chain.AsyncChain:
        """Queue fn to run with exclusive access; returns chain of result."""
        out: async_chain.AsyncResult = async_chain.AsyncResult()

        if not getattr(self.node, "alive", True):
            # dead incarnation (restart_node): its queued work must not run —
            # ghost tasks would keep writing registers into the shared
            # journal and data store, contaminating the new incarnation's
            # durable state.  The chain never settles, like a crashed process.
            return out

        if _STORE_GROUP:
            # grouped route: queue the structured entry; the drain merges
            # every same-tick entry under ONE SafeCommandStore
            self._queue.append((context, fn, out))
            self._schedule_drain()
            return out

        def task():
            # honor the PreLoadContext contract (ref: PreLoadContext.java:42):
            # everything the task declared is in memory before it runs.  With
            # the journal as backing store the load is synchronous; a disk
            # journal would await the reads here before scheduling fn.
            self._load_context(context)
            safe = SafeCommandStore(self, context)
            try:
                result = fn(safe)
            except BaseException as e:  # noqa: BLE001
                safe.complete()
                out.set_failure(e)
                return
            safe.complete()
            out.set_success(result)

        self._queue.append(task)
        self._schedule_drain()
        return out

    def _schedule_drain(self) -> None:
        if self._draining:
            return
        self._draining = True
        self.node.scheduler.now(self._drain)

    def _drain(self) -> None:
        if not getattr(self.node, "alive", True):
            self._queue.clear()   # the process died with this work pending
            self._draining = False
            return
        if _STORE_GROUP:
            self._drain_grouped()
        else:
            while self._queue:
                task = self._queue.pop(0)
                try:
                    task()
                except BaseException as e:  # noqa: BLE001
                    self.node.agent.on_uncaught_exception(e)
        self._draining = False
        if self.paged_limit is not None:
            self._maybe_page_out()

    def _drain_grouped(self) -> None:
        """Run every same-tick queued op under ONE SafeCommandStore.

        Each batch = the queue as it stands: one merged PreLoadContext
        (one page-in pass), one SafeCommandStore, then the per-op fn
        bodies in queue order.  After each fn its deferred notifications
        flush at the OP BOUNDARY (queued exactly where the per-op
        ``complete()`` would have queued them) and its chain settles —
        so the store-queue task order, listener_update call order and
        reply emission order are byte-identical to the per-task drain.
        Ops queued DURING the batch (notification tasks, nested
        executes) form the next batch, preserving the per-op FIFO."""
        while self._queue:
            batch, self._queue = self._queue, []
            self.group_sizes[len(batch)] = \
                self.group_sizes.get(len(batch), 0) + 1
            if self.paged_limit is not None:
                for context, _fn, _out in batch:
                    self._load_context(context)
            safe = SafeCommandStore(self, _merge_contexts(batch))
            for _context, fn, out in batch:
                try:
                    result = fn(safe)
                except BaseException as e:  # noqa: BLE001
                    safe.flush_pending()
                    try:
                        out.set_failure(e)
                    except BaseException as e2:  # noqa: BLE001
                        self.node.agent.on_uncaught_exception(e2)
                    continue
                safe.flush_pending()
                try:
                    out.set_success(result)
                except BaseException as e:  # noqa: BLE001
                    self.node.agent.on_uncaught_exception(e)
            safe.complete()   # no-op: every op's pendings already flushed

    # -- journal-backed paging ----------------------------------------------
    def _load_context(self, context: PreLoadContext) -> None:
        if self.paged_limit is None:
            return   # nothing is ever paged out: every lookup would miss
        for txn_id in (context.primary_txn_id, *context.additional_txn_ids):
            if txn_id is not None and txn_id not in self.commands:
                self.page_in(txn_id)

    def page_in(self, txn_id: TxnId):
        """Reload a paged-out (terminal) command from the journal.  Returns
        the installed Command or None if the journal has no record (never
        witnessed, or erased — the watermarks answer for those)."""
        journal = self.node.journal
        if journal is None:
            return None
        cmd = journal.reconstruct(self, txn_id)
        if cmd is None or not (cmd.save_status is SaveStatus.Applied
                               or cmd.is_truncated() or cmd.is_invalidated()):
            return None   # only terminal commands are ever paged out
        self.commands[txn_id] = cmd
        return cmd

    def _maybe_page_out(self) -> None:
        """Evict terminal commands beyond the page limit; the journal
        retains their registers + bodies for page_in.  Listener sets on
        terminal commands are dead (notifications fire on transitions, and
        terminal commands have none left).  A command is only evicted after
        proving the journal round-trips it to the SAME terminal status —
        paging must never degrade state (a degraded Stable without its
        frontier would execute early on reload)."""
        excess = len(self.commands) - self.paged_limit
        if excess <= 0:
            return
        journal = self.node.journal
        if journal is None:
            return
        evictable = sorted(tid for tid, cmd in self.commands.items()
                           if (cmd.save_status is SaveStatus.Applied
                               or cmd.is_truncated() or cmd.is_invalidated())
                           and journal.has_register(self.store_id, tid))
        for tid in evictable:
            if excess <= 0:
                break
            rc = journal.reconstruct(self, tid, probe=True)
            if rc is None or rc.save_status is not \
                    self.commands[tid].save_status:
                continue   # not faithfully reloadable: keep it in memory
            del self.commands[tid]
            self.transient_listeners.pop(tid, None)
            excess -= 1

    # -- range-txn interval index -------------------------------------------
    def put_range_command(self, txn_id: TxnId, ranges: Ranges) -> None:
        if self.range_commands.get(txn_id) == ranges:
            return   # re-registration on a status message: index unchanged
        self.range_commands[txn_id] = ranges
        self._range_version += 1

    def drop_range_command(self, txn_id: TxnId) -> None:
        if self.range_commands.pop(txn_id, None) is not None:
            self._range_version += 1

    def range_index(self):
        """Checkpointed interval index over the range-domain txns — the
        CINTIA stabbing structure (ref: utils/SearchableRangeList.java:19-48),
        rebuilt lazily after mutations (range txns mutate rarely — epoch
        fences and durability rounds — while the PreAccept scan stabs it on
        every keyed dep computation)."""
        if self._range_index_version != self._range_version:
            from ..utils.interval_index import SearchableRangeList
            self._range_index = SearchableRangeList(
                (r.start, r.end, tid)
                for tid, rs in self.range_commands.items() for r in rs)
            self._range_index_version = self._range_version
        return self._range_index

    # -- state helpers ------------------------------------------------------
    def cfk(self, token: int) -> CommandsForKey:
        c = self.commands_for_key.get(token)
        if c is None:
            c = self.commands_for_key[token] = CommandsForKey(token)
        return c

    def command_if_present(self, txn_id: TxnId) -> Optional[Command]:
        return self.commands.get(txn_id)

    def command_maybe_paged(self, txn_id: TxnId) -> Optional[Command]:
        """Command record, reloading a paged-out terminal one if needed —
        for readers that bypass SafeCommandStore (scans, barriers)."""
        cmd = self.commands.get(txn_id)
        if cmd is None and self.paged_limit is not None:
            cmd = self.page_in(txn_id)
        return cmd

    # -- exclusive sync point fencing (ref: CommandStore.rejectBefore) ------
    def mark_reject_before(self, ranges: Ranges, txn_id: TxnId) -> None:
        """An ExclusiveSyncPoint at txn_id fences these ranges: later
        PreAccepts/Accepts of LOWER TxnIds are rejected, guaranteeing no txn
        below the fence can newly decide (the bootstrap-snapshot coverage
        invariant relies on this)."""
        m = self.reject_before if self.reject_before is not None \
            else ReducingRangeMap.empty()
        self.reject_before = m.add(ranges, txn_id,
                                   lambda a, b: a if a >= b else b)

    def reject_before_floor(self, keys_or_ranges) -> Optional[TxnId]:
        if self.reject_before is None:
            return None
        from .redundant import _as_ranges
        ranges = _as_ranges(keys_or_ranges)
        return self.reject_before.fold_over_ranges(
            ranges, lambda v, acc: v if acc is None or v > acc else acc, None)

    def owned_at(self, epoch: int) -> Ranges:
        return self.ranges_for_epoch.at(epoch)

    def owned_current(self) -> Ranges:
        return self.ranges_for_epoch.current()

    def unsafe_set_command(self, command: Command) -> None:
        self.commands[command.txn_id] = command

    def __repr__(self):
        return f"CommandStore#{self.store_id}@{self.node.node_id}"


class SafeCommandStore:
    """Exclusive view of a CommandStore during one task
    (ref: local/SafeCommandStore.java:56).  Listener notifications triggered
    by updates are deferred until the task completes to avoid reentrancy."""

    def __init__(self, store: CommandStore, context: PreLoadContext):
        self.store = store
        self.context = context
        self._pending_notifications: List[Tuple[TxnId, TxnId]] = []
        self._pending_transients: List[TxnId] = []
        self._completed = False

    # -- command access -----------------------------------------------------
    def get(self, txn_id: TxnId) -> Command:
        """Get or create the command record (ref: SafeCommandStore.get with
        truncation-on-read via RedundantBefore, :79-189).  A paged-out
        terminal command reloads from the journal first."""
        cmd = self.store.commands.get(txn_id)
        if cmd is None and self.store.paged_limit is not None:
            cmd = self.store.page_in(txn_id)
        if cmd is None:
            cmd = Command(txn_id)
            self.store.commands[txn_id] = cmd
        return cmd

    def if_present(self, txn_id: TxnId) -> Optional[Command]:
        cmd = self.store.commands.get(txn_id)
        if cmd is None and self.store.paged_limit is not None:
            cmd = self.store.page_in(txn_id)
        return cmd

    def update(self, command: Command, notify: bool = True) -> Command:
        """Install a new version of the command; queues listener
        notifications for any watchers."""
        prev = self.store.commands.get(command.txn_id)
        self.store.commands[command.txn_id] = command
        journal = self.store.node.journal
        if journal is not None:
            # the command's fixed-width columns are the journal's registers;
            # variable-size fields reconstruct from the message log
            # (ref: SerializerSupport.reconstruct's register arguments)
            journal.record_registers(self.store.store_id, command)
        if notify and prev is not None and command.save_status != prev.save_status:
            for listener in command.listeners:
                self._pending_notifications.append((listener, command.txn_id))
            if command.txn_id in self.store.transient_listeners:
                self._pending_transients.append(command.txn_id)
        return command

    def notify_listeners(self, command: Command) -> None:
        for listener in command.listeners:
            self._pending_notifications.append((listener, command.txn_id))

    def add_transient_listener(self, txn_id: TxnId, fn: Callable) -> None:
        self.store.transient_listeners.setdefault(txn_id, []).append(fn)

    def remove_transient_listeners(self, txn_id: TxnId) -> None:
        self.store.transient_listeners.pop(txn_id, None)

    def remove_transient_listener(self, txn_id: TxnId, fn: Callable) -> None:
        fns = self.store.transient_listeners.get(txn_id)
        if fns is not None:
            try:
                fns.remove(fn)
            except ValueError:
                pass
            if not fns:
                del self.store.transient_listeners[txn_id]

    def notify_transient(self, command: Command) -> None:
        fns = self.store.transient_listeners.get(command.txn_id)
        if fns:
            for fn in list(fns):
                fn(self, command)

    # -- cfk / scans --------------------------------------------------------
    def cfk(self, token: int) -> CommandsForKey:
        return self.store.cfk(token)

    def map_reduce_active(self, keys_or_ranges, started_before: Timestamp,
                          witnesses: Kinds, fn, acc):
        """The PreAccept conflict scan over this store's owned slice
        (ref: SafeCommandStore.java:269-286; InMemoryCommandStore.java:863-877).
        Covers both the per-key indexes and the range-txn scan.

        The scan window is the store's FULL ownership history, not just the
        ranges owned at started_before's epoch: a dual-quorum PreAccept at a
        prior-epoch replica (epoch handoff — the replica owns NOTHING in the
        new epoch) must still report the in-flight txns it witnessed on its
        old ranges, or the new owner's capture fence collects empty deps and
        writes committed at the old quorum are lost across the handoff.  The
        caller already slices ``keys_or_ranges`` to the message's epoch
        window; extra history only ever ADDS witnessed conflicts (safe)."""
        owned = self.store.ranges_for_epoch.all()
        if isinstance(keys_or_ranges, Ranges):
            scan_ranges = keys_or_ranges.slice(owned)
            for token, cfk in self.store.commands_for_key.items():
                if scan_ranges.contains_token(token):
                    acc = cfk.map_reduce_active(started_before, witnesses,
                                                lambda tid, a, t=token: fn(t, tid, a), acc)
            acc = self._scan_range_commands_ranges(scan_ranges, started_before,
                                                   witnesses, fn, acc)
        else:
            for token in keys_or_ranges.tokens():
                if not owned.contains_token(token):
                    continue
                cfk = self.store.commands_for_key.get(token)
                if cfk is not None:
                    acc = cfk.map_reduce_active(started_before, witnesses,
                                                lambda tid, a, t=token: fn(t, tid, a), acc)
                acc = self._scan_range_commands_token(token, started_before,
                                                      witnesses, fn, acc)
        return acc

    def _range_txn_live(self, tid: TxnId, started_before, witnesses) -> bool:
        if tid >= started_before or not witnesses.test(tid.kind()):
            return False
        cmd = self.store.command_maybe_paged(tid)
        return cmd is None or not cmd.is_invalidated()

    def _scan_range_commands_token(self, token: int, started_before, witnesses,
                                   fn, acc):
        for _s, _e, tid in self.store.range_index().stabbing(token):
            if self._range_txn_live(tid, started_before, witnesses):
                acc = fn(Ranges.of(Range(token, token + 1)), tid, acc)
        return acc

    def _scan_range_commands_ranges(self, scan: Ranges, started_before,
                                    witnesses, fn, acc):
        index = self.store.range_index()
        per_tid: Dict[TxnId, List[Range]] = {}
        for sel in scan:
            for s, e, tid in index.overlapping(sel.start, sel.end):
                per_tid.setdefault(tid, []).append(
                    Range(max(s, sel.start), min(e, sel.end)))
        for tid in sorted(per_tid):
            if self._range_txn_live(tid, started_before, witnesses):
                acc = fn(Ranges.of(*per_tid[tid]), tid, acc)
        return acc

    def map_reduce_full(self, keys_or_ranges, test_txn_id: TxnId,
                        witnesses: Kinds, fn, acc):
        """Recovery-time scan over ALL witnessed txns
        (ref: SafeCommandStore mapReduceFull).  Full ownership history for
        the same reason as map_reduce_active: recovery votes from a
        prior-epoch replica must include its old-range witnesses."""
        owned = self.store.ranges_for_epoch.all()
        if isinstance(keys_or_ranges, Ranges):
            scan_ranges = keys_or_ranges.slice(owned)
            for token, cfk in self.store.commands_for_key.items():
                if scan_ranges.contains_token(token):
                    acc = cfk.map_reduce_full(test_txn_id, witnesses,
                                              lambda info, a, t=token: fn(t, info, a), acc)
            for tid, ranges in self.store.range_commands.items():
                if witnesses.test(tid.kind()) and not ranges.intersecting(scan_ranges).is_empty():
                    cmd = self.store.command_maybe_paged(tid)
                    info = _range_txn_info(tid, cmd)
                    if info is not None:
                        acc = fn(ranges[0].start, info, acc)
        else:
            for token in keys_or_ranges.tokens():
                if not owned.contains_token(token):
                    continue
                cfk = self.store.commands_for_key.get(token)
                if cfk is not None:
                    acc = cfk.map_reduce_full(test_txn_id, witnesses,
                                              lambda info, a, t=token: fn(t, info, a), acc)
                for tid, ranges in self.store.range_commands.items():
                    if witnesses.test(tid.kind()) and ranges.contains_token(token):
                        cmd = self.store.command_maybe_paged(tid)
                        info = _range_txn_info(tid, cmd)
                        if info is not None:
                            acc = fn(token, info, acc)
        return acc

    # -- watermarks ---------------------------------------------------------
    def ranges(self, epoch: int) -> Ranges:
        return self.store.owned_at(epoch)

    def max_conflict(self, keys_or_ranges) -> Timestamp:
        return self.store.max_conflicts.get_max(keys_or_ranges)

    def update_max_conflicts(self, keys_or_ranges, ts: Timestamp) -> None:
        self.store.max_conflicts.update(keys_or_ranges, ts)

    def redundant_before(self) -> RedundantBefore:
        return self.store.redundant_before

    def durable_before(self) -> DurableBefore:
        return self.store.durable_before

    def progress_log(self):
        return self.store.progress_log

    def node(self):
        return self.store.node

    def time(self):
        return self.store.node

    def agent(self):
        return self.store.node.agent

    def data_store(self):
        return self.store.node.data_store

    # -- completion ---------------------------------------------------------
    def complete(self) -> None:
        """Flush deferred listener notifications (each as its own store task,
        mirroring the reference's executor hand-off per listener update)."""
        if self._completed:
            return
        self._completed = True
        self.flush_pending()

    def flush_pending(self) -> None:
        """Emit the deferred notifications accumulated so far, leaving the
        safe view open.  This is the r20 grouped drain's OP-BOUNDARY flush:
        called after each sub-op's fn, it queues that op's notification
        task exactly where the per-op ``complete()`` would have — same
        store-queue order, same listener_update sequence."""
        notifications, self._pending_notifications = self._pending_notifications, []
        transients, self._pending_transients = self._pending_transients, []
        if not notifications and not transients:
            return
        from . import commands as commands_mod

        def run(safe: "SafeCommandStore"):
            for listener_id, updated_id in notifications:
                commands_mod.listener_update(safe, listener_id, updated_id)
            for txn_id in transients:
                cmd = safe.if_present(txn_id)
                if cmd is not None:
                    safe.notify_transient(cmd)
        self.store.execute(PreLoadContext.empty(), run)


def _range_txn_info(tid: TxnId, cmd: Optional[Command]):
    from .commands_for_key import InternalStatus, TxnInfo
    if cmd is None:
        return TxnInfo(tid, InternalStatus.TRANSITIVELY_KNOWN)
    if cmd.is_invalidated():
        return TxnInfo(tid, InternalStatus.INVALIDATED)
    from .status import Status
    if cmd.has_been(Status.Applied):
        st = InternalStatus.APPLIED
    elif cmd.has_been(Status.Stable):
        st = InternalStatus.STABLE
    elif cmd.has_been(Status.Committed):
        st = InternalStatus.COMMITTED
    elif cmd.has_been(Status.Accepted):
        st = InternalStatus.ACCEPTED
    else:
        st = InternalStatus.PREACCEPTED
    return TxnInfo(tid, st, cmd.execute_at)


class CommandStores:
    """The shard group for one node (ref: local/CommandStores.java:78)."""

    def __init__(self, node, num_stores: int = 1, distributor=None):
        from .shard_distributor import EvenSplit
        self.node = node
        self.num_stores = num_stores
        self.stores: List[CommandStore] = []
        self._next_id = 0
        # pluggable range->store policy (ref: local/ShardDistributor.java)
        self.distributor = distributor if distributor is not None \
            else EvenSplit()

    # -- topology -----------------------------------------------------------
    def update_topology(self, topology, epoch: Optional[int] = None,
                        bootstrap: bool = True) -> None:
        """Assign this node's owned ranges across stores and bootstrap any
        newly-adopted ranges (ref: CommandStores.updateTopology :401-482).

        Assignment is STICKY: ranges a store already holds never migrate to
        a sibling store (moving them would spuriously re-bootstrap data this
        node already serves); only net-new ranges are distributed, evenly by
        token span (ShardDistributor.EvenSplit analogue)."""
        epoch = epoch if epoch is not None else topology.epoch
        owned = topology.ranges_for_node(self.node.node_id)
        first = not self.stores
        if first:
            for _ in range(self.num_stores):
                store = CommandStore(self._next_id, self.node,
                                     paged_limit=getattr(self.node,
                                                         "paged_limit", None))
                self._next_id += 1
                self.stores.append(store)
            for store, chunk in zip(self.stores,
                                    self.distributor.split(owned, len(self.stores))):
                store.ranges_for_epoch.snapshot(epoch, chunk)
            return

        prev_union = Ranges.empty()
        for store in self.stores:
            prev_union = prev_union.with_(store.ranges_for_epoch.current())
        net_new = owned.without(prev_union)
        new_chunks = self.distributor.split(net_new, len(self.stores))
        for store, extra in zip(self.stores, new_chunks):
            retained = store.ranges_for_epoch.current().intersecting(owned)
            store.ranges_for_epoch.snapshot(epoch, retained.with_(extra))
            if not extra.is_empty() and bootstrap:
                from .bootstrap import Bootstrap
                Bootstrap(store, extra, epoch).start()

    # -- scatter-gather -----------------------------------------------------
    def intersecting(self, select: Unseekables, min_epoch: int,
                     max_epoch: int) -> List[CommandStore]:
        out = []
        for store in self.stores:
            owned = store.ranges_for_epoch.all_between(min_epoch, max_epoch)
            if not owned.is_empty() and (
                    select.intersects(owned) if not isinstance(select, Ranges)
                    else owned.intersects(select)):
                out.append(store)
        return out

    def for_each(self, context: PreLoadContext, select: Unseekables,
                 min_epoch: int, max_epoch: int,
                 fn: Callable[[SafeCommandStore], None]) -> async_chain.AsyncChain:
        stores = self.intersecting(select, min_epoch, max_epoch)
        chains = [s.execute(context, fn) for s in stores]
        return async_chain.all_of(chains).map(lambda _: None)

    def map_reduce(self, context: PreLoadContext, select: Unseekables,
                   min_epoch: int, max_epoch: int,
                   map_fn: Callable[[SafeCommandStore], "object"],
                   reduce_fn: Callable[["object", "object"], "object"]
                   ) -> async_chain.AsyncChain:
        """(ref: CommandStores.mapReduce :575-643)."""
        stores = self.intersecting(select, min_epoch, max_epoch)
        if not stores:
            return async_chain.success(None)
        chains = [s.execute(context, map_fn) for s in stores]
        return async_chain.reduce(chains, reduce_fn)

    def unavailable_for_read(self, participants) -> bool:
        """Safe-to-read gate: any intersecting store still bootstrapping its
        snapshot cannot serve reads (ref: safeToRead,
        local/CommandStore.java:159-176)."""
        return bool(self._read_blockers(participants))

    def _read_blockers(self, participants) -> List[CommandStore]:
        return [s for s in self.stores
                if not s.bootstrapping.is_empty()
                and participants.intersects(s.bootstrapping)]

    def when_readable(self, participants, fn: Callable[[], None],
                      on_unavailable: Optional[Callable[[], None]] = None,
                      deadline_micros: int = 500_000) -> None:
        """Run ``fn`` once no intersecting store is mid-bootstrap — reads
        DEFER behind the safe-to-read gate rather than refusing (the
        reference's ReadData waits on safeToRead; refusing turns every
        bootstrap window into read unavailability for the whole shard).

        The deferral carries a deadline: a bootstrap can itself be gated on
        transactions whose Apply needs this read (the fence awaits every
        lower TxnId), so waiting forever deadlocks the cycle.  Past the
        deadline, ``on_unavailable`` fires and the coordinator falls back to
        another replica / recovery, which breaks the cycle."""
        blockers = self._read_blockers(participants)
        if not blockers:
            fn()
            return
        state = {"n": len(blockers), "fired": False}

        def one_done():
            state["n"] -= 1
            if state["n"] == 0 and not state["fired"]:
                state["fired"] = True
                # re-check: another bootstrap may have begun meanwhile
                self.when_readable(participants, fn, on_unavailable,
                                   deadline_micros)

        def expire():
            if not state["fired"]:
                state["fired"] = True
                # drop the dead waiters: a wedged bootstrap must not pin one
                # read continuation per expired deferral for its whole outage
                for s in blockers:
                    try:
                        s._bootstrap_waiters.remove(one_done)
                    except ValueError:
                        pass
                if on_unavailable is not None:
                    on_unavailable()

        for s in blockers:
            s.defer_until_bootstrap(one_done)
        if on_unavailable is not None:
            self.node.scheduler.once(deadline_micros, expire)

    def unsafe_all_stores(self) -> List[CommandStore]:
        return list(self.stores)
