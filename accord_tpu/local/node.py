"""The per-process node facade.

Rebuild of ref: accord-core/src/main/java/accord/local/Node.java:100-780 —
owns the MessageSink, TopologyManager, CommandStores, the HLC
(``unique_now`` CAS loop :341-366), the coordinate() entry point (:567-596),
receive() dispatch (:715-736), epoch await (:296-329) and home/progress key
selection (:598-673).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from .. import api
from ..primitives.keys import Ranges, Route, RoutingKeys, Seekables
from ..primitives.timestamp import Domain, Timestamp, TxnId, TxnKind
from ..primitives.txn import Txn
from ..topology.manager import TopologyManager
from ..topology.topology import Topologies, Topology
from ..utils import async_chain, invariants
from .command_store import CommandStores, PreLoadContext
from .fastpath import proto_fastpath_enabled

_FASTPATH = proto_fastpath_enabled()


def _resolve_device_mode(device_mode: Optional[bool]) -> bool:
    """Device (TPU kernel) protocol path: explicit arg > ACCORD_TPU_DEVICE
    env > on iff 64-bit JAX is enabled (the kernels' precondition — the test
    conftest, bench, burn CLI and graft entries all enable it at startup)."""
    if device_mode is not None:
        return device_mode
    import os
    env = os.environ.get("ACCORD_TPU_DEVICE")
    if env is not None:
        return env.lower() not in ("0", "false", "off", "")
    import jax
    return bool(jax.config.jax_enable_x64)


class Node:
    """(ref: local/Node.java)."""

    def __init__(self, node_id: int,
                 message_sink: api.MessageSink,
                 config_service: api.ConfigurationService,
                 scheduler: api.Scheduler,
                 data_store: api.DataStore,
                 agent: api.Agent,
                 random,
                 now_micros: Callable[[], int],
                 progress_log_factory: Optional[Callable] = None,
                 num_stores: int = 2,
                 local_config: Optional[api.LocalConfig] = None,
                 device_mode: Optional[bool] = None,
                 journal=None,
                 paged_limit: Optional[int] = None):
        self.node_id = node_id
        # journal-backed command paging threshold (None = keep everything)
        self.paged_limit = paged_limit
        self.message_sink = message_sink
        self.config_service = config_service
        self.scheduler = scheduler
        self.data_store = data_store
        self.agent = agent
        self.random = random
        self.now_micros = now_micros
        self.local_config = local_config or api.LocalConfig()
        self.device_mode = _resolve_device_mode(device_mode)
        if progress_log_factory is None:
            from ..impl.progress_log import SimpleProgressLog
            progress_log_factory = SimpleProgressLog
        self.progress_log_factory = progress_log_factory
        self.topology_manager = TopologyManager(node_id)
        # observability bundle (obs.Observability) the harness attaches —
        # the sim cluster and maelstrom runner share one per run; None
        # means unobserved (zero cost beyond getattr+None checks)
        self.obs = None
        # per-node device dispatch scheduler (r08): coalesces deps flushes
        # and drain ticks across this node's CommandStores into fused
        # kernel launches when the cost model says fusion wins; None in
        # pure host mode (no device launches to coalesce)
        if self.device_mode:
            from .dispatch import DeviceDispatcher
            self.dispatcher = DeviceDispatcher(self)
        else:
            self.dispatcher = None
        self.command_stores = CommandStores(self, num_stores)
        self.journal = journal
        self.alive = True
        self._hlc = 0
        self._hlc_reserved = 0
        if journal is not None:
            # a restarted incarnation must never reissue a timestamp the
            # previous one used: the journal's high-water mark bounds every
            # id this node WITNESSED, and the flush-before-issue reservation
            # (reserve_hlc) bounds every id a past incarnation ISSUED — even
            # one whose PreAccepts were all dropped in a partition
            self._hlc = max(journal.max_hlc + 1, journal.hlc_reserved)
            self._hlc_reserved = journal.hlc_reserved
        self._coordinating: Dict[TxnId, object] = {}  # active coordinations
        self._pending_topologies: Dict[int, Topology] = {}  # out-of-order epochs
        # PROTO_FASTPATH: (topology, owned Ranges) pair for _owned_ranges
        self._owned_memo = None
        # r20 store-grouped execution counters (the serving stats surface
        # reads them): ops delivered through receive_group, and ops that
        # fell back to the per-op path (cross-epoch waits at receive_group;
        # control verbs / reconfig gossip at the envelope unbatcher)
        self.n_grouped_ops = 0
        self.n_group_fallbacks = 0

    # -- time (ref: Node.java:341-366) --------------------------------------
    HLC_RESERVE_BATCH = 1 << 20   # ids per journal reservation write

    def _reserve_hlc(self) -> None:
        """Flush-before-issue, batched: before handing out an id at or past
        the journaled reservation, persist a new bound ``hlc + K`` — one
        journal write per ~million ids buys an exact restart floor."""
        if self.journal is not None and self._hlc >= self._hlc_reserved:
            self._hlc_reserved = self._hlc + self.HLC_RESERVE_BATCH
            self.journal.reserve_hlc(self._hlc_reserved)

    def unique_now(self) -> Timestamp:
        now = self.now_micros()
        self._hlc = max(self._hlc + 1, now)
        self._reserve_hlc()
        return Timestamp.from_values(self.epoch(), self._hlc, self.node_id)

    def unique_now_at_least(self, at_least: Timestamp) -> Timestamp:
        now = self.now_micros()
        self._hlc = max(self._hlc + 1, now, at_least.hlc() + 1)
        self._reserve_hlc()
        epoch = max(self.epoch(), at_least.epoch())
        return Timestamp.from_values(epoch, self._hlc, self.node_id)

    def now(self) -> Timestamp:
        return Timestamp.from_values(self.epoch(), self.now_micros(), self.node_id)

    def next_txn_id(self, kind: TxnKind, domain: Domain) -> TxnId:
        ts = self.unique_now()
        return TxnId.create(ts.epoch(), ts.hlc(), kind, domain, self.node_id)

    # -- topology -----------------------------------------------------------
    def epoch(self) -> int:
        return self.topology_manager.epoch()

    def topology(self) -> TopologyManager:
        return self.topology_manager

    def on_topology_update(self, topology: Topology) -> None:
        """(ref: Node.java:247 ConfigurationService.Listener).  Epochs must
        be ingested contiguously; later epochs arriving early are buffered."""
        if self.topology_manager.has_epoch(topology.epoch):
            return
        known = self.topology_manager.epoch()
        if known != 0 and topology.epoch > known + 1:
            self._pending_topologies[topology.epoch] = topology
            self.config_service.fetch_topology_for_epoch(known + 1)
            return
        first = known == 0
        self.topology_manager.on_topology_update(topology)
        self.command_stores.update_topology(topology)
        if not first:
            self._start_epoch_sync(topology)
        nxt = self._pending_topologies.pop(topology.epoch + 1, None)
        if nxt is not None:
            self.on_topology_update(nxt)

    def restore_topologies(self, topologies) -> None:
        """Restart path: re-ingest the epoch history WITHOUT re-bootstrapping
        (the data store is durable; the journal restores the metadata) and
        without re-fencing every historical epoch (the previous incarnation
        already synced them — the reject_before fences themselves come back
        via journal reconstruction of the sync-point commands)."""
        latest = None
        for topology in sorted(topologies, key=lambda t: t.epoch):
            if self.topology_manager.has_epoch(topology.epoch):
                continue
            self.topology_manager.on_topology_update(topology)
            self.command_stores.update_topology(topology, bootstrap=False)
            latest = topology
        if latest is not None:
            self._ack_epoch(latest.epoch)

    def _start_epoch_sync(self, topology: Topology) -> None:
        """Fence the new epoch: an ExclusiveSyncPoint over our owned ranges
        captures every in-flight earlier txn; once it executes, this node's
        view is caught up and it acks the epoch so coordination can use the
        new topology's fast path (ref: TopologyManager epoch sync,
        CommandStores.updateTopology sync leg)."""
        from ..coordinate.sync_point import coordinate_sync_point
        epoch = topology.epoch
        owned = topology.ranges_for_node(self.node_id)
        if owned.is_empty():
            self._ack_epoch(epoch)
            return
        sync_id = self.next_txn_id(TxnKind.ExclusiveSyncPoint, Domain.Range)

        def on_done(_sp, failure):
            if failure is not None:
                # Invalidate the abandoned fence id FIRST: replicas that
                # witnessed it hold it in later txns' dep sets, and an
                # undecided zombie dep stalls their execution until a slow
                # recovery cycle invalidates it.  Then retry with a fresh id
                # after a jittered backoff (don't stampede a recovery that
                # may be finishing the old one — invalidation is
                # best-effort and loses cleanly to a live ballot).
                self.agent.on_handled_exception(failure)
                self.invalidate_abandoned(sync_id, owned)
                delay = 1_000_000 + self.random.next_int(1_000_000)
                self.scheduler.once(delay,
                                    lambda: self._start_epoch_sync(topology))
            else:
                self._ack_epoch(epoch)

        coordinate_sync_point(self, owned, exclusive=True,
                              txn_id=sync_id).begin(on_done)

    def invalidate_abandoned(self, txn_id: TxnId, participants) -> None:
        """Best-effort invalidation of a coordination this node is
        abandoning (a fence id it will not retry).  If the txn actually
        decided somewhere, the invalidation ballot loses and recovery
        completes it — either terminal state unblocks waiters."""
        from ..coordinate.recover import _next_ballot_bits, _propose_invalidate
        from ..primitives.keys import Route as _Route
        from ..primitives.timestamp import Ballot
        route = _Route(None, participants, is_full=False)
        ballot = Ballot(*_next_ballot_bits(self))
        try:
            topologies = self.topology().for_epoch(participants,
                                                   txn_id.epoch())
        except Exception:
            return
        _propose_invalidate(self, txn_id, route, ballot, topologies,
                            on_invalidated=lambda: None,
                            on_redundant=lambda: None,
                            on_failed=lambda _f: None)

    def _ack_epoch(self, epoch: int) -> None:
        self.topology_manager.on_epoch_sync_complete(self.node_id, epoch)
        self.config_service.acknowledge_epoch(api.EpochReady.done(epoch))

    def with_epoch(self, epoch: int, fn: Callable[[], None]) -> None:
        """Run fn once the epoch's topology is known (ref: Node.java:296-329)."""
        if self.topology_manager.has_epoch(epoch):
            fn()
            return
        self.config_service.fetch_topology_for_epoch(epoch)
        self.topology_manager.await_epoch(epoch).begin(
            lambda _t, fail: fn() if fail is None else
            self.agent.on_uncaught_exception(fail))

    # -- routing (ref: Node.java:598-673) -----------------------------------
    def compute_route(self, txn_id: TxnId, keys: Seekables) -> Route:
        home_key = self.select_home_key(txn_id, keys)
        return Route.full(home_key, keys.to_unseekables())

    def _owned_ranges(self) -> Ranges:
        """This node's owned ranges in the CURRENT topology.  Topology is
        immutable and ``ranges_for_node`` allocates a fresh Ranges per
        call, so under PROTO_FASTPATH the answer is cached keyed on the
        topology object's identity (one entry — replaced on epoch change)
        instead of being rebuilt for every message's progress-key probe."""
        topology = self.topology_manager.current()
        if not _FASTPATH:
            return topology.ranges_for_node(self.node_id)
        cached = self._owned_memo
        if cached is None or cached[0] is not topology:
            cached = (topology, topology.ranges_for_node(self.node_id))
            self._owned_memo = cached
        return cached[1]

    def select_home_key(self, txn_id: TxnId, keys: Seekables) -> int:
        """Pick a home key among the txn's keys, preferring one this node
        owns (ref: Node.selectHomeKey)."""
        owned = self._owned_ranges()
        if isinstance(keys, Ranges):
            for r in keys:
                if owned.contains_token(r.start):
                    return r.start
            return keys[0].start
        for k in keys:
            if owned.contains_token(k.token()):
                return k.token()
        return keys[0].token()

    def select_progress_key(self, txn_id: TxnId, route: Route) -> Optional[int]:
        """The home key if we replicate it, else None (ref: Node.java:652-673)."""
        owned = self._owned_ranges()
        return route.home_key if owned.contains_token(route.home_key) else None

    def is_home_shard_replica(self, txn_id: TxnId, route: Route) -> bool:
        return self._owned_ranges().contains_token(route.home_key)

    # -- messaging ----------------------------------------------------------
    def send(self, to: int, request,
             callback: Optional[api.Callback] = None) -> None:
        if callback is not None:
            self.message_sink.send_with_callback(to, request, callback)
        else:
            self.message_sink.send(to, request)

    def send_to_all(self, nodes, request_factory,
                    callback: Optional[api.Callback] = None) -> None:
        for to in sorted(nodes):
            self.send(to, request_factory(to), callback)

    def reply(self, to: int, reply_context, reply) -> None:
        self.message_sink.reply(to, reply_context, reply)

    def receive(self, request, from_id: int, reply_context) -> None:
        """(ref: Node.java:715-736)."""
        wait_for = getattr(request, "wait_for_epoch", 0)
        if wait_for > self.topology_manager.epoch():
            self.config_service.fetch_topology_for_epoch(wait_for)
            self.topology_manager.await_epoch(wait_for).begin(
                lambda _t, fail: self.receive(request, from_id, reply_context)
                if fail is None else None)
            return
        self.scheduler.now(lambda: self._process(request, from_id, reply_context))

    def receive_group(self, items, from_id: int) -> None:
        """r20 store-grouped delivery: a run of protocol requests from one
        ``accord_batch`` envelope processes under ONE scheduler hop — the
        per-op ``_process`` bodies run back-to-back in a single callback,
        so their store tasks land in one queue tick and the grouped drain
        merges them under one SafeCommandStore.  Per-op semantics are
        unchanged: each item gets the same epoch gate, witness stamps,
        journal record and handler body it would get via ``receive``.
        Items awaiting a later epoch fall back to the per-op path (the
        grouper cannot prove when their wait resolves)."""
        ready = []
        for request, reply_context in items:
            wait_for = getattr(request, "wait_for_epoch", 0)
            if wait_for > self.topology_manager.epoch():
                self.n_group_fallbacks += 1
                self.receive(request, from_id, reply_context)
            else:
                ready.append((request, reply_context))
        if not ready:
            return
        self.n_grouped_ops += len(ready)

        def run():
            for request, reply_context in ready:
                self._process(request, from_id, reply_context)

        self.scheduler.now(run)

    def witness_timestamp(self, ts) -> None:
        """HLC receive rule: merge a remotely-witnessed timestamp into the
        local clock so later ids exceed it (ref: Node.java uniqueNow(atLeast)
        — without it, a node with a lagging physical clock keeps issuing ids
        below its peers' epoch fences and every txn it coordinates bounces)."""
        h = ts.hlc()
        if h > self._hlc:
            self._hlc = h

    def _process(self, request, from_id: int, reply_context) -> None:
        tid = getattr(request, "txn_id", None)
        if tid is not None:
            self.witness_timestamp(tid)
        ex = getattr(request, "execute_at", None)
        if ex is not None:
            self.witness_timestamp(ex)
        if self.journal is not None and request.type.has_side_effects:
            self.journal.record_message(request, from_id)
        try:
            request.process(self, from_id, reply_context)
        except BaseException as e:  # noqa: BLE001
            try:
                self.message_sink.reply_with_unknown_failure(from_id, reply_context, e)
            except BaseException:
                pass
            self.agent.on_handled_exception(e)

    # -- local scatter-gather (ref: Node.java mapReduceConsumeLocal) --------
    def map_reduce_consume_local(self, context: PreLoadContext, select,
                                 min_epoch: int, max_epoch: int, map_fn,
                                 reduce_fn, consume: Callable) -> None:
        chain = self.command_stores.map_reduce(context, select, min_epoch,
                                               max_epoch, map_fn, reduce_fn)
        chain.begin(lambda result, fail: consume(result, fail))

    def for_each_local(self, context: PreLoadContext, select, min_epoch: int,
                       max_epoch: int, fn) -> async_chain.AsyncChain:
        return self.command_stores.for_each(context, select, min_epoch,
                                            max_epoch, fn)

    # -- coordination entry (ref: Node.java:567-596) ------------------------
    def coordinate(self, txn: Txn,
                   txn_id: Optional[TxnId] = None,
                   _retries: int = 0) -> async_chain.AsyncResult:
        from ..coordinate.coordinate_transaction import CoordinateTransaction
        from ..coordinate.errors import Rejected
        if txn.kind is TxnKind.EphemeralRead:
            # non-durable: no consensus rounds, no recovery, no watchdog —
            # a failure surfaces to the caller, who simply retries
            # (ref: CoordinateEphemeralRead)
            from ..coordinate.ephemeral import coordinate_ephemeral_read
            return coordinate_ephemeral_read(self, txn)
        explicit_id = txn_id is not None
        if txn_id is None:
            txn_id = self.next_txn_id(txn.kind, txn.domain())
        result = async_chain.AsyncResult()
        self._coordinating[txn_id] = result
        result.begin(lambda _r, _f: self._coordinating.pop(txn_id, None))

        from ..obs import spans_of
        sp = spans_of(self)
        if sp is not None:
            # root span of this txn's tree: the client-visible window.
            # Phase children (preaccept/accept/stable/read/apply) attach
            # in the coordinate FSMs; a fence-Rejected retry runs under a
            # FRESH TxnId, so the retry's tree is its own root — the
            # ``retries`` attr counts the hop and the old root carries
            # the terminating ``retry`` event.
            sp.begin_txn(str(txn_id), node=self.node_id,
                         kind=txn.kind.name, retries=_retries)
            result.begin(lambda _r, f: sp.end_txn(
                str(txn_id), "ok" if f is None else type(f).__name__))

        superseded = {"flag": False}

        def settle(value, failure):
            # A caller-pinned TxnId (sync-point fences: the id IS the
            # bootstrap/epoch watermark) must NOT be transparently swapped
            # for a fresh one — propagate Rejected so the caller re-picks
            # its fence id and re-marks its watermark.
            if isinstance(failure, Rejected) and not explicit_id \
                    and _retries < 5:
                # fenced by an ExclusiveSyncPoint: the TxnId can never newly
                # decide here — but unfenced replicas may retain (fast-path)
                # PreAccepts of it that a later recovery could complete.
                # Invalidate the old id FIRST (always immediately — it runs
                # in the OLD id's epoch), and only then retry with a fresh
                # id (ref: CoordinateTransaction.java:87-94
                # proposeAndCommitInvalidate before any client retry);
                # retrying immediately risks the payload applying under both
                # ids.  Mark this attempt superseded so its watchdog does
                # not race the invalidation.  When the rejecting fence's
                # bound is known, bump the HLC past it so the fresh id
                # clears the fence; a fence minted in a LATER epoch
                # additionally makes the retry wait for that topology
                # (epoch-major timestamps — see _invalidate_then_retry).
                floor = getattr(failure, "floor", None)
                retry_epoch = None
                if floor is not None:
                    self.unique_now_at_least(floor)
                    if floor.epoch() > self.epoch():
                        retry_epoch = floor.epoch()
                if sp is not None:
                    # the old id's tree ends here; the retry's fresh id
                    # opens its own root (retries attr links the hop count)
                    sp.event(str(txn_id), "retry",
                             reason="Rejected", attempt=_retries + 1)
                    sp.end_txn(str(txn_id), "Rejected-retried")
                superseded["flag"] = True
                self._coordinating.pop(txn_id, None)
                self._invalidate_then_retry(txn, txn_id, _retries, result,
                                            retry_at_epoch=retry_epoch)
                return
            result.settle(value, failure)

        def start():
            CoordinateTransaction.coordinate(self, txn_id, txn).begin(settle)
            self.scheduler.once(15_000_000, watchdog)

        def watchdog():
            # a coordination whose every round was lost/preempted can wedge
            # while the txn itself reaches a terminal outcome via recovery;
            # adopt that outcome for the client (ref: the coordinator-side
            # Recover adoption in Node.recover / CoordinationAdapter)
            if result.is_done() or superseded["flag"]:
                return
            from ..coordinate.recover import Recover
            if sp is not None:
                sp.event(str(txn_id), "watchdog_recover")
            route = self.compute_route(txn_id, txn.keys)
            Recover.recover(self, txn_id, route, txn).begin(on_recovered)

        def on_recovered(value, failure):
            if result.is_done() or superseded["flag"]:
                return
            if failure is not None:
                from ..coordinate.errors import Invalidated, Truncated
                if isinstance(failure, (Truncated, Invalidated)):
                    # terminal: the txn's window is below the redundancy
                    # watermark with no decided state reachable — the op is
                    # indeterminate for the client; retrying the recovery
                    # can never learn more (ref: Infer's truncated-outcome
                    # mapping in coordinate/Infer.java)
                    result.set_failure(failure)
                    return
                self.agent.on_handled_exception(failure)
                self.scheduler.once(5_000_000, watchdog)
                return
            outcome, payload = value
            if outcome == "invalidated":
                from ..coordinate.errors import Invalidated
                result.set_failure(Invalidated(txn_id))
            elif outcome in ("applied", "executed") and payload is not None:
                result.set_success(payload)
            else:
                # applied but the outcome was already erased everywhere we
                # asked: the txn took effect but the client result is gone
                from ..coordinate.errors import Truncated
                result.set_failure(Truncated(txn_id))

        self.with_epoch(txn_id.epoch(), start)
        return result

    def _invalidate_then_retry(self, txn: Txn, old_id: TxnId, retries: int,
                               result: async_chain.AsyncResult,
                               attempt: int = 0,
                               retry_at_epoch: Optional[int] = None) -> None:
        """Invalidate a fence-Rejected TxnId before the client retry
        (ref: coordinate/Invalidate.java proposeAndCommitInvalidate via
        CoordinateTransaction.java:87-94).  If invalidation reports the old
        id redundant — it actually decided somewhere — adopt its outcome
        instead of issuing a duplicate transaction.  ``retry_at_epoch``
        makes the FRESH id wait for a later fence epoch's topology;
        invalidation itself always runs immediately in the old id's epoch
        (deferring it would leave recoverable PreAccepts of the old id
        while the client already resubmitted — the double-apply hazard)."""
        from ..coordinate.recover import (Recover, _next_ballot_bits,
                                          _propose_invalidate)
        from ..primitives.timestamp import Ballot
        route = self.compute_route(old_id, txn.keys)
        ballot = Ballot(*_next_ballot_bits(self))
        topologies = self.topology().for_epoch(route.participants,
                                               old_id.epoch())

        def retry():
            def go():
                self.coordinate(txn, _retries=retries + 1).begin(
                    result.settle)
            if retry_at_epoch is None or retry_at_epoch <= self.epoch():
                go()
                return
            fired = {"flag": False}

            def once():
                if not fired["flag"]:
                    fired["flag"] = True
                    go()

            # await_epoch never fails on its own: back it with a deadline
            # that retries in the CURRENT epoch rather than hanging the
            # client (the fresh id may be re-rejected, but retries are
            # bounded and the old id is already invalidated)
            self.with_epoch(retry_at_epoch, once)
            self.scheduler.once(15_000_000, once)

        def adopt():
            # the old id reached a decision after all: finish it and hand
            # its outcome to the client rather than re-running the payload
            Recover.recover(self, old_id, route, txn).begin(adopted)

        def adopted(value, failure):
            if failure is not None:
                result.set_failure(failure)
                return
            outcome, payload = value
            if outcome == "invalidated":
                retry()
            elif outcome in ("applied", "executed") and payload is not None:
                result.set_success(payload)
            else:
                from ..coordinate.errors import Truncated
                result.set_failure(Truncated(old_id))

        def failed(failure):
            if attempt < 3:
                delay = 500_000 + self.random.next_int(500_000)
                self.scheduler.once(delay, lambda: self._invalidate_then_retry(
                    txn, old_id, retries, result, attempt + 1))
            else:
                result.set_failure(failure)

        _propose_invalidate(self, old_id, route, ballot, topologies,
                            on_invalidated=retry, on_redundant=adopt,
                            on_failed=failed)

    def recover(self, txn_id: TxnId, route: Route) -> async_chain.AsyncResult:
        """(ref: Node.java:685-713)."""
        from ..coordinate.recover import Recover
        existing = self._coordinating.get(txn_id)
        if existing is not None:
            return existing
        result = async_chain.AsyncResult()
        self._coordinating[txn_id] = result
        result.begin(lambda _r, _f: self._coordinating.pop(txn_id, None))

        def start():
            Recover.recover(self, txn_id, route).begin(result.settle)

        self.with_epoch(txn_id.epoch(), start)
        return result

    def __repr__(self):
        return f"Node({self.node_id})"
