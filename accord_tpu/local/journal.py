"""Message-sourced durability: the per-node journal and restart/reload
reconstruction.

Rebuild of ref: accord-core/src/main/java/accord/local/SerializerSupport.java:96-420
and the simulation journal accord-core/src/test/java/accord/impl/basic/
Journal.java:82-171 + DelayedCommandStores.java:96-175.

The reference persists, per command, a handful of fixed-size *registers*
(SaveStatus, executeAt, promised/accepted ballots, durability) and
reconstructs every variable-size field (txn, deps, writes, result, route)
from the set of witnessed side-effecting *messages*
(``MessageType.hasSideEffects``, ``SerializerSupport.reconstruct``).  We keep
exactly that split:

- ``record_registers`` is hooked at the single command-update choke point
  (SafeCommandStore.update) — the registers are precisely the fixed-width
  columns of the command's struct-of-arrays form;
- ``record_message`` is hooked at Node._process for side-effecting verbs;
  local knowledge upgrades (coordinate/fetch_data.propagate) record the
  merged CheckStatusOk, mirroring the reference's PROPAGATE_* local messages;
- bootstrap watermarks/progress are tiny auxiliary records (the reference
  persists RedundantBefore et al as per-store fields via its integration's
  storage; only Commands are message-sourced).

Reconstruction comes in two grains:
- ``restore(node)``: full node restart — rebuild every store's commands,
  per-key conflict indexes, watermark maps and fences, then resume the
  execution drain;
- ``evict_and_reload(store, txn_id)``: the reference's cache-eviction test
  (random ``isLoadedCheck`` evictions) — drop one command and rebuild it
  from the journal in place, proving the serialization contract.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..primitives.keys import Ranges
from ..primitives.timestamp import Ballot, Timestamp, TxnId, TxnKind
from ..utils import invariants
from .command import Command, WaitingOn
from .status import Durability, SaveStatus, Status


# Message-body slots per txn, by what reconstruct() needs from them
# (ref: SerializerSupport PRE_ACCEPT_TYPES / ACCEPT / COMMIT / APPLY sets;
# txn bodies are captured generically from any message carrying one).
_COMMIT_TYPES = ("COMMIT_SLOW_PATH_REQ", "COMMIT_MAXIMAL_REQ",
                 "STABLE_FAST_PATH_REQ", "STABLE_SLOW_PATH_REQ",
                 "STABLE_MAXIMAL_REQ")
_APPLY_TYPES = ("APPLY_MINIMAL_REQ", "APPLY_MAXIMAL_REQ",
                "APPLY_THEN_WAIT_UNTIL_APPLIED_REQ")


class _Registers:
    """Fixed-width persisted columns of one command on one store
    (ref: the register args of SerializerSupport.reconstruct)."""

    __slots__ = ("save_status", "execute_at", "promised", "accepted",
                 "durability")

    def __init__(self, save_status: SaveStatus,
                 execute_at: Optional[Timestamp],
                 promised: Ballot, accepted: Ballot, durability: Durability):
        self.save_status = save_status
        self.execute_at = execute_at
        self.promised = promised
        self.accepted = accepted
        self.durability = durability


class _Bodies:
    """Witnessed side-effecting message bodies for one txn."""

    __slots__ = ("txn", "route", "accepts", "commit", "apply", "propagate")

    def __init__(self):
        self.txn = None          # latest full/partial txn seen in any message
        self.route = None
        self.accepts: List[Tuple[Ballot, object]] = []   # (ballot, request)
        self.commit = None       # best-hydration Commit request
        self.apply = None        # Apply request
        self.propagate = None    # merged CheckStatusOk from fetch_data


class Journal:
    """One node's durable log (survives Node object death)."""

    def __init__(self):
        self._bodies: Dict[TxnId, _Bodies] = {}
        self._registers: Dict[int, Dict[TxnId, _Registers]] = {}
        # per-store durable watermark snapshots, latest-wins (bounded —
        # replaying every SetShardDurable verb would grow with run length)
        self._watermarks: Dict[int, Tuple[list, list]] = {}
        # per-store bootstrap progress, NETTED: cumulative started ranges,
        # currently-done ranges, and the max fence watermark per range
        self._bs_started: Dict[int, Ranges] = {}
        self._bs_done: Dict[int, Ranges] = {}
        self._bs_marks: Dict[int, List[Tuple[Ranges, TxnId]]] = {}
        self.max_hlc = 0
        # flush-before-issue HLC reservation: a true upper bound on every
        # timestamp this node's past incarnations may have ISSUED (max_hlc
        # only bounds what got journaled somewhere — a coordinator whose
        # PreAccepts were all dropped could otherwise reissue a TxnId)
        self.hlc_reserved = 0
        self.restoring = False
        # diagnostics: reconstructions that had to degrade status for lack
        # of a message body (should stay 0 in healthy runs)
        self.degraded = 0
        # topology epoch ledger (r17, elastic serving): plain epoch docs
        # (net.reconfig.topology_to_doc shape), ascending, deduped by
        # epoch — a restarted node recovers the epoch history it had
        # ingested, including a proposal journaled but never broadcast
        self._topologies: List[dict] = []

    def record_topology(self, doc: dict) -> None:
        """One ingested/proposed topology epoch (latest contiguous ledger;
        a duplicate epoch is a no-op — ingest is idempotent)."""
        epoch = doc.get("epoch")
        if any(d.get("epoch") == epoch for d in self._topologies):
            return
        self._topologies.append(doc)
        self._topologies.sort(key=lambda d: d.get("epoch", 0))

    def topologies(self) -> List[dict]:
        return list(self._topologies)

    # -- recording -----------------------------------------------------------
    def record_message(self, request, from_id: int) -> None:
        if self.restoring:
            return
        txn_id = getattr(request, "txn_id", None)
        if txn_id is None:
            return
        type_name = request.type.name
        if type_name.startswith("PROPAGATE"):
            # local knowledge-upgrade message: its body is the merged
            # CheckStatusOk (ref: Propagate.java carries the found state)
            self.record_propagate(txn_id, request.ok)
            return
        self._note_hlc(txn_id)
        ex = getattr(request, "execute_at", None)
        if ex is not None:
            self._note_hlc(ex)
        b = self._bodies.get(txn_id)
        if b is None:
            b = self._bodies[txn_id] = _Bodies()
        route = getattr(request, "route", None)
        if route is not None:
            if b.route is None or (b.route.home_key is None
                                   and route.home_key is not None):
                b.route = route
            elif route.home_key is not None \
                    and route.home_key == b.route.home_key:
                b.route = b.route.with_(route)
            # else: divergent home key (a recovery coordinator picks its
            # own) — keep the existing route; either one is usable
        txn = getattr(request, "txn", None)
        if txn is not None:
            b.txn = txn
        if type_name == "ACCEPT_REQ":
            b.accepts.append((request.ballot, request))
        elif type_name in _COMMIT_TYPES:
            # prefer a body that carries the txn (maximal hydration)
            if b.commit is None or getattr(request, "txn", None) is not None:
                b.commit = request
        elif type_name in _APPLY_TYPES:
            if b.apply is None or getattr(request, "txn", None) is not None:
                b.apply = request

    def record_propagate(self, txn_id: TxnId, ok) -> None:
        """Local knowledge upgrade (ref: PROPAGATE_* local messages are
        side-effecting and journaled, messages/MessageType.java)."""
        if self.restoring:
            return
        b = self._bodies.get(txn_id)
        if b is None:
            b = self._bodies[txn_id] = _Bodies()
        b.propagate = ok if b.propagate is None else b.propagate.merge(ok)
        self._note_hlc(txn_id)
        if ok.execute_at is not None:
            self._note_hlc(ok.execute_at)

    def record_registers(self, store_id: int, command: Command) -> None:
        regs = self._registers.get(store_id)
        if regs is None:
            regs = self._registers[store_id] = {}
        if command.save_status is SaveStatus.Erased:
            # erased on this store: the watermarks answer for it here —
            # drop its registers (the journal's own truncation, ref: Cleanup
            # ERASE wipes the journal's messages)
            self.drop_register(store_id, command.txn_id)
            return
        regs[command.txn_id] = _Registers(
            command.save_status, command.execute_at, command.promised,
            command.accepted, command.durability)
        self._note_hlc(command.txn_id)
        if command.execute_at is not None:
            self._note_hlc(command.execute_at)

    def record_watermarks(self, store_id: int, durable_entries: list,
                          redundant_entries: list) -> None:
        """Latest durable/redundant watermark segments for one store
        (the reference persists RedundantBefore/DurableBefore as per-store
        fields; max-merge maps, so latest-wins is the whole history)."""
        self._watermarks[store_id] = (durable_entries, redundant_entries)

    def record_bootstrap(self, store_id: int, ranges: Ranges,
                         epoch: int) -> None:
        self._bs_started[store_id] = self._bs_started.get(
            store_id, Ranges.empty()).with_(ranges)
        # a re-bootstrap of previously-done ranges reopens them
        self._bs_done[store_id] = self._bs_done.get(
            store_id, Ranges.empty()).without(ranges)

    def record_bootstrapped_at(self, store_id: int, ranges: Ranges,
                               fence: TxnId) -> None:
        self._bs_marks.setdefault(store_id, []).append((ranges, fence))
        self._note_hlc(fence)

    def record_bootstrap_done(self, store_id: int, ranges: Ranges,
                              epoch: int) -> None:
        self._bs_done[store_id] = self._bs_done.get(
            store_id, Ranges.empty()).with_(ranges)

    def reserve_hlc(self, bound: int) -> None:
        """Batched id reservation: the node persists ``hlc + K`` before
        handing out ids up to that bound, so a restart restores a true
        upper bound on issued timestamps instead of a heuristic slack."""
        if bound > self.hlc_reserved:
            self.hlc_reserved = bound

    def _note_hlc(self, ts) -> None:
        h = ts.hlc()
        if h > self.max_hlc:
            self.max_hlc = h

    # -- reconstruction ------------------------------------------------------
    def registered_txns(self, store_id: int):
        return sorted(self._registers.get(store_id, {}))

    def has_register(self, store_id: int, txn_id: TxnId) -> bool:
        return txn_id in self._registers.get(store_id, {})

    def drop_register(self, store_id: int, txn_id: TxnId) -> None:
        """Erase one store's register (and the bodies once no store retains
        any) — the paged-out analogue of the Erased register drop."""
        regs = self._registers.get(store_id)
        if regs is not None:
            regs.pop(txn_id, None)
        if not any(txn_id in r for r in self._registers.values()):
            self._bodies.pop(txn_id, None)

    def reconstruct(self, store, txn_id: TxnId,
                    probe: bool = False) -> Optional[Command]:
        """Rebuild one command from registers + message bodies
        (ref: SerializerSupport.reconstruct).  WaitingOn is NOT built here —
        callers recompute it from the deps against current store state (the
        reference's waitingOnProvider), which also re-clears already-applied
        dependencies.  ``probe=True`` marks a fidelity check (page-out
        eligibility) rather than a real restore: a degraded probe keeps the
        command in memory and loses nothing, so it must not pollute the
        ``degraded`` diagnostic that healthy runs assert stays 0."""
        reg = self._registers.get(store.store_id, {}).get(txn_id)
        if reg is None:
            return None
        ss = reg.save_status
        # in-flight execution states resume one step back: transient waiters
        # died with the process, and re-running the write is idempotent
        # (the data store dedups by TxnId)
        if ss is SaveStatus.ReadyToExecute:
            ss = SaveStatus.Stable
        elif ss is SaveStatus.Applying:
            ss = SaveStatus.PreApplied
        b = self._bodies.get(txn_id) or _Bodies()
        route = b.route
        if route is None and b.propagate is not None:
            route = b.propagate.route

        if ss is SaveStatus.Invalidated:
            return Command(txn_id, save_status=SaveStatus.Invalidated,
                           durability=Durability.UniversalOrInvalidated,
                           route=route)
        if ss in (SaveStatus.Erased, SaveStatus.ErasedOrInvalidated):
            return Command(txn_id, save_status=ss, durability=reg.durability)
        if ss in (SaveStatus.TruncatedApply, SaveStatus.TruncatedApplyWithDeps,
                  SaveStatus.TruncatedApplyWithOutcome):
            writes, result = self._outcome(b)
            return Command(txn_id, save_status=ss, durability=reg.durability,
                           route=route, execute_at=reg.execute_at,
                           writes=writes, result=result)
        if ss in (SaveStatus.Uninitialised, SaveStatus.NotDefined):
            return Command(txn_id, save_status=ss, promised=reg.promised,
                           durability=reg.durability, route=route)

        owned = self._owned_window(store, txn_id, reg.execute_at)
        partial_txn = self._partial_txn(b, owned)
        partial_deps = None
        if ss >= SaveStatus.Committed:
            partial_deps = self._stable_deps(b, owned)
            if partial_deps is None:
                # commit body lost (should not happen): degrade to
                # PreCommitted and let the progress log re-fetch
                if not probe:
                    self.degraded += 1
                ss = SaveStatus.PreCommitted
        elif ss >= SaveStatus.Accepted and ss != SaveStatus.AcceptedInvalidate \
                and ss != SaveStatus.AcceptedInvalidateWithDefinition:
            partial_deps = self._accept_deps(b, reg.accepted, owned)
        if ss >= SaveStatus.PreAccepted and partial_txn is None \
                and ss.known.is_definition_known():
            if not probe:
                self.degraded += 1
            return Command(txn_id, save_status=SaveStatus.NotDefined,
                           promised=reg.promised, durability=reg.durability,
                           route=route)
        writes = result = None
        if ss >= SaveStatus.PreApplied:
            writes, result = self._outcome(b)
            if writes is None and result is None \
                    and not txn_id.kind().is_sync_point():
                if not probe:
                    self.degraded += 1
                ss = SaveStatus.Stable if partial_deps is not None \
                    else SaveStatus.PreCommitted
        waiting_on = WaitingOn.none() if ss is SaveStatus.Applied else None
        progress_key = None
        if route is not None and route.home_key is not None:
            progress_key = store.node.select_progress_key(txn_id, route)
        return Command(txn_id, save_status=ss, durability=reg.durability,
                       route=route, progress_key=progress_key,
                       promised=reg.promised, accepted=reg.accepted,
                       partial_txn=partial_txn, partial_deps=partial_deps,
                       execute_at=reg.execute_at, waiting_on=waiting_on,
                       writes=writes, result=result)

    def _owned_window(self, store, txn_id: TxnId,
                      execute_at: Optional[Timestamp]) -> Ranges:
        from .commands import apply_window_epochs
        min_epoch, max_epoch = apply_window_epochs(txn_id, execute_at)
        return store.ranges_for_epoch.all_between(min_epoch, max_epoch)

    @staticmethod
    def _partial_txn(b: _Bodies, owned: Ranges):
        src = None
        if b.txn is not None:
            src = b.txn
        elif b.commit is not None and getattr(b.commit, "txn", None) is not None:
            src = b.commit.txn
        elif b.apply is not None and getattr(b.apply, "txn", None) is not None:
            src = b.apply.txn
        elif b.propagate is not None and b.propagate.partial_txn is not None:
            src = b.propagate.partial_txn
        if src is None:
            return None
        return src.slice(owned, True)

    @staticmethod
    def _stable_deps(b: _Bodies, owned: Ranges):
        for src in (b.commit, b.apply):
            if src is not None and getattr(src, "deps", None) is not None:
                return src.deps.slice(owned)
        if b.propagate is not None and b.propagate.partial_deps is not None:
            return b.propagate.partial_deps.slice(owned)
        return None

    @staticmethod
    def _accept_deps(b: _Bodies, accepted: Ballot, owned: Ranges):
        chosen = None
        for ballot, req in b.accepts:
            if ballot == accepted:
                chosen = req
        if chosen is None and b.accepts:
            chosen = b.accepts[-1][1]
        if chosen is None or chosen.deps is None:
            return None
        return chosen.deps.slice(owned)

    @staticmethod
    def _outcome(b: _Bodies):
        if b.apply is not None:
            return b.apply.writes, b.apply.result
        if b.propagate is not None and b.propagate.writes is not None:
            return b.propagate.writes, b.propagate.result
        return None, None

    # -- full restart --------------------------------------------------------
    def restore(self, node) -> None:
        """Rebuild every store of a freshly-constructed node (topologies must
        already be fed via Node.restore_topologies).  Pass 1 installs
        watermarks + commands + per-key indexes synchronously; pass 2 (a
        store task per store) rebuilds WaitingOn frontiers and resumes the
        execution drain; finally interrupted bootstraps are restarted."""
        from .bootstrap import Bootstrap
        from .command_store import PreLoadContext
        stores = {s.store_id: s for s in node.command_stores.unsafe_all_stores()}
        self.restoring = True
        try:
            # watermarks first: dep-clearing in pass 2 needs them
            for sid, store in stores.items():
                for ranges, fence in self._bs_marks.get(sid, ()):
                    store.redundant_before.add_bootstrapped(ranges, fence)
                snap = self._watermarks.get(sid)
                if snap is not None:
                    durable, redundant = snap
                    store.durable_before.merge_entries(durable)
                    for start, end, before in redundant:
                        from ..primitives.keys import Range
                        store.redundant_before.add_redundant(
                            Ranges.of(Range(start, end)), before)
            for store in stores.values():
                for txn_id in self.registered_txns(store.store_id):
                    cmd = self.reconstruct(store, txn_id)
                    if cmd is None:
                        continue
                    store.commands[txn_id] = cmd
                    self._rebuild_indexes(store, cmd)
        finally:
            self.restoring = False
        for store in stores.values():
            store.execute(PreLoadContext.empty(), self._resume_drain)
        # re-bootstrap what lacks data coverage: interrupted fetches
        # (started - done) plus ranges adopted while this node was down
        # (owned now, but neither held since this node's first epoch nor
        # covered by any bootstrap record).  Rebased to the CURRENT epoch:
        # a fence coordinated now only reaches current owners, and the
        # multi-epoch donor sweep (Bootstrap._donors) finds the data.
        for sid, store in stores.items():
            owned = store.owned_current()
            if owned.is_empty():
                continue
            baseline = store.ranges_for_epoch.earliest()
            s = self._bs_started.get(sid, Ranges.empty())
            incomplete = s.without(self._bs_done.get(sid, Ranges.empty()))
            missed = owned.without(baseline).without(s)
            need = incomplete.with_(missed).intersecting(owned)
            if not need.is_empty():
                Bootstrap(store, need, max(2, node.epoch())).start()

    def _rebuild_indexes(self, store, cmd: Command) -> None:
        """Re-derive the non-journaled per-store indexes from a reconstructed
        command: CommandsForKey / range_commands, MaxConflicts, the
        ExclusiveSyncPoint fence, and the device mirror (all are caches over
        the command log — exactly why they are not persisted)."""
        from .commands_for_key import InternalStatus
        txn_id = cmd.txn_id
        if cmd.save_status in (SaveStatus.Erased,
                               SaveStatus.ErasedOrInvalidated):
            return
        if not txn_id.kind().is_globally_visible():
            return
        keys = cmd.partial_txn.keys if cmd.partial_txn is not None else None
        if keys is None:
            return
        status = _internal_status(cmd)
        execute_at = (cmd.execute_at if status.has_execute_at()
                      and cmd.execute_at is not None else None)
        if isinstance(keys, Ranges):
            if status is not InternalStatus.INVALIDATED:
                existing = store.range_commands.get(txn_id)
                store.put_range_command(txn_id, keys if existing is None
                                        else existing.with_(keys))
        else:
            from .commands import _per_key_deps
            for key in keys:
                store.cfk(key.token()).update(
                    txn_id, status, execute_at,
                    witnessed_deps=_per_key_deps(cmd.partial_deps,
                                                 key.token()))
        ts = cmd.execute_at if cmd.execute_at is not None else txn_id
        store.max_conflicts.update(keys, ts)
        if txn_id.kind() is TxnKind.ExclusiveSyncPoint \
                and isinstance(keys, Ranges) \
                and status is not InternalStatus.INVALIDATED:
            store.mark_reject_before(keys, txn_id)
        if store.device is not None:
            store.device.register(txn_id, int(status), keys)
            if execute_at is not None:
                store.device.update_status(txn_id, int(status), execute_at)

    def _resume_drain(self, safe) -> None:
        """Pass 2: rebuild WaitingOn for every Stable/PreApplied command (the
        reference's waitingOnProvider at reconstruct) and re-arm liveness."""
        from . import commands as commands_mod
        store = safe.store
        pending = [c for c in store.commands.values()
                   if c.save_status in (SaveStatus.Stable,
                                        SaveStatus.PreApplied)]
        pending.sort(key=lambda c: (c.execute_at or c.txn_id, c.txn_id))
        for cmd in pending:
            waiting_on = commands_mod.initialise_waiting_on(
                safe, cmd.txn_id, cmd.execute_at, cmd.partial_deps)
            cur = safe.get(cmd.txn_id)
            safe.update(cur.updated(waiting_on=waiting_on), notify=False)
            if not commands_mod.maybe_execute(safe, cmd.txn_id) \
                    and store.device is not None:
                store.device.arm(safe, cmd.txn_id)
        # re-seed the progress log so in-flight txns keep a liveness owner
        log = safe.progress_log()
        for cmd in store.commands.values():
            if cmd.is_truncated() or cmd.is_invalidated() \
                    or cmd.durability.is_durable():
                continue
            ss = cmd.save_status
            if ss is SaveStatus.Applied:
                log.durable_local(safe, cmd.txn_id)
            elif ss >= SaveStatus.Stable:
                log.stable(safe, cmd.txn_id)
            elif ss >= SaveStatus.Committed:
                log.precommitted(safe, cmd.txn_id)
            elif ss >= SaveStatus.Accepted:
                log.accepted(safe, cmd.txn_id)
            elif ss is SaveStatus.PreAccepted:
                log.pre_accepted(safe, cmd.txn_id)

    # -- cache eviction / reload --------------------------------------------
    def evict_and_reload(self, store, txn_id: TxnId):
        """Drop one command and rebuild it from the journal, in place
        (ref: DelayedCommandStores random isLoadedCheck evictions).  Runs as
        a store task; returns a chain of (evicted, reloaded) for tests.
        Durable listeners survive (the reference persists them in
        CommonAttributes); transient listeners live outside the command."""
        from . import commands as commands_mod
        from .command_store import PreLoadContext

        def task(safe):
            old = store.commands.get(txn_id)
            if old is None or old.save_status in (SaveStatus.Applying,):
                return None
            new = self.reconstruct(store, txn_id)
            if new is None:
                return None
            new = new.updated(listeners=old.listeners)
            if new.save_status in (SaveStatus.Stable, SaveStatus.PreApplied):
                waiting = commands_mod.initialise_waiting_on(
                    safe, txn_id, new.execute_at, new.partial_deps)
                new = new.updated(waiting_on=waiting)
            store.commands[txn_id] = new
            if new.save_status in (SaveStatus.Stable, SaveStatus.PreApplied):
                # mirror the stable()/apply() tail: still-waiting commands
                # must re-enter the drain — device mode has no listeners, so
                # an unarmed reloaded waiter would never wake (lost wakeup)
                if not commands_mod.maybe_execute(safe, txn_id) \
                        and store.device is not None:
                    store.device.arm(safe, txn_id)
            return (old, store.commands[txn_id])

        return store.execute(PreLoadContext.for_txn(txn_id), task)


def _internal_status(cmd: Command):
    from .commands_for_key import InternalStatus
    if cmd.is_invalidated():
        return InternalStatus.INVALIDATED
    if cmd.save_status is SaveStatus.Applied or cmd.is_truncated():
        return InternalStatus.APPLIED
    if cmd.has_been(Status.Stable):
        return InternalStatus.STABLE
    if cmd.has_been(Status.Committed):
        return InternalStatus.COMMITTED
    if cmd.has_been(Status.Accepted):
        return InternalStatus.ACCEPTED
    return InternalStatus.PREACCEPTED
