"""All protocol state transitions, as pure-ish functions over SafeCommandStore.

Rebuild of ref: accord-core/src/main/java/accord/local/Commands.java:98-1192 —
preaccept/accept/commit/precommit/apply/commitInvalidate (:131-527), the
execution drain maybeExecute (:656-733), initialiseWaitingOn/updateWaitingOn
(:735-830), updateDependencyAndMaybeExecute (:832) and listener fan-out.

The listener-DFS NotifyWaitingOn walker (:1011-1192) is replaced by (a) the
same-store deferred listener queue (SafeCommandStore.complete) and (b) the
batched device drain (accord_tpu.ops.drain) for the high-throughput path.
"""

from __future__ import annotations

import enum
from typing import List, Optional, Tuple

from ..primitives.deps import PartialDeps
from ..primitives.keys import Ranges, Route
from ..primitives.timestamp import Ballot, Timestamp, TxnId, TxnKind
from ..primitives.txn import PartialTxn
from ..primitives.writes import Writes
from ..utils import invariants
from .command import Command, WaitingOn
from .command_store import PreLoadContext, SafeCommandStore
from .commands_for_key import InternalStatus
from .redundant import RedundantStatus
from .status import Durability, SaveStatus, Status, save_status_for


class AcceptOutcome(enum.IntEnum):
    """(ref: Commands.AcceptOutcome)."""
    Success = 0
    Redundant = 1
    RejectedBallot = 2
    Insufficient = 3
    Truncated = 4
    Rejected = 5      # fenced by an ExclusiveSyncPoint (rejectBefore)


class CommitOutcome(enum.IntEnum):
    Success = 0
    Redundant = 1
    Insufficient = 2
    Rejected = 3


class ApplyOutcome(enum.IntEnum):
    Success = 0
    Redundant = 1
    Insufficient = 2


# ---------------------------------------------------------------------------
# PreAccept (ref: Commands.java:131-196)
# ---------------------------------------------------------------------------

def preaccept(safe: SafeCommandStore, txn_id: TxnId, partial_txn: PartialTxn,
              route: Route, progress_key: Optional[int],
              permit_fast_path: bool = True, ballot: Ballot = Ballot.ZERO
              ) -> Tuple[AcceptOutcome, Optional[Timestamp]]:
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreAccepted):
        return AcceptOutcome.Redundant, cmd.execute_at
    if cmd.promised > ballot:
        return AcceptOutcome.RejectedBallot, None
    if safe.redundant_before().status(txn_id, partial_txn.keys) in (
            RedundantStatus.SHARD_REDUNDANT,):
        return AcceptOutcome.Truncated, None
    if not txn_id.kind().is_sync_point():
        # An ExclusiveSyncPoint fence rejects NEW witnessing of lower TxnIds
        # at any ballot: they could otherwise (slow-path or via recovery
        # resurrection) decide past the fence and straddle a bootstrap
        # snapshot boundary (ref: Commands.preaccept rejectBefore check).
        # The original coordinator retries with a fresh TxnId; a recovery
        # coordinator receives this as a non-witness vote and the electorate
        # math (superseding rejects) decides the txn's fate.
        #
        # DELIBERATE DELTA: the reference applies the fold to sync points
        # too (the ESP early-return in CommandStore.java:326-336 sits after
        # the reject check) — but there a rejected PreAccept still witnesses
        # the txn, returning a rejected timestamp the coordinator then
        # invalidates.  Ours refuses to witness outright and the caller
        # re-picks a fresh id.  Applying THAT semantic to ESPs breaks the
        # fence-id-is-bootstrap-watermark invariant: concurrent bootstraps
        # race, the loser's pre-marked bootstrapped_at keeps pruning deps
        # with a boundary dep that never coordinates, and coverage holes
        # lose writes across snapshot handoffs (observed: burn seeds 3/7).
        # An old ESP witnessed behind a newer fence is harmless here — it
        # carries no payload and its fence marking max-merges to a no-op.
        floor = safe.store.reject_before_floor(partial_txn.keys)
        if floor is not None and txn_id < floor:
            # return the fence bound: the coordinator bumps its HLC past it
            # before retrying, or a drift-behind node re-issues doomed ids
            # until its clock catches up on its own
            return AcceptOutcome.Rejected, floor

    witnessed_at = _compute_witnessed_at(safe, txn_id, partial_txn, permit_fast_path)
    safe.update_max_conflicts(partial_txn.keys, witnessed_at)

    new_cmd = cmd.updated(
        save_status=SaveStatus.PreAccepted,
        route=route if cmd.route is None else cmd.route.with_(route),
        progress_key=progress_key,
        partial_txn=partial_txn if cmd.partial_txn is None
        else cmd.partial_txn.with_partial(partial_txn),
        execute_at=witnessed_at)
    safe.update(new_cmd)
    _register_txn(safe, txn_id, partial_txn, InternalStatus.PREACCEPTED)
    if txn_id.kind() is TxnKind.ExclusiveSyncPoint and \
            isinstance(partial_txn.keys, Ranges):
        safe.store.mark_reject_before(partial_txn.keys, txn_id)
    safe.progress_log().pre_accepted(safe, txn_id)
    return AcceptOutcome.Success, witnessed_at


def _compute_witnessed_at(safe: SafeCommandStore, txn_id: TxnId,
                          partial_txn: PartialTxn,
                          permit_fast_path: bool) -> Timestamp:
    """Propose the witnessed timestamp: the txn's own id if it still beats
    every conflict (fast path), else a fresh unique timestamp above the
    conflict floor (ref: CommandStore.preaccept logic)."""
    if txn_id.kind().is_sync_point():
        # sync points execute at their own id (ref: Txn.Kind.SyncPoint docs)
        return txn_id
    max_conflict = safe.max_conflict(partial_txn.keys)
    node = safe.node()
    if permit_fast_path and txn_id > max_conflict and txn_id.epoch() >= node.epoch():
        return txn_id
    return node.unique_now_at_least(max_conflict).with_epoch_at_least(txn_id.epoch())


def _per_key_deps(partial_deps: Optional[PartialDeps],
                  token: int) -> Optional[List[TxnId]]:
    """The command's dep ids on one key — what freezes into the CFK's
    missing[] divergence when the deps are fixed."""
    if partial_deps is None:
        return None
    ids = list(partial_deps.key_deps.txn_ids_for(token))
    ids.extend(partial_deps.range_deps.intersecting_token(token))
    return ids


def _register_txn(safe: SafeCommandStore, txn_id: TxnId,
                  partial_txn: PartialTxn, status: InternalStatus,
                  execute_at: Optional[Timestamp] = None,
                  partial_deps: Optional[PartialDeps] = None) -> None:
    if not txn_id.kind().is_globally_visible():
        return
    keys = partial_txn.keys if partial_txn is not None else None
    if keys is None:
        return
    if isinstance(keys, Ranges):
        existing = safe.store.range_commands.get(txn_id)
        safe.store.put_range_command(txn_id, keys if existing is None
                                     else existing.with_(keys))
    else:
        for key in keys:
            safe.cfk(key.token()).update(
                txn_id, status, execute_at,
                witnessed_deps=_per_key_deps(partial_deps, key.token()))
    if safe.store.device is not None:
        safe.store.device.register(txn_id, int(status), keys)
        if execute_at is not None and status.has_execute_at():
            safe.store.device.update_status(txn_id, int(status), execute_at)


def _update_cfk_status(safe: SafeCommandStore, cmd: Command,
                       status: InternalStatus,
                       execute_at: Optional[Timestamp] = None,
                       partial_deps: Optional[PartialDeps] = None) -> None:
    if not cmd.txn_id.kind().is_globally_visible():
        return
    if safe.store.device is not None:
        safe.store.device.update_status(cmd.txn_id, int(status), execute_at)
    if cmd.partial_txn is None:
        return
    keys = cmd.partial_txn.keys
    if isinstance(keys, Ranges):
        return  # range txns tracked via range_commands + command status
    for key in keys:
        safe.cfk(key.token()).update(
            cmd.txn_id, status, execute_at,
            witnessed_deps=_per_key_deps(partial_deps, key.token()))


def recover(safe: SafeCommandStore, txn_id: TxnId, partial_txn: PartialTxn,
            route: Route, progress_key: Optional[int],
            ballot: Ballot) -> Tuple[AcceptOutcome, Optional[Ballot]]:
    """BeginRecovery's local transition: promise the recovery ballot and
    witness the txn if unseen (ref: Commands.java recover + preacceptOrRecover).
    Never grants a fast-path vote — the witnessed timestamp for an unseen txn
    is computed with the fast path disabled."""
    cmd = safe.get(txn_id)
    if cmd.is_truncated():
        return AcceptOutcome.Truncated, None
    if cmd.promised > ballot:
        return AcceptOutcome.RejectedBallot, cmd.promised
    if not cmd.has_been(Status.PreAccepted):
        outcome, _ = preaccept(safe, txn_id, partial_txn, route, progress_key,
                               permit_fast_path=False, ballot=ballot)
        if outcome not in (AcceptOutcome.Success, AcceptOutcome.Redundant):
            return outcome, None
        cmd = safe.get(txn_id)
    safe.update(cmd.updated(promised=ballot), notify=False)
    return AcceptOutcome.Success, None


# ---------------------------------------------------------------------------
# Accept (ref: Commands.java:198-280)
# ---------------------------------------------------------------------------

def accept(safe: SafeCommandStore, txn_id: TxnId, ballot: Ballot, route: Route,
           keys, progress_key: Optional[int], execute_at: Timestamp,
           partial_deps: PartialDeps) -> Tuple[AcceptOutcome, Optional[Ballot]]:
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreCommitted):
        return AcceptOutcome.Redundant, None
    if cmd.promised > ballot:
        return AcceptOutcome.RejectedBallot, cmd.promised
    if not txn_id.kind().is_sync_point() and ballot == Ballot.ZERO \
            and not cmd.has_been(Status.PreAccepted):
        # Fence check also at Accept: an original-coordinator slow-path
        # Accept can arrive after the fence (see preaccept).  Guards:
        # already-witnessed commands pass (the fence witnessed them — their
        # executeAt-vs-fence ordering is handled by the executeAt-gated
        # apply), and recovery ballots pass (a recovered txn that reached
        # the Accept phase at a quorum must survive; invalidating it could
        # lose a committed write).
        floor = safe.store.reject_before_floor(keys)
        if floor is not None and txn_id < floor:
            return AcceptOutcome.Rejected, floor

    new_status = (SaveStatus.AcceptedWithDefinition if cmd.is_defined()
                  else SaveStatus.Accepted)
    new_cmd = cmd.updated(
        save_status=new_status,
        route=route if cmd.route is None else cmd.route.with_(route),
        progress_key=progress_key if cmd.progress_key is None else cmd.progress_key,
        promised=ballot, accepted=ballot,
        execute_at=execute_at,
        partial_deps=partial_deps)
    safe.update(new_cmd)
    safe.update_max_conflicts(keys, execute_at)
    _update_cfk_status(safe, new_cmd, InternalStatus.ACCEPTED, execute_at,
                       partial_deps=partial_deps)
    safe.progress_log().accepted(safe, txn_id)
    return AcceptOutcome.Success, None


def accept_invalidate(safe: SafeCommandStore, txn_id: TxnId,
                      ballot: Ballot) -> Tuple[AcceptOutcome, Optional[Ballot]]:
    """(ref: Commands.acceptInvalidate)."""
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreCommitted):
        return AcceptOutcome.Redundant, None
    if cmd.promised > ballot:
        return AcceptOutcome.RejectedBallot, cmd.promised
    new_status = (SaveStatus.AcceptedInvalidateWithDefinition if cmd.is_defined()
                  else SaveStatus.AcceptedInvalidate)
    safe.update(cmd.updated(save_status=new_status, promised=ballot,
                            accepted=ballot))
    return AcceptOutcome.Success, None


# ---------------------------------------------------------------------------
# Commit / Stable (ref: Commands.java:306-462)
# ---------------------------------------------------------------------------

def commit(safe: SafeCommandStore, txn_id: TxnId, target_stable: bool,
           ballot: Ballot, route: Route, partial_txn: Optional[PartialTxn],
           execute_at: Timestamp, partial_deps: Optional[PartialDeps],
           progress_key: Optional[int] = None) -> CommitOutcome:
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreCommitted):
        known_at = cmd.execute_at_if_known()
        if known_at is not None and known_at != execute_at:
            safe.agent().on_inconsistent_timestamp(cmd, known_at, execute_at)
    if target_stable:
        if cmd.is_stable() or cmd.is_invalidated() or cmd.is_truncated():
            return CommitOutcome.Redundant
    else:
        if cmd.has_been(Status.Committed):
            return CommitOutcome.Redundant
    if cmd.promised > ballot:
        return CommitOutcome.Rejected

    merged_txn = cmd.partial_txn
    if partial_txn is not None:
        merged_txn = (partial_txn if merged_txn is None
                      else merged_txn.with_partial(partial_txn))
    if merged_txn is None:
        return CommitOutcome.Insufficient
    if partial_deps is None and cmd.partial_deps is None:
        return CommitOutcome.Insufficient
    deps = partial_deps if partial_deps is not None else cmd.partial_deps

    new_cmd = cmd.updated(
        save_status=SaveStatus.Committed,
        route=route if cmd.route is None else cmd.route.with_(route),
        progress_key=progress_key if cmd.progress_key is None else cmd.progress_key,
        partial_txn=merged_txn,
        execute_at=execute_at,
        partial_deps=deps)
    new_cmd = safe.update(new_cmd)
    safe.update_max_conflicts(merged_txn.keys, execute_at)
    _register_txn(safe, txn_id, merged_txn, InternalStatus.COMMITTED,
                  execute_at, partial_deps=deps)
    safe.progress_log().precommitted(safe, txn_id)

    if target_stable:
        return stable(safe, txn_id)
    return CommitOutcome.Success


def stable(safe: SafeCommandStore, txn_id: TxnId) -> CommitOutcome:
    """Commit -> Stable: freeze deps, build the WaitingOn frontier, try to
    execute (ref: Commands.commit stable path + initialiseWaitingOn)."""
    cmd = safe.get(txn_id)
    if cmd.is_stable() or cmd.is_invalidated() or cmd.is_truncated():
        return CommitOutcome.Redundant
    invariants.check_state(cmd.has_been(Status.Committed),
                           "stable before committed: %s", cmd)
    waiting_on = initialise_waiting_on(safe, txn_id, cmd.execute_at,
                                       cmd.partial_deps)
    new_cmd = cmd.updated(save_status=SaveStatus.Stable, waiting_on=waiting_on)
    safe.update(new_cmd)
    _update_cfk_status(safe, new_cmd, InternalStatus.STABLE, new_cmd.execute_at)
    safe.progress_log().stable(safe, txn_id)
    if not maybe_execute(safe, txn_id) and safe.store.device is not None:
        # device drain mode: the remaining waiting set becomes an adjacency
        # row; ready_frontier ticks drive it instead of per-dep listeners
        safe.store.device.arm(safe, txn_id)
    return CommitOutcome.Success


def precommit(safe: SafeCommandStore, txn_id: TxnId,
              execute_at: Timestamp) -> CommitOutcome:
    """(ref: Commands.precommit)."""
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreCommitted):
        known_at = cmd.execute_at_if_known()
        if known_at is not None and known_at != execute_at:
            safe.agent().on_inconsistent_timestamp(cmd, known_at, execute_at)
        return CommitOutcome.Redundant
    new_cmd = safe.update(cmd.updated(
        save_status=save_status_for(Status.PreCommitted, cmd.known()),
        execute_at=execute_at))
    # surface the decided executeAt in the per-key index (as an accepted-
    # grade entry: deps not yet frozen) so recovery's accepted-no-witness
    # scan sees it even before the full Commit arrives
    _update_cfk_status(safe, new_cmd, InternalStatus.ACCEPTED, execute_at)
    safe.progress_log().precommitted(safe, txn_id)
    return CommitOutcome.Success


def commit_invalidate(safe: SafeCommandStore, txn_id: TxnId) -> None:
    """(ref: Commands.commitInvalidate)."""
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreCommitted) and cmd.known().execute_at.is_decided_and_known_to_execute():
        invariants.illegal_state("invalidating a pre-committed txn %s", txn_id)
    if cmd.is_invalidated():
        return
    new_cmd = cmd.updated(save_status=SaveStatus.Invalidated,
                          durability=Durability.UniversalOrInvalidated)
    safe.update(new_cmd)
    safe.notify_listeners(new_cmd)
    _update_cfk_status(safe, new_cmd, InternalStatus.INVALIDATED)
    safe.store.drop_range_command(txn_id)
    safe.progress_log().clear(txn_id)


# ---------------------------------------------------------------------------
# Apply (ref: Commands.java:464-527)
# ---------------------------------------------------------------------------

def apply(safe: SafeCommandStore, txn_id: TxnId, route: Route,
          execute_at: Timestamp, partial_deps: Optional[PartialDeps],
          partial_txn: Optional[PartialTxn], writes: Optional[Writes],
          result) -> ApplyOutcome:
    cmd = safe.get(txn_id)
    if cmd.has_been(Status.PreApplied):
        return ApplyOutcome.Redundant
    if not cmd.has_been(Status.Committed):
        outcome = commit(safe, txn_id, False, Ballot.MAX, route, partial_txn,
                         execute_at, partial_deps)
        if outcome is CommitOutcome.Insufficient:
            return ApplyOutcome.Insufficient
        cmd = safe.get(txn_id)
    known_at = cmd.execute_at_if_known()
    if known_at is not None and known_at != execute_at:
        safe.agent().on_inconsistent_timestamp(cmd, known_at, execute_at)

    waiting_on = cmd.waiting_on
    if waiting_on is None:
        waiting_on = initialise_waiting_on(safe, txn_id, execute_at,
                                           cmd.partial_deps)
    new_cmd = cmd.updated(save_status=SaveStatus.PreApplied,
                          waiting_on=waiting_on, writes=writes, result=result)
    safe.update(new_cmd)
    safe.progress_log().executed(safe, txn_id)
    if not maybe_execute(safe, txn_id) and safe.store.device is not None:
        safe.store.device.arm(safe, txn_id)
    return ApplyOutcome.Success


# ---------------------------------------------------------------------------
# WaitingOn construction + the execution drain
# (ref: Commands.java:656-857)
# ---------------------------------------------------------------------------

def initialise_waiting_on(safe: SafeCommandStore, txn_id: TxnId,
                          execute_at: Timestamp,
                          partial_deps: Optional[PartialDeps]) -> WaitingOn:
    """Build the execution frontier from the stable deps: one bit per dep we
    own locally; bits already satisfiable are cleared inline
    (ref: Commands.initialiseWaitingOn :735-830)."""
    if partial_deps is None:
        return WaitingOn.none()
    owned = safe.ranges(execute_at.epoch()).with_(safe.ranges(txn_id.epoch()))
    # The deps' covering records the window this store processed the commit
    # over — for a dual-quorum ESP (bootstrap/durability fence) that window
    # reaches BELOW txn_id.epoch to the store's prior-epoch ranges.  A donor
    # that lost the range in the new epoch must still wait on its old-range
    # deps before the fence applies locally (the snapshot-coverage gate), so
    # the waiting set is built over the union (ref: Commands.initialiseWaitingOn
    # uses safeStore.ranges().allBetween(minEpoch, executeAt.epoch())).
    covering = getattr(partial_deps, "covering", None)
    if covering is not None:
        owned = owned.with_(covering)
    dep_ids: List[TxnId] = []
    seen = set()
    for token in partial_deps.key_deps.keys:
        if owned.contains_token(token):
            for d in partial_deps.key_deps.txn_ids_for(token):
                if d not in seen and d != txn_id:
                    seen.add(d)
                    dep_ids.append(d)
    for rng in partial_deps.range_deps.ranges:
        if owned.intersects(Ranges.of(rng)):
            for d in partial_deps.range_deps.intersecting_range(rng):
                if d not in seen and d != txn_id:
                    seen.add(d)
                    dep_ids.append(d)
    dep_ids.sort()

    waiting_on = WaitingOn.all_of(dep_ids)
    for d in dep_ids:
        waiting_on = _maybe_clear_dep(safe, txn_id, execute_at, waiting_on, d,
                                      partial_deps)
    return waiting_on


def _maybe_clear_dep(safe: SafeCommandStore, txn_id: TxnId,
                     execute_at: Timestamp, waiting_on: WaitingOn,
                     dep: TxnId, partial_deps: PartialDeps) -> WaitingOn:
    dep_cmd = safe.if_present(dep)
    # the dep set itself records where the dep participates — essential for
    # deps we never witnessed locally (pre-bootstrap: the snapshot covers
    # them, so they must clear instantly, not trigger a fetch).  Clearance
    # is PER watermark entry (locally_settled): shard-redundant sub-ranges
    # clear unconditionally; pre-bootstrap sub-ranges clear unless the dep
    # has a KNOWN executeAt past that entry's fence (then it will apply
    # here directly and per-key order vs the snapshot must hold — the
    # cross-fence window is closed by reject_before; any residue fails
    # loudly in the versioned data store rather than losing a write).
    participants = _resolve_dep_participants(safe, dep, partial_deps)
    dep_exec = (dep_cmd.execute_at_if_known() if dep_cmd is not None else None)
    if safe.redundant_before().locally_settled(dep, participants, dep_exec):
        return waiting_on.with_done(dep, True)
    device = safe.store.device is not None
    if dep_cmd is None:
        # not yet witnessed locally: register a placeholder that will notify
        # us, and tell the progress log to fetch the blocker's state.  In
        # device mode the drain graph (not a listener) tracks the edge.
        placeholder = Command(dep)
        if not device:
            placeholder = placeholder.with_listener(txn_id)
        safe.update(placeholder, notify=False)
        _report_blocker(safe, dep, partial_deps)
        return waiting_on
    if dep_cmd.is_invalidated() or dep_cmd.is_truncated() or dep_cmd.save_status is SaveStatus.Applied:
        return waiting_on.with_done(dep, True)
    dep_execute_at = dep_cmd.execute_at_if_known()
    if dep_execute_at is not None and _never_applies_here(safe, dep_cmd,
                                                          dep_execute_at):
        # will never apply on this store (exec-epoch ownership moved):
        # see _dep_clearance
        return waiting_on.with_done(dep, True)
    if not txn_id.kind().awaits_only_deps():
        if dep_execute_at is not None and dep_execute_at > execute_at:
            # executes after us: not our dependency (ref: updateWaitingOn;
            # skipped for awaits-only-deps kinds, ref: Commands.java:804)
            return waiting_on.with_done(dep, False)
    if not device:
        safe.update(dep_cmd.with_listener(txn_id), notify=False)
    # Report the blocker whether it is undecided (we may have missed its
    # Commit) or decided-but-unapplied (we may have missed its Apply): both
    # can only be unblocked by fetching remote state if the originator is
    # gone (ref: NotifyWaitingOn walks to the deepest unapplied dep and
    # registers it with ProgressLog.waiting until HasOutcome).  Entries for
    # deps that apply promptly are retired before their first fetch.
    _report_blocker(safe, dep, partial_deps)
    return waiting_on


def _resolve_dep_participants(safe: SafeCommandStore, dep: TxnId,
                              partial_deps: PartialDeps):
    """Where does ``dep`` participate: from the dep set itself, else from
    its locally-known route."""
    participants = partial_deps.participants(dep)
    if participants.is_empty():
        participants = _dep_participants(safe, dep)
    return participants


def _report_blocker(safe: SafeCommandStore, dep: TxnId,
                    partial_deps: PartialDeps) -> None:
    safe.progress_log().waiting(
        dep, 0, None, _resolve_dep_participants(safe, dep, partial_deps))


def _dep_participants(safe: SafeCommandStore, dep: TxnId):
    cmd = safe.if_present(dep)
    if cmd is not None and cmd.route is not None:
        return cmd.route.participants
    return Ranges.empty()


def maybe_execute(safe: SafeCommandStore, txn_id: TxnId,
                  always_notify: bool = False) -> bool:
    """The executeAt-gated drain step for one txn
    (ref: Commands.maybeExecute :656-733)."""
    cmd = safe.get(txn_id)
    if cmd.save_status not in (SaveStatus.Stable, SaveStatus.PreApplied):
        if always_notify:
            safe.notify_listeners(cmd)
        return False
    if cmd.is_waiting():
        if always_notify:
            safe.notify_listeners(cmd)
        return False

    if safe.store.device is not None:
        safe.store.device.on_driven(txn_id)

    if cmd.save_status is SaveStatus.Stable:
        new_cmd = cmd.updated(save_status=SaveStatus.ReadyToExecute)
        safe.update(new_cmd)
        safe.notify_listeners(new_cmd)
        safe.notify_transient(new_cmd)
        safe.progress_log().ready_to_execute(safe, txn_id)
        return True

    # PreApplied: perform the writes then mark Applied.  Transient listeners
    # (pending reads) are notified synchronously BEFORE the writes apply so
    # they observe the pre-apply store state (the read gate contract in
    # messages/read_data.read_on_store).
    new_cmd = cmd.updated(save_status=SaveStatus.Applying)
    safe.update(new_cmd, notify=False)
    safe.notify_transient(new_cmd)
    _apply_writes(safe, new_cmd)
    return True


def _apply_writes(safe: SafeCommandStore, cmd: Command) -> None:
    store = safe.store
    # The write window is the ranges this store legitimately processed the
    # txn over — the covering of its sliced definition (which the message
    # layer computed from the coordinator's multi-epoch window, so dropped
    # prior-epoch donors still apply over their old ranges).
    if cmd.partial_txn is not None:
        owned = cmd.partial_txn.covering
    else:
        owned = safe.ranges(cmd.execute_at.epoch())
    # a write landing mid-bootstrap is deferred until the snapshot installs
    # (defer order == drain order); thereafter applying DIRECTLY is always
    # safe — the versioned data store inserts at the executeAt-sorted
    # position and dedups by TxnId, so snapshot and direct apply form a
    # monotone union whichever subset each delivered (the old
    # "snapshot-covered" skip assumed snapshot completeness and lost writes
    # whenever a donor legitimately served before a new-epoch-executing
    # txn applied at it)
    if not store.bootstrapping.is_empty() and cmd.writes is not None \
            and not cmd.writes.is_empty() \
            and cmd.writes.keys.intersects(store.bootstrapping):
        txn_id = cmd.txn_id
        store.defer_until_bootstrap(
            lambda: store.execute(PreLoadContext.for_txn(txn_id),
                                  lambda s: _apply_writes(s, s.get(txn_id))))
        return

    def on_done(_result, failure):
        if failure is not None:
            store.node.agent.on_uncaught_exception(failure)
            return
        store.execute(PreLoadContext.for_txn(cmd.txn_id),
                      lambda s: post_apply(s, cmd.txn_id))

    if cmd.writes is not None and not cmd.writes.is_empty():
        cmd.writes.apply_to(store.node.data_store, owned).begin(on_done)
    else:
        on_done(None, None)


def post_apply(safe: SafeCommandStore, txn_id: TxnId) -> None:
    """(ref: Commands.postApply :565-648)."""
    cmd = safe.get(txn_id)
    if cmd.save_status is not SaveStatus.Applying:
        return
    new_cmd = cmd.updated(save_status=SaveStatus.Applied)
    safe.update(new_cmd)
    _update_cfk_status(safe, new_cmd, InternalStatus.APPLIED, new_cmd.execute_at)
    if new_cmd.partial_txn is not None and new_cmd.execute_at is not None \
            and not isinstance(new_cmd.partial_txn.keys, Ranges):
        for key in new_cmd.partial_txn.keys:
            safe.store.timestamps_for_key.get(key.token()).on_executed(
                safe, txn_id, new_cmd.execute_at)
    safe.notify_listeners(new_cmd)
    safe.notify_transient(new_cmd)
    safe.progress_log().durable_local(safe, txn_id)


# ---------------------------------------------------------------------------
# Listener fan-out (ref: Commands.java listenerUpdate + :776-857)
# ---------------------------------------------------------------------------

def listener_update(safe: SafeCommandStore, listener_id: TxnId,
                    updated_id: TxnId) -> None:
    listener = safe.if_present(listener_id)
    if listener is None or listener.waiting_on is None:
        return
    if listener.save_status not in (SaveStatus.Stable, SaveStatus.PreApplied):
        return
    dep = safe.if_present(updated_id)
    if dep is None:
        # the dep's record was erased (Cleanup dropped it after the shard
        # watermark passed it): the watermark answers for it now — without
        # this leg the erase notification is a lost wakeup and the waiter
        # wedges forever (ref: Commands.removeRedundantDependencies)
        if not listener.waiting_on.is_waiting_on(updated_id):
            return
        cleared = _settle_absent_or_redundant_dep(safe, listener, updated_id,
                                                  None)
        if cleared is None:
            return
        safe.update(listener.updated(
            waiting_on=listener.waiting_on.with_done(updated_id, cleared)),
            notify=False)
        maybe_execute(safe, listener_id)
        return
    update_dependency_and_maybe_execute(safe, listener, dep)


def _settle_absent_or_redundant_dep(safe: SafeCommandStore, waiter: Command,
                                    dep_id: TxnId,
                                    dep_cmd: Optional[Command]
                                    ) -> Optional[bool]:
    """Clearance that needs no dep record: the redundancy/bootstrap
    watermarks answer for erased or never-witnessed dependencies (the same
    rules _maybe_clear_dep applies at WaitingOn construction, re-applied
    when the watermark advances under an already-built frontier).
    Returns True (clear as applied/invalidated) or None (still gating)."""
    participants = _resolve_dep_participants(safe, dep_id, waiter.partial_deps)
    dep_exec = (dep_cmd.execute_at_if_known() if dep_cmd is not None else None)
    if safe.redundant_before().locally_settled(dep_id, participants, dep_exec):
        return True
    return None


def _dep_clearance(safe: SafeCommandStore, dep: Command,
                   listener_txn_id: TxnId,
                   listener_execute_at) -> Optional[bool]:
    """The one clearing rule both drain mechanisms share
    (ref: Commands.updateWaitingOn): None = still gating; True = dep is
    applied/invalidated/truncated (or will never apply on this store);
    False = dep executes after us.  Waiters whose kind awaits_only_deps
    (ExclusiveSyncPoint/EphemeralRead) never drop executes-after deps
    (ref: Commands.java:804) — their local apply must prove every lower
    TxnId applied."""
    if dep.save_status is SaveStatus.Applied or dep.is_invalidated() \
            or dep.is_truncated():
        return True
    dep_execute_at = dep.execute_at_if_known()
    if dep_execute_at is not None and _never_applies_here(safe, dep,
                                                         dep_execute_at):
        # The dep executes in an epoch where this store owns none of its
        # participation: its Apply fan-out will never arrive here, so
        # waiting would deadlock the epoch handoff (e.g. a donor's fence
        # awaiting a new-epoch txn).  The joiner receives it directly and
        # the versioned data store's txn-id-keyed union keeps reads exact.
        return True
    if listener_txn_id.kind().awaits_only_deps():
        return None
    if (dep_execute_at is not None and listener_execute_at is not None
            and dep_execute_at > listener_execute_at):
        return False
    return None


def apply_window_epochs(txn_id: TxnId,
                        execute_at: Optional[Timestamp]) -> Tuple[int, int]:
    """The epoch window a txn's Commit/Apply distribution can reach on a
    store: [txn epoch .. executeAt epoch], extended ONE EPOCH BELOW for sync
    points — the dual-quorum handoff leg, where a dropped prior-epoch owner
    still receives and applies the fence over its old ranges (shared by the
    drain clearance, journal reconstruction, and fetch_data's propagate —
    keep in sync or reconstruction slices silently diverge from clearance)."""
    min_epoch = txn_id.epoch()
    if txn_id.kind().is_sync_point():
        min_epoch = max(1, min_epoch - 1)
    max_epoch = max(txn_id.epoch(),
                    execute_at.epoch() if execute_at is not None else 0)
    return min_epoch, max_epoch


def _never_applies_here(safe: SafeCommandStore, dep: Command,
                        dep_execute_at: Timestamp) -> bool:
    participants = dep.participants()
    if participants is None:
        return False   # unknown participation: stay conservative
    # Without the sync-point epoch extension a donor clears its waiting on a
    # joiner's bootstrap fence as "never applies here", applies its own
    # fence early, and serves a snapshot missing writes the fence was
    # supposed to gate on (lost write on the joiner).
    min_epoch, max_epoch = apply_window_epochs(dep.txn_id, dep_execute_at)
    window = safe.store.ranges_for_epoch.all_between(min_epoch, max_epoch)
    if isinstance(participants, Ranges):
        return not window.intersects(participants)
    return not participants.intersects(window)


def update_dependency_and_maybe_execute(safe: SafeCommandStore,
                                        listener: Command,
                                        dep: Command) -> None:
    """(ref: Commands.updateDependencyAndMaybeExecute :832)."""
    if not listener.waiting_on.is_waiting_on(dep.txn_id):
        return
    new_waiting = listener.waiting_on
    remove_listener = False
    cleared = _dep_clearance(safe, dep, listener.txn_id, listener.execute_at)
    if cleared is not None:
        new_waiting = new_waiting.with_done(dep.txn_id, cleared)
        remove_listener = True
    if new_waiting is listener.waiting_on:
        return
    updated = listener.updated(waiting_on=new_waiting)
    safe.update(updated, notify=False)
    if remove_listener:
        safe.update(dep.without_listener(listener.txn_id), notify=False)
    maybe_execute(safe, listener.txn_id)


def refresh_waiting_and_maybe_execute(safe: SafeCommandStore,
                                      txn_id: TxnId) -> bool:
    """Device-drain execution step: the kernel's ready_frontier proposed this
    txn as executable; re-validate every remaining WaitingOn bit against the
    authoritative host command records (same clearing rules as
    update_dependency_and_maybe_execute), then try to execute.  A mirror
    divergence degrades to a no-op — the bits stay set and the txn is
    re-proposed on a later tick."""
    cmd = safe.if_present(txn_id)
    if cmd is None or cmd.waiting_on is None:
        return False
    if cmd.save_status not in (SaveStatus.Stable, SaveStatus.PreApplied):
        return False
    w = cmd.waiting_on
    for dep in w.waiting_ids():
        dep_cmd = safe.if_present(dep)
        cleared = None
        if dep_cmd is not None:
            cleared = _dep_clearance(safe, dep_cmd, txn_id, cmd.execute_at)
        if cleared is None:
            # erased record or stale placeholder: the watermarks decide
            cleared = _settle_absent_or_redundant_dep(safe, cmd, dep, dep_cmd)
        if cleared is not None:
            w = w.with_done(dep, cleared)
    if w is not cmd.waiting_on:
        safe.update(cmd.updated(waiting_on=w), notify=False)
    return maybe_execute(safe, txn_id)


# ---------------------------------------------------------------------------
# Durability + truncation entry points (ref: Commands.java:879-975)
# ---------------------------------------------------------------------------

def set_durability(safe: SafeCommandStore, txn_id: TxnId,
                   durability: Durability) -> None:
    cmd = safe.get(txn_id)
    if durability <= cmd.durability:
        return
    safe.update(cmd.updated(durability=cmd.durability.merge(durability)),
                notify=False)
    if durability.is_durable():
        safe.progress_log().durable(safe, txn_id)


def set_truncated_apply(safe: SafeCommandStore, txn_id: TxnId) -> None:
    """Truncate a majority-durable applied command: drop txn/deps/waiting but
    KEEP the outcome (writes/result) — a recovery adopting this txn's result
    for a wedged client coordinator still needs it (ref: SaveStatus
    TruncatedApplyWithOutcome; outcome drops only at ERASE)."""
    cmd = safe.get(txn_id)
    if cmd.is_truncated():
        return
    new_cmd = cmd.updated(save_status=SaveStatus.TruncatedApply,
                          partial_txn=None, partial_deps=None,
                          waiting_on=None)
    safe.update(new_cmd)
    safe.notify_listeners(new_cmd)
    if safe.store.device is not None:
        safe.store.device.on_terminal(txn_id)


def set_erased(safe: SafeCommandStore, txn_id: TxnId) -> None:
    cmd = safe.get(txn_id)
    new_cmd = cmd.updated(save_status=SaveStatus.Erased,
                          partial_txn=None, partial_deps=None,
                          waiting_on=None, writes=None, result=None,
                          route=None)
    safe.update(new_cmd)
    safe.notify_listeners(new_cmd)
    if safe.store.device is not None:
        safe.store.device.on_terminal(txn_id)
