"""Bootstrap: adoption of newly-owned ranges after reconfiguration.

Rebuild of ref: accord-core/src/main/java/accord/local/Bootstrap.java:81
(design comment :30-60).  When an epoch grants this node ranges it did not
previously replicate:

1. Mark ``bootstrapped_at`` in RedundantBefore for the ranges — transactions
   below the watermark are pre-bootstrap: excluded from deps, and their
   writes are NOT applied locally (the snapshot covers them).
2. Fence with an ExclusiveSyncPoint over the ranges: every earlier txn is
   decided, and each replica applies the fence only after they applied
   locally.
3. Fetch a DataStore snapshot from a donor replica of the previous epoch
   and install it.  The donor serves only after IT has locally applied the
   fence (messages/fetch_snapshot.py), so the snapshot contains every write
   executing below the fence.
4. Mark the ranges safe to read; until then reads are Nacked so the
   coordinator uses another replica (ref: safeToRead smearing,
   local/CommandStore.java:159-176).

Each attempt retries with the next donor on failure
(ref: Bootstrap.Attempt + Agent.onFailedBootstrap).
"""

from __future__ import annotations

from typing import List

from .. import api
from ..primitives.keys import Ranges
from ..primitives.timestamp import Domain, TxnId, TxnKind


class Bootstrap:
    """One bootstrap attempt set for one store's added ranges."""

    def __init__(self, store, ranges: Ranges, epoch: int):
        self.store = store
        self.node = store.node
        self.ranges = ranges
        self.epoch = epoch
        self.done = False

    def start(self) -> None:
        node = self.node
        if not getattr(node, "alive", True):
            # dead incarnation (restart): a surviving retry timer must not
            # write phantom bootstrap records into the shared journal — a
            # fresh fence recorded here would never coordinate (the dead
            # sink drops sends) yet would raise the restored pre-bootstrap
            # watermark past writes the real snapshot never covered
            return
        # don't waste a cluster-wide consensus round on the fence if the
        # prior epoch's topology (our donor source) is not yet known
        prev_epoch = self.epoch - 1
        if prev_epoch >= 1 and not node.topology().has_epoch(prev_epoch):
            node.with_epoch(prev_epoch, self.start)
            return
        # 1. watermark == the fence's own TxnId (ref: Bootstrap.java creates
        # the ExclusiveSyncPoint id first and uses IT as bootstrappedAt).
        # This identity matters: the deps floor prunes entries below
        # bootstrapped_at from PreAccept replies, and collectDeps adds the
        # boundary itself as a dependency — which must therefore be a REAL
        # coordinated txn whose deps transitively cover everything pruned.
        bootstrapped_at = node.next_txn_id(TxnKind.ExclusiveSyncPoint,
                                           Domain.Range)
        self._current_fence = bootstrapped_at
        self.store.redundant_before.add_bootstrapped(self.ranges, bootstrapped_at)
        self.store.bootstrapping = self.store.bootstrapping.with_(self.ranges)
        if node.journal is not None:
            # the watermark + in-progress marker are per-store persisted
            # fields (the reference stores RedundantBefore via its
            # integration's storage, not the message log)
            node.journal.record_bootstrap(self.store.store_id, self.ranges,
                                          self.epoch)
            node.journal.record_bootstrapped_at(self.store.store_id,
                                                self.ranges, bootstrapped_at)
        # 2. fence, coordinated AT the watermark id
        from ..coordinate.sync_point import coordinate_sync_point
        coordinate_sync_point(node, self.ranges, exclusive=True,
                              txn_id=bootstrapped_at) \
            .begin(self._on_fenced)

    def _on_fenced(self, sync_point, failure) -> None:
        if failure is not None:
            # invalidate the abandoned fence id before retrying with a fresh
            # one: replicas that witnessed it hold an undecided zombie dep
            # otherwise (see Node.invalidate_abandoned)
            self.node.invalidate_abandoned(self._current_fence, self.ranges)
            self.node.agent.on_failed_bootstrap("fence", self.ranges,
                                                self._retry, failure)
            return
        prev_epoch = self.epoch - 1
        if prev_epoch >= 1 and not self.node.topology().has_epoch(prev_epoch):
            # unknown prior-epoch topology is a retryable condition, NOT a
            # trivially-complete bootstrap: completing here would mark empty
            # ranges safe-to-read.  Wait for the epoch, then retry.
            self.node.agent.on_failed_bootstrap(
                "unknown-prev-epoch", self.ranges, self._retry,
                RuntimeError(f"topology for epoch {prev_epoch} not yet known"))
            return
        donors = self._donors()
        if not donors:
            # no prior-epoch replicas exist (fresh keyspace): trivially done
            self._complete()
            return
        fence = sync_point.sync_id if sync_point is not None else None
        self._fetch(donors, self.ranges, fence)

    def _donors(self) -> List[int]:
        """Replicas of these ranges in any epoch from the adoption epoch's
        predecessor up to the current predecessor, most recent first.  A
        single-epoch donor set wedges after further churn: a retry's fresh
        fence (current-epoch TxnId) never reaches a donor that no longer
        owns the ranges, so it can never serve — while recent owners both
        witness the fence and hold the data (their own bootstraps completed
        or they Nack via the unavailable-for-read gate and we move on)."""
        from ..impl.sorter import SizeOfIntersectionSorter
        manager = self.node.topology()
        donors: List[int] = []
        newest = max(self.epoch, self.node.epoch())
        for epoch in range(newest - 1, self.epoch - 2, -1):
            if epoch < 1 or not manager.has_epoch(epoch):
                continue
            prev = manager.get_topology_for_epoch(epoch)
            epoch_donors = {n for shard in prev.for_selection(self.ranges)
                            for n in shard.nodes if n != self.node.node_id}
            # within an epoch, widest-covering donors first: one snapshot
            # fetch can then cover the whole request
            for n in SizeOfIntersectionSorter.preferred(prev, epoch_donors,
                                                        self.ranges):
                if n not in donors:
                    donors.append(n)
        return donors

    def _fetch(self, donors: List[int], remaining: Ranges, fence,
               cycle: int = 0) -> None:
        """Fetch ``remaining`` from donors in turn; each donor may cover only
        part, so iterate until nothing remains.  Exhausting the donor list
        with data still missing re-polls the SAME fence after a short
        backoff (donors defer while the fence is unapplied locally; a fresh
        consensus round for a new fence is only needed if the fence itself
        died — the full-restart fallback after several dry cycles).
        ``fence`` is the ExclusiveSyncPoint TxnId the donor must have
        locally applied before serving (see messages/fetch_snapshot.py)."""
        from ..messages.fetch_snapshot import FetchSnapshot, FetchSnapshotOk
        node = self.node
        if not getattr(node, "alive", True):
            return
        if remaining.is_empty():
            self._complete()
            return
        if not donors:
            if cycle < 6:
                delay = 700_000 + node.random.next_int(600_000)
                node.scheduler.once(delay, lambda: self._fetch(
                    self._donors(), remaining, fence, cycle + 1))
                return
            self.node.agent.on_failed_bootstrap(
                "fetch", remaining, self._retry,
                RuntimeError(f"all donors exhausted with {remaining} missing"))
            return
        donor, rest = donors[0], donors[1:]
        outer = self

        class Cb(api.Callback):
            def on_success(self, from_id: int, reply) -> None:
                if outer.done:
                    return
                if isinstance(reply, FetchSnapshotOk):
                    node.data_store.install_snapshot(reply.snapshot)
                    outer._fetch(rest, remaining.without(reply.covered),
                                 fence, cycle)
                else:
                    outer._fetch(rest, remaining, fence, cycle)

            def on_failure(self, from_id: int, failure: BaseException) -> None:
                if outer.done:
                    return
                node.agent.on_handled_exception(failure)
                outer._fetch(rest, remaining, fence, cycle)

        node.send(donor, FetchSnapshot(remaining, self.epoch - 1, fence), Cb())

    def _complete(self) -> None:
        if not getattr(self.node, "alive", True):
            return
        self.done = True
        self.store.bootstrapping = self.store.bootstrapping.without(self.ranges)
        if self.node.journal is not None:
            self.node.journal.record_bootstrap_done(self.store.store_id,
                                                    self.ranges, self.epoch)
        if self.store.bootstrapping.is_empty():
            self.store.bootstrap_complete()

    def _retry(self) -> None:
        if not self.done:
            self.node.scheduler.once(500_000, self.start)
