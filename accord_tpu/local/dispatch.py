"""Per-node device dispatch scheduler: cross-store launch coalescing (r08).

r06 made every deps *scan* cheap (regime-adaptive routing); r07 made the
accelerator a survivable failure domain.  What remained (device_index's own
docstring flagged it) is the LAUNCH tax: every CommandStore paid its own
device dispatch per flush and per drain tick, so on a node with many stores
the per-launch overhead (dispatch + PCIe/ICI round trip) dominates the
per-element work the kernels already amortize.  This module is the analogue
of the reference's per-store task-queue amortization
(InMemoryCommandStore's executor batching, SURVEY §7) lifted to the DEVICE
boundary:

- **Flush coalescing**: deps flushes from all CommandStores of one node
  that become runnable in the same sim event-loop step register with ONE
  dispatcher event.  Stores whose adaptive route is a device kernel are
  priced fused-vs-solo (the same micro-probe calibration the r06 router
  uses: fusing S launches saves (S-1) round trips and pays for the padding
  waste of stacking unequal tables); when fusion wins, ONE store-tagged
  ATTRIBUTED kernel launch (ops.deps_kernel.fused_flat_attr, or
  parallel.sharded.sharded_fused_attr under a mesh — floors/elision fold
  in-kernel, r15) answers every member.
- **Async harvest**: the fused launch is enqueued WITHOUT blocking — jax's
  async dispatch overlaps the device work with host protocol processing —
  and each member harvests its block in its own store task, enqueued at
  dispatch in store-id order: results land at the next event-loop boundary
  BEFORE any dependent task of that store runs, so determinism is the
  scheduler order, never device completion order.
- **Tick coalescing**: drain ticks registered within one tick window share
  one dispatcher event, and the single-device frontier sweeps of the
  members fuse into one vmapped launch
  (ops.drain_kernel.fused_ready_frontier[_ell]) when the same pricing says
  it pays.

Correctness contract: every fused launch is BIT-IDENTICAL to the solo
launches it replaces (tests/test_routing.py property tests), and the r07
fault ladder composes — a device fault inside a fused launch fails the
WHOLE batch over to the host route deterministically, then quarantines
per-store exactly as solo faults do (tests/test_device_faults.py).

Knobs: ``ACCORD_TPU_FUSION=off`` pins solo launches (the conftest canary
asserts tier-1 passes with it set — fusion must never become load-bearing
for correctness); everything else is priced, not thresholded.
"""

from __future__ import annotations

import os
import time
from functools import partial
from typing import Dict, List, Optional

import numpy as np

from ..obs import devprof
from ..ops import deps_kernel as dk
from ..ops import drain_kernel as drk
from ..utils import faults
from .device_index import _pow2_at_least


def fusion_enabled() -> bool:
    """The ACCORD_TPU_FUSION escape hatch: default ON; "off"/"0"/"false"/
    "no" pins every launch solo (correctness must never depend on fusion)."""
    return os.environ.get("ACCORD_TPU_FUSION", "").lower() not in (
        "off", "0", "false", "no")


def _profiled_harvest(name, dev0, members, download):
    """Run one fused-result ``download()`` under the device profiler (when
    armed): the harvest-barrier slice, pid-matched to the dispatch slice's
    node row.  Shared by the flush and tick harvest paths."""
    prof = devprof.PROFILER
    _t0 = time.perf_counter() if prof is not None else 0.0
    out = download()
    if prof is not None:
        prof.complete(name, _t0, time.perf_counter(), cat="fused",
                      pid=getattr(getattr(dev0.store, "node", None),
                                  "node_id", 0) or 0,
                      args={"members": members})
    return out


class FusedFlushLaunch:
    """One in-flight fused ATTRIBUTED deps launch: the shared device
    buffers plus the member hints.  The download happens at the FIRST
    member's harvest (faults.check rides it — one transfer crossing per
    fused launch) and is TWO-STAGE like the solo path: the stacked scalar
    headers first, then one slice carrying only the live prefix of every
    member's (merged, under a mesh) entry block; any device-boundary
    failure poisons the whole batch: every member quarantines and serves
    its flush from the snapshot host scan."""

    def __init__(self, dev_out, hints, s: int, k: int, d_ent: int,
                 b_pad: int, wide: bool):
        self.hdr_dev, self.ent_dev = dev_out
        self.hints = hints
        self.s = s
        self.k = k
        self.d_ent = d_ent        # entries per store = d_ent * s
        self.b_pad = b_pad
        self.wide = wide
        self._out = None
        self.failed: Optional[BaseException] = None

    def materialize(self):
        if self.failed is not None:
            raise self.failed
        if self._out is None:
            from .device_index import _prefix_len
            n_s = len(self.hints)
            itemsize = 8 if self.wide else 4
            dev0 = self.hints[0]["dev"]
            faults.check("transfer", "fused header download")
            hdr = _profiled_harvest(
                "fused_flush_harvest_header", dev0,
                n_s, lambda: np.asarray(self.hdr_dev))
            hdr = hdr.reshape(n_s, 5 + self.b_pad)
            s_eff = self.d_ent * self.s
            maxtot = min(int(hdr[:, 0].max()), s_eff)
            length = _prefix_len(maxtot, s_eff)
            faults.check("transfer", "fused entry download")
            ent3 = self.ent_dev.reshape(n_s, s_eff)[:, :length]
            ent = _profiled_harvest(
                "fused_flush_harvest_entries", dev0,
                n_s, lambda: np.asarray(ent3))
            # byte accounting lands on the first harvester (deterministic:
            # harvest order is store-id order)
            dev0.download_bytes += hdr.nbytes + ent.nbytes
            dev0.download_bytes_padded += \
                hdr.nbytes + n_s * s_eff * itemsize
            dev0.attr_download_bytes += hdr.nbytes + ent.nbytes
            self._out = (hdr, ent)
        return self._out

    def poison(self, exc: BaseException) -> None:
        if self.failed is None:
            self.failed = exc
            for h in self.hints:
                h["dev"]._device_fault(exc, f"fused collect: {exc}",
                                       sliced=True)
                h["probing"] = False


class FusedTick:
    """One in-flight fused drain-frontier launch (see FusedFlushLaunch for
    the failure contract)."""

    def __init__(self, dev_out, group):
        self.dev = dev_out
        self.rows = {id(dev): (i, live, dev.drain.version)
                     for i, (dev, _st, live) in enumerate(group)}
        self.members = [dev for dev, _st, _lv in group]
        self._out = None
        self.failed: Optional[BaseException] = None

    def serves(self, dev) -> bool:
        return id(dev) in self.rows

    def version_for(self, dev) -> int:
        return self.rows[id(dev)][2]

    def result_for(self, dev) -> np.ndarray:
        if self.failed is not None:
            raise self.failed
        if self._out is None:
            faults.check("transfer", "fused drain download")
            self._out = _profiled_harvest(
                "fused_tick_harvest", self.members[0],
                len(self.members), lambda: np.asarray(self.dev))
        i, live, _v = self.rows[id(dev)]
        ready = self._out[i][: len(live)]
        return live[ready & dev.drain.active[live]]

    def poison(self, exc: BaseException) -> None:
        if self.failed is None:
            self.failed = exc
            for dev in self.members:
                dev._device_fault(exc, f"fused drain collect: {exc}")


class DeviceDispatcher:
    """The per-node scheduler coalescing device launches across the node's
    CommandStores (module docstring)."""

    def __init__(self, node):
        self.node = node
        self.fusion = fusion_enabled()
        self._flush_pending: List = []
        self._flush_scheduled = False
        self._tick_pending: List = []
        self._tick_scheduled = False
        # launch accounting (the bench "# index" line and the sim stats
        # read these): fused launches serve many member flushes/ticks each
        self.n_fused_launches = 0
        self.n_fused_members = 0
        self.n_solo_flushes = 0
        self.n_fused_tick_launches = 0
        self.n_fused_tick_members = 0
        self.n_solo_ticks = 0
        # cross-request flush occupancy (r16): one dispatcher event
        # serves every store flush registered in the same scheduler tick,
        # and each store's batch carries every query queued by that
        # tick's ops — the serving path's batch envelopes land their
        # sub-ops in one tick precisely so these ratios grow.  events ->
        # member flushes -> queries is the device-side occupancy ladder
        # (the wire-side analogue is the server's batch_occupancy_p50).
        self.n_flush_events = 0
        self.n_flush_members = 0
        self.n_flush_queries = 0
        # observer(kind, n_members, nq) — the sim cluster wires stats/trace
        self.on_fused = None

    def _handled(self, exc: BaseException) -> None:
        agent = getattr(self.node, "agent", None)
        if agent is not None and hasattr(agent, "on_handled_exception"):
            agent.on_handled_exception(exc)

    # -- flush side ---------------------------------------------------------
    def register_flush(self, dev) -> None:
        self._flush_pending.append(dev)
        if not self._flush_scheduled:
            self._flush_scheduled = True
            # one scheduler hop (zero sim-time) so every same-instant
            # message's store task enqueues its queries BEFORE dispatch
            self.node.scheduler.now(self._run_flushes)

    def _run_flushes(self) -> None:
        from .command_store import PreLoadContext
        self._flush_scheduled = False
        devs = self._flush_pending
        self._flush_pending = []
        if not getattr(self.node, "alive", True):
            return    # dead incarnation (restart): ghost work must not run
        devs.sort(key=lambda d: d.store.store_id)
        plans = []
        for dev in devs:
            batch = dev._q_pending
            dev._q_pending = []
            if batch:
                plans.append((dev, batch))
        if plans:
            self.n_flush_events += 1
            self.n_flush_members += len(plans)
            self.n_flush_queries += sum(len(b) for _d, b in plans)
        hints: Dict[int, dict] = {}
        launch = None
        if self.fusion and len(plans) >= 2:
            try:
                for dev, batch in plans:
                    h = dev.fused_eligible([q for q, _b, _d in batch])
                    if h is not None:
                        h["batch"] = batch
                        hints[id(dev)] = h
                if len(hints) >= 2 and \
                        self._fused_flush_pays(list(hints.values())):
                    launch = self._launch_fused_flush(list(hints.values()))
                else:
                    hints = {}
            except BaseException as e:  # noqa: BLE001
                # NOT a device fault (those are absorbed inside
                # _launch_fused_flush as the whole-batch host failover) —
                # an unexpected host-side error must never strand the
                # claimed batches with their done callbacks unfired: fall
                # back to solo flushes, which carry their own failure
                # delivery
                self._handled(e)
                hints = {}
                launch = None
        # harvest order IS the deterministic scheduler order: one store
        # task per member, enqueued here in ascending store id
        for dev, batch in plans:
            h = hints.get(id(dev))
            if h is not None:
                dev.store.execute(
                    PreLoadContext.empty(),
                    partial(dev.fused_harvest, hint=h, launch=launch))
            else:
                self.n_solo_flushes += 1
                dev.store.execute(PreLoadContext.empty(),
                                  partial(dev._flush_batch, batch=batch))

    def _stacked_attr(self, hints):
        """Pre-stacked [S, ...] AttrCols + AttrIndex for the fused
        attributed launch, cached on the members' attr versions and index
        identities: 16 stores' twenty extra per-store pytrees per launch
        measured ~5ms of pure jax argument flattening on the tiny-flush
        regime — stacking host-side hands the jit TWO pytrees and keeps
        the device copies resident between launches."""
        import jax.numpy as jnp
        key = (tuple(id(h["dev"]) for h in hints),
               tuple(h["dev"].deps.attr_version for h in hints),
               tuple(h["aidx"].seq for h in hints))
        cached = getattr(self, "_stacked_attr_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1], cached[2]
        n_max = max(h["dev"].deps.capacity for h in hints)
        cols = []
        for h in hints:
            hc = h["dev"].deps._attr_host_cols()
            cols.append([np.concatenate(
                [a, np.full(n_max - len(a),
                            dk.SLOT_FREE if i == 1 else (1 if i == 0 else 0),
                            a.dtype)]) if len(a) < n_max else a
                for i, a in enumerate(hc)])
        sa = dk.AttrCols(*(jnp.asarray(np.stack(c))
                           for c in zip(*cols)))
        pads = [h["aidx"].pad for h in hints]
        f_max = max(len(p[0]) for p in pads)
        t_max = max(len(p[4]) for p in pads)
        l_max = max(len(p[6]) for p in pads)
        import numpy as _np

        def tail(a, n, fill):
            if len(a) >= n:
                return a
            out = _np.full(n, fill, a.dtype)
            out[: len(a)] = a
            return out

        inf = _np.int64(_np.iinfo(_np.int64).max)
        rows = []
        for p in pads:
            live_l = p[5][-1]
            rows.append((tail(p[0], f_max, inf),
                         tail(p[1], f_max + 1, 0), tail(p[2], f_max + 1, 0),
                         tail(p[3], f_max + 1, 0),
                         tail(p[4], t_max, inf), tail(p[5], t_max + 1, live_l),
                         tail(p[6], l_max, inf), tail(p[7], l_max, 0),
                         tail(p[8], l_max, 0), tail(p[9], l_max, 0),
                         p[10]))
        si = dk.AttrIndex(*(jnp.asarray(np.stack(c)) for c in zip(*rows)))
        self._stacked_attr_cache = (key, sa, si)
        return sa, si

    def _fused_flush_pays(self, hints) -> bool:
        """Price ONE fused launch against the members' solo launches with
        the r06 micro-probe calibration: fusing saves (S-1) round trips
        and pays the padding waste of stacking unequal tables / batches."""
        dev0 = hints[0]["dev"]
        calib = dev0._calibration()
        rtt, c_dev = calib["rtt"], calib["c_dev"]
        d = 1
        if dev0.mesh is not None:
            d = max(len(dev0.mesh.devices.flat), 1)
            rtt = calib.get("rtt_mesh", rtt)
        solo = sum(2.0 * rtt + c_dev * h["solo_elems"] for h in hints)
        b_pad = _pow2_at_least(max(h["b_pad"] for h in hints), 1)
        q_m = max(h["q_m"] for h in hints)
        n_max = max(h["cap"] for h in hints)
        m_max = max(h["m_iv"] for h in hints)
        fused_elems = len(hints) * b_pad * (n_max // d) * q_m * m_max
        # the deferred harvest needs begin-time mirror snapshots the solo
        # immediate path never takes — charge the stale members' copies at
        # the measured memcpy rate (version-cached, so an unmutated mirror
        # re-fuses for free)
        c_copy = calib.get("c_copy", calib["c_host"] / 20.0)
        snap_cost = c_copy * sum(h["snap_elems"] for h in hints)
        return 2.0 * rtt + c_dev * fused_elems + snap_cost < solo

    def _launch_fused_flush(self, hints) -> Optional[FusedFlushLaunch]:
        prof = devprof.PROFILER
        _t0 = time.perf_counter() if prof is not None else 0.0
        devs = [h["dev"] for h in hints]
        mesh = devs[0].mesh            # one node -> one mesh for all stores
        d = 1 if mesh is None else max(len(mesh.devices.flat), 1)
        q_m = max(h["q_m"] for h in hints)
        b_pad = _pow2_at_least(max(h["b_pad"] for h in hints), 1)
        s = max(min(dev._batch_flat, b_pad * (h["cap"] // d)
                    * h["m_iv"] * q_m)
                for dev, h in zip(devs, hints))
        k = max(min(dev._batch_k, (h["cap"] // d) * h["m_iv"] * q_m)
                for dev, h in zip(devs, hints))
        qmats = np.empty((len(hints), b_pad, 7 + 2 * q_m), np.int64)
        pm = np.zeros(len(hints), np.int64)
        pl = np.zeros(len(hints), np.int64)
        pn = np.zeros(len(hints), np.int32)
        m_max = max(h["m_iv"] for h in hints)
        # the fused trace pads every table to the group's interval width,
        # so codes scale on m_max; the entry dtype must hold the WIDEST
        # member's codes — under a mesh the merged entries carry GLOBAL
        # slot ids on the padded shard stride, so the crossover is the
        # whole padded slot space
        rankbs = np.zeros((len(hints), b_pad), np.int64)
        pad_shard_n = max(h["cap"] // d for h in hints)
        if mesh is not None:
            wide = dk.wide_codes(d * pad_shard_n, m_max, q_m)
        else:
            wide = any(dk.wide_codes(h["cap"], m_max, q_m) for h in hints)
        for i, h in enumerate(hints):
            qnp, qmi, nq = h["qnp"], h["q_m"], h["nq"]
            rows_p = np.minimum(np.arange(b_pad), nq - 1)
            qmats[i, :, :7] = qnp[rows_p, :7]
            qmats[i, :, 7:7 + q_m] = dk.PAD_LO
            qmats[i, :, 7 + q_m:] = dk.PAD_HI
            qmats[i, :, 7:7 + qmi] = qnp[rows_p, 7:7 + qmi]
            qmats[i, :, 7 + q_m:7 + q_m + qmi] = qnp[rows_p, 7 + qmi:]
            rankbs[i] = h["rankb_np"][rows_p]
            if h["prune"] is not None:
                pm[i], pl[i], pn[i] = h["prune"]
            h["gmap"] = np.where(np.arange(b_pad) < nq,
                                 np.arange(b_pad), -1)
            h["row"] = i
            h["d"] = d
            h["d_mesh"] = d
            h["shard_n"] = h["cap"] // d
            h["pad_shard_n"] = pad_shard_n if mesh is not None else None
            h["b_pad_c"] = b_pad
            h["q_m_c"] = q_m
            h["m_max"] = m_max
            h["mq"] = m_max * q_m
            h["wide"] = wide
            h["qmat_np"] = qmats[i]
            h["rankb_pad"] = rankbs[i]
        # commit first (probe bookkeeping, mirror snapshots, route
        # observation): a launch fault below must still find the begin-time
        # snapshot to serve the host failover from
        for h in hints:
            h["dev"].fused_commit(h)
        try:
            dk.launch_check("fused")
            tables = [h["dev"].fused_table() for h in hints]
            for h, t in zip(hints, tables):
                h["table"] = t
            import jax.numpy as jnp
            # static leg switches, OR'd over the group: a member with a
            # trivial floor map / empty elision index just computes
            # nothing in the shared legs
            fl_ = any(not h.get("floor_skip", False) for h in hints)
            el_ = any(h["aidx"].u > 0 for h in hints)
            if mesh is not None:
                from ..parallel.sharded import sharded_fused_attr
                attrs = [h["dev"].deps.device_attr_cols_sharded(mesh)
                         for h in hints]
                aidxs = [h["aidx"].device_replicated(mesh) for h in hints]
                out = sharded_fused_attr(mesh, len(hints), q_m, s, k,
                                         wide, fl_, el_)(
                    *tables, *attrs, *aidxs, jnp.asarray(qmats),
                    jnp.asarray(rankbs), jnp.asarray(pm),
                    jnp.asarray(pl), jnp.asarray(pn))
            else:
                sa, si = self._stacked_attr(hints)
                out = dk.fused_flat_attr(tables, sa, si, qmats,
                                         rankbs, (pm, pl, pn),
                                         q_m, s, k, wide, fl_, el_)
        except faults.DEVICE_EXCEPTIONS as e:
            # a device fault inside the fused launch fails the WHOLE batch
            # over to the host route, then quarantines per-store as solo
            # faults do
            for h in hints:
                h["dev"].fused_fail_to_host(h, e)
            return None
        self.n_fused_launches += 1
        self.n_fused_members += len(hints)
        if prof is not None:
            # pack + stack + async enqueue of ONE store-tagged launch in
            # place of len(hints) solo launches — the coalescing win as a
            # timeline slice (harvest lands in fused_flush_harvest)
            prof.complete("fused_flush_dispatch", _t0, time.perf_counter(),
                          cat="fused", pid=getattr(self.node, "node_id", 0),
                          args={"members": len(hints),
                                "nq": sum(h["nq"] for h in hints)})
        if self.on_fused is not None:
            self.on_fused("flush", len(hints),
                          sum(h["nq"] for h in hints))
        return FusedFlushLaunch(out, hints, s, k,
                                d if mesh is not None else 1, b_pad, wide)

    # -- tick side ----------------------------------------------------------
    def register_tick(self, dev) -> None:
        self._tick_pending.append(dev)
        if not self._tick_scheduled:
            self._tick_scheduled = True
            self.node.scheduler.once(dev.TICK_DELAY_MICROS, self._run_ticks)

    def _run_ticks(self) -> None:
        from .command_store import PreLoadContext
        self._tick_scheduled = False
        devs = self._tick_pending
        self._tick_pending = []
        if not getattr(self.node, "alive", True):
            return    # dead incarnation (restart): ghost work must not run
        devs.sort(key=lambda d: d.store.store_id)
        fused_by: Dict[int, FusedTick] = {}
        if self.fusion and len(devs) >= 2:
            try:
                fused_by = self._prepare_fused_ticks(devs)
            except BaseException as e:  # noqa: BLE001
                # an unexpected host-side error preparing the fused sweep
                # must never leave the members' _tick_scheduled flags
                # stuck True (a node-wide lost wakeup): every member still
                # gets its solo tick task below
                self._handled(e)
                fused_by = {}
        for dev in devs:
            f = fused_by.get(id(dev))
            if f is None:
                self.n_solo_ticks += 1
            dev.store.execute(PreLoadContext.empty(),
                              partial(dev._tick, fused=f))

    def _prepare_fused_ticks(self, devs) -> Dict[int, FusedTick]:
        # a widened-wavefront store (r19, _drain_wavefront > 1) is mid-
        # cascade and runs the level kernel solo — the fused frontier sweep
        # would shrink its candidate set back to one antichain
        cands = [d for d in devs
                 if not (d.host_pinned or d._dev_quar_flushes > 0)
                 and getattr(d, "_drain_wavefront", 1) <= 1
                 and d.drain.active.any()]
        if len(cands) < 2:
            return {}
        try:
            dk.launch_check("fused drain")
            built = [(d,) + d.drain.state() for d in cands]
        except faults.DEVICE_EXCEPTIONS as e:
            # whole-batch failover: every candidate quarantines; their
            # tick tasks sweep on host via the quarantine guard
            for d in cands:
                d._device_fault(e, f"fused drain tick: {e}")
            return {}
        dense, ell = [], []
        for dev, state, live in built:
            if isinstance(state, drk.EllDrainState):
                ell.append((dev, state, live))
            else:
                n = state.status.shape[0]
                if dev.mesh is not None \
                        and n % len(dev.mesh.devices.flat) == 0 \
                        and dev._mesh_tick_pays(n):
                    continue       # the solo mesh sweep is the modeled winner
                dense.append((dev, state, live))
        out: Dict[int, FusedTick] = {}
        calib = devs[0]._calibration()
        for group, kernel, kind in (
                (dense, drk.fused_ready_frontier, "dense"),
                (ell, drk.fused_ready_frontier_ell, "ell")):
            if len(group) < 2 or not self._fused_tick_pays(group, calib,
                                                           kind):
                continue
            prof = devprof.PROFILER
            _t0 = time.perf_counter() if prof is not None else 0.0
            try:
                out_dev = kernel([st for _d, st, _lv in group])
            except faults.DEVICE_EXCEPTIONS as e:
                for dev, _st, _lv in group:
                    dev._device_fault(e, f"fused drain launch: {e}")
                continue
            ft = FusedTick(out_dev, group)
            self.n_fused_tick_launches += 1
            self.n_fused_tick_members += len(group)
            if prof is not None:
                prof.complete("fused_tick_dispatch", _t0,
                              time.perf_counter(), cat="fused",
                              pid=getattr(self.node, "node_id", 0),
                              args={"members": len(group), "kind": kind})
            if self.on_fused is not None:
                self.on_fused("tick", len(group), 0)
            for dev, _st, _lv in group:
                out[id(dev)] = ft
        return out

    def _fused_tick_pays(self, group, calib, kind: str) -> bool:
        rtt, c_dev = calib["rtt"], calib["c_dev"]
        if kind == "dense":
            sizes = [st.status.shape[0] for _d, st, _lv in group]
            n_max = max(sizes)
            waste = c_dev * (len(sizes) * n_max * n_max
                             - sum(n * n for n in sizes))
        else:
            shapes = [st.adj_idx.shape for _d, st, _lv in group]
            n_max = max(sh[0] for sh in shapes)
            d_max = max(sh[1] for sh in shapes)
            waste = c_dev * (len(shapes) * n_max * d_max
                             - sum(n * dd for n, dd in shapes))
        return 2.0 * rtt * (len(group) - 1) > waste
