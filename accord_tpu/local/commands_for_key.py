"""Per-key conflict index — the PreAccept hot structure.

Rebuild of ref: accord-core/src/main/java/accord/local/CommandsForKey.java:132
(TxnInfo ladder :293-410, mapReduceActive :614-650, mapReduceFull :553-612,
the missing[]/transitive-elision design comment :73-131).

This is the host (correctness) implementation: a sorted vector of TxnInfo per
key with the scan API.  The batched device analogue — the same scan as a
masked searchsorted/prefix kernel over the CSR key->txn adjacency, vmapped
over keys and in-flight txns — lives in accord_tpu.ops.deps_kernels and is
validated against this implementation.

Two compressions keep dep sets O(active) instead of O(history), both from
the reference's design comment (CommandsForKey.java:73-131):

- **missing[] encoding.**  The collection implies the deps of every command
  in it ("deps = every lower TxnId here"); each command stores only its
  DIVERGENCE — the lower TxnIds it did NOT witness — in ``TxnInfo.missing``.
  The invariant making later inserts cheap: when a command's deps freeze,
  every per-key dep id is ensured present in the collection (transitively
  witnessed if unseen), so any id inserted AFTER the freeze is guaranteed
  unwitnessed and is appended to the frozen command's missing.  Ids that
  reach Committed+ (or Invalidated) are elided from every missing array —
  recovery of a decided id never deciphers fast-path votes, which is the
  missing collection's only consumer.

- **Transitive-dependency elision.**  mapReduceActive skips any decided
  (Committed+) txn whose executeAt is below the latest committed WRITE
  executing before the query bound: depending on that later write reaches
  them transitively through its stable deps.  Recovery stays exact (see the
  reference's argument: any recovery quorum either reports the later write
  Stable — recovering its deps — or witnesses the earlier txn directly).
"""

from __future__ import annotations

import bisect
import enum
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..primitives.timestamp import Kinds, Timestamp, TxnId
from ..utils import invariants


class InternalStatus(enum.IntEnum):
    """Compressed per-key view of a txn's protocol state
    (ref: CommandsForKey.java InternalStatus)."""
    TRANSITIVELY_KNOWN = 0   # witnessed only via another txn's deps
    PREACCEPTED = 1
    ACCEPTED = 2
    COMMITTED = 3            # executeAt decided
    STABLE = 4
    APPLIED = 5
    INVALIDATED = 6

    def has_execute_at(self) -> bool:
        """ACCEPTED carries the proposed executeAt (recovery's accepted-
        no-witness reasoning needs it); COMMITTED+ the decided one."""
        return InternalStatus.ACCEPTED <= self <= InternalStatus.APPLIED


class TxnInfo:
    """(ref: CommandsForKey.java:293-410) — TxnId + per-key status +
    executeAt + the missing divergence (None until deps freeze)."""

    __slots__ = ("txn_id", "status", "execute_at", "missing")

    def __init__(self, txn_id: TxnId, status: InternalStatus,
                 execute_at: Optional[Timestamp] = None,
                 missing: Optional[List[TxnId]] = None):
        self.txn_id = txn_id
        self.status = status
        self.execute_at = execute_at if execute_at is not None else txn_id
        # sorted lower TxnIds this command did NOT witness; None = deps not
        # yet known here (witness queries must fall back to the Command)
        self.missing = missing

    def deps_known(self) -> bool:
        return self.missing is not None

    def witnesses_id(self, txn_id: TxnId) -> Optional[bool]:
        """Whether this command's per-key deps include txn_id; None if the
        collection cannot answer.  missing[] only records LOWER unwitnessed
        ids (the implied-deps convention covers only ids below this one), so
        membership of HIGHER ids — possible via accept-phase deps collected
        up to a later executeAt — must fall back to the Command record."""
        if self.missing is None or txn_id > self.txn_id:
            return None
        i = bisect.bisect_left(self.missing, txn_id)
        present_in_missing = i < len(self.missing) and self.missing[i] == txn_id
        return not present_in_missing

    def __repr__(self):
        return f"TxnInfo({self.txn_id}, {self.status.name})"


class CommandsForKey:
    """All (globally visible) transactions witnessed on one key, ordered by
    TxnId, with a parallel executeAt-ordered view of committed txns."""

    __slots__ = ("token", "_ids", "_infos", "prune_before",
                 "_committed_write_execs", "_n_unwitnessable",
                 "_elide_version", "_packed_cw")

    def __init__(self, token: int):
        self.token = token
        self._ids: List[TxnId] = []        # sorted
        self._infos: Dict[TxnId, TxnInfo] = {}
        # txns with txnId < prune_before are redundant (covered by
        # RedundantBefore) and excluded from deps
        self.prune_before: Optional[TxnId] = None
        # executeAts of decided (Committed+) writes, sorted — the elision
        # pivot lookup must not rescan the whole history on the hot path
        # (ref: the committed[] executeAt-ordered array, CommandsForKey.java)
        self._committed_write_execs: List[Timestamp] = []
        # count of TRANSITIVELY_KNOWN/INVALIDATED entries: when 0 AND no
        # committed-write pivot exists below the bound, NOTHING on this key
        # can elide — the batched device attribution skips per-dep elision
        # lookups wholesale (see can_elide)
        self._n_unwitnessable = 0
        # monotone counter of _committed_write_execs CONTENT mutations —
        # keys the packed-pivot-array cache the device/host batch elision
        # consumes.  A length-based key is NOT sound: a decided write's
        # executeAt moving (r14 find) keeps the length while changing the
        # pivot content
        self._elide_version = 0
        self._packed_cw = None   # (_elide_version, (msb, lsb, node) i64/i32)

    def _cw_mutated(self) -> None:
        self._elide_version += 1
        self._packed_cw = None

    def packed_committed_execs(self):
        """The elision pivot list as three numpy columns (msb, lsb int64;
        node int32), ascending in the SAME order the Timestamp objects
        sort (unsigned on the packed words) — the per-key building block
        of the batched elision index (device_index._attr_elide_index).
        Cached per _elide_version; rebuild is O(n) over a per-key list."""
        import numpy as np

        from ..ops.packing import to_i64
        hit = self._packed_cw
        if hit is not None and hit[0] == self._elide_version:
            return hit[1]
        n = len(self._committed_write_execs)
        m = np.empty(n, np.int64)
        l = np.empty(n, np.int64)
        nd = np.empty(n, np.int32)
        for i, ts in enumerate(self._committed_write_execs):
            m[i] = to_i64(ts.msb)
            l[i] = to_i64(ts.lsb)
            nd[i] = ts.node
        packed = (m, l, nd)
        self._packed_cw = (self._elide_version, packed)
        return packed

    # -- update path --------------------------------------------------------
    def update(self, txn_id: TxnId, status: InternalStatus,
               execute_at: Optional[Timestamp] = None,
               witnessed_deps: Optional[List[TxnId]] = None) -> None:
        """Witness or advance a txn on this key
        (ref: CommandsForKey insert/update :652+).  ``witnessed_deps`` is
        the command's per-key dep ids when its deps freeze (accept/commit):
        it drives the missing[] maintenance."""
        if not txn_id.kind().is_globally_visible():
            return
        info = self._infos.get(txn_id)
        if info is None:
            info = TxnInfo(txn_id, status, execute_at)
            self._infos[txn_id] = info
            bisect.insort(self._ids, txn_id)
            self._on_inserted(txn_id, status)
            if status in (InternalStatus.TRANSITIVELY_KNOWN,
                          InternalStatus.INVALIDATED):
                self._n_unwitnessable += 1
            if InternalStatus.COMMITTED <= status <= InternalStatus.APPLIED \
                    and txn_id.kind().is_write():
                bisect.insort(self._committed_write_execs, info.execute_at)
                self._cw_mutated()
        else:
            prev = info.status
            info.status = max(info.status, status)   # never regress
            was_un = prev in (InternalStatus.TRANSITIVELY_KNOWN,
                              InternalStatus.INVALIDATED)
            now_un = info.status in (InternalStatus.TRANSITIVELY_KNOWN,
                                     InternalStatus.INVALIDATED)
            if was_un != now_un:
                self._n_unwitnessable += 1 if now_un else -1
            # the executeAt may only advance with the status grade: a late
            # ACCEPTED-grade update carrying a *proposed* executeAt must not
            # regress the decided executeAt of a COMMITTED+ entry (it would
            # skew the elision pivot and recovery scans) — guard here rather
            # than relying on every caller's ordering guards
            if execute_at is not None and status.has_execute_at() \
                    and (status >= prev or prev < InternalStatus.COMMITTED) \
                    and execute_at != info.execute_at:
                if InternalStatus.COMMITTED <= prev <= InternalStatus.APPLIED \
                        and txn_id.kind().is_write():
                    # r14 torture-rig find: a decided-grade update moving an
                    # already-indexed write's executeAt left the OLD value in
                    # _committed_write_execs and never inserted the new one —
                    # elision then pivots on a ghost timestamp.  Keep the
                    # pivot list in lockstep with the executeAt it indexes.
                    i = bisect.bisect_left(self._committed_write_execs,
                                           info.execute_at)
                    if i < len(self._committed_write_execs) \
                            and self._committed_write_execs[i] == info.execute_at:
                        del self._committed_write_execs[i]
                    bisect.insort(self._committed_write_execs, execute_at)
                    self._cw_mutated()
                info.execute_at = execute_at
            if info.status is InternalStatus.INVALIDATED \
                    and InternalStatus.COMMITTED <= prev <= InternalStatus.APPLIED \
                    and txn_id.kind().is_write():
                # illegal in a healthy run (commit_invalidate guards it) but
                # a stale pivot from an invalidated write must never elide
                # genuinely-live deps
                i = bisect.bisect_left(self._committed_write_execs,
                                       info.execute_at)
                if i < len(self._committed_write_execs) \
                        and self._committed_write_execs[i] == info.execute_at:
                    del self._committed_write_execs[i]
                    self._cw_mutated()
            if prev < InternalStatus.COMMITTED and (
                    info.status >= InternalStatus.COMMITTED):
                # decided: elide from every missing array — recovery of a
                # decided id never needs fast-path witness info
                # (ref: the missing-elision rule, CommandsForKey.java:82-88)
                self._elide_from_missing(txn_id)
                if info.status is not InternalStatus.INVALIDATED \
                        and txn_id.kind().is_write():
                    bisect.insort(self._committed_write_execs, info.execute_at)
                    self._cw_mutated()
        if witnessed_deps is not None:
            # (re)freeze: a higher-ballot accept or the commit may carry a
            # different proposal — last-wins, recomputed vs the collection
            self._freeze_deps(info, witnessed_deps)

    def _freeze_deps(self, info: TxnInfo, witnessed_deps: List[TxnId]) -> None:
        """The command's per-key deps are now fixed: ensure every dep id is
        present (transitively witnessed) so later inserts are provably
        unwitnessed, then record the divergence."""
        witnessed = set()
        for d in witnessed_deps:
            if d == info.txn_id:
                continue
            witnessed.add(d)
            # sync points are range-domain: they never enter a per-key index
            # (ref: the CommandsForKey invariant that key deps on
            # (Exclusive)SyncPoints are not added) — without this, every
            # boundary fence dep lands in EVERY key's collection as a
            # transitive entry and the index grows with fence history
            if not d.kind().is_sync_point():
                self.witness_transitive(d)
        kinds = info.txn_id.kind().witnesses()
        hi = bisect.bisect_left(self._ids, info.txn_id)
        missing = []
        for i in range(hi):
            tid = self._ids[i]
            if tid in witnessed or not kinds.test(tid.kind()):
                continue
            other = self._infos[tid]
            if other.status >= InternalStatus.COMMITTED:
                continue   # decided (or invalidated): elided
            missing.append(tid)
        info.missing = missing

    def _on_inserted(self, txn_id: TxnId, status: InternalStatus) -> None:
        """A new id entered the collection: every LATER command whose deps
        are already frozen is guaranteed not to have witnessed it (its dep
        ids were all ensured present at freeze time)."""
        if status >= InternalStatus.COMMITTED:
            return   # decided on arrival: elided everywhere
        lo = bisect.bisect_right(self._ids, txn_id)
        for i in range(lo, len(self._ids)):
            info = self._infos[self._ids[i]]
            if info.missing is None:
                continue
            if not info.txn_id.kind().witnesses().test(txn_id.kind()):
                continue
            j = bisect.bisect_left(info.missing, txn_id)
            if j >= len(info.missing) or info.missing[j] != txn_id:
                info.missing.insert(j, txn_id)

    def _elide_from_missing(self, txn_id: TxnId) -> None:
        lo = bisect.bisect_right(self._ids, txn_id)
        for i in range(lo, len(self._ids)):
            info = self._infos[self._ids[i]]
            if not info.missing:
                continue
            j = bisect.bisect_left(info.missing, txn_id)
            if j < len(info.missing) and info.missing[j] == txn_id:
                del info.missing[j]

    def witness_transitive(self, txn_id: TxnId) -> None:
        if self.prune_before is not None and txn_id < self.prune_before:
            return   # decided+applied everywhere: never re-enters the index
        if txn_id.kind().is_globally_visible() and txn_id not in self._infos:
            self._infos[txn_id] = TxnInfo(txn_id,
                                          InternalStatus.TRANSITIVELY_KNOWN)
            bisect.insort(self._ids, txn_id)
            self._on_inserted(txn_id, InternalStatus.TRANSITIVELY_KNOWN)
            self._n_unwitnessable += 1

    def remove(self, txn_id: TxnId) -> None:
        info = self._infos.get(txn_id)
        if info is not None:
            if info.status in (InternalStatus.TRANSITIVELY_KNOWN,
                               InternalStatus.INVALIDATED):
                self._n_unwitnessable -= 1
            if InternalStatus.COMMITTED <= info.status <= InternalStatus.APPLIED \
                    and txn_id.kind().is_write():
                # r14 torture-rig find: the pivot followed the entry out of
                # the index only when a LATER prune happened to drop
                # something (the cut==0 early return skipped the rebuild) —
                # until then elision pivoted on a write no scan can return.
                # Retract it with the entry: conservative (more deps
                # scanned), and the pivot list's invariant becomes simply
                # "the decided writes present in the index".
                i = bisect.bisect_left(self._committed_write_execs,
                                       info.execute_at)
                if i < len(self._committed_write_execs) \
                        and self._committed_write_execs[i] == info.execute_at:
                    del self._committed_write_execs[i]
                    self._cw_mutated()
            del self._infos[txn_id]
            i = bisect.bisect_left(self._ids, txn_id)
            if i < len(self._ids) and self._ids[i] == txn_id:
                del self._ids[i]

    def set_prune_before(self, txn_id: TxnId) -> None:
        if self.prune_before is None or txn_id > self.prune_before:
            self.prune_before = txn_id

    def prune(self) -> int:
        """Physically drop entries below the prune watermark — the shard
        watermark guarantees everything below it has applied (or been
        invalidated) at every replica, so no dep set or recovery query needs
        them (ref: CommandsForKey.java prune vs RedundantBefore).  Returns
        #entries dropped."""
        if self.prune_before is None:
            return 0
        cut = bisect.bisect_left(self._ids, self.prune_before)
        if cut == 0:
            return 0
        dropped = self._ids[:cut]
        for tid in dropped:
            del self._infos[tid]
        del self._ids[:cut]
        # their missing entries are dead weight now
        for tid in dropped:
            self._elide_from_missing(tid)
        # rebuild the pivot list (prune is rare; the hot path stays O(log n))
        self._committed_write_execs = sorted(
            info.execute_at for info in self._infos.values()
            if InternalStatus.COMMITTED <= info.status <= InternalStatus.APPLIED
            and info.txn_id.kind().is_write())
        self._cw_mutated()
        self._n_unwitnessable = sum(
            1 for info in self._infos.values()
            if info.status in (InternalStatus.TRANSITIVELY_KNOWN,
                               InternalStatus.INVALIDATED))
        return cut

    def may_elide_any(self) -> bool:
        """Monotone pre-filter for the batch attribution: False when no
        entry on this key can be elided for ANY bound (no committed writes
        recorded, no unwitnessable entries) — the common key skips the
        per-bound pivot lookup entirely."""
        return bool(self._committed_write_execs) or self._n_unwitnessable > 0

    def can_elide(self, bound: Timestamp):
        """Batch fast-path for the device attribution: returns None when NO
        entry on this key can be elided for ``bound`` (no unwitnessable
        entries and no committed-write pivot below the bound), else the
        pivot to pass to is_elided."""
        pivot = self.max_committed_write_before(bound)
        if pivot is None and self._n_unwitnessable == 0:
            return None
        return pivot if pivot is not None else Timestamp.NONE

    # -- scan API -----------------------------------------------------------
    def max_committed_write_before(self, bound: Timestamp) -> Optional[Timestamp]:
        """The latest executeAt of a decided (Committed+) WRITE executing
        before ``bound`` — the transitive-elision pivot, answered from the
        incrementally-maintained executeAt-sorted list in O(log n)
        (ref: mapReduceActive's maxCommittedBefore over the committed[]
        array, CommandsForKey.java:614)."""
        i = bisect.bisect_left(self._committed_write_execs, bound)
        return self._committed_write_execs[i - 1] if i > 0 else None

    def is_elided(self, info: TxnInfo, bound: Timestamp,
                  pivot: Optional[Timestamp] = None) -> bool:
        """The one active-scan skip rule, shared by the host fold and the
        device query attribution (keep them in lockstep): transitively-known
        and invalidated entries never appear; decided entries executing
        below the latest decided write before ``bound`` are reached through
        that write's stable deps."""
        if info.status in (InternalStatus.INVALIDATED,
                           InternalStatus.TRANSITIVELY_KNOWN):
            return True
        if InternalStatus.COMMITTED <= info.status <= InternalStatus.APPLIED:
            if pivot is None:
                pivot = self.max_committed_write_before(bound)
            return pivot is not None and info.execute_at < pivot
        return False

    def map_reduce_active(self, started_before: Timestamp, witnesses: Kinds,
                          fn: Callable[[TxnId, "object"], "object"], acc):
        """Fold over active txns with txnId < started_before whose kind the
        querying txn must witness (ref: CommandsForKey.java:614-650).
        Skips invalidated and transitively-known txns, anything below the
        prune watermark, and — the transitive elision — decided txns whose
        executeAt is below the latest committed write before the bound."""
        hi = bisect.bisect_left(self._ids, started_before)
        lo = 0
        if self.prune_before is not None:
            lo = bisect.bisect_left(self._ids, self.prune_before)
        pivot = self.max_committed_write_before(started_before)
        for i in range(lo, hi):
            tid = self._ids[i]
            info = self._infos[tid]
            if self.is_elided(info, started_before, pivot):
                continue
            if not witnesses.test(tid.kind()):
                continue
            acc = fn(tid, acc)
        return acc

    def map_reduce_full(self, test_txn_id: TxnId, witnesses: Kinds,
                        fn: Callable[[TxnInfo, "object"], "object"], acc):
        """Fold over ALL txns (any bound, any status) for recovery queries
        (ref: CommandsForKey.java:553-612)."""
        for tid in list(self._ids):
            info = self._infos[tid]
            if not witnesses.test(tid.kind()):
                continue
            acc = fn(info, acc)
        return acc

    # -- queries ------------------------------------------------------------
    def get(self, txn_id: TxnId) -> Optional[TxnInfo]:
        return self._infos.get(txn_id)

    def size(self) -> int:
        return len(self._ids)

    def txn_ids(self) -> List[TxnId]:
        return list(self._ids)

    def max_committed_execute_at(self) -> Optional[Timestamp]:
        best: Optional[Timestamp] = None
        for info in self._infos.values():
            if info.status.has_execute_at() or info.status is InternalStatus.APPLIED:
                if best is None or info.execute_at > best:
                    best = info.execute_at
        return best

    def max_applied_before(self, bound: Timestamp) -> Optional[Timestamp]:
        best: Optional[Timestamp] = None
        for info in self._infos.values():
            if info.status is InternalStatus.APPLIED and info.execute_at < bound:
                if best is None or info.execute_at > best:
                    best = info.execute_at
        return best

    def last_witnessed(self) -> Optional[TxnId]:
        return self._ids[-1] if self._ids else None

    def __repr__(self):
        return f"CommandsForKey({self.token}, n={len(self._ids)})"
