"""Per-key conflict index — the PreAccept hot structure.

Rebuild of ref: accord-core/src/main/java/accord/local/CommandsForKey.java:132
(TxnInfo ladder :293-410, mapReduceActive :614-650, mapReduceFull :553-612).

This is the host (correctness) implementation: a sorted vector of TxnInfo per
key with the scan API.  The batched device analogue — the same scan as a
masked searchsorted/prefix kernel over the CSR key->txn adjacency, vmapped
over keys and in-flight txns — lives in accord_tpu.ops.deps_kernels and is
validated against this implementation.

The reference additionally compresses deps via ``missing[]`` arrays and
transitive-dependency elision against maxAppliedWrite (CommandsForKey.java:73-131).
Here we keep the full (uncompressed, always-correct) dep set host-side and
apply pruning only through RedundantBefore watermarks; compression is a
device-format concern.
"""

from __future__ import annotations

import bisect
import enum
from typing import Callable, Dict, Iterator, List, Optional, Tuple

from ..primitives.timestamp import Kinds, Timestamp, TxnId
from ..utils import invariants


class InternalStatus(enum.IntEnum):
    """Compressed per-key view of a txn's protocol state
    (ref: CommandsForKey.java InternalStatus)."""
    TRANSITIVELY_KNOWN = 0   # witnessed only via another txn's deps
    PREACCEPTED = 1
    ACCEPTED = 2
    COMMITTED = 3            # executeAt decided
    STABLE = 4
    APPLIED = 5
    INVALIDATED = 6

    def has_execute_at(self) -> bool:
        return InternalStatus.COMMITTED <= self <= InternalStatus.APPLIED


class TxnInfo:
    """(ref: CommandsForKey.java:293-410) — TxnId + per-key status +
    executeAt."""

    __slots__ = ("txn_id", "status", "execute_at")

    def __init__(self, txn_id: TxnId, status: InternalStatus,
                 execute_at: Optional[Timestamp] = None):
        self.txn_id = txn_id
        self.status = status
        self.execute_at = execute_at if execute_at is not None else txn_id

    def __repr__(self):
        return f"TxnInfo({self.txn_id}, {self.status.name})"


class CommandsForKey:
    """All (globally visible) transactions witnessed on one key, ordered by
    TxnId, with a parallel executeAt-ordered view of committed txns."""

    __slots__ = ("token", "_ids", "_infos", "prune_before")

    def __init__(self, token: int):
        self.token = token
        self._ids: List[TxnId] = []        # sorted
        self._infos: Dict[TxnId, TxnInfo] = {}
        # txns with txnId < prune_before are redundant (covered by
        # RedundantBefore) and excluded from deps
        self.prune_before: Optional[TxnId] = None

    # -- update path --------------------------------------------------------
    def update(self, txn_id: TxnId, status: InternalStatus,
               execute_at: Optional[Timestamp] = None) -> None:
        """Witness or advance a txn on this key
        (ref: CommandsForKey insert/update :652+)."""
        if not txn_id.kind().is_globally_visible():
            return
        info = self._infos.get(txn_id)
        if info is None:
            self._infos[txn_id] = TxnInfo(txn_id, status, execute_at)
            bisect.insort(self._ids, txn_id)
        else:
            # never regress
            if status < info.status and not (
                    status == InternalStatus.INVALIDATED):
                return
            info.status = max(info.status, status)
            if execute_at is not None and status.has_execute_at():
                info.execute_at = execute_at

    def witness_transitive(self, txn_id: TxnId) -> None:
        if txn_id not in self._infos:
            self.update(txn_id, InternalStatus.TRANSITIVELY_KNOWN)

    def remove(self, txn_id: TxnId) -> None:
        if txn_id in self._infos:
            del self._infos[txn_id]
            i = bisect.bisect_left(self._ids, txn_id)
            if i < len(self._ids) and self._ids[i] == txn_id:
                del self._ids[i]

    def set_prune_before(self, txn_id: TxnId) -> None:
        if self.prune_before is None or txn_id > self.prune_before:
            self.prune_before = txn_id

    def prune(self) -> int:
        """Physically drop entries below the prune watermark — the shard
        watermark guarantees everything below it has applied (or been
        invalidated) at every replica, so no dep set or recovery query needs
        them (ref: CommandsForKey.java prune vs RedundantBefore).  Returns
        #entries dropped."""
        if self.prune_before is None:
            return 0
        cut = bisect.bisect_left(self._ids, self.prune_before)
        if cut == 0:
            return 0
        for tid in self._ids[:cut]:
            del self._infos[tid]
        del self._ids[:cut]
        return cut

    # -- scan API -----------------------------------------------------------
    def map_reduce_active(self, started_before: Timestamp, witnesses: Kinds,
                          fn: Callable[[TxnId, "object"], "object"], acc):
        """Fold over active txns with txnId < started_before whose kind the
        querying txn must witness (ref: CommandsForKey.java:614-650).
        Skips invalidated txns and anything below the prune watermark."""
        hi = bisect.bisect_left(self._ids, started_before)
        lo = 0
        if self.prune_before is not None:
            lo = bisect.bisect_left(self._ids, self.prune_before)
        for i in range(lo, hi):
            tid = self._ids[i]
            info = self._infos[tid]
            if info.status is InternalStatus.INVALIDATED:
                continue
            if not witnesses.test(tid.kind()):
                continue
            acc = fn(tid, acc)
        return acc

    def map_reduce_full(self, test_txn_id: TxnId, witnesses: Kinds,
                        fn: Callable[[TxnInfo, "object"], "object"], acc):
        """Fold over ALL txns (any bound, any status) for recovery queries
        (ref: CommandsForKey.java:553-612)."""
        for tid in self._ids:
            info = self._infos[tid]
            if not witnesses.test(tid.kind()):
                continue
            acc = fn(info, acc)
        return acc

    # -- queries ------------------------------------------------------------
    def get(self, txn_id: TxnId) -> Optional[TxnInfo]:
        return self._infos.get(txn_id)

    def size(self) -> int:
        return len(self._ids)

    def txn_ids(self) -> List[TxnId]:
        return list(self._ids)

    def max_committed_execute_at(self) -> Optional[Timestamp]:
        best: Optional[Timestamp] = None
        for info in self._infos.values():
            if info.status.has_execute_at() or info.status is InternalStatus.APPLIED:
                if best is None or info.execute_at > best:
                    best = info.execute_at
        return best

    def max_applied_before(self, bound: Timestamp) -> Optional[Timestamp]:
        best: Optional[Timestamp] = None
        for info in self._infos.values():
            if info.status is InternalStatus.APPLIED and info.execute_at < bound:
                if best is None or info.execute_at > best:
                    best = info.execute_at
        return best

    def last_witnessed(self) -> Optional[TxnId]:
        return self._ids[-1] if self._ids else None

    def __repr__(self):
        return f"CommandsForKey({self.token}, n={len(self._ids)})"
