"""Independent second verifier: Elle-style list-append dependency-cycle
checking (ref: accord-core/src/test/java/accord/verify/ElleVerifier.java,
which shells out to jepsen's Elle; clojure is unreachable in this
environment, so this is a self-contained reimplementation of Elle's
list-append analysis: build the wr/ww/rw dependency graph from uniquely
tagged appends and detect G1a-style phantom reads plus G1c / G-single / G2
cycles via SCC).

Deliberately DISJOINT strengths from sim.verifier.StrictSerializability-
Verifier: this checker knows nothing about real time — it condemns pure
data-dependency cycles among possibly-concurrent transactions; the other
checker anchors serialization points against real-time windows.  The
composite (CompositeVerifier, ref verify/CompositeVerifier.java) runs both;
a history must satisfy each.

Edge semantics over the per-key final append order F_k (every append is
uniquely tagged, so writers are unambiguous — Elle's core trick):
  wr: the writer of the LAST element of an observed prefix precedes the
      reader;
  ww: the writer of F_k[i] precedes the writer of F_k[i+1];
  rw: a reader that observed prefix length n anti-depends-on (precedes)
      the writer of F_k[n] — it serialized before that append landed.

Cycle classification (Adya): a cycle in wr∪ww alone is G1c; a cycle with
exactly one rw edge is G-single; more than one rw is G2 — all are
serializability violations for this workload and all fail verify().
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .verifier import HistoryViolation


class ListAppendCycleChecker:
    """Same feed API as StrictSerializabilityVerifier (begin / on_result /
    set_final / verify)."""

    def __init__(self):
        self._next_op = 0
        self.reads: Dict[int, Dict[int, tuple]] = {}
        self.writes: Dict[int, Dict[int, tuple]] = {}
        self.finals: Dict[int, tuple] = {}

    def begin(self) -> int:
        op = self._next_op
        self._next_op += 1
        return op

    def on_result(self, op_id: int, start_micros: int, end_micros: int,
                  reads: Dict[int, tuple], appends: Dict[int, tuple]) -> None:
        self.reads[op_id] = dict(reads)
        self.writes[op_id] = dict(appends)

    def set_final(self, token: int, value: tuple) -> None:
        self.finals[token] = tuple(value)

    # -- analysis -----------------------------------------------------------
    def _writer_index(self):
        """token -> {value: (position, writer op)}; None writer = the value
        landed but its op never reported success (indeterminate client
        outcome) — edges touching it still hold, with the landed position."""
        writer_of: Dict[Tuple[int, str], int] = {}
        for op, appends in self.writes.items():
            for token, values in appends.items():
                for v in values:
                    writer_of[(token, v)] = op
        index: Dict[int, Dict[str, Tuple[int, Optional[int]]]] = {}
        for token, final in self.finals.items():
            index[token] = {v: (i, writer_of.get((token, v)))
                            for i, v in enumerate(final)}
        return index

    def _build_graph(self):
        index = self._writer_index()
        edges: Dict[int, Dict[int, str]] = {}
        anomalies: List[str] = []

        def add(a: Optional[int], b: Optional[int], kind: str) -> None:
            if a is None or b is None or a == b:
                return
            # strongest-kind-wins is irrelevant for cycle EXISTENCE; keep
            # the first kind seen, prefer non-rw for classification
            row = edges.setdefault(a, {})
            prev = row.get(b)
            if prev is None or (prev == "rw" and kind != "rw"):
                row[b] = kind

        # ww chains along each key's final order
        for token, final in self.finals.items():
            idx = index[token]
            for i in range(1, len(final)):
                add(idx[final[i - 1]][1], idx[final[i]][1], "ww")

        # wr + rw per observed read
        for op, reads in self.reads.items():
            for token, prefix in reads.items():
                final = self.finals.get(token)
                if final is None:
                    continue
                n = len(prefix)
                if n > len(final) or tuple(final[:n]) != tuple(prefix):
                    anomalies.append(
                        f"G1a/phantom: op {op} read {prefix!r} of key "
                        f"{token}, not a prefix of the final {final!r}")
                    continue
                idx = index[token]
                if n > 0:
                    add(idx[final[n - 1]][1], op, "wr")
                if n < len(final):
                    add(op, idx[final[n]][1], "rw")
        return edges, anomalies

    def _find_cycle(self, edges) -> Optional[List[Tuple[int, int, str]]]:
        """Iterative DFS cycle search; returns the witness edge list."""
        WHITE, GREY, BLACK = 0, 1, 2
        color: Dict[int, int] = {}
        parent: Dict[int, Tuple[int, str]] = {}
        for root in edges:
            if color.get(root, WHITE) is not WHITE:
                continue
            stack = [(root, iter(edges.get(root, ())))]
            color[root] = GREY
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    c = color.get(nxt, WHITE)
                    if c is GREY:
                        # unwind the witness
                        cycle = [(node, nxt, edges[node][nxt])]
                        cur = node
                        while cur != nxt:
                            prev, kind = parent[cur]
                            cycle.append((prev, cur, kind))
                            cur = prev
                        cycle.reverse()
                        return cycle
                    if c is WHITE:
                        color[nxt] = GREY
                        parent[nxt] = (node, edges[node][nxt])
                        stack.append((nxt, iter(edges.get(nxt, ()))))
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    stack.pop()
        return None

    def verify(self) -> None:
        edges, anomalies = self._build_graph()
        if anomalies:
            raise HistoryViolation("; ".join(anomalies[:5]))
        cycle = self._find_cycle(edges)
        if cycle is not None:
            kinds = [k for (_a, _b, k) in cycle]
            n_rw = sum(1 for k in kinds if k == "rw")
            label = ("G1c" if n_rw == 0
                     else "G-single" if n_rw == 1 else "G2")
            path = " -> ".join(f"{a}-[{k}]->{b}" for a, b, k in cycle)
            raise HistoryViolation(
                f"{label} dependency cycle among txns: {path}")


class CompositeVerifier:
    """Run every checker over the same feed; a history must satisfy each
    (ref: verify/CompositeVerifier.java).  Checker disagreement — one
    accepting what another rejects — surfaces as the rejecting checker's
    violation, failing the run."""

    def __init__(self, *checkers):
        self.checkers = list(checkers)

    def begin(self) -> int:
        ids = [c.begin() for c in self.checkers]
        assert all(i == ids[0] for i in ids), "checker op-id drift"
        return ids[0]

    def on_result(self, op_id, start_micros, end_micros, reads, appends):
        for c in self.checkers:
            c.on_result(op_id, start_micros, end_micros, reads, appends)

    def set_final(self, token, value):
        for c in self.checkers:
            c.set_final(token, value)

    def verify(self) -> None:
        failures = []
        for c in self.checkers:
            try:
                c.verify()
            except HistoryViolation as e:
                failures.append(f"{type(c).__name__}: {e}")
        if failures:
            raise HistoryViolation(" || ".join(failures))
