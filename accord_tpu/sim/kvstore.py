"""Simple versioned KV workload implementing the data-plane SPI.

Modelled on the reference's list-append test store
(ref: accord-core/src/test/java/accord/impl/list/ListStore.java,
ListRead/ListUpdate/ListQuery, and maelstrom/MaelstromRead etc.): values are
append-lists so the strict-serializability verifier can reconstruct order.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .. import api
from ..primitives.keys import IntKey, Keys, Ranges
from ..primitives.timestamp import Timestamp, TxnId, TxnKind, Domain
from ..primitives.txn import Txn
from ..utils import async_chain


class KVDataStore(api.DataStore):
    """Versioned store: token -> (list value, last-applied executeAt,
    applied TxnIds).  The applied-id set makes duplicate detection exact:
    two distinct txns appending equal values are still distinguishable, so
    a genuine lost-write/duplicate fails the assert instead of passing on
    value membership."""

    def __init__(self, node_id: int):
        self.node_id = node_id
        self.data: Dict[int, Tuple[tuple, Timestamp, frozenset]] = {}

    def get(self, token: int) -> tuple:
        entry = self.data.get(token)
        return entry[0] if entry is not None else ()

    def snapshot(self, ranges: Ranges) -> Dict[int, Tuple[tuple, Timestamp, frozenset]]:
        return {t: v for t, v in self.data.items() if ranges.contains_token(t)}

    def install_snapshot(self, snapshot: Dict[int, Tuple[tuple, Timestamp, frozenset]]) -> None:
        for token, (value, at, ids) in snapshot.items():
            mine = self.data.get(token)
            if mine is None or mine[1] < at:
                self.data[token] = (value, at, ids)

    def apply_append(self, token: int, values: tuple, execute_at: Timestamp,
                     txn_id: TxnId) -> None:
        entry = self.data.get(token)
        if entry is not None and entry[1] >= execute_at:
            # Stale apply: the value already reflects this-or-later
            # executeAt.  Legitimate ONLY as a re-apply of the same txn —
            # after a bootstrap snapshot install, the snapshot may already
            # contain writes whose Apply messages race with it (versioned,
            # like the reference's Timestamped ListStore values).  Anything
            # else is a lost-write protocol violation and must fail loudly.
            assert txn_id in entry[2], (
                f"out-of-order apply on key {token}: {txn_id} {values} @ "
                f"{execute_at} not in applied set @ {entry[1]} "
                f"(node {self.node_id})")
            return
        current, ids = (entry[0], entry[2]) if entry is not None else ((), frozenset())
        self.data[token] = (current + values, execute_at, ids | {txn_id})


class KVData(api.Data):
    """token -> list snapshot (ref: maelstrom/Data + list/ListData)."""

    def __init__(self, values: Optional[Dict[int, tuple]] = None):
        self.values: Dict[int, tuple] = dict(values or {})

    def merge(self, other: "KVData") -> "KVData":
        out = dict(self.values)
        out.update(other.values)
        return KVData(out)

    def __repr__(self):
        return f"KVData({self.values})"


class KVRead(api.Read):
    def __init__(self, keys: Keys):
        self._keys = keys

    def keys(self) -> Keys:
        return self._keys

    def read(self, key, safe_store, execute_at, store: KVDataStore):
        return async_chain.success(KVData({key.token(): store.get(key.token())}))

    def slice(self, ranges: Ranges) -> "KVRead":
        return KVRead(self._keys.slice(ranges))

    def merge(self, other: Optional["KVRead"]) -> "KVRead":
        if other is None:
            return self
        return KVRead(self._keys.with_(other._keys))


class KVWrite(api.Write):
    def __init__(self, appends: Dict[int, tuple]):
        self.appends = appends

    def apply(self, key, txn_id: TxnId, execute_at, store: KVDataStore):
        vals = self.appends.get(key.token())
        if vals:
            store.apply_append(key.token(), vals, execute_at, txn_id)
        return async_chain.success(None)


class KVUpdate(api.Update):
    """Blind append update (list-append workload)."""

    def __init__(self, appends: Dict[int, tuple]):
        self.appends = dict(appends)

    def keys(self) -> Keys:
        return Keys([IntKey(t) for t in self.appends])

    def apply(self, execute_at, data) -> KVWrite:
        return KVWrite(self.appends)

    def slice(self, ranges: Ranges) -> "KVUpdate":
        return KVUpdate({t: v for t, v in self.appends.items()
                         if ranges.contains_token(t)})

    def merge(self, other: Optional["KVUpdate"]) -> "KVUpdate":
        if other is None:
            return self
        out = dict(self.appends)
        out.update(other.appends)
        return KVUpdate(out)


class KVResult(api.Result):
    def __init__(self, txn_id: TxnId, reads: Dict[int, tuple],
                 appends: Dict[int, tuple]):
        self.txn_id = txn_id
        self.reads = reads
        self.appends = appends

    def __repr__(self):
        return f"KVResult(reads={self.reads}, appends={self.appends})"


class KVQuery(api.Query):
    def compute(self, txn_id, execute_at, keys, data, read, update) -> KVResult:
        reads = dict(data.values) if data is not None else {}
        appends = update.appends if update is not None else {}
        return KVResult(txn_id, reads, appends)


def kv_txn(read_tokens: List[int], appends: Dict[int, tuple]) -> Txn:
    """Build a read/append transaction over IntKeys."""
    all_tokens = sorted(set(read_tokens) | set(appends))
    keys = Keys([IntKey(t) for t in all_tokens])
    kind = TxnKind.Write if appends else TxnKind.Read
    read = KVRead(Keys([IntKey(t) for t in sorted(set(read_tokens))]))
    update = KVUpdate(appends) if appends else None
    return Txn(kind, keys, read, update, KVQuery())
