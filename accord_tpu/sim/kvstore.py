"""Simple versioned KV workload implementing the data-plane SPI.

Modelled on the reference's list-append test store
(ref: accord-core/src/test/java/accord/impl/list/ListStore.java,
ListRead/ListUpdate/ListQuery, and maelstrom/MaelstromRead etc.): values are
append-lists so the strict-serializability verifier can reconstruct order.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .. import api
from ..primitives.keys import IntKey, Keys, Ranges
from ..primitives.timestamp import Timestamp, TxnId, TxnKind, Domain
from ..primitives.txn import Txn
from ..utils import async_chain


class KVDataStore(api.DataStore):
    """Versioned list-append store: token -> ordered append log of
    (values, executeAt, TxnId) — the reference's Timestamped ListStore
    (accord-core test impl/list/ListStore.java).  Versioning lets a read
    that arrives AFTER its txn (or later txns) applied locally still serve
    the exact pre-state at its executeAt, and makes duplicate detection
    exact (dedup by TxnId, not value membership)."""

    def __init__(self, node_id: int):
        self.node_id = node_id
        # per key: append log sorted by executeAt
        self.log: Dict[int, List[Tuple[tuple, Timestamp, TxnId]]] = {}

    def tokens(self):
        return self.log.keys()

    def get(self, token: int) -> tuple:
        entries = self.log.get(token, ())
        return tuple(v for vals, _at, _tid in entries for v in vals)

    def read_at(self, token: int, execute_at: Timestamp) -> tuple:
        """The key's value just below ``execute_at`` — what a txn executing
        there must observe."""
        return tuple(v for vals, at, _tid in self.log.get(token, ())
                     if at < execute_at for v in vals)

    def snapshot(self, ranges: Ranges) -> Dict[int, list]:
        return {t: list(entries) for t, entries in self.log.items()
                if ranges.contains_token(t)}

    def install_snapshot(self, snapshot: Dict[int, list]) -> None:
        for token, entries in snapshot.items():
            mine = self.log.setdefault(token, [])
            have = {tid for _v, _at, tid in mine}
            merged = mine + [e for e in entries if e[2] not in have]
            merged.sort(key=lambda e: e[1])
            self.log[token] = merged

    def apply_append(self, token: int, values: tuple, execute_at: Timestamp,
                     txn_id: TxnId) -> None:
        """Insert at the executeAt-sorted position, deduplicating by TxnId.
        The log is a monotone union: a bootstrap snapshot and the direct
        Apply fan-out can each deliver any subset, in any order, and the
        union converges.  An entry landing below the high-water mark is
        legitimate exactly when a snapshot raced ahead of a deferred apply;
        serving a WRONG read remains impossible because reads gate on their
        deps having applied locally first (read_on_store)."""
        entries = self.log.setdefault(token, [])
        if any(tid == txn_id for _v, _at, tid in entries):
            return   # re-apply of the same txn: idempotent
        import bisect
        i = bisect.bisect_left([e[1] for e in entries], execute_at)
        entries.insert(i, (values, execute_at, txn_id))


class KVData(api.Data):
    """token -> list snapshot (ref: maelstrom/Data + list/ListData)."""

    def __init__(self, values: Optional[Dict[int, tuple]] = None):
        self.values: Dict[int, tuple] = dict(values or {})

    def merge(self, other: "KVData") -> "KVData":
        out = dict(self.values)
        out.update(other.values)
        return KVData(out)

    def __repr__(self):
        return f"KVData({self.values})"


class KVRead(api.Read):
    def __init__(self, keys: Keys):
        self._keys = keys

    def keys(self) -> Keys:
        return self._keys

    def read(self, key, safe_store, execute_at, store: KVDataStore):
        return async_chain.success(
            KVData({key.token(): store.read_at(key.token(), execute_at)}))

    def slice(self, ranges: Ranges) -> "KVRead":
        return KVRead(self._keys.slice(ranges))

    def merge(self, other: Optional["KVRead"]) -> "KVRead":
        if other is None:
            return self
        return KVRead(self._keys.with_(other._keys))


class KVRangeRead(api.Read):
    """Range-domain read: scans every key the store holds within the ranges
    (ref: the reference burn's range reads through list/ListRead)."""

    def __init__(self, ranges: Ranges):
        self._ranges = ranges

    def keys(self) -> Ranges:
        return self._ranges

    def read(self, rng, safe_store, execute_at, store: KVDataStore):
        vals = {}
        for token in list(store.tokens()):
            if rng.start <= token < rng.end:
                vals[token] = store.read_at(token, execute_at)
        return async_chain.success(KVData(vals))

    def slice(self, ranges: Ranges) -> "KVRangeRead":
        return KVRangeRead(self._ranges.intersecting(ranges))

    def merge(self, other: Optional["KVRangeRead"]) -> "KVRangeRead":
        if other is None:
            return self
        return KVRangeRead(self._ranges.with_(other._ranges))


class KVWrite(api.Write):
    def __init__(self, appends: Dict[int, tuple]):
        self.appends = appends

    def apply(self, key, txn_id: TxnId, execute_at, store: KVDataStore):
        vals = self.appends.get(key.token())
        if vals:
            store.apply_append(key.token(), vals, execute_at, txn_id)
        return async_chain.success(None)


class KVUpdate(api.Update):
    """Blind append update (list-append workload)."""

    def __init__(self, appends: Dict[int, tuple]):
        self.appends = dict(appends)

    def keys(self) -> Keys:
        return Keys([IntKey(t) for t in self.appends])

    def apply(self, execute_at, data) -> KVWrite:
        return KVWrite(self.appends)

    def slice(self, ranges: Ranges) -> "KVUpdate":
        return KVUpdate({t: v for t, v in self.appends.items()
                         if ranges.contains_token(t)})

    def merge(self, other: Optional["KVUpdate"]) -> "KVUpdate":
        if other is None:
            return self
        out = dict(self.appends)
        out.update(other.appends)
        return KVUpdate(out)


class KVResult(api.Result):
    def __init__(self, txn_id: TxnId, reads: Dict[int, tuple],
                 appends: Dict[int, tuple]):
        self.txn_id = txn_id
        self.reads = reads
        self.appends = appends

    def __repr__(self):
        return f"KVResult(reads={self.reads}, appends={self.appends})"


class KVQuery(api.Query):
    def compute(self, txn_id, execute_at, keys, data, read, update) -> KVResult:
        reads = dict(data.values) if data is not None else {}
        appends = update.appends if update is not None else {}
        return KVResult(txn_id, reads, appends)


def kv_txn(read_tokens: List[int], appends: Dict[int, tuple]) -> Txn:
    """Build a read/append transaction over IntKeys."""
    all_tokens = sorted(set(read_tokens) | set(appends))
    keys = Keys([IntKey(t) for t in all_tokens])
    kind = TxnKind.Write if appends else TxnKind.Read
    read = KVRead(Keys([IntKey(t) for t in sorted(set(read_tokens))]))
    update = KVUpdate(appends) if appends else None
    return Txn(kind, keys, read, update, KVQuery())


def kv_ephemeral_read(read_tokens: List[int]) -> Txn:
    """A non-durable per-key-linearizable read
    (ref: coordinate/CoordinateEphemeralRead.java)."""
    keys = Keys([IntKey(t) for t in sorted(set(read_tokens))])
    return Txn(TxnKind.EphemeralRead, keys, KVRead(keys), None, KVQuery())


def kv_range_read(ranges: Ranges) -> Txn:
    """A range-domain read transaction."""
    return Txn(TxnKind.Read, ranges, KVRangeRead(ranges), None, KVQuery())
