"""Strict-serializability verification of client-observed results.

Rebuild of ref: accord-core/src/test/java/accord/verify/
StrictSerializabilityVerifier.java:58 (adapted to the list-append workload):
every client reply must be consistent with SOME total order of transactions
that (a) respects per-key list-prefix semantics and (b) respects real time —
if txn A completed before txn B began, A must not observe effects of B and B
must observe at least A's effects on any key both touch.

The list-append workload makes this checkable per key without graph search:
each applied append is tagged uniquely, so a read of key k pins the exact
prefix of appends it observed.  We check:
  1. prefix consistency: every observed list is a prefix of the final list
     (no lost, reordered, or phantom appends);
  2. monotonic real time per key: if read R1 completed before R2 started,
     R1's observed prefix must be <= R2's;
  3. own-write visibility ordering: a txn that appended v must have its
     append placed after the prefix it read.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils import invariants


class HistoryViolation(AssertionError):
    pass


class _Observation:
    __slots__ = ("start", "end", "token", "prefix_len", "op_id")

    def __init__(self, start: int, end: int, token: int, prefix_len: int,
                 op_id: int):
        self.start = start
        self.end = end
        self.token = token
        self.prefix_len = prefix_len
        self.op_id = op_id


class StrictSerializabilityVerifier:
    """Collects client operations and verifies on demand."""

    def __init__(self):
        self._next_op = 0
        # per token: list of (observed prefix tuple, op)
        self.reads: List[_Observation] = []
        self.read_values: Dict[int, Dict[int, tuple]] = {}  # op_id -> token -> value
        self.writes: Dict[int, Dict[int, tuple]] = {}       # op_id -> token -> appended
        self.op_times: Dict[int, Tuple[int, int]] = {}
        self.finals: Dict[int, tuple] = {}

    def begin(self) -> int:
        op = self._next_op
        self._next_op += 1
        return op

    def on_result(self, op_id: int, start_micros: int, end_micros: int,
                  reads: Dict[int, tuple], appends: Dict[int, tuple]) -> None:
        self.op_times[op_id] = (start_micros, end_micros)
        self.read_values[op_id] = dict(reads)
        self.writes[op_id] = dict(appends)
        for token, value in reads.items():
            self.reads.append(_Observation(start_micros, end_micros, token,
                                           len(value), op_id))

    def set_final(self, token: int, value: tuple) -> None:
        self.finals[token] = value

    # -- checks -------------------------------------------------------------
    def verify(self) -> None:
        self._check_prefixes()
        self._check_realtime()
        self._check_own_writes()

    def _check_prefixes(self) -> None:
        """Every observed list must be a prefix of the final list; appended
        values must appear exactly once in the final list."""
        for op_id, reads in self.read_values.items():
            for token, observed in reads.items():
                final = self.finals.get(token)
                if final is None:
                    continue
                if tuple(final[:len(observed)]) != tuple(observed):
                    raise HistoryViolation(
                        f"op {op_id} read {observed} on key {token}, not a "
                        f"prefix of final {final}")
        for token, final in self.finals.items():
            seen = {}
            for v in final:
                if v in seen:
                    raise HistoryViolation(
                        f"duplicate append {v!r} on key {token}: {final}")
                seen[v] = True

    def _check_realtime(self) -> None:
        """If op A ended before op B started, B must observe at least as long
        a prefix on any key both read (per-key real-time monotonicity)."""
        by_token: Dict[int, List[_Observation]] = {}
        for obs in self.reads:
            by_token.setdefault(obs.token, []).append(obs)
        for token, obss in by_token.items():
            obss.sort(key=lambda o: o.end)
            max_completed_prefix = -1
            completed: List[_Observation] = []
            for obs in sorted(obss, key=lambda o: o.start):
                # all observations that completed before obs started
                floor = max((o.prefix_len for o in obss if o.end < obs.start),
                            default=0)
                if obs.prefix_len < floor:
                    raise HistoryViolation(
                        f"real-time violation on key {token}: op {obs.op_id} "
                        f"(start {obs.start}) observed prefix {obs.prefix_len} "
                        f"< {floor} observed by an earlier-completed op")

    def _check_own_writes(self) -> None:
        """A txn that read prefix P of key k and appended v must have v at
        a position >= len(P) in the final order (its write follows its read
        in the serial order)."""
        for op_id, appends in self.writes.items():
            reads = self.read_values.get(op_id, {})
            for token, values in appends.items():
                final = self.finals.get(token)
                if final is None or not values:
                    continue
                for v in values:
                    if v not in final:
                        raise HistoryViolation(
                            f"committed append {v!r} of op {op_id} missing "
                            f"from final {final} on key {token}")
                observed = reads.get(token)
                if observed is not None:
                    pos = final.index(values[0])
                    if pos < len(observed):
                        raise HistoryViolation(
                            f"op {op_id} appended {values[0]!r} at position "
                            f"{pos} but had read prefix of length "
                            f"{len(observed)} on key {token}")
