"""Strict-serializability verification of client-observed results.

Rebuild of ref: accord-core/src/test/java/accord/verify/
StrictSerializabilityVerifier.java:58 (adapted to the list-append workload):
every client reply must be consistent with SOME total order of transactions
that (a) respects per-key list-prefix semantics and (b) respects real time —
if txn A completed before txn B began, A must not observe effects of B and B
must observe at least A's effects on any key both touch.

The list-append workload pins exact per-key step indices: appends are
uniquely tagged, so a read of key k that observed prefix P witnessed step
``len(P)`` of k's register, and a write whose value lands at position p in
the final order produced step ``p+1``.  That lets us rebuild the reference's
incremental max-predecessor graph as a post-hoc fixpoint over (key, step)
nodes instead of its intrusive-linked-list machinery.

Checks, in order of increasing strength:
  1. prefix consistency: every observed list is a prefix of the final list
     (no lost, reordered, or phantom appends);
  2. monotonic real time per key: if read R1 completed before R2 started,
     R1's observed prefix must be <= R2's;
  3. own-write visibility ordering: a txn that appended v after reading
     prefix P must have v at exactly position len(P) in the final order
     (read and write share one serialization point);
  4. cross-key cycles (ref StrictSerializabilityVerifier.java:58): per
     (key, step) node, propagate the maximum predecessor step reachable per
     key through the transitive closure of happens-before edges —
       (a) anything witnessed coincident with step s of key b precedes
           step s+1 of b;
       (b) reads coincident with a write precede the write's step —
     and flag a node that can reach itself.  This catches multi-key
     anomalies (e.g. write-skew style cycles) that every per-key check
     passes.  Real-time windows ride the same graph: each node carries the
     latest serialization lower bound (max start of any writer/predecessor
     witness) and earliest upper bound (min end of any witness); a node
     whose lower bound exceeds its upper bound is a real-time violation
     (ref Step.writtenAfter/writtenBefore/maxPredecessorWrittenAfter).
"""

from __future__ import annotations

from collections import defaultdict, deque
from typing import Dict, List, Optional, Tuple

from ..utils import invariants

_NEG = float("-inf")
_POS = float("inf")


class HistoryViolation(AssertionError):
    pass


class _Observation:
    __slots__ = ("start", "end", "token", "prefix_len", "op_id")

    def __init__(self, start: int, end: int, token: int, prefix_len: int,
                 op_id: int):
        self.start = start
        self.end = end
        self.token = token
        self.prefix_len = prefix_len
        self.op_id = op_id


class StrictSerializabilityVerifier:
    """Collects client operations and verifies on demand."""

    def __init__(self):
        self._next_op = 0
        # per token: list of (observed prefix tuple, op)
        self.reads: List[_Observation] = []
        self.read_values: Dict[int, Dict[int, tuple]] = {}  # op_id -> token -> value
        self.writes: Dict[int, Dict[int, tuple]] = {}       # op_id -> token -> appended
        self.op_times: Dict[int, Tuple[int, int]] = {}
        self.finals: Dict[int, tuple] = {}

    def begin(self) -> int:
        op = self._next_op
        self._next_op += 1
        return op

    def on_result(self, op_id: int, start_micros: int, end_micros: int,
                  reads: Dict[int, tuple], appends: Dict[int, tuple]) -> None:
        self.op_times[op_id] = (start_micros, end_micros)
        self.read_values[op_id] = dict(reads)
        self.writes[op_id] = dict(appends)
        for token, value in reads.items():
            self.reads.append(_Observation(start_micros, end_micros, token,
                                           len(value), op_id))

    def set_final(self, token: int, value: tuple) -> None:
        self.finals[token] = value

    # -- checks -------------------------------------------------------------
    def verify(self) -> None:
        self._effective_finals = self._compute_effective_finals()
        self._check_prefixes()
        self._check_realtime()
        self._check_own_writes()
        self._check_cross_key()

    def _compute_effective_finals(self) -> Dict[int, tuple]:
        """The reference sequence per token used to pin step positions.
        A recorded quorum-read final is authoritative — a read observing
        beyond it is an anomaly that _check_prefixes must flag, so it is
        never extended.  For tokens whose final read failed (burn skips
        set_final there) the longest observation substitutes, but only as a
        PARTIAL final: checks that require completeness consult
        ``token in self.finals`` before trusting absence."""
        finals: Dict[int, tuple] = {}
        for reads in self.read_values.values():
            for token, observed in reads.items():
                cur = finals.get(token, ())
                if len(observed) > len(cur):
                    finals[token] = tuple(observed)
        finals.update(self.finals)
        return finals

    def _check_prefixes(self) -> None:
        """Every observed list must be a prefix of the (effective) final
        list; appended values must appear exactly once in the final list
        (ref Register.updateSequence 'Inconsistent sequences')."""
        for op_id, reads in self.read_values.items():
            for token, observed in reads.items():
                final = self._effective_finals.get(token)
                if final is None:
                    continue
                if tuple(final[:len(observed)]) != tuple(observed):
                    raise HistoryViolation(
                        f"op {op_id} read {observed} on key {token}, not a "
                        f"prefix of final {final}")
        for token, final in self._effective_finals.items():
            seen = {}
            for v in final:
                if v in seen:
                    raise HistoryViolation(
                        f"duplicate append {v!r} on key {token}: {final}")
                seen[v] = True

    def _check_realtime(self) -> None:
        """If op A ended before op B started, B must observe at least as long
        a prefix on any key both read (per-key real-time monotonicity).
        Plane sweep: walk observations by start time, holding a running max
        of prefixes among already-completed observations."""
        by_token: Dict[int, List[_Observation]] = {}
        for obs in self.reads:
            by_token.setdefault(obs.token, []).append(obs)
        for token, obss in by_token.items():
            by_start = sorted(obss, key=lambda o: o.start)
            by_end = sorted(obss, key=lambda o: o.end)
            done = 0            # index into by_end of next not-yet-counted op
            floor = 0           # max prefix among ops with end < current start
            floor_op = None
            for obs in by_start:
                while done < len(by_end) and by_end[done].end < obs.start:
                    if by_end[done].prefix_len > floor:
                        floor = by_end[done].prefix_len
                        floor_op = by_end[done].op_id
                    done += 1
                if obs.prefix_len < floor:
                    raise HistoryViolation(
                        f"real-time violation on key {token}: op {obs.op_id} "
                        f"(start {obs.start}) observed prefix {obs.prefix_len}"
                        f" < {floor} observed by earlier-completed op "
                        f"{floor_op}")

    def _check_own_writes(self) -> None:
        """A txn that read prefix P of key k and appended v must have v at
        exactly position len(P) in the final order: the read and the write
        share one serialization point (executeAt), so nothing can serialize
        between them on the same key."""
        for op_id, appends in self.writes.items():
            reads = self.read_values.get(op_id, {})
            for token, values in appends.items():
                final = self._effective_finals.get(token)
                if final is None or not values:
                    continue
                complete = token in self.finals
                for v in values:
                    if v not in final and complete:
                        raise HistoryViolation(
                            f"committed append {v!r} of op {op_id} missing "
                            f"from final {final} on key {token}")
                observed = reads.get(token)
                # position equality is valid even against a partial final:
                # positions inside any observed prefix are final positions
                if observed is not None and values[0] in final:
                    pos = final.index(values[0])
                    if pos != len(observed):
                        raise HistoryViolation(
                            f"op {op_id} appended {values[0]!r} at position "
                            f"{pos} but read a prefix of length "
                            f"{len(observed)} on key {token}")

    # -- cross-key max-predecessor graph ------------------------------------
    def _witnessed_steps(self, op_id: int):
        """(witness, read_step, wrote) for an op.

        witness: token -> the step index witnessed coincident with the op —
          for a read, the observed prefix length (+1 if the op also wrote
          the key: the write is part of the coincident observation, ref
          witnessRead's 'implicitly longer by one'); for a blind write, the
          step pinned by the value's position in the final order (the ref
          resolves these lazily via FutureWrites/UnknownStepHolder — the
          post-hoc formulation can use the final directly).
        read_step: token -> the step witnessed by the READ alone (excludes
          the op's own write).
        """
        reads = self.read_values.get(op_id, {})
        appends = self.writes.get(op_id, {})
        witness: Dict[int, int] = {}
        read_step: Dict[int, int] = {}
        for token, observed in reads.items():
            read_step[token] = len(observed)
            wrote = bool(appends.get(token))
            witness[token] = len(observed) + (1 if wrote else 0)
        for token, values in appends.items():
            if not values or token in witness:
                continue
            final = self._effective_finals.get(token)
            if final is None or values[0] not in final:
                continue    # unresolvable blind write (missing-final token)
            witness[token] = final.index(values[0]) + 1
        return witness, read_step, appends

    def _check_cross_key(self) -> None:
        """Propagate max predecessors across keys and flag self-reachable
        steps (cycles) and real-time window inversions
        (ref StrictSerializabilityVerifier.java:58, Step.onChange)."""
        # -- build the happens-before edge set over (token, step) nodes
        edges = set()
        witnessed_until: Dict[Tuple[int, int], float] = {}
        written_before: Dict[Tuple[int, int], float] = {}
        written_after: Dict[Tuple[int, int], float] = {}

        for op_id, (start, end) in self.op_times.items():
            witness, read_step, appends = self._witnessed_steps(op_id)
            for token, s in witness.items():
                node = (token, s)
                if start > witnessed_until.get(node, _NEG):
                    witnessed_until[node] = start
                if end < written_before.get(node, _POS):
                    written_before[node] = end
                if appends.get(token) and start > written_after.get(node, _NEG):
                    written_after[node] = start
            # (a) anything witnessed coincident with step s_b of key b
            #     precedes step s_b+1 of b (ref Step.updatePeers +
            #     receiveKnowledgePhasedPredecessors via maxPeers)
            items = list(witness.items())
            for a, sa in items:
                for b, sb in items:
                    if a != b:
                        edges.add(((a, sa), (b, sb + 1)))
            # (b) keys only read precede the keys written by the same txn
            #     (ref Step.updatePredecessorsOfWrite)
            for b in appends:
                sb = witness.get(b)
                if sb is None or not appends[b]:
                    continue
                for a, ra in read_step.items():
                    if a != b:
                        edges.add(((a, ra), (b, sb)))

        # intra-key register order: (k, i) -> (k, i+1)
        max_step: Dict[int, int] = {}
        for (t, s) in (n for e in edges for n in e):
            if s > max_step.get(t, 0):
                max_step[t] = s
        for node in witnessed_until:
            t, s = node
            if s > max_step.get(t, 0):
                max_step[t] = s
        for t, final in self._effective_finals.items():
            if len(final) > max_step.get(t, 0):
                max_step[t] = len(final)
        for t, m in max_step.items():
            for i in range(m):
                edges.add(((t, i), (t, i + 1)))
                # a step is written after anything that witnessed its
                # direct predecessor state (ref propagateToDirectSuccessor)
                wu = witnessed_until.get((t, i))
                if wu is not None and wu > written_after.get((t, i + 1), _NEG):
                    written_after[(t, i + 1)] = wu

        # -- fixpoint: max predecessor per key + folded lower time bounds.
        # Monotone (steps and times only increase, both bounded), so a plain
        # worklist converges; this subsumes the ref's intrusive back-link
        # refresh queue.
        out_edges = defaultdict(list)
        for u, v in edges:
            out_edges[u].append(v)
        maxpred: Dict[Tuple[int, int], Dict[int, int]] = defaultdict(dict)
        lower = dict(written_after)   # serialization-point lower bounds
        work = deque(out_edges.keys())
        queued = set(work)
        while work:
            u = work.popleft()
            queued.discard(u)
            tu, su = u
            mu = maxpred.get(u)
            lu = lower.get(u, _NEG)
            for v in out_edges[u]:
                mv = maxpred[v]
                changed = False
                if mu:
                    for k, s in mu.items():
                        if mv.get(k, -1) < s:
                            mv[k] = s
                            changed = True
                if mv.get(tu, -1) < su:
                    mv[tu] = su
                    changed = True
                if lu > lower.get(v, _NEG):
                    lower[v] = lu
                    changed = True
                if changed and v not in queued and v in out_edges:
                    work.append(v)
                    queued.add(v)
            # nodes with no outgoing edges still get checked below

        for node, mp in maxpred.items():
            t, s = node
            if mp.get(t, -1) >= s:
                raise HistoryViolation(
                    f"cross-key cycle: key {t} step {s} reaches itself "
                    f"through happens-before relations (max predecessors "
                    f"{mp})")
        for node, lo in lower.items():
            hi = written_before.get(node, _POS)
            if lo > hi:
                t, s = node
                raise HistoryViolation(
                    f"real-time inversion on key {t} step {s}: must have "
                    f"been written after {lo} (a predecessor's bound) but "
                    f"was witnessed complete by {hi}")
