"""Topology construction helpers for tests / maelstrom
(ref: accord-maelstrom/src/main/java/accord/maelstrom/TopologyFactory.java:
hash-space split into `shards` ranges x rf)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..primitives.keys import MAX_TOKEN, MIN_TOKEN, Range
from ..topology.shard import Shard
from ..topology.topology import Topology


def build_topology(epoch: int, node_ids: Sequence[int], rf: int,
                   num_shards: int,
                   min_token: int = 0, max_token: int = 1_000_000,
                   fast_path_all: bool = True) -> Topology:
    """Split [min_token, max_token) into num_shards ranges, replicating each
    on rf consecutive nodes (round-robin)."""
    node_ids = sorted(node_ids)
    n = len(node_ids)
    assert rf <= n
    span = max_token - min_token
    shards: List[Shard] = []
    for i in range(num_shards):
        start = min_token + span * i // num_shards
        end = min_token + span * (i + 1) // num_shards
        replicas = [node_ids[(i + j) % n] for j in range(rf)]
        electorate = frozenset(replicas) if fast_path_all else frozenset()
        shards.append(Shard(Range(start, end), replicas, electorate))
    return Topology(epoch, shards)


def split_shard(topology: Topology, rng, epoch: int) -> Topology:
    """True SPLIT (ref: TopologyRandomizer.java:427 SPLIT): one range
    becomes two at a random interior token, SAME owners both sides — every
    replica keeps all its data (no bootstrap), but scope slicing, dual-
    quorum windows and deps coverage now see two shards."""
    shards = list(topology.shards)
    wide = [i for i, s in enumerate(shards)
            if s.range.end - s.range.start >= 2]
    if not wide:
        return Topology(epoch, shards)
    i = wide[rng.next_int(len(wide))]
    s = shards[i]
    cut = s.range.start + 1 + rng.next_int(s.range.end - s.range.start - 1)
    shards[i:i + 1] = [
        Shard(Range(s.range.start, cut), list(s.nodes), s.fast_path_electorate),
        Shard(Range(cut, s.range.end), list(s.nodes), s.fast_path_electorate)]
    return Topology(epoch, shards)


def merge_shards(topology: Topology, rng, epoch: int) -> Topology:
    """MERGE (ref: TopologyRandomizer MERGE): two adjacent ranges become
    one owned by the FIRST's replicas — the second range's owners that are
    not in the first set lose it (a partial handoff), and first-set
    replicas that did not own the second range bootstrap just that slice
    (the old owner keeps part of its data: the partial-bootstrap path)."""
    shards = list(topology.shards)
    if len(shards) < 3:
        return Topology(epoch, shards)
    i = rng.next_int(len(shards) - 1)
    a, b = shards[i], shards[i + 1]
    merged = Shard(Range(a.range.start, b.range.end), list(a.nodes),
                   frozenset(a.nodes))
    shards[i:i + 2] = [merged]
    return Topology(epoch, shards)


def move_boundary(topology: Topology, rng, epoch: int) -> Topology:
    """Single-boundary move (ref: TopologyRandomizer MOVE): shift the
    boundary between two adjacent shards — each side keeps most of its
    range while one slice changes owners, so adopters bootstrap a sub-range
    of a shard they otherwise retain."""
    shards = list(topology.shards)
    if len(shards) < 2:
        return Topology(epoch, shards)
    i = rng.next_int(len(shards) - 1)
    a, b = shards[i], shards[i + 1]
    lo = a.range.start + 1
    hi = b.range.end - 1
    if hi <= lo:
        return Topology(epoch, shards)
    cut = lo + rng.next_int(hi - lo)
    shards[i:i + 2] = [
        Shard(Range(a.range.start, cut), list(a.nodes),
              a.fast_path_electorate),
        Shard(Range(cut, b.range.end), list(b.nodes),
              b.fast_path_electorate)]
    return Topology(epoch, shards)


def mutate_electorates(topology: Topology, rng) -> Topology:
    """Randomize each shard's fast-path electorate within the legal bounds
    (ref: topology/TopologyRandomizer.java updateFastPath): any subset of
    the replicas with at least ``rf - max_failures`` members keeps the
    fast/slow quorum intersection sound (Shard asserts it)."""
    shards: List[Shard] = []
    for s in topology.shards:
        lo = len(s.nodes) - s.max_failures
        size = lo + rng.next_int(len(s.nodes) - lo + 1)
        chosen: List[int] = list(s.nodes)
        while len(chosen) > size:
            chosen.pop(rng.next_int(len(chosen)))
        shards.append(Shard(s.range, list(s.nodes), frozenset(chosen),
                            joining=s.joining))
    return Topology(topology.epoch, shards)
