"""Topology construction helpers for tests / maelstrom
(ref: accord-maelstrom/src/main/java/accord/maelstrom/TopologyFactory.java:
hash-space split into `shards` ranges x rf)."""

from __future__ import annotations

from typing import List, Optional, Sequence

from ..primitives.keys import MAX_TOKEN, MIN_TOKEN, Range
from ..topology.shard import Shard
from ..topology.topology import Topology


def build_topology(epoch: int, node_ids: Sequence[int], rf: int,
                   num_shards: int,
                   min_token: int = 0, max_token: int = 1_000_000,
                   fast_path_all: bool = True) -> Topology:
    """Split [min_token, max_token) into num_shards ranges, replicating each
    on rf consecutive nodes (round-robin)."""
    node_ids = sorted(node_ids)
    n = len(node_ids)
    assert rf <= n
    span = max_token - min_token
    shards: List[Shard] = []
    for i in range(num_shards):
        start = min_token + span * i // num_shards
        end = min_token + span * (i + 1) // num_shards
        replicas = [node_ids[(i + j) % n] for j in range(rf)]
        electorate = frozenset(replicas) if fast_path_all else frozenset()
        shards.append(Shard(Range(start, end), replicas, electorate))
    return Topology(epoch, shards)


def mutate_electorates(topology: Topology, rng) -> Topology:
    """Randomize each shard's fast-path electorate within the legal bounds
    (ref: topology/TopologyRandomizer.java updateFastPath): any subset of
    the replicas with at least ``rf - max_failures`` members keeps the
    fast/slow quorum intersection sound (Shard asserts it)."""
    shards: List[Shard] = []
    for s in topology.shards:
        lo = len(s.nodes) - s.max_failures
        size = lo + rng.next_int(len(s.nodes) - lo + 1)
        chosen: List[int] = list(s.nodes)
        while len(chosen) > size:
            chosen.pop(rng.next_int(len(chosen)))
        shards.append(Shard(s.range, list(s.nodes), frozenset(chosen),
                            joining=s.joining))
    return Topology(topology.epoch, shards)
