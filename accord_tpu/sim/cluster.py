"""Deterministic in-process cluster: discrete-event simulation.

Rebuild of ref: accord-core/src/test/java/accord/impl/basic/Cluster.java:102,
NodeSink.java:46, RandomDelayQueue.java, PendingQueue.java.  One seeded
RandomSource drives simulated time, per-link latency, delivery actions
(DELIVER / DROP / DELIVER_WITH_FAILURE / FAILURE) and partitions — the whole
distributed system is a pure function of (seed, workload).
"""

from __future__ import annotations

import enum
import heapq
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .. import api
from ..local.node import Node
from ..topology.topology import Topology
from ..utils import async_chain
from ..utils.random_source import RandomSource


class Action(enum.Enum):
    """(ref: impl/basic/NodeSink.java:46)."""
    DELIVER = 0
    DROP = 1
    DELIVER_WITH_FAILURE = 2   # deliver, but report failure to the sender
    FAILURE = 3                # don't deliver, report failure


class PendingQueue:
    """Simulated-time priority queue (ref: impl/basic/PendingQueue.java)."""

    def __init__(self):
        self._heap: List[List] = []
        self._seq = itertools.count()
        self.now = 0

    def add(self, at_micros: int, fn: Callable[[], None]) -> List:
        """Schedule ``fn``; the returned entry is a cancellation handle for
        ``cancel`` (entries are [at, seq, fn] lists — seq is unique, so
        heap ordering never compares the callables)."""
        entry = [max(at_micros, self.now), next(self._seq), fn]
        heapq.heappush(self._heap, entry)
        return entry

    @staticmethod
    def cancel(entry: List) -> None:
        """Tombstone a pending entry in place: pop() and is_empty() skip
        it, so a cancelled timeout costs one heap slot, not a live
        callback held for its full horizon."""
        entry[2] = None

    def pop(self) -> Optional[Callable[[], None]]:
        while self._heap:
            at, seq, fn = heapq.heappop(self._heap)
            if fn is None:
                continue
            self.now = max(self.now, at)
            return fn
        return None

    def is_empty(self) -> bool:
        return not any(fn is not None for _, _, fn in self._heap)


class _Scheduled(api.Scheduled):
    def __init__(self):
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def is_cancelled(self) -> bool:
        return self.cancelled


class SimScheduler(api.Scheduler):
    """(ref: the simulated Scheduler in impl/basic)."""

    def __init__(self, queue: PendingQueue):
        self.queue = queue

    def now(self, run: Callable[[], None]) -> None:
        self.queue.add(self.queue.now, run)

    def once(self, delay_micros: int, run: Callable[[], None]) -> api.Scheduled:
        handle = _Scheduled()

        def fire():
            if not handle.cancelled:
                run()
        self.queue.add(self.queue.now + delay_micros, fire)
        return handle

    def recurring(self, interval_micros: int, run: Callable[[], None]) -> api.Scheduled:
        handle = _Scheduled()

        def fire():
            if handle.cancelled:
                return
            run()
            self.queue.add(self.queue.now + interval_micros, fire)
        self.queue.add(self.queue.now + interval_micros, fire)
        return handle


class _ReplyContext:
    __slots__ = ("reply_to", "callback_id")

    def __init__(self, reply_to: int, callback_id: int):
        self.reply_to = reply_to
        self.callback_id = callback_id


class NodeSink(api.MessageSink):
    """Simulated network out for one node (ref: impl/basic/NodeSink.java)."""

    def __init__(self, node_id: int, cluster: "Cluster"):
        self.node_id = node_id
        self.cluster = cluster
        # set on restart: this incarnation's process died — everything it
        # still tries to send is a ghost and is silently dropped
        self.dead = False
        self._callbacks: Dict[int, api.Callback] = {}
        self._callback_seq = itertools.count(1)
        # pending-timeout queue entries by callback id: cancelled the moment
        # the (final) reply or failure resolves the callback — a completed
        # request must not leave a dead lambda in the heap for the full
        # timeout horizon (measurable heap bloat in long burns)
        self._timeout_entries: Dict[int, List] = {}

    def send(self, to: int, request) -> None:
        if self.dead:
            return
        self.cluster.route_request(self.node_id, to, request, callback_id=0)

    def send_with_callback(self, to: int, request, callback: api.Callback) -> None:
        if self.dead:
            return
        cid = next(self._callback_seq)
        self._callbacks[cid] = callback
        self.cluster.route_request(self.node_id, to, request, callback_id=cid)
        timeout = self.cluster.request_timeout_micros
        # barrier reads (sync points, commit-fused reads, WaitOnCommit) reply
        # only when the replica's drain releases them — give them room before
        # declaring the replica dead (ref: Maelstrom sink's per-type sweeper)
        if getattr(request, "is_slow_read", False):
            timeout *= 10
        # small deterministic jitter: co-scheduled requests (a coordinator
        # fanning one message to every replica in one quantum) must not
        # time out at the same instant and fire as a synchronized retry
        # storm.  Drawn from a dedicated stream so the protocol/chaos
        # randomness is untouched.
        timeout += self.cluster.timeout_jitter()
        self._timeout_entries[cid] = self.cluster.queue.add(
            self.cluster.queue.now + timeout,
            lambda: self._fail_pending(cid, to, f"timeout to {to}"))

    def reply(self, to: int, reply_context, reply) -> None:
        if self.dead or reply_context is None:
            return   # local requests (Propagate) have no reply path
        self.cluster.route_reply(self.node_id, to, reply_context, reply)

    def fail_callback(self, cid: int, from_id: int) -> None:
        """The network told us the request failed (Action.FAILURE /
        DELIVER_WITH_FAILURE) — fail the pending callback now; a late real
        reply for the same cid is ignored (already popped), exactly like a
        reply racing a timeout."""
        self._fail_pending(cid, from_id, f"reported-failed to {from_id}")

    def _fail_pending(self, cid: int, from_id: int, msg: str) -> None:
        if self.dead:
            return
        entry = self._timeout_entries.pop(cid, None)
        if entry is not None:
            PendingQueue.cancel(entry)
        cb = self._callbacks.pop(cid, None)
        if cb is not None:
            from ..coordinate.errors import Timeout as TimeoutError_
            self.cluster.schedule_at_node(
                self.node_id,
                lambda: cb.on_failure(from_id, TimeoutError_(msg=msg)))

    # -- inbound (called by cluster on delivery) ----------------------------
    def deliver_reply(self, from_id: int, reply_context: _ReplyContext, reply) -> None:
        cid = reply_context.callback_id
        cb = self._callbacks.get(cid)
        if cb is None:
            return
        final = reply.is_final() if hasattr(reply, "is_final") else True
        if final:
            del self._callbacks[cid]
            entry = self._timeout_entries.pop(cid, None)
            if entry is not None:
                PendingQueue.cancel(entry)
        from ..messages.base import FailureReply
        if isinstance(reply, FailureReply):
            cb.on_failure(from_id, reply.failure)
        else:
            cb.on_success(from_id, reply)

class SimConfigService(api.ConfigurationService):
    """Static/epoch-list configuration service
    (ref: maelstrom/SimpleConfigService.java + test MockConfigurationService)."""

    def __init__(self, cluster: "Cluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.listeners: List = []

    def register_listener(self, listener) -> None:
        self.listeners.append(listener)

    def current_topology(self) -> Topology:
        return self.cluster.topologies[-1]

    def get_topology_for_epoch(self, epoch: int) -> Optional[Topology]:
        for t in self.cluster.topologies:
            if t.epoch == epoch:
                return t
        return None

    def fetch_topology_for_epoch(self, epoch: int) -> None:
        t = self.get_topology_for_epoch(epoch)
        if t is not None:
            node = self.cluster.nodes[self.node_id]
            self.cluster.schedule_at_node(
                self.node_id, lambda: node.on_topology_update(t))

    def acknowledge_epoch(self, epoch_ready, start_sync: bool = True) -> None:
        # gossip "sync complete" to everyone (ref: onRemoteSyncComplete)
        epoch = epoch_ready.epoch
        for other in self.cluster.nodes.values():
            self.cluster.schedule_at_node(
                other.node_id,
                lambda o=other: o.topology_manager.on_epoch_sync_complete(
                    self.node_id, epoch))


class SimAgent(api.Agent):
    """(ref: test impl TestAgent)."""

    def __init__(self, cluster: "Cluster"):
        self.cluster = cluster

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.cluster.failures.append(failure)

    def on_handled_exception(self, failure: BaseException) -> None:
        pass

    def on_inconsistent_timestamp(self, command, prev, next_ts) -> None:
        self.cluster.failures.append(
            AssertionError(f"inconsistent timestamp {prev} vs {next_ts} on {command}"))


class Cluster:
    """(ref: impl/basic/Cluster.java)."""

    def __init__(self, node_ids: Optional[Sequence[int]] = None,
                 topology: Topology = None,
                 seed: int = 0, num_stores: int = 2,
                 data_store_factory: Optional[Callable[[int], api.DataStore]] = None,
                 progress_log_factory=None,
                 mean_latency_micros: int = 1_000,
                 request_timeout_micros: int = 1_000_000,
                 device_mode: Optional[bool] = None,
                 paged_limit: Optional[int] = None,
                 journal_factory: Optional[Callable[[int], object]] = None):
        node_ids = list(node_ids if node_ids is not None else topology.nodes())
        self._device_mode = device_mode
        self._paged_limit = paged_limit
        # per-node journal constructor override (default: the in-memory
        # Journal; tests pass accord_tpu.journal.DurableJournal to run the
        # whole sim over the on-disk WAL stack)
        self._journal_factory = journal_factory
        self.random = RandomSource(seed)
        # dedicated stream for request-timeout jitter: seeded from the run
        # seed WITHOUT consuming a draw from ``self.random`` (node/restart
        # fork seeds stay exactly what they were without jitter)
        self._timeout_rng = RandomSource(seed ^ 0x7E9_1713)
        self.queue = PendingQueue()
        self.topologies: List[Topology] = [topology] if topology else []
        self.nodes: Dict[int, Node] = {}
        self.sinks: Dict[int, NodeSink] = {}
        self.failures: List[BaseException] = []
        self.mean_latency_micros = mean_latency_micros
        self.request_timeout_micros = request_timeout_micros
        self._data_store_factory = data_store_factory
        self._progress_log_factory = progress_log_factory
        self._num_stores = num_stores
        self.partitioned: Set[frozenset] = set()  # pairs that cannot talk
        self.drop_probability = 0.0
        self.deliver_with_failure_probability = 0.0
        self.failure_probability = 0.0
        # per-node clock drift: node_id -> (num, den, offset_micros); a
        # node's local clock reads queue.now * num // den + offset
        # (ref: BurnTest.java:330-340 FrequentLargeRange clock drift).
        # Rational arithmetic keeps the simulation bit-deterministic.
        self.clock_drift: Dict[int, Tuple[int, int, int]] = {}
        # per-directed-link FIFO floor: messages on one link never reorder
        # (TCP-like; multi-part replies such as CommitOk-then-ReadOk rely on
        # it).  Latency stays random ACROSS links.
        self._link_last: Dict[tuple, int] = {}
        # test hook (ref: test NetworkFilter): return True to drop a request
        self.message_filter: Optional[Callable[[int, int, object], bool]] = None
        # recovery-nemesis hook (r14): the most recent BeginRecovery
        # observed on the wire — (coordinator id, txn_id, route).  Purely
        # observational (set from the deterministic routing path), consumed
        # by the burn's recovery-under-chaos nemesis to aim its legs
        # (coordinator kill / partition / ballot race) at a LIVE recovery.
        self.last_recovery: Optional[Tuple[int, object, object]] = None
        # unified observability (obs.Observability): the metrics registry
        # is ALWAYS live — it is the store behind ``stats`` — while span
        # recording obeys the ACCORD_TPU_OBS knob.  ``stats`` keeps its
        # exact legacy keys (LegacyStats is a dict-compatible view over
        # registry counters), so every determinism gate compares the same
        # bytes it always did.
        from ..obs import Observability
        from ..obs.metrics import LegacyStats
        self.obs = Observability(now=lambda: self.queue.now)
        self.stats = LegacyStats(self.obs.metrics)
        if self.obs.flight is not None:
            # post-mortem bundles capture the live per-store device gauges
            # at the anomaly; read through self.nodes so restarts and
            # topology growth stay covered (sorted for byte-determinism)
            from ..obs.metrics import index_counters

            def device_gauges():
                out = {}
                for nid in sorted(self.nodes):
                    stores = self.nodes[nid].command_stores
                    for s in stores.unsafe_all_stores():
                        if s.device is not None:
                            out[f"{nid}/{s.store_id}"] = \
                                index_counters(s.device)
                return out

            self.obs.flight.gauge_source = device_gauges
        # structured event trace (ref: accord.impl.basic.Trace); off unless
        # a Trace instance is attached
        self.trace = None
        # per-node durability scheduling, driven by explicit ticks (sim) —
        # (ref: CoordinateDurabilityScheduling wired in test Cluster.java)
        self.durability: Dict[int, "object"] = {}
        # per-node-identity durable journal: survives restart_node
        # (ref: the simulation Journal, impl/basic/Journal.java)
        from ..local.journal import Journal
        self.journals: Dict[int, Journal] = {}

        scheduler = SimScheduler(self.queue)
        for nid in node_ids:
            sink = NodeSink(nid, self)
            self.sinks[nid] = sink
            data_store = (data_store_factory(nid) if data_store_factory
                          else _NullDataStore())
            self.journals[nid] = (journal_factory(nid) if journal_factory
                                  else Journal())
            node = Node(
                node_id=nid, message_sink=sink,
                config_service=SimConfigService(self, nid),
                scheduler=scheduler, data_store=data_store,
                agent=SimAgent(self), random=self.random.fork(),
                now_micros=lambda nid=nid: self.node_now(nid),
                progress_log_factory=progress_log_factory,
                num_stores=num_stores, device_mode=device_mode,
                journal=self.journals[nid], paged_limit=paged_limit)
            self.nodes[nid] = node
            from ..impl.durability_scheduling import DurabilityScheduling
            self.durability[nid] = DurabilityScheduling(node)
            self._wire_route_trace(node)
        if topology is not None:
            for node in self.nodes.values():
                node.on_topology_update(topology)

    def _wire_route_trace(self, node: "Node") -> None:
        """Surface every DeviceState deps-scan routing decision through the
        cluster stats (always) and the structured trace (when attached) —
        the sim-side leg of the route observability the bench's ``# index``
        line provides (utils.trace.Trace.record_route).  A node-level
        observer, so stores created later (topology updates, bootstrap)
        are covered without re-wiring."""
        node.obs = self.obs    # span recorder for the coordinate FSMs

        def observer(store, route, nq, tids=None, nid=node.node_id):
            key = "DepsRoute." + route
            self.stats[key] = self.stats.get(key, 0) + nq
            self.obs.metrics.counter("deps_route_queries",
                                     node=nid, route=route).inc(nq)
            sid = getattr(store, "store_id", -1)
            if self.obs.flight is not None:
                self.obs.flight.on_route(nid, sid, route, nq)
            if self.trace is not None:
                self.trace.record_route(self.queue.now, nid, sid, route, nq)
            sp = self.obs.spans
            if sp is not None and tids:
                # stamp the route each txn's deps scan actually took onto
                # its span tree (the ISSUE's "deps route taken"); unknown
                # txn keys (non-coordinated scans) drop inside event()
                for tid in tids:
                    sp.event(str(tid), "deps_route", route=route,
                             node=nid, store=sid)

        node.route_observer = observer

        def fault_observer(store, event, detail, nid=node.node_id):
            """Device-fault/degradation events from DeviceState: counted in
            stats (always) and the structured trace (when attached) — the
            sim-side leg of the degradation-ladder observability."""
            key = "DeviceFault." + event
            self.stats[key] = self.stats.get(key, 0) + 1
            self.obs.metrics.counter("device_fault_events",
                                     node=nid, event=event).inc()
            if self.obs.flight is not None:
                self.obs.flight.on_fault(nid, getattr(store, "store_id", -1),
                                         event, detail)
            if self.trace is not None:
                sid = getattr(store, "store_id", -1)
                if event in ("quarantine", "reprobe", "restore"):
                    self.trace.record_quarantine(self.queue.now, nid, sid,
                                                 event, detail)
                else:
                    self.trace.record_fault(self.queue.now, nid, sid,
                                            event, detail)

        node.fault_observer = fault_observer

        def drain_observer(store, mode, frontier, nid=node.node_id):
            """One drain-tick frontier sweep (mode device/fused/host/ell/
            mesh, frontier = ready candidates): the drain-regime forensics
            leg — per-tick frontier sizes as a registry histogram and a
            flight-ring entry, so a drain stall's shape (many empty sweeps?
            one giant antichain?) is in the post-mortem, not lost."""
            m = self.obs.metrics
            m.counter("drain_ticks", node=nid, mode=mode).inc()
            m.histogram("drain_frontier_size", node=nid).observe(frontier)
            if self.obs.flight is not None:
                self.obs.flight.on_drain(nid, getattr(store, "store_id", -1),
                                         mode, frontier)

        node.drain_observer = drain_observer

        disp = getattr(node, "dispatcher", None)
        if disp is not None:
            def fused_observer(kind, members, nq, nid=node.node_id):
                """One fused cross-store launch (flush or tick) from the
                node's DeviceDispatcher: counted in stats (always) and the
                structured trace (when attached) — the harvest-barrier leg
                of the r08 launch-coalescing observability."""
                key = "DeviceDispatch.fused_" + kind
                self.stats[key] = self.stats.get(key, 0) + 1
                m = self.obs.metrics
                m.counter("fused_launches", node=nid, kind=kind).inc()
                m.counter("fused_members", node=nid, kind=kind).inc(members)
                if self.obs.flight is not None:
                    self.obs.flight.on_fused(nid, kind, members, nq)
                if self.trace is not None:
                    self.trace.record_fused(self.queue.now, nid, kind,
                                            members, nq)

            disp.on_fused = fused_observer

    def timeout_jitter(self) -> int:
        """Small deterministic per-request timeout jitter (micros)."""
        return self._timeout_rng.next_int(4096)

    def node_now(self, nid: int) -> int:
        """The node's drifted local clock (simulated time by default)."""
        d = self.clock_drift.get(nid)
        if d is None:
            return self.queue.now
        num, den, offset = d
        return self.queue.now * num // den + offset

    # -- network ------------------------------------------------------------
    def _latency(self) -> int:
        # uniform in [mean/2, 3*mean/2] (ref: RandomDelayQueue LatencySupplier)
        m = self.mean_latency_micros
        return m // 2 + self.random.next_int(m + 1)

    def _action(self, src: int, dst: int) -> Action:
        if src != dst:
            if frozenset((src, dst)) in self.partitioned:
                return Action.DROP
            if self.drop_probability and self.random.decide(self.drop_probability):
                return Action.DROP
            # delivered-but-reported-failed: the classic duplicate-
            # coordination trigger — the sender believes the request died
            # and retries/recovers while it actually took effect
            # (ref: NodeSink.java:46 DELIVER_WITH_FAILURE)
            if self.deliver_with_failure_probability and self.random.decide(
                    self.deliver_with_failure_probability):
                return Action.DELIVER_WITH_FAILURE
            # fast-failure: not delivered AND the sender is told so
            # immediately, instead of waiting out the timeout (ref: FAILURE)
            if self.failure_probability and self.random.decide(
                    self.failure_probability):
                return Action.FAILURE
        return Action.DELIVER

    def _deliver_at(self, src: int, dst: int) -> int:
        at = self.queue.now + (self._latency() if src != dst else 0)
        key = (src, dst)
        at = max(at, self._link_last.get(key, 0))
        self._link_last[key] = at
        return at

    def route_request(self, src: int, dst: int, request, callback_id: int) -> None:
        verb = type(request).__name__
        self.stats[verb] = self.stats.get(verb, 0) + 1
        if verb == "BeginRecovery":
            self.last_recovery = (src, request.txn_id, request.route)
        action = self._action(src, dst)
        filtered = (action in (Action.DROP, Action.FAILURE)
                    or (self.message_filter is not None
                        and self.message_filter(src, dst, request)))
        if self.trace is not None:
            self.trace.record(self.queue.now,
                              "SEND" if not filtered else "DROP",
                              src, dst, repr(request))
        if action in (Action.DELIVER_WITH_FAILURE, Action.FAILURE) \
                and callback_id:
            # FAILURE is the fast-failure report (told so promptly, ref
            # Cluster's Action.FAILURE): fire the callback after a tiny
            # constant delay — far below link latency, so it exercises the
            # fast-failure timing race a 1-RTT loss cannot, while staying
            # asynchronous (an instant callback would re-enter the
            # coordinator from inside its own send loop).
            # DELIVER_WITH_FAILURE keeps the delivery-latency failure (the
            # "delivered but reported failed" race).  The latency draw is
            # taken either way so the FAILURE leg perturbs neither the
            # random stream nor the link's in-order watermark.
            linked_at = self._deliver_at(src, dst)
            fail_at = self.queue.now + 10 if action is Action.FAILURE \
                else linked_at
            self.queue.add(fail_at, lambda: (
                self.sinks[src].fail_callback(callback_id, dst)))
        if filtered:
            return
        ctx = _ReplyContext(src, callback_id)
        self.queue.add(self._deliver_at(src, dst),
                       lambda: self.nodes[dst].receive(request, src, ctx))

    def route_reply(self, src: int, dst: int, ctx: _ReplyContext, reply) -> None:
        self.stats[type(reply).__name__] = self.stats.get(type(reply).__name__, 0) + 1
        action = self._action(src, dst)
        # a reply has no callback of its own: FAILURE degrades to a plain
        # loss; DELIVER_WITH_FAILURE degrades to a plain delivery
        if self.trace is not None:
            delivered = action in (Action.DELIVER,
                                   Action.DELIVER_WITH_FAILURE)
            self.trace.record(self.queue.now,
                              "REPLY" if delivered else "DROP_REPLY",
                              src, dst, repr(reply))
        if action in (Action.DROP, Action.FAILURE):
            return
        self.queue.add(self._deliver_at(src, dst),
                       lambda: self.sinks[dst].deliver_reply(src, ctx, reply))

    def schedule_at_node(self, node_id: int, fn: Callable[[], None]) -> None:
        self.queue.add(self.queue.now, fn)

    # -- reconfiguration ----------------------------------------------------
    def add_topology(self, topology: Topology) -> None:
        """Introduce a new epoch: every node learns it (simulated delivery),
        updates its stores, bootstraps added ranges, syncs, and acks
        (ref: Cluster topology updates + TopologyRandomizer delivery)."""
        assert topology.epoch == self.topologies[-1].epoch + 1
        self.topologies.append(topology)
        for nid in topology.nodes() | set(self.nodes):
            node = self.nodes.get(nid)
            if node is None:
                # a genuinely new node joins the cluster
                node = self._add_node(nid)
            self.queue.add(self.queue.now + self._latency(),
                           lambda n=node: n.on_topology_update(topology))

    def _add_node(self, nid: int) -> Node:
        from ..local.journal import Journal
        scheduler = SimScheduler(self.queue)
        sink = NodeSink(nid, self)
        self.sinks[nid] = sink
        data_store = (self._data_store_factory(nid) if self._data_store_factory
                      else _NullDataStore())
        if nid not in self.journals:
            self.journals[nid] = (self._journal_factory(nid)
                                  if self._journal_factory else Journal())
        node = Node(node_id=nid, message_sink=sink,
                    config_service=SimConfigService(self, nid),
                    scheduler=scheduler, data_store=data_store,
                    agent=SimAgent(self), random=self.random.fork(),
                    now_micros=lambda nid=nid: self.node_now(nid),
                    progress_log_factory=self._progress_log_factory,
                    num_stores=self._num_stores,
                    device_mode=self._device_mode,
                    journal=self.journals[nid],
                    paged_limit=self._paged_limit)
        self.nodes[nid] = node
        from ..impl.durability_scheduling import DurabilityScheduling
        self.durability[nid] = DurabilityScheduling(node)
        self._wire_route_trace(node)
        # the joiner must know prior epochs to pick bootstrap donors
        for t in self.topologies:
            self.queue.add(self.queue.now,
                           lambda tt=t, n=node: n.on_topology_update(tt))
        return node

    # -- restart ------------------------------------------------------------
    def restart_node(self, nid: int) -> Node:
        """Crash-and-restart one node: the old incarnation's process state
        (in-flight coordinations, listeners, caches) dies; the durable state
        (data store + journal) survives, and the new incarnation rebuilds
        its command stores from the journal
        (ref: the journal-reload leg of the burn test,
        impl/basic/DelayedCommandStores.java:96-175 — generalized to a full
        process restart)."""
        old = self.nodes[nid]
        old.alive = False
        old_sink = self.sinks[nid]
        old_sink.dead = True
        if self.trace is not None:
            self.trace.record(self.queue.now, "RESTART", nid, nid, "")
        sink = NodeSink(nid, self)
        # continue the callback numbering: a late reply addressed to a dead
        # incarnation's callback id must never resolve to a fresh callback
        # of the new incarnation (type confusion — e.g. a ghost ReadOk
        # delivered into a Propose round)
        sink._callback_seq = old_sink._callback_seq
        self.sinks[nid] = sink
        node = Node(node_id=nid, message_sink=sink,
                    config_service=SimConfigService(self, nid),
                    scheduler=SimScheduler(self.queue),
                    data_store=old.data_store,        # durable
                    agent=SimAgent(self), random=self.random.fork(),
                    now_micros=lambda nid=nid: self.node_now(nid),
                    progress_log_factory=self._progress_log_factory,
                    num_stores=self._num_stores,
                    device_mode=self._device_mode,
                    journal=self.journals[nid],
                    paged_limit=self._paged_limit)       # durable
        self.nodes[nid] = node
        from ..impl.durability_scheduling import DurabilityScheduling
        self.durability[nid] = DurabilityScheduling(node)
        self._wire_route_trace(node)
        node.restore_topologies(self.topologies)
        self.journals[nid].restore(node)
        return node

    # -- partitions / chaos -------------------------------------------------
    def partition(self, a: int, b: int) -> None:
        self.partitioned.add(frozenset((a, b)))

    def heal(self) -> None:
        self.partitioned.clear()

    # -- run loop -----------------------------------------------------------
    def run_until_quiescent(self, max_micros: int = 60_000_000) -> None:
        """Run until the queue is empty or the deadline passes.  The
        deadline is checked against the NEXT event's time (like run_for):
        popping first would advance ``now`` past the deadline and still
        run the event — work scheduled beyond the horizon must not
        execute."""
        deadline = self.queue.now + max_micros
        while True:
            t = self._peek_time()
            if t is None or t > deadline:
                return
            fn = self.queue.pop()
            if fn is None:
                return
            fn()

    def run_for(self, micros: int) -> None:
        deadline = self.queue.now + micros
        while self._peek_time() is not None and self._peek_time() <= deadline:
            fn = self.queue.pop()
            if fn is None:
                break
            fn()
        self.queue.now = max(self.queue.now, deadline)

    def _peek_time(self) -> Optional[int]:
        while self.queue._heap and self.queue._heap[0][2] is None:
            heapq.heappop(self.queue._heap)
        return self.queue._heap[0][0] if self.queue._heap else None


class _NullDataStore(api.DataStore):
    pass
