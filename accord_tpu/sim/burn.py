"""The burn test: deterministic chaos simulation.

Rebuild of ref: accord-core/src/test/java/accord/burn/BurnTest.java:108 +
impl/basic/Cluster.java:102.  One seeded RandomSource drives:

- a random multi-key list-append workload from random coordinators at random
  simulated times (zipf-ish key skew);
- network chaos re-randomized periodically: partitions + message drops over
  the simulated links (ref: NodeSink DELIVER/DROP, Cluster.java:518-630);
- per-node clock drift: each node's local clock runs at a distinct rational
  rate with a distinct offset (ref: BurnTest.java:330-340 FrequentLargeRange);
- topology churn: periodic epochs shuffling membership/shard counts
  (ref: topology/TopologyRandomizer.java:58-115);
- simulated persistence: random node crash-restarts reconstructing state
  from the journal, plus random command eviction/reload
  (ref: impl/basic/Journal.java:82-171, DelayedCommandStores.java:96-175);
- strict-serializability verification of every client-observed result plus
  end-of-run accounting that every op resolved
  (ref: verify/StrictSerializabilityVerifier.java, BurnTest.java:480-499).

The whole run is a pure function of (seed, parameters): same seed, same
message counts, same results — which is itself the race detector
(ref: burn/ReconcilingLogger same-seed diffing).
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict, List, Optional, Tuple

from ..topology.topology import Topology
from ..utils.random_source import RandomSource
from .cluster import Cluster
from .kvstore import (KVDataStore, kv_ephemeral_read, kv_range_read, kv_txn)
from .topology_factory import build_topology, mutate_electorates
from .elle import CompositeVerifier, ListAppendCycleChecker
from .verifier import StrictSerializabilityVerifier


class BurnResult:
    def __init__(self):
        self.ops_ok = 0
        self.ops_failed = 0
        self.ops_unresolved = 0
        self.epochs = 1
        self.restarts = 0
        self.evictions = 0
        self.stats: Dict[str, int] = {}
        # post-chaos quiescence gate (ref BurnTest.java:480-499): recovery
        # traffic observed in a silent window after the drain, and whether
        # every op resolved within the bounded drain
        self.quiet_recovery_msgs = 0
        self.drain_micros_used = 0
        self.kernel_wall: Dict[str, float] = {}   # wall timings (not seeded)
        # unified observability exports (obs.*): the registry snapshot is a
        # pure function of the seed (sim-time stamps only) and the span
        # export is the canonical byte string same-seed runs must reproduce
        # exactly; span_export is None under ACCORD_TPU_OBS=off
        self.metrics_snapshot: Optional[Dict] = None
        self.span_export: Optional[str] = None
        self.fast_path_rate: Optional[float] = None
        self.phase_latencies: Dict[str, Dict[str, int]] = {}
        # black-box flight recorder (obs.flight): canonical JSON of every
        # anomaly post-mortem bundle this run dumped — byte-identical
        # across same-seed runs (None under ACCORD_TPU_OBS=off)
        self.flight_export: Optional[str] = None
        self.flight_postmortems = 0
        # r14 recovery-under-chaos: recovery lifecycle totals (attempt /
        # executed / applied / invalidated / preempted / timeout /
        # truncated, from coordinate.recover's counters) and, when the
        # nemesis is armed, its per-leg fire counts — both also mirrored
        # into ``stats`` so the same-seed determinism gates compare them
        self.recoveries: Dict[str, int] = {}
        self.nemesis: Dict[str, int] = {}
        # r17 serving-shaped churn: per-planner fire counts (add /
        # remove / move via net.reconfig's plan functions — the exact
        # operations the TCP reconfigure verb proposes), mirrored into
        # ``stats`` like the nemesis legs
        self.reconfig_churn: Dict[str, int] = {}

    def __repr__(self):
        return (f"BurnResult(ok={self.ops_ok}, failed={self.ops_failed}, "
                f"unresolved={self.ops_unresolved}, epochs={self.epochs}, "
                f"restarts={self.restarts}, evictions={self.evictions})")


def run_burn(seed: int, n_ops: int = 100, n_keys: int = 20,
             node_ids=(1, 2, 3, 4, 5), rf: int = 3, shards: int = 4,
             workload_micros: int = 20_000_000,
             chaos: bool = True, churn: bool = True, restarts: bool = True,
             drain_micros: int = 120_000_000,
             probe=None, probe_micros: int = 0,
             boundary_churn_only: bool = False,
             device_faults: Optional[str] = None,
             device_fault_p: float = 0.05,
             recovery_nemesis: bool = False,
             reconfig_churn: bool = False) -> BurnResult:
    if device_faults is not None:
        # DEVICE-FAULT NEMESIS: arm the accelerator-boundary fault
        # registry (utils.faults) for the whole run — one fault class, or
        # "all".  The fault stream is seeded from the run seed WITHOUT
        # touching ``rs``, so the protocol/chaos randomness — and therefore
        # deps_found and every client-visible outcome — is byte-identical
        # to the fault-free run at the same seed (the quarantine ->
        # host-fallback ladder in local.device_index absorbs every fault).
        # Paranoia mode rides along: it is the detector for stale_result.
        from ..utils import faults
        kinds = sorted(faults.DEVICE_FAULT_KINDS) if device_faults == "all" \
            else [device_faults]
        frng = RandomSource((seed << 8) ^ 0xFA17)
        prior_paranoia = faults.PARANOIA
        try:
            for k in kinds:
                faults.inject_device_fault(k, device_fault_p, frng.fork())
            faults.PARANOIA = True
            return run_burn(seed, n_ops=n_ops, n_keys=n_keys,
                            node_ids=node_ids, rf=rf, shards=shards,
                            workload_micros=workload_micros, chaos=chaos,
                            churn=churn, restarts=restarts,
                            drain_micros=drain_micros, probe=probe,
                            probe_micros=probe_micros,
                            boundary_churn_only=boundary_churn_only,
                            recovery_nemesis=recovery_nemesis,
                            reconfig_churn=reconfig_churn)
        finally:
            faults.PARANOIA = prior_paranoia
            for k in kinds:
                faults.clear_device_faults(k)
    rs = RandomSource(seed)
    topology = build_topology(1, node_ids, rf, shards)
    cluster = Cluster(topology=topology, seed=rs.next_int(1 << 30),
                      data_store_factory=KVDataStore,
                      # journal-backed paging: terminal commands beyond this
                      # per-store count page out and reload on demand
                      paged_limit=150)
    # composite verification (ref: verify/CompositeVerifier.java): the
    # real-time-anchored checker AND the independent Elle-style dependency-
    # cycle checker both pass, or the run fails with the dissenting
    # checker's witness
    verifier = CompositeVerifier(StrictSerializabilityVerifier(),
                                 ListAppendCycleChecker())
    result = BurnResult()
    wl = rs.fork()           # workload randomness
    net = rs.fork()          # chaos randomness
    top = rs.fork()          # churn randomness

    # per-node clock drift: ±1% rate + up to 2s initial offset — orders of
    # magnitude beyond real crystal drift, enough to exercise every
    # HLC-merge/fence path without drowning the run in slow paths
    # (ref: BurnTest.java:330-340 FrequentLargeRange)
    drift = rs.fork()
    for nid in node_ids:
        cluster.clock_drift[nid] = (990 + drift.next_int(21), 1000,
                                    drift.next_int(2_000_000))

    # hot-key skew: a few keys get most of the traffic
    hot = [wl.next_int(n_keys) for _ in range(max(2, n_keys // 5))]

    def pick_key() -> int:
        if wl.decide(0.5):
            return hot[wl.next_int(len(hot))] * 10
        return wl.next_int(n_keys) * 10

    outstanding: List[dict] = []

    def submit_op(op_seed: int):
        from ..primitives.keys import Range, Ranges
        node_id = sorted(cluster.nodes)[wl.next_int(len(cluster.nodes))]
        roll = wl.next_float()
        window = None
        if roll < 0.06:
            # non-durable single-key linearizable read
            # (ref: the burn's EphemeralRead mix, BurnTest.java:124-259)
            keys = [pick_key()]
            writes = {}
            txn = kv_ephemeral_read(keys)
        elif roll < 0.14:
            # range-domain read over a zipf-ish key window
            lo = wl.next_int(n_keys)
            hi = min(n_keys, lo + 1 + wl.next_int(4))
            window = [k * 10 for k in range(lo, hi)]
            keys, writes = window, {}
            txn = kv_range_read(Ranges.of(Range(lo * 10, hi * 10)))
        else:
            n = wl.next_int(3) + 1
            keys = sorted({pick_key() for _ in range(n)})
            writes = {}
            for k in keys:
                if wl.decide(0.6):
                    writes[k] = (f"s{op_seed}k{k}",)
            txn = kv_txn(keys, writes)
        op = {"id": verifier.begin(), "start": cluster.queue.now,
              "done": False, "writes": writes, "keys": keys, "node": node_id}
        outstanding.append(op)

        def attempt(attempt_no: int, txn, node_id: int):
            op["node"] = node_id

            def on_done(res, failure):
                if op["done"]:
                    return   # already counted lost (coordinator restarted)
                if failure is not None:
                    # a real client retries a failed op (fresh txn, fresh
                    # value tags — the failed attempt's write may still land
                    # as its own committed txn, which the verifier's prefix
                    # checks accommodate).  Bounded: reported-failure
                    # windows (DELIVER_WITH_FAILURE) otherwise surface
                    # most of a window's ops as client failures.
                    if attempt_no < 3 and cluster.queue.now < \
                            workload_micros + drain_micros // 2:
                        if writes:
                            retag = {k: (f"s{op_seed}a{attempt_no}k{k}",)
                                     for k in writes}
                            retry_txn = kv_txn(keys, retag)
                        else:
                            retry_txn = txn   # reads retry verbatim
                        nxt = sorted(cluster.nodes)[
                            wl.next_int(len(cluster.nodes))]
                        attempt(attempt_no + 1, retry_txn, nxt)
                        return
                    op["done"] = True
                    result.ops_failed += 1
                    return
                op["done"] = True
                result.ops_ok += 1
                reads = res.reads
                if window is not None:
                    # a range read observing nothing on a window key
                    # observed the empty prefix — record it so real-time
                    # checks bite
                    reads = {t: res.reads.get(t, ()) for t in window}
                verifier.on_result(op["id"], op["start"], cluster.queue.now,
                                   reads, res.appends)

            cluster.nodes[node_id].coordinate(txn).begin(on_done)

        attempt(0, txn, node_id)

    # schedule the workload across the window
    for i in range(n_ops):
        at = wl.next_int(workload_micros)
        cluster.queue.add(at, lambda i=i: submit_op(i))

    # chaos: re-randomize partitions / drops every 2s of sim time
    def shake():
        cluster.heal()
        cluster.drop_probability = 0.0
        cluster.deliver_with_failure_probability = 0.0
        cluster.failure_probability = 0.0
        if cluster.queue.now > workload_micros:
            return
        roll = net.next_int(10)
        nodes = sorted(cluster.nodes)
        if roll < 3 and len(nodes) >= 3:
            a, b = net.pick(nodes), net.pick(nodes)
            if a != b:
                cluster.partition(a, b)
        elif roll < 5:
            cluster.drop_probability = 0.05 + 0.1 * net.next_float()
        elif roll < 7:
            # delivered-but-reported-failed + fast-failure windows: the
            # duplicate-coordination trigger (ref: NodeSink.java:46
            # DELIVER_WITH_FAILURE / FAILURE)
            cluster.deliver_with_failure_probability = \
                0.02 + 0.04 * net.next_float()
            cluster.failure_probability = 0.01 + 0.03 * net.next_float()
        cluster.queue.add(cluster.queue.now + 2_000_000, shake)

    if chaos:
        cluster.queue.add(2_000_000, shake)

    if probe is not None:
        # diagnostics hook: inspect live cluster state at a fixed sim time
        cluster.queue.add(probe_micros, lambda: probe(cluster))

    # topology churn: a few epochs during the workload
    def churn_once():
        """INCREMENTAL topology mutation (ref: topology/TopologyRandomizer
        .java:58-115 — one SPLIT/MERGE/MEMBERSHIP change per epoch).  The
        reference's randomizer never hands the whole ring over at once: a
        wholesale swap leaves every new owner bootstrapping simultaneously,
        which no real reconfiguration produces and which starves reads of
        any serving replica."""
        if cluster.queue.now > workload_micros:
            return
        # don't stack reconfigurations: churning while the previous epoch's
        # data is still migrating compounds bootstrap fences across nodes
        # into dependency cycles (no operator/controller reconfigures a
        # cluster mid-rebalance; the reference randomizer's 1s cadence is
        # effectively gated the same way by its instant in-memory fetches)
        if any(not s.bootstrapping.is_empty()
               for node in cluster.nodes.values()
               for s in node.command_stores.unsafe_all_stores()):
            cluster.queue.add(cluster.queue.now + 2_000_000, churn_once)
            return
        current = cluster.topologies[-1]
        all_ids = list(node_ids)
        members = sorted(current.nodes())
        roll = 4 + top.next_int(3) if boundary_churn_only \
            else top.next_int(7)
        if roll >= 4:
            # arbitrary shard-boundary mutation (ref: TopologyRandomizer
            # .java:427 SPLIT/MERGE/MOVE): one boundary changes while every
            # other shard is untouched — the partial-bootstrap shapes a
            # uniform ring re-split never produces
            from .topology_factory import (merge_shards, move_boundary,
                                           split_shard)
            mut = (split_shard, merge_shards, move_boundary)[roll - 4]
            topo = mut(current, top, current.epoch + 1)
            cluster.add_topology(topo)
            result.epochs += 1
            cluster.queue.add(cluster.queue.now + 4_000_000
                              + top.next_int(4_000_000), churn_once)
            return
        if roll == 0 and len(members) < len(all_ids):
            # membership: add one node
            members = sorted(members + [top.pick(
                [n for n in all_ids if n not in members])])
        elif roll == 1 and len(members) > max(3, rf):
            # membership: drop one node
            members = [n for n in members if n != top.pick(members)]
        # roll 2: keep members, reshard only; roll 3: FASTPATH (below)
        # keep the run's replication degree through churn (ref: the
        # TopologyRandomizer varies rf 2..9, BurnTest.java:600-609) — capping
        # at 3 silently collapsed every big-cluster run's geometry at the
        # first epoch change
        new_rf = min(rf, len(members))
        prev_shards = len(current.shards)
        # the shard-count cap follows the run's configuration (same defect
        # class as the old rf<=3 cap: a shards=6 run must keep exercising
        # 6-shard geometry through churn, not collapse to 5 at epoch 2)
        new_shards = max(2, min(max(5, shards),
                                prev_shards + top.next_int(3) - 1))
        topo = build_topology(current.epoch + 1, members, new_rf, new_shards)
        if roll == 3:
            # mutate the fast-path electorate (ref: TopologyRandomizer
            # FASTPATH action): shrink electorates within legal bounds so
            # fast-path quorum math is exercised off the everyone-votes
            # default through the rest of the run
            topo = mutate_electorates(topo, top)
        cluster.add_topology(topo)
        result.epochs += 1
        cluster.queue.add(cluster.queue.now + 4_000_000 + top.next_int(4_000_000),
                          churn_once)

    if churn:
        cluster.queue.add(4_000_000 + top.next_int(2_000_000), churn_once)

    # background durability rounds at randomized rates (ref: burn wires
    # CoordinateDurabilityScheduling with randomized frequencies,
    # Cluster.java:302-372): these advance the watermarks that drive
    # truncation, keeping per-store state bounded
    dur = rs.fork()

    def durability_round():
        # runs through the drain (durability advancing is how home
        # progress-log entries retire — stopping at drain/2 left
        # legitimate not-yet-durable entries probing forever, which the
        # quiescence gate would misread as a leak) but stands down once
        # every client op resolved, so the drain loop's early-exit (all
        # done AND queue empty) stays reachable
        if cluster.queue.now > workload_micros + drain_micros:
            return
        if cluster.queue.now > workload_micros \
                and all(op["done"] for op in outstanding):
            return
        nid = sorted(cluster.nodes)[dur.next_int(len(cluster.nodes))]
        sched = cluster.durability.get(nid)
        if sched is not None:
            if dur.decide(0.8):
                sched.shard_tick()
            else:
                sched.global_tick()
        cluster.queue.add(cluster.queue.now + 500_000 +
                          dur.next_int(1_500_000), durability_round)

    cluster.queue.add(1_000_000 + dur.next_int(1_000_000), durability_round)

    # simulated persistence chaos: node crash-restarts (journal restore) and
    # random command eviction/reload (ref: the burn's Journal +
    # DelayedCommandStores random isLoadedCheck evictions)
    rst = rs.fork()

    def crash_node(nid: int) -> None:
        # the crash kills the node's client sessions: their ops become
        # indeterminate for the client (not fed to the verifier) — shared
        # by the ambient restarts and the recovery nemesis's kill leg so
        # crash accounting can never diverge between them
        for op in outstanding:
            if not op["done"] and op["node"] == nid:
                op["done"] = True
                result.ops_failed += 1
        cluster.restart_node(nid)
        result.restarts += 1

    def maybe_restart():
        if cluster.queue.now > workload_micros:
            return
        crash_node(sorted(cluster.nodes)[rst.next_int(len(cluster.nodes))])
        cluster.queue.add(cluster.queue.now + 6_000_000 +
                          rst.next_int(6_000_000), maybe_restart)

    def evict_tick():
        if cluster.queue.now > workload_micros:
            return
        nid = sorted(cluster.nodes)[rst.next_int(len(cluster.nodes))]
        node = cluster.nodes[nid]
        journal = cluster.journals[nid]
        for store in node.command_stores.unsafe_all_stores():
            txn_ids = sorted(store.commands)
            for _ in range(min(3, len(txn_ids))):
                tid = txn_ids[rst.next_int(len(txn_ids))]
                journal.evict_and_reload(store, tid)
                result.evictions += 1
        cluster.queue.add(cluster.queue.now + 1_500_000 +
                          rst.next_int(1_000_000), evict_tick)

    if restarts:
        cluster.queue.add(4_000_000 + rst.next_int(4_000_000), maybe_restart)
        cluster.queue.add(1_000_000 + rst.next_int(1_000_000), evict_tick)

    # RECOVERY-UNDER-CHAOS NEMESIS (r14, ISSUE 10): aim chaos AT live
    # recoveries instead of around them.  The cluster records the most
    # recent BeginRecovery it routed (coordinator, txn, route); each tick
    # fires one leg at it:
    #   kill      — crash-restart the recovery coordinator mid-recovery
    #               (its promise ballot dies with it; peers must re-recover)
    #   partition — cut the coordinator off from part of its recovery
    #               quorum for a window, then heal
    #   race      — start a SECOND concurrent recoverer for the same txn
    #               from another node (the ballot race: exactly one wins,
    #               the loser must observe Preempted, never a double apply)
    # The stream is a dedicated fork appended after every existing fork,
    # so arming the nemesis perturbs no other stream and a nemesis-off run
    # is byte-identical to r13.  Composes with --device-faults.
    nem = rs.fork()

    def nemesis_tick():
        if cluster.queue.now > workload_micros:
            return
        seen = cluster.last_recovery
        if seen is not None:
            cluster.last_recovery = None   # each observation drives one leg
            src, txn_id, route = seen
            leg = nem.next_int(3)
            if leg == 0 and src in cluster.nodes:
                crash_node(src)
                result.nemesis["kill"] = result.nemesis.get("kill", 0) + 1
            elif leg == 1:
                others = [n for n in sorted(cluster.nodes) if n != src]
                if others:
                    other = others[nem.next_int(len(others))]
                    cluster.partition(src, other)
                    pair = frozenset((src, other))
                    cluster.queue.add(
                        cluster.queue.now + 1_500_000,
                        lambda p=pair: cluster.partitioned.discard(p))
                    result.nemesis["partition"] = \
                        result.nemesis.get("partition", 0) + 1
            else:
                others = [n for n in sorted(cluster.nodes) if n != src]
                if others:
                    other = others[nem.next_int(len(others))]
                    cluster.nodes[other].recover(txn_id, route).begin(
                        lambda r, f: None)   # Preempted losses are the point
                    result.nemesis["race"] = \
                        result.nemesis.get("race", 0) + 1
        cluster.queue.add(cluster.queue.now + 1_200_000
                          + nem.next_int(800_000), nemesis_tick)

    if recovery_nemesis:
        cluster.queue.add(3_000_000 + nem.next_int(1_000_000), nemesis_tick)

    # SERVING-SHAPED EPOCH CHURN (r17, elastic serving): drive the EXACT
    # reconfiguration operations the TCP ``reconfigure`` verb proposes —
    # net.reconfig.plan_join / plan_leave / plan_move, pure functions of
    # the current topology — through the sim's deterministic delivery,
    # composed with the recovery nemesis and device faults (membership
    # change racing recovery racing kill -9: the Jepsen scenario class).
    # The stream is a dedicated fork appended after EVERY existing fork
    # (wl, net, top, drift, dur, rst, nem), so arming it perturbs no
    # other stream and a churn-off run is byte-identical to r16.
    rcf = rs.fork()

    def reconfig_tick():
        if cluster.queue.now > workload_micros:
            return
        # the operator no-stacking guard (the TCP verb rejects the same
        # way): never propose while a rebalance is migrating data
        if any(not s.bootstrapping.is_empty()
               for node in cluster.nodes.values()
               for s in node.command_stores.unsafe_all_stores()):
            cluster.queue.add(cluster.queue.now + 2_000_000, reconfig_tick)
            return
        from ..net.reconfig import plan_join, plan_leave, plan_move
        current = cluster.topologies[-1]
        members = sorted(current.nodes())
        absent = [n for n in node_ids if n not in members]
        roll = rcf.next_int(3)
        if roll == 0 and absent:
            leg, topo = "add", plan_join(current, rcf.pick(absent),
                                         current.epoch + 1)
        elif roll == 1 and len(members) > max(3, rf):
            leg, topo = "remove", plan_leave(current, rcf.pick(members),
                                             current.epoch + 1)
        else:
            shard = current.shards[rcf.next_int(len(current.shards))]
            leg, topo = "move", plan_move(current, shard.range.start,
                                          members[rcf.next_int(
                                              len(members))],
                                          current.epoch + 1)
        cluster.add_topology(topo)
        result.epochs += 1
        result.reconfig_churn[leg] = result.reconfig_churn.get(leg, 0) + 1
        cluster.queue.add(cluster.queue.now + 5_000_000
                          + rcf.next_int(3_000_000), reconfig_tick)

    if reconfig_churn:
        cluster.queue.add(4_500_000 + rcf.next_int(1_500_000),
                          reconfig_tick)

    # run the workload window + drain until every op resolves
    cluster.run_for(workload_micros)
    cluster.heal()
    cluster.drop_probability = 0.0
    deadline = cluster.queue.now + drain_micros
    while cluster.queue.now < deadline:
        if all(op["done"] for op in outstanding) and cluster.queue.is_empty():
            break
        fn = cluster.queue.pop()
        if fn is None:
            break
        fn()

    result.ops_unresolved = sum(1 for op in outstanding if not op["done"])
    result.drain_micros_used = max(0, cluster.queue.now - workload_micros)

    # post-chaos QUIESCENCE GATE (ref: BurnTest.java:480-499): chaos and
    # workload have stopped and every surviving op resolved — run a silent
    # window and count recovery/fetch traffic.  A healthy cluster decays to
    # idle; a slow liveness leak (progress logs grinding, recovery loops)
    # shows up as sustained CheckStatus/BeginRecovery flow and fails the
    # endurance legs' gate.
    quiet_before = dict(cluster.stats)
    cluster.run_for(10_000_000)
    for verb in ("CheckStatus", "BeginRecovery", "WaitOnCommit",
                 "InformOfTxnId", "AcceptInvalidate"):
        result.quiet_recovery_msgs += (cluster.stats.get(verb, 0)
                                       - quiet_before.get(verb, 0))

    # final reads: quorum-read every key from a live member and pin finals
    member = sorted(cluster.topologies[-1].nodes())[0]
    for k in range(n_keys):
        token = k * 10
        out: List[Tuple[object, Optional[BaseException]]] = []
        cluster.nodes[member].coordinate(kv_txn([token], {})).begin(
            lambda r, f: out.append((r, f)))
        cluster.run_until_quiescent()
        if out and out[0][1] is None:
            verifier.set_final(token, out[0][0].reads[token])

    if cluster.failures:
        raise AssertionError(f"seed {seed}: node-level failures: "
                             f"{cluster.failures[:3]}")
    verifier.verify()
    result.stats = dict(cluster.stats)
    # lived kernel batching: mean deps-scan batch size across all stores
    # (store-level coalescing; 1.0 would mean every query dispatched alone)
    nq = nd = ndeps = nfb = nff = nft = 0
    kt: Dict[str, float] = {}
    for node in cluster.nodes.values():
        disp = getattr(node, "dispatcher", None)
        if disp is not None:
            nff += disp.n_fused_launches
            nft += disp.n_fused_tick_launches
        for s in node.command_stores.unsafe_all_stores():
            if s.device is not None:
                nq += s.device.n_queries
                nd += s.device.n_dispatches
                ndeps += s.device.n_kernel_deps
                nfb += s.device.n_fallback_queries
                for k, (_c, sec) in s.device.kernel_times.items():
                    kt[k] = kt.get(k, 0.0) + sec
    result.stats["device_queries"] = nq
    result.stats["device_dispatches"] = nd
    # r08 launch coalescing: fused cross-store launches (flush / tick)
    # this run's dispatchers performed — like the routing mix, a cost-model
    # outcome, so the fault-equivalence gate strips it (a quarantined store
    # cannot fuse) while the determinism double-run still compares it
    result.stats["device_fused_launches"] = nff
    result.stats["device_fused_tick_launches"] = nft
    # total exact (query, dep) pairs the deps scans produced: identical
    # across routes by construction, so a device-fault run must report the
    # SAME number as the fault-free run at the same seed — the burn-level
    # bit-equivalence gate for the degradation ladder
    result.stats["deps_found"] = ndeps
    result.stats["device_fallback_queries"] = nfb
    # wall-clock timings live OUTSIDE stats: stats must stay a pure
    # function of the seed (the determinism double-run compares it)
    result.kernel_wall = {k: round(1e3 * sec, 1) for k, sec in kt.items()}

    # unified observability export (obs.*): fold every store's attribute
    # counters into the registry as labeled gauges, then snapshot — the
    # one deterministic record the double-run gate compares byte-for-byte
    # — and export the span trees (sim-time stamped, canonical JSON)
    from ..obs.metrics import collect_device_state
    for nid in sorted(cluster.nodes):
        for s in cluster.nodes[nid].command_stores.unsafe_all_stores():
            if s.device is not None:
                collect_device_state(cluster.obs.metrics, s.device,
                                     node=nid, store=s.store_id)
    result.metrics_snapshot = cluster.obs.metrics.snapshot()
    spans = cluster.obs.spans
    if spans is not None:
        result.span_export = spans.export_json()
        result.fast_path_rate = spans.fast_path_rate()
        result.phase_latencies = cluster.obs.metrics.phase_percentiles()
    flight = cluster.obs.flight
    if flight is not None:
        result.flight_export = flight.export_json()
        result.flight_postmortems = len(flight)
    # recovery lifecycle totals + nemesis leg counts ride the stats dict so
    # the same-seed double-run compares them byte-for-byte like everything
    # else (all sourced from sim-deterministic counters)
    result.recoveries = cluster.obs.metrics.counter_totals("recoveries",
                                                           by="event")
    for ev, n in sorted(result.recoveries.items()):
        result.stats[f"Recovery.{ev}"] = n
    for leg, n in sorted(result.nemesis.items()):
        result.stats[f"RecoveryNemesis.{leg}"] = n
    for leg, n in sorted(result.reconfig_churn.items()):
        result.stats[f"ReconfigChurn.{leg}"] = n
    return result


def main(argv=None):
    p = argparse.ArgumentParser(description="accord_tpu burn test")
    p.add_argument("-s", "--seed", type=int, default=None)
    p.add_argument("-c", "--count", type=int, default=1)
    p.add_argument("-o", "--ops", type=int, default=100)
    p.add_argument("--loop-seed", type=int, default=None,
                   help="run seeds loop-seed, loop-seed+1, ... forever")
    p.add_argument("--no-chaos", action="store_true")
    p.add_argument("--no-churn", action="store_true")
    p.add_argument("--no-restarts", action="store_true")
    p.add_argument("--device-faults", default=None,
                   help="inject one accelerator fault class for the whole "
                        "run: kernel_launch | transfer | hbm_oom | "
                        "stale_result | all")
    p.add_argument("--device-fault-p", type=float, default=0.05,
                   help="per-boundary-crossing fault probability")
    p.add_argument("--recovery-nemesis", action="store_true",
                   help="aim chaos at live recoveries: coordinator kill "
                        "mid-recovery, partition/heal around the recovery "
                        "quorum, concurrent-recoverer ballot races")
    p.add_argument("--reconfig-churn", action="store_true",
                   help="serving-shaped epoch churn: add/remove/move "
                        "epochs via the SAME net.reconfig planners the "
                        "TCP reconfigure verb proposes (dedicated RNG "
                        "fork appended last; composes with "
                        "--recovery-nemesis and --device-faults)")
    args = p.parse_args(argv)

    if args.loop_seed is not None:
        seed = args.loop_seed
        while True:
            r = run_burn(seed, n_ops=args.ops, chaos=not args.no_chaos,
                         churn=not args.no_churn,
                         restarts=not args.no_restarts,
                         device_faults=args.device_faults,
                         device_fault_p=args.device_fault_p,
                         recovery_nemesis=args.recovery_nemesis,
                         reconfig_churn=args.reconfig_churn)
            print(f"seed {seed}: {r}")
            seed += 1
    start = args.seed if args.seed is not None else 0
    for seed in range(start, start + args.count):
        r = run_burn(seed, n_ops=args.ops, chaos=not args.no_chaos,
                     churn=not args.no_churn, restarts=not args.no_restarts,
                     device_faults=args.device_faults,
                     device_fault_p=args.device_fault_p,
                     recovery_nemesis=args.recovery_nemesis,
                     reconfig_churn=args.reconfig_churn)
        print(f"seed {seed}: {r}")


if __name__ == "__main__":
    main()
