"""Device-side packing/compare helpers for the 128-bit timestamp format.

The host format (primitives.timestamp) packs a timestamp as
``msb = epoch<<16 | hlc_hi16``, ``lsb = hlc_lo48<<16 | flags``, plus an
int32 node id; the total order is (msb, lsb, node) compared *unsigned*
(ref: accord-core/src/main/java/accord/primitives/Timestamp.java:41-45 and
its compareTo).  On device we keep exactly that layout as three arrays
(int64, int64, int32) so TxnIds are usable directly as sort/compare keys.

JAX int64 is signed, and the lsb's top bit is live for realistic HLCs
(micros-since-epoch exceeds 2^47), so unsigned comparison is implemented by
flipping the sign bit — ``x ^ i64min`` maps unsigned order onto signed order.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

def enable_x64() -> None:
    """Opt in to 64-bit JAX for the device data plane.  Call once at process
    start, before any other JAX work (entry points, bench, and the test
    conftest all do)."""
    jax.config.update("jax_enable_x64", True)


def ensure_x64() -> None:
    """The protocol's ids are 128-bit (2 x int64 words); the device data
    plane requires 64-bit integer support.  On TPU, int64 compares/bitwise
    are emulated with int32 pairs by XLA — acceptable here (the kernels are
    compare/reduce bound, and the one matmul runs in bf16).

    x64 is a PRECONDITION, not a side effect: flipping the process-global
    flag lazily mid-run would silently change dtype-promotion semantics for
    unrelated JAX code in the host application.  Callers must opt in via
    enable_x64() (or jax.config / JAX_ENABLE_X64) at startup.
    """
    if not jax.config.jax_enable_x64:
        raise RuntimeError(
            "accord_tpu device kernels require 64-bit JAX; call "
            "accord_tpu.ops.packing.enable_x64() (or set JAX_ENABLE_X64=true) "
            "at process start before building device state")

from ..primitives.timestamp import Timestamp, TxnId, TxnKind

_MASK64 = (1 << 64) - 1
I64_SIGN = -(1 << 63)


def to_i64(v: int) -> int:
    """Unsigned 64-bit value -> the same bits as a python int in int64 range."""
    v &= _MASK64
    return v - (1 << 64) if v >= (1 << 63) else v


def to_u64(v: int) -> int:
    """Signed int64 bits -> unsigned python int."""
    return int(v) & _MASK64


def _flip(x):
    """Map unsigned int64 order onto signed order."""
    return jnp.bitwise_xor(x, jnp.int64(I64_SIGN))


def ts_lt(a_msb, a_lsb, a_node, b_msb, b_lsb, b_node):
    """Elementwise (a < b) under the timestamp total order, unsigned on the
    two int64 words, then node id."""
    am, bm = _flip(a_msb), _flip(b_msb)
    al, bl = _flip(a_lsb), _flip(b_lsb)
    return (am < bm) | ((am == bm) & ((al < bl) | ((al == bl) & (a_node < b_node))))


def ts_le(a_msb, a_lsb, a_node, b_msb, b_lsb, b_node):
    return ~ts_lt(b_msb, b_lsb, b_node, a_msb, a_lsb, a_node)


def ts_eq(a_msb, a_lsb, a_node, b_msb, b_lsb, b_node):
    return (a_msb == b_msb) & (a_lsb == b_lsb) & (a_node == b_node)


def masked_ts_max(msb, lsb, node, mask):
    """Lexicographic max of the timestamps selected by ``mask`` along the last
    axis; returns Timestamp.NONE's bits where the mask is empty.

    Three vectorized passes (max msb, then max lsb among msb-ties, then node)
    instead of a custom reduction — compiles to plain reduces on the VPU.
    """
    neg = jnp.int64(I64_SIGN)  # unsigned-min sentinel after flip
    fm = jnp.where(mask, _flip(msb), neg)
    m1 = jnp.max(fm, axis=-1, keepdims=True)
    tie1 = mask & (fm == m1)
    fl = jnp.where(tie1, _flip(lsb), neg)
    m2 = jnp.max(fl, axis=-1, keepdims=True)
    tie2 = tie1 & (fl == m2)
    nn = jnp.where(tie2, node, jnp.int32(-1))
    m3 = jnp.max(nn, axis=-1)
    any_ = jnp.any(mask, axis=-1)
    out_msb = jnp.where(any_, _flip(m1[..., 0]), jnp.int64(0))
    out_lsb = jnp.where(any_, _flip(m2[..., 0]), jnp.int64(0))
    out_node = jnp.where(any_, m3, jnp.int32(0))
    return out_msb, out_lsb, out_node


# -- host-side packing --------------------------------------------------------

def pack_timestamps(ts_list) -> tuple:
    """[Timestamp] -> (msb int64[n], lsb int64[n], node int32[n]) numpy."""
    ensure_x64()
    n = len(ts_list)
    msb = np.zeros(n, dtype=np.int64)
    lsb = np.zeros(n, dtype=np.int64)
    node = np.zeros(n, dtype=np.int32)
    for i, t in enumerate(ts_list):
        msb[i] = to_i64(t.msb)
        lsb[i] = to_i64(t.lsb)
        node[i] = t.node
    return msb, lsb, node


def unpack_timestamp(msb: int, lsb: int, node: int) -> Timestamp:
    return Timestamp(to_u64(msb), to_u64(lsb), int(node))


def unpack_txn_id(msb: int, lsb: int, node: int) -> TxnId:
    return TxnId(to_u64(msb), to_u64(lsb), int(node))


def kind_ordinal(t: TxnId) -> int:
    return int(t.kind())


KIND_COUNT = len(TxnKind)
