"""Device data plane: the protocol's hot loops as fused TPU kernels.

- deps_kernel: batched PreAccept dependency calculation over a SoA conflict
  index (ref: local/CommandsForKey.java:614, messages/PreAccept.java:245)
- drain_kernel: executeAt-gated Kahn fixpoint execution drain
  (ref: local/Commands.java:656-857)
- packing: packed-timestamp compare/reduce helpers shared by both
"""

from .deps_kernel import (DepsQuery, DepsTable, build_query, build_table,
                          calculate_deps, empty_table, extract_deps)
from .drain_kernel import (DrainState, blocking_matrix, drain, drain_auto,
                           drain_ell_auto, drain_ell_logdepth,
                           drain_logdepth, drain_logdepth_enabled,
                           level_assign_dense, level_assign_ell,
                           ready_frontier)
from .packing import masked_ts_max, pack_timestamps, ts_le, ts_lt

__all__ = [
    "DepsQuery", "DepsTable", "build_query", "build_table", "calculate_deps",
    "empty_table", "extract_deps",
    "DrainState", "blocking_matrix", "drain", "drain_auto", "drain_ell_auto",
    "drain_ell_logdepth", "drain_logdepth", "drain_logdepth_enabled",
    "level_assign_dense", "level_assign_ell", "ready_frontier",
    "masked_ts_max", "pack_timestamps", "ts_le", "ts_lt",
]
