"""Execution drain — executeAt-gated Kahn fixpoint over the dependency graph.

Rebuild of ref: accord-core/src/main/java/accord/local/Commands.java:656-857
(maybeExecute / updateDependencyAndMaybeExecute / NotifyWaitingOn) — the
reference drains the graph reactively, one listener callback per dependency
transition; here the whole frontier advances in one device fixpoint.

The Accord execution rule (local/Command.java WaitingOn): a Stable txn i may
execute when every dependency j with ``executeAt(j) < executeAt(i)`` has
Applied; dependencies that execute after i, or were invalidated, are removed
from the waiting set; undecided (not-yet-Committed) dependencies always
block.

Kernel form: with adjacency ``adj[i, j]`` (i depends on j), per-slot status
and packed executeAt, precompute the static blocking matrix

    B[i, j] = adj[i, j] & (undecided[j] | executeAt(j) < executeAt(i))
                        & ~invalidated[j]

then iterate

    waiting[i]  = any_j B[i, j] & ~applied[j]        (a masked matvec — MXU)
    ready       = stable & ~applied & ~waiting
    applied    |= ready

to fixpoint under ``lax.while_loop``.  Each sweep applies a whole antichain
of the executeAt order, so the loop runs O(depth) times, not O(txns); the
matvec is done in bf16 so XLA tiles it onto the MXU for large N.

Log-depth form (r19, ROADMAP item 2): for a decided drain graph the blocking
relation is STATIC, so each slot's execution round is a pure function of the
graph — ``level[i] = 1 + max_j level[blocking deps of i]`` (0 = already
applied, INF = blocked forever: an undecided/decided-not-stable dep, or an
``awaits_all`` cycle).  :func:`level_assign_ell` computes it in O(log depth)
device rounds by interleaving one Bellman relax (a single [N, D] gather) with
a pointer jump over each row's critical-parent chain (``ptr, off <-
ptr[ptr], off + off[ptr]`` — Wyllie list ranking generalized to DAG
critical-path depth).  Every update is a path-witnessed lower bound, so the
pass is sound on any graph and exact at stationarity; levels that exceed N
are clamped to INF (a witness walk longer than N must ride a cycle, and
blocking cycles can only arise through ``awaits_all`` edges).  The fixpoint
kernels above remain the byte-exact oracle — ``drain_auto`` routes between
the two by the measured cost model (never thresholds) and the
``ACCORD_TPU_DRAIN=fixpoint`` escape hatch pins the oracle everywhere.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import faults
from .deps_kernel import (SLOT_APPLIED, SLOT_COMMITTED, SLOT_FREE,
                          SLOT_INVALIDATED, SLOT_STABLE, launch_check)
from .packing import ts_lt


class DrainState(NamedTuple):
    adj: jnp.ndarray         # bool[N, N]  i depends on j
    status: jnp.ndarray      # int32[N]    SLOT_*
    exec_msb: jnp.ndarray    # int64[N]    executeAt (valid when status >= COMMITTED)
    exec_lsb: jnp.ndarray    # int64[N]
    exec_node: jnp.ndarray   # int32[N]
    awaits_all: jnp.ndarray  # bool[N]     row i awaits ALL deps regardless of
    #                          executeAt order (ExclusiveSyncPoint /
    #                          EphemeralRead, ref: Txn.Kind.awaitsOnlyDeps)


def blocking_matrix(state: DrainState) -> jnp.ndarray:
    """Precompute B[i, j]: does dep j (ever) gate i's execution?"""
    undecided = (state.status >= 0) & (state.status < SLOT_COMMITTED)
    invalidated = state.status == SLOT_INVALIDATED
    free = state.status == SLOT_FREE
    exec_before = ts_lt(state.exec_msb[None, :], state.exec_lsb[None, :],
                        state.exec_node[None, :],
                        state.exec_msb[:, None], state.exec_lsb[:, None],
                        state.exec_node[:, None])       # [i, j]: exec(j) < exec(i)
    gate = undecided[None, :] | exec_before | state.awaits_all[:, None]
    return state.adj & gate & ~(invalidated | free)[None, :]


def _drain_fix(state: DrainState):
    """The dense fixpoint body shared by :func:`drain` (legacy 2-tuple) and
    :func:`drain_levels` (forensic 3-tuple): returns (applied, newly,
    sweeps) where ``sweeps`` counts while-loop iterations — one frontier
    sweep per executeAt antichain plus the terminating empty sweep.  The
    sweep count IS the serial-launch-equivalent cost of the drain (each
    sweep is one [N, N] matvec the device cannot overlap with the next),
    which is what makes a deep serial chain the regime's worst case."""
    blocking = blocking_matrix(state)
    blk = blocking.astype(jnp.bfloat16)               # [N, N] — MXU matvec
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED

    def body(carry):
        applied, _, sweeps = carry
        unapplied = (~applied).astype(jnp.bfloat16)
        waiting = (blk @ unapplied) > 0.5
        ready = stable & ~applied & ~waiting
        return applied | ready, jnp.any(ready), sweeps + 1

    def cond(carry):
        return carry[1]

    applied, _, sweeps = lax.while_loop(
        cond, body, (applied0, jnp.bool_(True), jnp.int32(0)))
    return applied, applied & ~applied0, sweeps


@jax.jit
def drain(state: DrainState) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the drain to fixpoint.

    Returns (applied bool[N], newly_executed bool[N]): the final applied set
    and which slots this call transitioned Stable -> executed.
    """
    applied, newly, _ = _drain_fix(state)
    return applied, newly


@jax.jit
def drain_levels(state: DrainState):
    """Forensic variant of :func:`drain`: (applied, newly, sweeps) — same
    fixpoint, same bytes, plus the sweep count (see _drain_fix)."""
    return _drain_fix(state)


@jax.jit
def ready_frontier(state: DrainState) -> jnp.ndarray:
    """One non-iterated sweep: which Stable txns are executable right now."""
    blocking = blocking_matrix(state)
    applied = state.status == SLOT_APPLIED
    waiting = jnp.any(blocking & ~applied[None, :], axis=1)
    return (state.status == SLOT_STABLE) & ~waiting


class EllDrainState(NamedTuple):
    """Sparse (ELL / padded-row-index) drain state for large in-flight sets:
    ``adj_idx[i, d]`` holds the slot indices row i depends on (-1 padded).
    The dense bool[N, N] matrix is 10GB at the 100k-in-flight spec; this is
    N x max_degree.  Device cost per sweep is an N x D gather instead of an
    MXU matvec — the right trade above a few thousand live slots."""

    adj_idx: jnp.ndarray     # int32[N, D]  deps of row i, -1 padded
    status: jnp.ndarray      # int32[N]
    exec_msb: jnp.ndarray    # int64[N]
    exec_lsb: jnp.ndarray    # int64[N]
    exec_node: jnp.ndarray   # int32[N]
    awaits_all: jnp.ndarray  # bool[N]


def _ell_blocking(state: EllDrainState):
    """B[i, d]: does dep adj_idx[i, d] (ever) gate i's execution?  Gathered
    per-edge instead of broadcast [N, N]."""
    j = jnp.clip(state.adj_idx, 0)
    valid = state.adj_idx >= 0
    st_j = state.status[j]
    undecided = (st_j >= 0) & (st_j < SLOT_COMMITTED)
    dead = (st_j == SLOT_INVALIDATED) | (st_j == SLOT_FREE)
    exec_before = ts_lt(state.exec_msb[j], state.exec_lsb[j],
                        state.exec_node[j],
                        state.exec_msb[:, None], state.exec_lsb[:, None],
                        state.exec_node[:, None])
    gate = undecided | exec_before | state.awaits_all[:, None]
    return valid & gate & ~dead, j


@jax.jit
def ready_frontier_ell(state: EllDrainState) -> jnp.ndarray:
    blocking, j = _ell_blocking(state)
    applied_j = state.status[j] == SLOT_APPLIED
    waiting = jnp.any(blocking & ~applied_j, axis=1)
    return (state.status == SLOT_STABLE) & ~waiting


# -- fused (batched-over-stores) frontier sweeps ------------------------------
#
# r08 launch coalescing: drain ticks from several CommandStores that land in
# the same event-loop step share ONE device dispatch.  Each store's state is
# padded to the group maximum (free rows gate nothing and are never Stable,
# so padding never changes a store's frontier) and stacked on a leading
# store axis; the sweep is the exact ready_frontier[_ell] trace vmapped over
# that axis — bit-identical to the solo sweeps it replaces.

# Keyed on raw per-store shape tuples, so a shape-churning workload (every
# store growing through a different _pow2 ladder) would grow one compiled
# program per distinct combination without bound.  LRU-bound it: steady
# state reuses a handful of keys, and an evicted program just recompiles
# on next use (counter surfaced on the ``# index:`` line).
_FUSED_FRONT_CACHE_CAP = 32
_FUSED_FRONT_CACHE = OrderedDict()


def _fused_cache_get(key):
    fn = _FUSED_FRONT_CACHE.get(key)
    if fn is not None:
        _FUSED_FRONT_CACHE.move_to_end(key)
    return fn


def _fused_cache_put(key, fn):
    _FUSED_FRONT_CACHE[key] = fn
    while len(_FUSED_FRONT_CACHE) > _FUSED_FRONT_CACHE_CAP:
        _FUSED_FRONT_CACHE.popitem(last=False)
        _COUNTERS["fused_front_evictions"] += 1
    return fn


def fused_ready_frontier(states):
    """One fused launch for S stores' frontier sweeps.  ``states`` is a
    list of dense DrainStates (possibly different n); padding + stacking
    happens INSIDE the jitted program (a single dispatch consumes the
    per-store buffers directly).  Returns bool[S, n_max]; row i's first n_i
    entries are exactly ready_frontier(states[i])."""
    shapes = tuple(st.status.shape[0] for st in states)
    key = ("dense", shapes)
    fn = _fused_cache_get(key)
    if fn is None:
        n_max = max(shapes)

        def pad(st):
            d = n_max - st.status.shape[0]
            return DrainState(
                jnp.pad(st.adj, ((0, d), (0, d))),
                jnp.pad(st.status, (0, d), constant_values=SLOT_FREE),
                jnp.pad(st.exec_msb, (0, d)), jnp.pad(st.exec_lsb, (0, d)),
                jnp.pad(st.exec_node, (0, d)),
                jnp.pad(st.awaits_all, (0, d)))

        def traced(sts):
            stacked = DrainState(*(jnp.stack(col) for col in
                                   zip(*(pad(st) for st in sts))))
            return jax.vmap(ready_frontier)(stacked)

        fn = _fused_cache_put(key, jax.jit(traced))
    return fn(tuple(states))


def fused_ready_frontier_ell(states):
    """ELL analogue of fused_ready_frontier: pads rows to the group max n
    and edge columns to the group max degree (-1 = no edge), stacks, and
    vmaps ready_frontier_ell — bit-identical per store."""
    shapes = tuple(st.adj_idx.shape for st in states)
    key = ("ell", shapes)
    fn = _fused_cache_get(key)
    if fn is None:
        n_max = max(s[0] for s in shapes)
        d_max = max(s[1] for s in shapes)

        def pad(st):
            d = n_max - st.status.shape[0]
            dd = d_max - st.adj_idx.shape[1]
            return EllDrainState(
                jnp.pad(st.adj_idx, ((0, d), (0, dd)), constant_values=-1),
                jnp.pad(st.status, (0, d), constant_values=SLOT_FREE),
                jnp.pad(st.exec_msb, (0, d)), jnp.pad(st.exec_lsb, (0, d)),
                jnp.pad(st.exec_node, (0, d)),
                jnp.pad(st.awaits_all, (0, d)))

        def traced(sts):
            stacked = EllDrainState(*(jnp.stack(col) for col in
                                      zip(*(pad(st) for st in sts))))
            return jax.vmap(ready_frontier_ell)(stacked)

        fn = _fused_cache_put(key, jax.jit(traced))
    return fn(tuple(states))


def _drain_ell_fix(state: EllDrainState):
    """ELL analogue of _drain_fix: (applied, newly, sweeps) with an [N, D]
    gather per sweep instead of the dense matvec."""
    blocking, j = _ell_blocking(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED

    def body(carry):
        applied, _, sweeps = carry
        waiting = jnp.any(blocking & ~applied[j], axis=1)
        ready = stable & ~applied & ~waiting
        return applied | ready, jnp.any(ready), sweeps + 1

    applied, _, sweeps = lax.while_loop(
        lambda c: c[1], body, (applied0, jnp.bool_(True), jnp.int32(0)))
    return applied, applied & ~applied0, sweeps


@jax.jit
def drain_ell(state: EllDrainState) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fixpoint drain over the ELL adjacency: each sweep applies a whole
    antichain, the per-sweep cost is an [N, D] gather (no [N, N] anywhere)."""
    applied, newly, _ = _drain_ell_fix(state)
    return applied, newly


@jax.jit
def drain_ell_levels(state: EllDrainState):
    """Forensic variant of :func:`drain_ell`: (applied, newly, sweeps)."""
    return _drain_ell_fix(state)


# -- log-depth drain (r19): level assignment by pointer jumping ---------------
#
# The fixpoint above pays one sweep per executeAt antichain — O(depth)
# serial device launches' worth of latency folded into one while_loop, which
# is exactly the serial-chain regime's loss (fixpoint_sweeps=4097 on the
# 4096-deep bench chain).  The level pass below computes every slot's
# execution round in O(log depth) rounds; the drain is then ONE masked
# compare (``applied |= stable & level-finite`` — or ``level <= watermark``
# for the prefix form the tick's wavefront uses).

# INF must survive ``off + level`` in int32 without wrapping (2*INF < 2^31)
# and ``level * n + j`` in int64 for the critical-parent argmax key
LEVEL_INF = 1 << 28


def _level_base(status, stable, applied0):
    """Initial bounds: applied -> 0, stable -> 1 (every stable row runs at
    round >= 1), anything else that can appear as a gating dep (undecided,
    Committed-not-yet-Stable) -> INF: it never applies inside a static
    drain, so rows waiting on it are blocked forever — the same gate
    ``blocking_matrix`` / ``_ell_blocking`` already encode."""
    return jnp.where(applied0, 0,
                     jnp.where(stable, 1, LEVEL_INF)).astype(jnp.int32)


def _level_loop(lv0, ptr0, off0, relax, n):
    """The shared doubling loop: interleave one Bellman relax (``relax(lv)``
    = 1 + max over blocking deps, representation-specific) with one pointer
    jump along the critical-parent chain.  Both are monotone path-witnessed
    lower bounds (a walk of ``off[i]`` blocking edges ends at ``ptr[i]``, and
    each blocking edge adds >= 1 level), so any interleaving stays sound;
    stationarity forces lv >= relax(lv), which pins lv to the unique DAG
    fixpoint — the exact level.  Levels above ``n`` are clamped to INF: a
    witness walk longer than the slot count must traverse a cycle (possible
    only via awaits_all edges), and every row on or upstream of a blocking
    cycle is blocked forever.  Returns (levels, rounds); rounds is bounded
    by depth+2 in the worst case and ~2*log2(depth)+c when the jump chain
    tracks the critical path (chains: always — the tie-break picks the
    latest-executing parent)."""

    def body(carry):
        lv, ptr, off, _ch, r = carry
        new = jnp.where(lv < LEVEL_INF, jnp.minimum(relax(lv), LEVEL_INF),
                        lv)
        new = jnp.maximum(new, lv)
        # jump: level(i) >= off(i) + level(ptr(i)) along the witness walk
        jumped = jnp.minimum(off + new[ptr], LEVEL_INF)
        new = jnp.where(off > 0, jnp.maximum(new, jumped), new)
        new = jnp.where(new > n, LEVEL_INF, new)
        # double the walk: i -> ptr(i) -> ptr(ptr(i))
        off = jnp.minimum(off + off[ptr], LEVEL_INF)
        ptr = ptr[ptr]
        return new, ptr, off, jnp.any(new != lv), r + 1

    lv, _p, _o, _c, rounds = lax.while_loop(
        lambda c: c[3] & (c[4] < jnp.int32(n + 3)), body,
        (lv0, ptr0, off0, jnp.bool_(True), jnp.int32(0)))
    return lv, rounds


def _critical_ptr(lv0, blocking, j, stable, n):
    """Each stable row's starting jump pointer: the blocking dep with the
    highest (level, slot) key — the latest-executing parent, the chain
    regime's critical parent.  Rows with no blocking dep (or not stable)
    point at themselves with off=0, so their jumps are no-ops."""
    rows = jnp.arange(n, dtype=jnp.int32)
    key = jnp.where(blocking, lv0[j].astype(jnp.int64) * n + j, jnp.int64(-1))
    best = jnp.argmax(key, axis=1)
    pj = jnp.take_along_axis(j, best[:, None], axis=1)[:, 0].astype(jnp.int32)
    has = jnp.any(blocking, axis=1)
    ptr = jnp.where(has & stable, pj, rows)
    off = jnp.where(ptr != rows, jnp.int32(1), jnp.int32(0))
    return ptr, off


def _ell_levels(state: EllDrainState):
    blocking, j = _ell_blocking(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED
    n = state.status.shape[0]
    lv0 = _level_base(state.status, stable, applied0)
    ptr0, off0 = _critical_ptr(lv0, blocking, j, stable, n)

    def relax(lv):
        cand = 1 + jnp.max(jnp.where(blocking, lv[j], 0), axis=1)
        return jnp.where(stable, cand, lv0)

    return _level_loop(lv0, ptr0, off0, relax, n)


@jax.jit
def level_assign_ell(state: EllDrainState):
    """(levels int32[N], rounds): each slot's execution round under the
    static drain — 0 applied, 1..N the fixpoint sweep that would apply it,
    LEVEL_INF blocked forever.  O(log depth) gather rounds on chains."""
    return _ell_levels(state)


def _dense_levels(state: DrainState):
    blocking = blocking_matrix(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED
    n = state.status.shape[0]
    lv0 = _level_base(state.status, stable, applied0)
    j = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :], (n, n))
    ptr0, off0 = _critical_ptr(lv0, blocking, j, stable, n)

    def relax(lv):
        cand = 1 + jnp.max(jnp.where(blocking, lv[None, :], 0), axis=1)
        return jnp.where(stable, cand, lv0)

    return _level_loop(lv0, ptr0, off0, relax, n)


@jax.jit
def level_assign_dense(state: DrainState):
    """Dense-state analogue of :func:`level_assign_ell` (one [N, N] masked
    row-max per relax round instead of the gather)."""
    return _dense_levels(state)


def _levels_to_drain(state, lv):
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED
    applied = applied0 | (stable & (lv < LEVEL_INF))
    return applied, applied & ~applied0


@jax.jit
def _drain_ell_logdepth_full(state: EllDrainState):
    lv, rounds = _ell_levels(state)
    applied, newly = _levels_to_drain(state, lv)
    depth = jnp.max(jnp.where(lv < LEVEL_INF, lv, 0))
    return applied, newly, rounds, depth


def drain_ell_logdepth(state: EllDrainState):
    """Log-depth drain over the ELL adjacency: (applied, newly, rounds) —
    byte-identical applied/newly to :func:`drain_ell_levels` (the fixpoint
    is the standing oracle), with ``rounds`` ~ O(log depth) doubling rounds
    in place of O(depth) sweeps."""
    applied, newly, rounds, _depth = _drain_ell_logdepth_full(state)
    return applied, newly, rounds


@jax.jit
def _drain_dense_logdepth_full(state: DrainState):
    lv, rounds = _dense_levels(state)
    applied, newly = _levels_to_drain(state, lv)
    depth = jnp.max(jnp.where(lv < LEVEL_INF, lv, 0))
    return applied, newly, rounds, depth


def drain_logdepth(state: DrainState):
    """Dense-state analogue of :func:`drain_ell_logdepth`."""
    applied, newly, rounds, _depth = _drain_dense_logdepth_full(state)
    return applied, newly, rounds


@jax.jit
def drain_ell_watermark(state: EllDrainState, watermark):
    """The level-drain prefix form: apply every stable slot whose execution
    round is <= ``watermark`` in ONE shot — byte-identical to running
    exactly ``watermark`` fixpoint sweeps of :func:`_drain_ell_fix`.  The
    tick's adaptive wavefront harvests candidates this way (watermark is
    traced, so one compilation serves every W)."""
    lv, _rounds = _ell_levels(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED
    applied = applied0 | (stable & (lv <= watermark))
    return applied, applied & ~applied0


@jax.jit
def drain_dense_watermark(state: DrainState, watermark):
    """Dense-state analogue of :func:`drain_ell_watermark`."""
    lv, _rounds = _dense_levels(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED
    applied = applied0 | (stable & (lv <= watermark))
    return applied, applied & ~applied0


@jax.jit
def drain_dense_logsq(state: DrainState):
    """The dense log-depth form the ISSUE names: log-squaring of the
    blocked-reachability semiring.  A stable row is blocked forever iff it
    reaches — through stable intermediates along blocking edges — either a
    dep that never applies (undecided / decided-not-stable) or a blocking
    cycle (awaits_all).  Squaring the step matrix closes all path lengths in
    O(log depth) bf16 [N, N] matmuls (MXU-shaped: this is the TPU-regime
    variant; on CPU the cost model prices its N^3 squarings out in favor of
    the ELL doubling pass).  Returns (applied, newly, squarings) with
    applied/newly byte-identical to :func:`drain_levels`."""
    blocking = blocking_matrix(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED
    bad = ~stable & ~applied0
    # step edges continue only through stable deps; edges into applied deps
    # are satisfied and edges into ``bad`` deps are terminal hits
    step = blocking & stable[None, :]
    hit = jnp.any(blocking & bad[None, :], axis=1)

    def body(carry):
        s, _ch, r = carry
        s2 = ((s.astype(jnp.bfloat16) @ s.astype(jnp.bfloat16)) > 0.5) | s
        return s2, jnp.any(s2 != s), r + 1

    closure, _ch, squarings = lax.while_loop(
        lambda c: c[1], body, (step, jnp.bool_(True), jnp.int32(0)))
    on_cycle = jnp.diagonal(closure)        # i reaches i in >= 1 step
    targets = (hit | on_cycle).astype(jnp.bfloat16)
    blocked = hit | on_cycle | \
        ((closure.astype(jnp.bfloat16) @ targets) > 0.5)
    applied = applied0 | (stable & ~blocked)
    return applied, applied & ~applied0, squarings


@jax.jit
def _dense_degree(adj):
    return jnp.max(jnp.sum(adj, axis=1))


def _pow2_deg(d: int) -> int:
    out = 4
    while out < d:
        out *= 2
    return out


_DENSE_TO_ELL_CACHE = {}


def dense_to_ell(state: DrainState,
                 max_degree: Optional[int] = None) -> EllDrainState:
    """Re-form a dense DrainState as the equivalent EllDrainState (same slot
    indexing, same gating edges) so the doubling pass can run its [N, D]
    gathers.  The scatter happens in-jit; only the max degree (one device
    reduction) crosses the boundary.  Used by the dense ``drain_auto``
    route — the serving tick never pays this, it builds ELL straight from
    the host edge lists."""
    if max_degree is None:
        max_degree = int(_dense_degree(state.adj))
    d = _pow2_deg(max(int(max_degree), 1))
    n = state.status.shape[0]
    key = (n, d)
    fn = _DENSE_TO_ELL_CACHE.get(key)
    if fn is None:
        def convert(adj):
            rows = jnp.arange(n, dtype=jnp.int32)[:, None]
            cols = jnp.broadcast_to(jnp.arange(n, dtype=jnp.int32)[None, :],
                                    (n, n))
            slot = jnp.where(adj, jnp.cumsum(adj, axis=1) - 1, d)
            flat = rows * (d + 1) + jnp.minimum(slot, d)
            out = jnp.full(n * (d + 1), -1, jnp.int32)
            out = out.at[flat.ravel()].max(cols.ravel())
            return out.reshape(n, d + 1)[:, :d]

        fn = _DENSE_TO_ELL_CACHE[key] = jax.jit(convert)
    return EllDrainState(fn(state.adj), state.status, state.exec_msb,
                         state.exec_lsb, state.exec_node, state.awaits_all)


# -- routing: priced, never thresholds ---------------------------------------

DRAIN_ENV = "ACCORD_TPU_DRAIN"


def drain_logdepth_enabled() -> bool:
    """The ``ACCORD_TPU_DRAIN=fixpoint`` escape hatch: when set, every
    routed drain runs the fixpoint oracle (same contract as
    ``ACCORD_TPU_FUSION=off``) — the log-depth kernels are a perf layer,
    never load-bearing for correctness."""
    return os.environ.get(DRAIN_ENV, "").strip().lower() not in (
        "fixpoint", "fix", "off", "0", "false", "no")


# process-wide probe coefficients (seconds per element); injectable via
# set_drain_calibration for tests
_DRAIN_CALIB = None

# per-shape observed graph stats from prior routed calls: the depth a
# fixpoint would pay and the rounds the doubling pass paid — the two
# measured quantities the price comparison needs.  Keyed on the state
# shape (the same key the jit cache uses), so steady-state workloads are
# priced from their own history, not guesses.
_ROUTE_STATS = {}

# route counters for the ``# index:`` line / forensics
_COUNTERS = {"drain_logdepth": 0, "drain_fixpoint": 0,
             "drain_logdepth_failovers": 0, "fused_front_evictions": 0}


def drain_counters() -> dict:
    return dict(_COUNTERS)


def reset_drain_routing() -> None:
    """Test hook: forget learned per-shape stats and counters (calibration
    is kept — reset it via set_drain_calibration)."""
    _ROUTE_STATS.clear()
    for k in _COUNTERS:
        _COUNTERS[k] = 0


def set_drain_calibration(c_sweep_ell: float, c_round_ell: float,
                          c_sweep_dense: float, c_sq_dense: float,
                          c_conv: float) -> None:
    global _DRAIN_CALIB
    _DRAIN_CALIB = {"c_sweep_ell": c_sweep_ell, "c_round_ell": c_round_ell,
                    "c_sweep_dense": c_sweep_dense, "c_sq_dense": c_sq_dense,
                    "c_conv": c_conv}


def _probe_chain_ell(n: int, d: int = 4) -> EllDrainState:
    import numpy as np
    adj_idx = np.full((n, d), -1, np.int32)
    adj_idx[1:, 0] = np.arange(n - 1, dtype=np.int32)
    hlc = np.arange(2, n + 2, dtype=np.int64)
    return EllDrainState(jnp.asarray(adj_idx),
                         jnp.full(n, SLOT_STABLE, jnp.int32),
                         jnp.asarray(hlc), jnp.zeros(n, jnp.int64),
                         jnp.ones(n, jnp.int32), jnp.zeros(n, bool))


def _probe_chain_dense(n: int) -> DrainState:
    import numpy as np
    adj = np.zeros((n, n), bool)
    adj[np.arange(1, n), np.arange(n - 1)] = True
    hlc = np.arange(2, n + 2, dtype=np.int64)
    return DrainState(jnp.asarray(adj), jnp.full(n, SLOT_STABLE, jnp.int32),
                      jnp.asarray(hlc), jnp.zeros(n, jnp.int64),
                      jnp.ones(n, jnp.int32), jnp.zeros(n, bool))


def _measure_drain_calibration() -> dict:
    """The once-per-process micro-probe behind the drain route: times one
    fixpoint sweep, one doubling round, one dense sweep, one dense squaring
    and the dense->ELL re-form on small known-depth chains, and divides by
    their element counts.  The crossover between fixpoint and doubling IS
    these slopes — no depth threshold is written down anywhere."""
    import statistics as _st
    import time as _time

    def timed(fn, reps=3):
        fn()                                     # warm + compile
        runs = []
        for _ in range(reps):
            t0 = _time.perf_counter()
            fn()
            runs.append(_time.perf_counter() - t0)
        return _st.median(runs)

    import numpy as np
    n, d = 256, 4
    ell = _probe_chain_ell(n, d)
    sweeps = int(np.asarray(drain_ell_levels(ell)[2]))
    t_fix = timed(lambda: jax.block_until_ready(drain_ell_levels(ell)[0]))
    c_sweep_ell = max(t_fix, 1e-9) / (sweeps * n * d)
    rounds = int(np.asarray(_drain_ell_logdepth_full(ell)[2]))
    t_dbl = timed(
        lambda: jax.block_until_ready(_drain_ell_logdepth_full(ell)[0]))
    c_round_ell = max(t_dbl, 1e-9) / (max(rounds, 1) * n * d)
    dense = _probe_chain_dense(n)
    sweeps_d = int(np.asarray(drain_levels(dense)[2]))
    t_fixd = timed(lambda: jax.block_until_ready(drain_levels(dense)[0]))
    c_sweep_dense = max(t_fixd, 1e-9) / (sweeps_d * n * n)
    sq = int(np.asarray(drain_dense_logsq(dense)[2]))
    t_sq = timed(
        lambda: jax.block_until_ready(drain_dense_logsq(dense)[0]))
    c_sq_dense = max(t_sq, 1e-9) / (max(sq, 1) * n * n * n)
    t_conv = timed(
        lambda: jax.block_until_ready(dense_to_ell(dense, 1).adj_idx))
    c_conv = max(t_conv, 1e-9) / (n * n)
    return {"c_sweep_ell": c_sweep_ell, "c_round_ell": c_round_ell,
            "c_sweep_dense": c_sweep_dense, "c_sq_dense": c_sq_dense,
            "c_conv": c_conv}


def drain_calibration() -> dict:
    global _DRAIN_CALIB
    if _DRAIN_CALIB is None:
        _DRAIN_CALIB = _measure_drain_calibration()
    return _DRAIN_CALIB


def _record_stats(key, depth: int, rounds: Optional[int]) -> None:
    st = _ROUTE_STATS.setdefault(key, {})
    st["depth"] = depth
    if rounds is not None:
        st["rounds"] = rounds


def drain_ell_auto(state: EllDrainState):
    """The routed ELL drain: (applied, newly, sweeps, route).  Prices the
    doubling pass against the per-sweep fixpoint from the probe slopes and
    this shape's observed depth/rounds; an unseen shape runs the doubling
    pass first (worst case a small constant over the fixpoint, best case
    exponentially cheaper) and the measurement itself becomes the price.
    A device fault inside the log-depth launch fails the WHOLE flush over
    to the fixpoint route — byte-identical results, one counter tick."""
    import numpy as np
    n, d = state.adj_idx.shape
    key = ("ell", n, d)
    route = "ell-logdepth"
    if not drain_logdepth_enabled():
        route = "ell-fixpoint"
    else:
        st = _ROUTE_STATS.get(key)
        if st is not None and "rounds" in st:
            cal = drain_calibration()
            cost_fix = (st["depth"] + 1) * n * d * cal["c_sweep_ell"]
            cost_dbl = (st["rounds"] + 1) * n * d * cal["c_round_ell"]
            if cost_fix < cost_dbl:
                route = "ell-fixpoint"
    if route == "ell-logdepth":
        try:
            launch_check("drain logdepth")
            applied, newly, rounds, depth = _drain_ell_logdepth_full(state)
            faults.check("transfer", "drain logdepth download")
            rounds = int(np.asarray(rounds))
            _record_stats(key, int(np.asarray(depth)), rounds)
            _COUNTERS["drain_logdepth"] += 1
            return applied, newly, rounds, route
        except faults.DEVICE_EXCEPTIONS:
            _COUNTERS["drain_logdepth_failovers"] += 1
            route = "ell-fixpoint-failover"
    applied, newly, sweeps = drain_ell_levels(state)
    sweeps = int(np.asarray(sweeps))
    _record_stats(key, sweeps - 1, None)
    _COUNTERS["drain_fixpoint"] += 1
    return applied, newly, sweeps, route


def drain_auto(state):
    """The routed drain for either representation: (applied, newly, sweeps,
    route).  Dense states price three ways — the dense fixpoint, the dense
    reachability log-squaring (MXU-shaped), and re-forming to ELL for the
    doubling pass — against this shape's observed depth; ELL states route
    via :func:`drain_ell_auto`."""
    import numpy as np
    if isinstance(state, EllDrainState):
        return drain_ell_auto(state)
    n = state.status.shape[0]
    key = ("dense", n)
    route = "dense-to-ell-logdepth"
    if not drain_logdepth_enabled():
        route = "dense-fixpoint"
    else:
        st = _ROUTE_STATS.get(key)
        if st is not None and "rounds" in st:
            cal = drain_calibration()
            d = st.get("ell_d", 4)
            cost_fix = (st["depth"] + 1) * n * n * cal["c_sweep_dense"]
            sq = max(int(st["depth"]).bit_length() + 1, 2)
            cost_sq = sq * n * n * n * cal["c_sq_dense"]
            cost_dbl = n * n * cal["c_conv"] + \
                (st["rounds"] + 1) * n * d * cal["c_round_ell"]
            costs = {"dense-fixpoint": cost_fix, "dense-logsq": cost_sq,
                     "dense-to-ell-logdepth": cost_dbl}
            route = min(costs, key=costs.get)
    if route == "dense-to-ell-logdepth":
        try:
            launch_check("drain logdepth")
            ell = dense_to_ell(state)
            applied, newly, rounds, depth = _drain_ell_logdepth_full(ell)
            faults.check("transfer", "drain logdepth download")
            rounds = int(np.asarray(rounds))
            _record_stats(key, int(np.asarray(depth)), rounds)
            _ROUTE_STATS[key]["ell_d"] = ell.adj_idx.shape[1]
            _COUNTERS["drain_logdepth"] += 1
            return applied, newly, rounds, route
        except faults.DEVICE_EXCEPTIONS:
            _COUNTERS["drain_logdepth_failovers"] += 1
            route = "dense-fixpoint-failover"
    if route == "dense-logsq":
        try:
            launch_check("drain logsq")
            applied, newly, sq = drain_dense_logsq(state)
            faults.check("transfer", "drain logsq download")
            _COUNTERS["drain_logdepth"] += 1
            return applied, newly, int(np.asarray(sq)), route
        except faults.DEVICE_EXCEPTIONS:
            _COUNTERS["drain_logdepth_failovers"] += 1
            route = "dense-fixpoint-failover"
    applied, newly, sweeps = drain_levels(state)
    sweeps = int(np.asarray(sweeps))
    _record_stats(key, sweeps - 1, None)
    _COUNTERS["drain_fixpoint"] += 1
    return applied, newly, sweeps, route
