"""Execution drain — executeAt-gated Kahn fixpoint over the dependency graph.

Rebuild of ref: accord-core/src/main/java/accord/local/Commands.java:656-857
(maybeExecute / updateDependencyAndMaybeExecute / NotifyWaitingOn) — the
reference drains the graph reactively, one listener callback per dependency
transition; here the whole frontier advances in one device fixpoint.

The Accord execution rule (local/Command.java WaitingOn): a Stable txn i may
execute when every dependency j with ``executeAt(j) < executeAt(i)`` has
Applied; dependencies that execute after i, or were invalidated, are removed
from the waiting set; undecided (not-yet-Committed) dependencies always
block.

Kernel form: with adjacency ``adj[i, j]`` (i depends on j), per-slot status
and packed executeAt, precompute the static blocking matrix

    B[i, j] = adj[i, j] & (undecided[j] | executeAt(j) < executeAt(i))
                        & ~invalidated[j]

then iterate

    waiting[i]  = any_j B[i, j] & ~applied[j]        (a masked matvec — MXU)
    ready       = stable & ~applied & ~waiting
    applied    |= ready

to fixpoint under ``lax.while_loop``.  Each sweep applies a whole antichain
of the executeAt order, so the loop runs O(depth) times, not O(txns); the
matvec is done in bf16 so XLA tiles it onto the MXU for large N.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .deps_kernel import (SLOT_APPLIED, SLOT_COMMITTED, SLOT_FREE,
                          SLOT_INVALIDATED, SLOT_STABLE)
from .packing import ts_lt


class DrainState(NamedTuple):
    adj: jnp.ndarray         # bool[N, N]  i depends on j
    status: jnp.ndarray      # int32[N]    SLOT_*
    exec_msb: jnp.ndarray    # int64[N]    executeAt (valid when status >= COMMITTED)
    exec_lsb: jnp.ndarray    # int64[N]
    exec_node: jnp.ndarray   # int32[N]
    awaits_all: jnp.ndarray  # bool[N]     row i awaits ALL deps regardless of
    #                          executeAt order (ExclusiveSyncPoint /
    #                          EphemeralRead, ref: Txn.Kind.awaitsOnlyDeps)


def blocking_matrix(state: DrainState) -> jnp.ndarray:
    """Precompute B[i, j]: does dep j (ever) gate i's execution?"""
    undecided = (state.status >= 0) & (state.status < SLOT_COMMITTED)
    invalidated = state.status == SLOT_INVALIDATED
    free = state.status == SLOT_FREE
    exec_before = ts_lt(state.exec_msb[None, :], state.exec_lsb[None, :],
                        state.exec_node[None, :],
                        state.exec_msb[:, None], state.exec_lsb[:, None],
                        state.exec_node[:, None])       # [i, j]: exec(j) < exec(i)
    gate = undecided[None, :] | exec_before | state.awaits_all[:, None]
    return state.adj & gate & ~(invalidated | free)[None, :]


def _drain_fix(state: DrainState):
    """The dense fixpoint body shared by :func:`drain` (legacy 2-tuple) and
    :func:`drain_levels` (forensic 3-tuple): returns (applied, newly,
    sweeps) where ``sweeps`` counts while-loop iterations — one frontier
    sweep per executeAt antichain plus the terminating empty sweep.  The
    sweep count IS the serial-launch-equivalent cost of the drain (each
    sweep is one [N, N] matvec the device cannot overlap with the next),
    which is what makes a deep serial chain the regime's worst case."""
    blocking = blocking_matrix(state)
    blk = blocking.astype(jnp.bfloat16)               # [N, N] — MXU matvec
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED

    def body(carry):
        applied, _, sweeps = carry
        unapplied = (~applied).astype(jnp.bfloat16)
        waiting = (blk @ unapplied) > 0.5
        ready = stable & ~applied & ~waiting
        return applied | ready, jnp.any(ready), sweeps + 1

    def cond(carry):
        return carry[1]

    applied, _, sweeps = lax.while_loop(
        cond, body, (applied0, jnp.bool_(True), jnp.int32(0)))
    return applied, applied & ~applied0, sweeps


@jax.jit
def drain(state: DrainState) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Run the drain to fixpoint.

    Returns (applied bool[N], newly_executed bool[N]): the final applied set
    and which slots this call transitioned Stable -> executed.
    """
    applied, newly, _ = _drain_fix(state)
    return applied, newly


@jax.jit
def drain_levels(state: DrainState):
    """Forensic variant of :func:`drain`: (applied, newly, sweeps) — same
    fixpoint, same bytes, plus the sweep count (see _drain_fix)."""
    return _drain_fix(state)


@jax.jit
def ready_frontier(state: DrainState) -> jnp.ndarray:
    """One non-iterated sweep: which Stable txns are executable right now."""
    blocking = blocking_matrix(state)
    applied = state.status == SLOT_APPLIED
    waiting = jnp.any(blocking & ~applied[None, :], axis=1)
    return (state.status == SLOT_STABLE) & ~waiting


class EllDrainState(NamedTuple):
    """Sparse (ELL / padded-row-index) drain state for large in-flight sets:
    ``adj_idx[i, d]`` holds the slot indices row i depends on (-1 padded).
    The dense bool[N, N] matrix is 10GB at the 100k-in-flight spec; this is
    N x max_degree.  Device cost per sweep is an N x D gather instead of an
    MXU matvec — the right trade above a few thousand live slots."""

    adj_idx: jnp.ndarray     # int32[N, D]  deps of row i, -1 padded
    status: jnp.ndarray      # int32[N]
    exec_msb: jnp.ndarray    # int64[N]
    exec_lsb: jnp.ndarray    # int64[N]
    exec_node: jnp.ndarray   # int32[N]
    awaits_all: jnp.ndarray  # bool[N]


def _ell_blocking(state: EllDrainState):
    """B[i, d]: does dep adj_idx[i, d] (ever) gate i's execution?  Gathered
    per-edge instead of broadcast [N, N]."""
    j = jnp.clip(state.adj_idx, 0)
    valid = state.adj_idx >= 0
    st_j = state.status[j]
    undecided = (st_j >= 0) & (st_j < SLOT_COMMITTED)
    dead = (st_j == SLOT_INVALIDATED) | (st_j == SLOT_FREE)
    exec_before = ts_lt(state.exec_msb[j], state.exec_lsb[j],
                        state.exec_node[j],
                        state.exec_msb[:, None], state.exec_lsb[:, None],
                        state.exec_node[:, None])
    gate = undecided | exec_before | state.awaits_all[:, None]
    return valid & gate & ~dead, j


@jax.jit
def ready_frontier_ell(state: EllDrainState) -> jnp.ndarray:
    blocking, j = _ell_blocking(state)
    applied_j = state.status[j] == SLOT_APPLIED
    waiting = jnp.any(blocking & ~applied_j, axis=1)
    return (state.status == SLOT_STABLE) & ~waiting


# -- fused (batched-over-stores) frontier sweeps ------------------------------
#
# r08 launch coalescing: drain ticks from several CommandStores that land in
# the same event-loop step share ONE device dispatch.  Each store's state is
# padded to the group maximum (free rows gate nothing and are never Stable,
# so padding never changes a store's frontier) and stacked on a leading
# store axis; the sweep is the exact ready_frontier[_ell] trace vmapped over
# that axis — bit-identical to the solo sweeps it replaces.

_FUSED_FRONT_CACHE = {}


def fused_ready_frontier(states):
    """One fused launch for S stores' frontier sweeps.  ``states`` is a
    list of dense DrainStates (possibly different n); padding + stacking
    happens INSIDE the jitted program (a single dispatch consumes the
    per-store buffers directly).  Returns bool[S, n_max]; row i's first n_i
    entries are exactly ready_frontier(states[i])."""
    shapes = tuple(st.status.shape[0] for st in states)
    key = ("dense", shapes)
    fn = _FUSED_FRONT_CACHE.get(key)
    if fn is None:
        n_max = max(shapes)

        def pad(st):
            d = n_max - st.status.shape[0]
            return DrainState(
                jnp.pad(st.adj, ((0, d), (0, d))),
                jnp.pad(st.status, (0, d), constant_values=SLOT_FREE),
                jnp.pad(st.exec_msb, (0, d)), jnp.pad(st.exec_lsb, (0, d)),
                jnp.pad(st.exec_node, (0, d)),
                jnp.pad(st.awaits_all, (0, d)))

        def traced(sts):
            stacked = DrainState(*(jnp.stack(col) for col in
                                   zip(*(pad(st) for st in sts))))
            return jax.vmap(ready_frontier)(stacked)

        fn = _FUSED_FRONT_CACHE[key] = jax.jit(traced)
    return fn(tuple(states))


def fused_ready_frontier_ell(states):
    """ELL analogue of fused_ready_frontier: pads rows to the group max n
    and edge columns to the group max degree (-1 = no edge), stacks, and
    vmaps ready_frontier_ell — bit-identical per store."""
    shapes = tuple(st.adj_idx.shape for st in states)
    key = ("ell", shapes)
    fn = _FUSED_FRONT_CACHE.get(key)
    if fn is None:
        n_max = max(s[0] for s in shapes)
        d_max = max(s[1] for s in shapes)

        def pad(st):
            d = n_max - st.status.shape[0]
            dd = d_max - st.adj_idx.shape[1]
            return EllDrainState(
                jnp.pad(st.adj_idx, ((0, d), (0, dd)), constant_values=-1),
                jnp.pad(st.status, (0, d), constant_values=SLOT_FREE),
                jnp.pad(st.exec_msb, (0, d)), jnp.pad(st.exec_lsb, (0, d)),
                jnp.pad(st.exec_node, (0, d)),
                jnp.pad(st.awaits_all, (0, d)))

        def traced(sts):
            stacked = EllDrainState(*(jnp.stack(col) for col in
                                      zip(*(pad(st) for st in sts))))
            return jax.vmap(ready_frontier_ell)(stacked)

        fn = _FUSED_FRONT_CACHE[key] = jax.jit(traced)
    return fn(tuple(states))


def _drain_ell_fix(state: EllDrainState):
    """ELL analogue of _drain_fix: (applied, newly, sweeps) with an [N, D]
    gather per sweep instead of the dense matvec."""
    blocking, j = _ell_blocking(state)
    stable = state.status == SLOT_STABLE
    applied0 = state.status == SLOT_APPLIED

    def body(carry):
        applied, _, sweeps = carry
        waiting = jnp.any(blocking & ~applied[j], axis=1)
        ready = stable & ~applied & ~waiting
        return applied | ready, jnp.any(ready), sweeps + 1

    applied, _, sweeps = lax.while_loop(
        lambda c: c[1], body, (applied0, jnp.bool_(True), jnp.int32(0)))
    return applied, applied & ~applied0, sweeps


@jax.jit
def drain_ell(state: EllDrainState) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fixpoint drain over the ELL adjacency: each sweep applies a whole
    antichain, the per-sweep cost is an [N, D] gather (no [N, N] anywhere)."""
    applied, newly, _ = _drain_ell_fix(state)
    return applied, newly


@jax.jit
def drain_ell_levels(state: EllDrainState):
    """Forensic variant of :func:`drain_ell`: (applied, newly, sweeps)."""
    return _drain_ell_fix(state)
