"""Batched PreAccept dependency calculation — the #1 hot loop, on device.

Rebuild of ref: accord-core/src/main/java/accord/local/CommandsForKey.java:614-650
(mapReduceActive) + messages/PreAccept.java:245-265 (calculatePartialDeps) +
utils/CheckpointIntervalArray.java (range stabbing), redesigned as one fused
TPU kernel instead of a per-key tree scan.

Design (SURVEY.md §7 stage 3): a command store's conflict index is a
struct-of-arrays table of up to N in-flight transactions.  Every slot stores
the packed TxnId, its kind, per-key status, and up to M touched *intervals*
``[lo, hi]`` (inclusive; a point key token t is stored as [t, t]; a range
[s, e) as [s, e-1]).  Unifying keys and ranges as intervals lets ONE kernel
answer both the KeyDeps scan and the RangeDeps stabbing query — the
reference needs two structures (CommandsForKey + SearchableRangeList) for
the same job.

The kernel computes, for a batch of B queries (in-flight PreAccepts):

    dep[b, j] = slot j live
              & witness_mask[b] admits kind[j]            (Txn.Kind.witnesses)
              & txn_id[j] < started_before[b]             (deps = strictly earlier)
              & intervals overlap (any of MxM pairs)
              & txn_id[j] != self[b]
              & txn_id[j] >= prune floor                  (RedundantBefore)

plus the per-query max-conflict timestamp over ALL overlapping live slots
(the MaxConflicts floor used to propose executeAt, ref:
local/MaxConflicts.java:32).  Everything is elementwise compares + reduces
over a [B, N, M, M] broadcast — embarrassingly parallel, static shapes,
fuses to a handful of VPU loops under jit.  B and N are padded to lane
multiples by the host packer.

Exact-geometry CSR (r10): the batched flat kernels no longer answer with
coarse (query, slot) pairs the host re-filters — every entry that leaves
the device is an exact overlap TRIPLE, encoded as one sorted composite
integer key::

    code = slot * (M_t * Q) + dep_interval_col * Q + query_interval_col

where ``M_t`` is the table's interval width and ``Q`` the query's.  Codes
ascend (slot-major, then dep column, then query column) within each CSR
row, which is exactly the (pair, m, q) order the host's old
``np.nonzero(overlap)`` geometry pass produced — so the device answer
plugs straight into attribution and ``_exact_geometry`` has nothing left
to do on any device route.  The result ships as TWO buffers, ``(header,
entries)``: the header (total, max_row_count, row_end[B]) is a few hundred
int32s the host fetches first; only the LIVE PREFIX of the entry buffer
crosses the wire after it (int32 entries whenever
``capacity * M_t * Q <= INT32_CODE_MAX``, int64 past that crossover).
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..primitives.timestamp import Kinds, Timestamp, TxnId
from .packing import (ensure_x64, masked_ts_max, to_i64, ts_eq, ts_lt,
                      unpack_txn_id)

def launch_check(what: str = "") -> None:
    """Device-boundary fault hook for every (un-jitted) kernel dispatch
    wrapper: raises utils.faults.KernelLaunchFault when a kernel-launch
    fault is armed.  Lives here — next to the kernels — so the injection
    surface and the thing it simulates stay in one place; a production
    process with nothing armed pays one dict miss."""
    from ..utils import faults
    faults.check("kernel_launch", what)


PAD_LO = np.int64(np.iinfo(np.int64).max)   # empty interval: lo > hi
PAD_HI = np.int64(np.iinfo(np.int64).min)

# widest triple code an int32 entry buffer can carry; codes are
# slot * M_t * Q + col * Q + q, so the crossover is capacity * M_t * Q.
# Module attribute (not inlined) so the int64 crossover is testable on
# tables that fit in memory — tests lower it and assert both widths agree.
INT32_CODE_MAX = 2**31 - 1


def wide_codes(capacity: int, m_t: int, q_m: int) -> bool:
    """True when triple codes for this (table, query) shape need int64
    entries.  Callers thread the result into the kernels as a STATIC
    argument (the dtype is part of the traced program, and the jit cache
    key must see it)."""
    return capacity * m_t * q_m > INT32_CODE_MAX


def _code_dtype(wide: bool):
    return jnp.int64 if wide else jnp.int32


def _code_sentinel(wide: bool):
    return (np.int64(np.iinfo(np.int64).max) if wide
            else np.int32(np.iinfo(np.int32).max))

# slot liveness/status codes (device view of CommandsForKey.InternalStatus)
SLOT_FREE = -1
SLOT_TRANSITIVE = 0
SLOT_PREACCEPTED = 1
SLOT_ACCEPTED = 2
SLOT_COMMITTED = 3
SLOT_STABLE = 4
SLOT_APPLIED = 5
SLOT_INVALIDATED = 6


class DepsTable(NamedTuple):
    """SoA conflict index: N slots x M intervals.  A pytree of device arrays;
    the device-format equivalent of one store's CommandsForKey map."""

    msb: jnp.ndarray        # int64[N]  packed TxnId
    lsb: jnp.ndarray        # int64[N]
    node: jnp.ndarray       # int32[N]
    kind: jnp.ndarray       # int32[N]  TxnKind ordinal
    status: jnp.ndarray     # int32[N]  SLOT_* (FREE/INVALIDATED excluded from deps)
    lo: jnp.ndarray         # int64[N, M]  inclusive interval starts (PAD_LO if unused)
    hi: jnp.ndarray         # int64[N, M]  inclusive interval ends   (PAD_HI if unused)

    @property
    def capacity(self) -> int:
        return self.msb.shape[0]


class DepsQuery(NamedTuple):
    """Batch of B dependency queries (one per PreAccept-ing txn)."""

    msb: jnp.ndarray          # int64[B]  started-before bound (usually the TxnId)
    lsb: jnp.ndarray          # int64[B]
    node: jnp.ndarray         # int32[B]
    witness_mask: jnp.ndarray  # int32[B]  bitmask over TxnKind ordinals
    lo: jnp.ndarray           # int64[B, M]
    hi: jnp.ndarray           # int64[B, M]
    self_msb: jnp.ndarray     # int64[B]  the querying TxnId itself — excluded
    self_lsb: jnp.ndarray     # int64[B]  from the dep set even when the bound
    self_node: jnp.ndarray    # int32[B]  exceeds it (Accept-phase executeAt)


def empty_table(capacity: int, max_intervals: int) -> DepsTable:
    ensure_x64()
    return DepsTable(
        msb=jnp.zeros(capacity, jnp.int64),
        lsb=jnp.zeros(capacity, jnp.int64),
        node=jnp.zeros(capacity, jnp.int32),
        kind=jnp.zeros(capacity, jnp.int32),
        status=jnp.full(capacity, SLOT_FREE, jnp.int32),
        lo=jnp.full((capacity, max_intervals), PAD_LO, jnp.int64),
        hi=jnp.full((capacity, max_intervals), PAD_HI, jnp.int64),
    )


@jax.jit
def scatter_table_rows(table: DepsTable, idx, msb, lsb, node, kind, status,
                       lo, hi) -> DepsTable:
    """One fused dirty-row update for all seven table arrays (a single jit
    dispatch instead of seven eager scatters — the update-in-place path
    that keeps the table device-resident between queries).  Placement
    follows the committed ``table`` arrays, so the r21 store-shard path
    runs the same program once per slice device."""
    return DepsTable(
        table.msb.at[idx].set(msb),
        table.lsb.at[idx].set(lsb),
        table.node.at[idx].set(node),
        table.kind.at[idx].set(kind),
        table.status.at[idx].set(status),
        table.lo.at[idx].set(lo),
        table.hi.at[idx].set(hi))


def _dep_mask_and_conflict(table: DepsTable, query: DepsQuery,
                           prune_msb=None, prune_lsb=None, prune_node=None):
    """Traceable core shared by calculate_deps (mask + max_conflict) and
    the flat-CSR path (mask only; XLA dead-code-eliminates the unused
    conflict reduce there).  ``prune_* = None`` means no floor."""
    if prune_msb is None:
        prune_msb = jnp.zeros((), jnp.int64)
        prune_lsb = jnp.zeros((), jnp.int64)
        prune_node = jnp.zeros((), jnp.int32)
    live = table.status >= SLOT_TRANSITIVE                     # [N]
    not_invalidated = table.status != SLOT_INVALIDATED         # [N]

    # interval overlap: any (query interval m) x (slot interval m') pair
    # q.lo[b,m] <= t.hi[j,m'] and t.lo[j,m'] <= q.hi[b,m]
    qlo = query.lo[:, None, :, None]                           # [B,1,M,1]
    qhi = query.hi[:, None, :, None]
    tlo = table.lo[None, :, None, :]                           # [1,N,1,M]
    thi = table.hi[None, :, None, :]
    overlap = jnp.any((qlo <= thi) & (tlo <= qhi), axis=(2, 3))  # [B,N]

    conflict = overlap & (live & not_invalidated)[None, :]

    # witness predicate: does this query's kind witness slot j's kind?
    witnessed = (query.witness_mask[:, None] >> table.kind[None, :]) & 1 > 0

    # strictly-earlier TxnId than the started-before bound
    earlier = ts_lt(table.msb[None, :], table.lsb[None, :], table.node[None, :],
                    query.msb[:, None], query.lsb[:, None], query.node[:, None])

    # never depend on yourself: the Accept-phase bound is executeAt, which
    # exceeds the txn's own id, so the strict compare alone is not enough
    not_self = ~ts_eq(table.msb[None, :], table.lsb[None, :], table.node[None, :],
                      query.self_msb[:, None], query.self_lsb[:, None],
                      query.self_node[:, None])

    # prune floor: exclude ids below the RedundantBefore watermark
    above_floor = ~ts_lt(table.msb, table.lsb, table.node,
                         prune_msb, prune_lsb, prune_node)

    dep_mask = conflict & witnessed & earlier & not_self & above_floor[None, :]
    return dep_mask, conflict


@jax.jit
def calculate_deps(table: DepsTable, query: DepsQuery,
                   prune_msb: jnp.ndarray = None, prune_lsb: jnp.ndarray = None,
                   prune_node: jnp.ndarray = None
                   ) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]]:
    """Returns (dep_mask bool[B, N], max_conflict (msb, lsb, node)[B]).

    max_conflict covers every live overlapping slot regardless of TxnId order
    or kind — it is the executeAt floor, not the dep set.
    """
    dep_mask, conflict = _dep_mask_and_conflict(table, query, prune_msb,
                                                prune_lsb, prune_node)
    # [1, N] inputs broadcast against the [B, N] mask inside masked_ts_max
    max_conflict = masked_ts_max(table.msb[None, :], table.lsb[None, :],
                                 table.node[None, :], conflict)
    return dep_mask, max_conflict


from functools import partial


def _compact_topk(dep_mask: jnp.ndarray, k: int):
    """Mask -> (idx int32[B, k] ascending slot indices padded with -1,
    counts int32[B]) — the compaction shared by every indices path.

    On TPU this is top_k (score = n - col for set bits, 0 otherwise, so
    top_k yields ascending column order among hits and pads with zeros).
    XLA's CPU top_k lowers to a pathological ~10x-slower loop than its
    sort, so the CPU backend (the virtual test/bench mesh) compacts by
    sorting set-bit columns ascending instead — identical output, chosen
    at trace time."""
    n = dep_mask.shape[1]
    col = jnp.arange(n, dtype=jnp.int32)
    counts = jnp.sum(dep_mask, axis=1, dtype=jnp.int32)
    if jax.default_backend() == "cpu":
        cols = jnp.where(dep_mask, col, jnp.int32(n))
        cols = jax.lax.slice_in_dim(jnp.sort(cols, axis=1), 0, min(k, n), axis=1)
        idx = jnp.where(cols < n, cols, -1)
    else:
        scores = jnp.where(dep_mask, n - col, 0)
        top, _ = jax.lax.top_k(scores, k)
        idx = jnp.where(top > 0, n - top, -1)
    return idx, counts


@partial(jax.jit, static_argnames=("m", "s", "k", "wide"))
def calculate_deps_flat(table: DepsTable, qmat: jnp.ndarray,
                        m: int, s: int, k: int, wide: bool = False):
    """The tunnel-optimal batched scan: the EXACT dep-triple set compacted
    into a packed CSR on device, so the download is the sparse result alone
    — and a two-stage one: ``(header, entries)``, where the host fetches
    the tiny header first and then only the live entry prefix.

    On a tunneled accelerator the wire dominates: the dense [B, 1+k]
    compaction ships megabytes at megabytes-per-second while the true dep
    sets are tens of entries per query.  Entries are the sorted composite
    overlap codes (module docstring) — no false-positive pair and no
    host-side geometry pass remain.
    """
    return flat_csr_local(table, qmat, m, s, k, wide=wide)


def query_from_qmat(qmat: jnp.ndarray, m: int) -> DepsQuery:
    return DepsQuery(
        qmat[:, 0], qmat[:, 1], qmat[:, 2].astype(jnp.int32),
        qmat[:, 3].astype(jnp.int32),
        qmat[:, 7:7 + m], qmat[:, 7 + m:7 + 2 * m],
        qmat[:, 4], qmat[:, 5], qmat[:, 6].astype(jnp.int32))


def _compact_rows(valid: jnp.ndarray, codes: jnp.ndarray, s: int, k: int):
    """Shared row compaction: pack each row's valid ``codes`` (already in
    their final per-row order) into the first ``counts[b]`` cells of a flat
    entry buffer.  Returns (counts int32[B], row_end int32[B], ent[s]).

    The pack is a POSITION sort (ascending column index of valid cells,
    invalid -> C sorts last) followed by a B*k scatter — scattering all B*C
    candidate positions directly is pathologically slow on TPU, and on the
    CPU backend a sort beats top_k ~10x (the r06 lesson), so both backends
    compact through the same sort here."""
    b, c = codes.shape
    counts = jnp.sum(valid, axis=1, dtype=jnp.int32)
    row_end = jnp.cumsum(counts)
    starts = row_end - counts
    k = min(k, c)
    col = jnp.arange(c, dtype=jnp.int32)
    if jax.default_backend() == "cpu":
        cols = jnp.where(valid, col, jnp.int32(c))
        cols = jax.lax.slice_in_dim(jnp.sort(cols, axis=1), 0, k, axis=1)
        vals = jnp.take_along_axis(codes, jnp.minimum(cols, c - 1), axis=1)
        ok = cols < c
    else:
        scores = jnp.where(valid, c - col, 0)
        top, tidx = jax.lax.top_k(scores, k)
        vals = jnp.take_along_axis(codes, tidx, axis=1)
        ok = top > 0
    pos = starts[:, None] + jnp.arange(k, dtype=jnp.int32)[None, :]
    pos = jnp.where(ok & (pos < s), pos, s)                    # s = dropped
    ent = jnp.full(s + 1, -1, codes.dtype).at[pos.reshape(-1)] \
        .set(vals.reshape(-1), mode="drop")[:s]
    return counts, row_end, ent


def _flat_phase1(table: DepsTable, qmat: jnp.ndarray, m: int, k: int,
                 prune=None):
    """Shared phase 1 of the dense flat kernels: exact mask -> per-row
    compacted slot indices -> overlap-triple expansion.  Returns
    (query, idx, pair_counts, sel, tlo, valid[B,kp,M,Q])."""
    query = query_from_qmat(qmat, m)
    if prune is None:
        mask, _conflict = _dep_mask_and_conflict(table, query)
    else:
        mask, _conflict = _dep_mask_and_conflict(table, query, *prune)
    n = mask.shape[1]
    kp = min(k, n)
    idx, pair_counts = _compact_topk(mask, kp)                 # [B,kp],[B]
    sel = jnp.clip(idx, 0)
    tlo = table.lo[sel]                                        # [B,kp,M]
    thi = table.hi[sel]
    qlo = query.lo[:, None, None, :]                           # [B,1,1,Q]
    qhi = query.hi[:, None, None, :]
    ov = (qlo <= thi[:, :, :, None]) & (tlo[:, :, :, None] <= qhi)
    valid = ov & (idx >= 0)[:, :, None, None]                  # [B,kp,M,Q]
    return query, idx, pair_counts, sel, tlo, valid


def _triple_codes(sel, m_t: int, m: int, wide: bool):
    dt = _code_dtype(wide)
    mq = m_t * m
    return (sel.astype(dt)[:, :, None, None] * mq
            + jnp.arange(m_t, dtype=dt)[None, None, :, None] * m
            + jnp.arange(m, dtype=dt)[None, None, None, :])


def flat_csr_local(table: DepsTable, qmat: jnp.ndarray,
                   m: int, s: int, k: int, prune=None, wide: bool = False):
    """The traceable body of calculate_deps_flat: exact mask over THIS
    table (a full table, or one mesh shard's slice under shard_map), then
    the EXACT overlap-triple expansion compacted into a two-buffer CSR —
    (header (total, maxc, row_end[B]) int32, entries[s] composite codes).

    Two phases keep it memory-safe: (1) the per-row slot indices compact
    through the mask exactly as before (no [B, N, M, Q] expansion of the
    full table); (2) only the <= k selected slots' interval rows are
    gathered (row gathers — effectively free on TPU) and expanded against
    the query intervals into sorted codes.  ``k`` caps the widest TRIPLE
    row, ``s`` the batch triple total; both sticky-learned by the caller
    from the header.  Overflow stays detectable: the reported maxc is the
    exact per-row triple count when every pair fit phase 1, and at least
    the (truncated-past-k) pair count otherwise — either way overflow
    reads as ``maxc > k`` and the caller re-runs escalated."""
    _query, idx, pair_counts, sel, _tlo, valid = \
        _flat_phase1(table, qmat, m, k, prune)
    m_t = table.lo.shape[1]
    codes = _triple_codes(sel, m_t, m, wide)
    b = valid.shape[0]
    valid_f = valid.reshape(b, -1)
    codes_f = codes.reshape(b, -1)   # ascending: slot-major, then col, q
    counts, row_end, ent = _compact_rows(valid_f, codes_f, s, k)
    maxc = jnp.maximum(jnp.max(counts), jnp.max(pair_counts))
    header = jnp.concatenate(
        [jnp.stack([row_end[-1], maxc]).astype(jnp.int32),
         row_end.astype(jnp.int32)])
    return header, ent


# -- bucketed index kernel ----------------------------------------------------
#
# The CINTIA-style device index (ref: utils/CheckpointIntervalArray.java:40-60,
# CheckpointIntervalArrayBuilder.java — the reference's checkpointed interval
# stabbing structure), redesigned for static shapes: the token space is cut
# into width-2^shift buckets; every NARROW slot interval is registered as an
# (lo, hi, slot) entry in each bucket it touches; intervals spanning many
# buckets — and bucket-overflow spill — live in a separate WIDE list that
# every query always checks (the reference's straggler/checkpoint split).
# A query probes only the K entries of the <= SPAN buckets each of its
# intervals touches, so the scan is O(candidates), not O(N): the exact
# predicate (overlap, earlier-TxnId, witness, liveness) runs per candidate,
# duplicates (one slot reachable via several buckets/intervals) are removed
# by an in-row sort, and the surviving slot ids compact into the same packed
# CSR the dense kernel ships.


class BucketTable(NamedTuple):
    """Device half of the bucket index: G buckets x K interval entries plus
    the wide/straggler entries (-1 slot = empty).

    Every IMMUTABLE per-slot column the predicate needs (packed TxnId,
    kind) is embedded in the entry: TPU gathers of scalar columns at
    arbitrary candidate indices lower to slow per-element loops (~140ms
    per gathered column at B=2048, C=4k over the VPU), while row gathers
    of whole bucket lines are effectively free.  Liveness needs no status
    column: entries are de-indexed on invalidate/free, so candidates are
    live by construction (the exact status/floor semantics are re-applied
    by the attribution pass either way).  ``bcol``/``wcol`` record each
    entry's interval COLUMN in its owning slot row — the third leg of the
    exact overlap triple the kernel emits (module docstring), so the host
    never rebuilds the geometry."""

    blo: jnp.ndarray     # int64[G, K] entry interval starts (PAD_LO empty)
    bhi: jnp.ndarray     # int64[G, K]
    bslot: jnp.ndarray   # int32[G, K] owning slot (-1 empty)
    bcol: jnp.ndarray    # int32[G, K] entry's interval column in its slot
    bmsb: jnp.ndarray    # int64[G, K] owning TxnId packed
    blsb: jnp.ndarray    # int64[G, K]
    bnode: jnp.ndarray   # int32[G, K]
    bkind: jnp.ndarray   # int32[G, K]
    wlo: jnp.ndarray     # int64[W] wide/straggler entries
    whi: jnp.ndarray     # int64[W]
    wslot: jnp.ndarray   # int32[W]
    wcol: jnp.ndarray    # int32[W]
    wmsb: jnp.ndarray    # int64[W]
    wlsb: jnp.ndarray    # int64[W]
    wnode: jnp.ndarray   # int32[W]
    wkind: jnp.ndarray   # int32[W]


def _entry_pred(query: DepsQuery, ov, slot, emsb, elsb, enode, ekind,
                extra_dims: int):
    """Exact per-entry predicate on embedded entry columns; ``extra_dims``
    broadcasts the per-query scalars over the candidate axes."""
    idx = (slice(None),) + (None,) * extra_dims
    valid = slot >= 0
    witnessed = (query.witness_mask[idx] >> ekind) & 1 > 0
    earlier = ts_lt(emsb, elsb, enode,
                    query.msb[idx], query.lsb[idx], query.node[idx])
    not_self = ~ts_eq(emsb, elsb, enode, query.self_msb[idx],
                      query.self_lsb[idx], query.self_node[idx])
    return valid & ov & witnessed & earlier & not_self


def bucketed_flat(table: DepsTable, buckets: BucketTable, qmat: jnp.ndarray,
                  m: int, span: int, s: int, k: int, prune=None,
                  row_offset=None, keff: int = None, wide: bool = False,
                  m_t: int = None):
    """Bucket-indexed batched deps scan -> two-buffer exact CSR
    (header(total, maxc, row_end[B]) int32, entries[s] composite overlap
    codes) — same layout as flat_csr_local, d=1.

    ``qmat`` carries the standard query columns plus m*span bucket-row
    columns (int64, -1 = no bucket) appended by the host packer.  ``table``
    is unused on the device (kept in the signature so dispatch snapshots
    stay uniform across kernels; may be None) except for its interval
    width, which scales the codes; all predicate data rides in ``buckets``.
    ``row_offset`` translates GLOBAL bucket rows to this shard's local rows
    under a row-sharded BucketTable (shard_map passes ``axis_index *
    local_rows``): rows outside the local slice become -1 (no bucket here)
    — the union over shards covers every global row.  ``keff`` slices the
    bucket entry axis to the mirror's live high-water occupancy (static, so
    XLA slices the operand before the gather): the [G, BUCKET_K] rows are
    mostly padding on spread keyspaces, and at the measured 18-entry
    high-water this cuts the candidate matrix — and the kernel wall — ~4x."""
    query = query_from_qmat(qmat, m)
    b = qmat.shape[0]
    if m_t is None:
        m_t = table.lo.shape[1]      # mesh locals pass m_t (table is None)
    mq = m_t * m
    dt = _code_dtype(wide)
    sent = _code_sentinel(wide)
    if keff is None:
        keff = buckets.blo.shape[1]
    keff = min(keff, buckets.blo.shape[1])
    blo, bhi = buckets.blo[:, :keff], buckets.bhi[:, :keff]
    bslot, bcol = buckets.bslot[:, :keff], buckets.bcol[:, :keff]
    bmsb, blsb = buckets.bmsb[:, :keff], buckets.blsb[:, :keff]
    bnode, bkind = buckets.bnode[:, :keff], buckets.bkind[:, :keff]
    qbuck = qmat[:, 7 + 2 * m:].astype(jnp.int32)          # [B, m*span]
    if row_offset is not None:
        n_local = blo.shape[0]
        local = qbuck - row_offset
        qbuck = jnp.where((qbuck >= 0) & (local >= 0) & (local < n_local),
                          local, -1)
    g = jnp.clip(qbuck, 0)
    has = qbuck >= 0                                        # [B, m*span]
    # bucket candidates: every entry of every touched bucket, each checked
    # against the query interval that touched the bucket (row gathers only)
    elo = blo[g]                                            # [B, m*span, K]
    ehi = bhi[g]
    qlo = jnp.repeat(query.lo, span, axis=1)[:, :, None]    # [B, m*span, 1]
    qhi = jnp.repeat(query.hi, span, axis=1)[:, :, None]
    ov = (elo <= qhi) & (qlo <= ehi) & has[:, :, None]      # [B, m*span, K]
    pred_b = _entry_pred(query, ov, bslot[g], bmsb[g],
                         blsb[g], bnode[g], bkind[g], 2)
    # the exact overlap triple is inherent in each candidate: the entry IS
    # one (slot, interval-column) and the probe axis IS the query interval
    q_of = jnp.repeat(jnp.arange(m, dtype=dt), span)[None, :, None]
    cand = (bslot[g].astype(dt) * mq + bcol[g].astype(dt) * m
            + q_of).reshape(b, -1)
    pred_b = pred_b.reshape(b, -1)
    # wide/straggler candidates: each entry crossed with every query
    # interval (the old any-reduce collapsed the triple; exact emission
    # keeps the [B, Q, W] cross — W is straggler-bounded by construction)
    w = buckets.wlo.shape[0]
    ov_w = ((buckets.wlo[None, None, :] <= query.hi[:, :, None])
            & (query.lo[:, :, None] <= buckets.whi[None, None, :]))
    pred_w = _entry_pred(query, ov_w, buckets.wslot[None, None, :],
                         buckets.wmsb[None, None, :],
                         buckets.wlsb[None, None, :],
                         buckets.wnode[None, None, :],
                         buckets.wkind[None, None, :], 2)   # [B, Q, W]
    cand_w = (buckets.wslot[None, None, :].astype(dt) * mq
              + buckets.wcol[None, None, :].astype(dt) * m
              + jnp.arange(m, dtype=dt)[None, :, None])
    cand = jnp.concatenate(
        [cand, jnp.broadcast_to(cand_w, (b, m, w)).reshape(b, -1)], axis=1)
    pred = jnp.concatenate([pred_b, pred_w.reshape(b, -1)], axis=1)
    if prune is not None:
        pmsb, plsb, pnode = prune
        above_b = ~ts_lt(bmsb[g], blsb[g], bnode[g],
                         pmsb, plsb, pnode).reshape(b, -1)
        above_w = ~ts_lt(buckets.wmsb[None, None, :],
                         buckets.wlsb[None, None, :],
                         buckets.wnode[None, None, :], pmsb, plsb, pnode)
        pred = pred & jnp.concatenate(
            [above_b,
             jnp.broadcast_to(above_w, (b, m, w)).reshape(b, -1)], axis=1)
    # dedupe (a triple is reachable via several buckets): sort the
    # surviving codes per row — which ALSO establishes the canonical
    # (slot, dep-col, query-col) ascending emit order — then mark adjacent
    # repeats; rejected candidates carry the sentinel and sort last
    hit = jnp.where(pred, cand, sent)
    hit = jnp.sort(hit, axis=1)
    uniq = (hit != sent) & jnp.concatenate(
        [jnp.ones((b, 1), bool), hit[:, 1:] != hit[:, :-1]], axis=1)
    counts, row_end, ent = _compact_rows(uniq, hit, s, k)
    header = jnp.concatenate(
        [jnp.stack([row_end[-1], jnp.max(counts)]).astype(jnp.int32),
         row_end.astype(jnp.int32)])
    return header, ent


bucketed_flat_jit = jax.jit(
    bucketed_flat, static_argnames=("m", "span", "s", "k", "keff", "wide"))


@partial(jax.jit, static_argnames=("m", "span", "s", "k", "keff", "wide"))
def bucketed_flat_pruned(table: DepsTable, buckets: BucketTable,
                         qmat: jnp.ndarray, m: int, span: int, s: int,
                         k: int, prune_msb: jnp.ndarray = None,
                         prune_lsb: jnp.ndarray = None,
                         prune_node: jnp.ndarray = None,
                         keff: int = None, wide: bool = False):
    return bucketed_flat(table, buckets, qmat, m, span, s, k,
                         (prune_msb, prune_lsb, prune_node),
                         keff=keff, wide=wide)


def decode_triples(codes: np.ndarray, m_t: int, q_m: int):
    """Host decode of composite overlap codes -> (slot, dep_col, q_col)
    int64 triples (the inverse of the kernel-side encoding)."""
    codes = codes.astype(np.int64)
    mq = np.int64(m_t * q_m)
    j = codes // mq
    rem = codes - j * mq
    m_i = rem // q_m
    return j, m_i, rem - m_i * q_m


# -- fused (batched-over-stores) dispatch ------------------------------------
#
# The launch-coalescing entry point (r08): one device dispatch answers the
# deps flushes of SEVERAL CommandStores that became runnable in the same
# event-loop step.  Each store's table is padded (free slots / PAD intervals
# prune themselves out of the mask, so padding never changes a store's
# answer) to the group maximum and stacked on a leading store axis; the
# per-store scan is the EXACT flat_csr_local trace vmapped over that axis —
# integer compares/sorts/cumsums vmap losslessly, so every store's CSR block
# is bit-identical to the solo launch it replaces.  The per-store prune
# floors ride as [S] triples (zeros = prune nothing, the ts_lt convention).

_FUSED_CACHE = {}


def _pad_table_cols(cols, n, m):
    """Pad one store's seven table columns to (n, m): appended slots are
    FREE and appended interval columns are PAD (lo > hi) — structurally
    excluded from the dep mask, so the padded scan answers exactly what the
    unpadded one does."""
    msb, lsb, node, kind, status, lo, hi = cols
    dn = n - msb.shape[0]
    dm = m - lo.shape[1]
    pad1 = lambda a, fill: jnp.pad(a, (0, dn), constant_values=fill)  # noqa: E731
    pad2 = lambda a, fill: jnp.pad(a, ((0, dn), (0, dm)),             # noqa: E731
                                   constant_values=fill)
    return (pad1(msb, 0), pad1(lsb, 0), pad1(node, 0), pad1(kind, 0),
            pad1(status, SLOT_FREE), pad2(lo, PAD_LO), pad2(hi, PAD_HI))


def fused_flat_csr(tables: Sequence[DepsTable], qmats: np.ndarray,
                   prunes: Tuple[np.ndarray, np.ndarray, np.ndarray],
                   m: int, s: int, k: int, wide: bool = False):
    """One fused launch for S stores' batched deps scans.

    ``tables``: each store's (cached, device-resident) DepsTable — may
    differ in capacity/max_intervals; padding + stacking happens INSIDE the
    jitted program so the launch consumes the cached per-store buffers
    directly (no host re-upload, no eager stack dispatches).
    ``qmats``: int64[S, B, 7 + 2m] (per-store query matrices, row-padded to
    a common B by the caller).  ``prunes``: per-store floor triples
    (int64[S], int64[S], int32[S]); zeros prune nothing.
    Returns (header int32[S, 2 + B], entries [S, s]) — row i is EXACTLY
    the solo calculate_deps_flat[_pruned] output for store i (codes scale
    on the GROUP interval width m_max, which the harvest decodes with)."""
    caps = tuple((t.capacity, t.lo.shape[1]) for t in tables)
    b = qmats.shape[1]
    key = (caps, b, m, s, k, wide)
    fn = _FUSED_CACHE.get(key)
    if fn is None:
        n_max = max(c for c, _ in caps)
        m_max = max(mi for _, mi in caps)

        def traced(flat_cols, qm, pm, pl, pn):
            padded = [_pad_table_cols(cols, n_max, m_max)
                      for cols in flat_cols]
            stacked = DepsTable(*(jnp.stack(col)
                                  for col in zip(*padded)))
            return jax.vmap(
                lambda t, q, a, b_, c: flat_csr_local(t, q, m, s, k,
                                                      (a, b_, c),
                                                      wide=wide)
            )(stacked, qm, pm, pl, pn)

        fn = _FUSED_CACHE[key] = jax.jit(traced)
    return fn(tuple(tuple(t) for t in tables), jnp.asarray(qmats),
              jnp.asarray(prunes[0]), jnp.asarray(prunes[1]),
              jnp.asarray(prunes[2]))


@partial(jax.jit, static_argnames=("m", "s", "k", "wide"))
def calculate_deps_flat_pruned(table: DepsTable, qmat: jnp.ndarray,
                               prune_msb: jnp.ndarray, prune_lsb: jnp.ndarray,
                               prune_node: jnp.ndarray,
                               m: int, s: int, k: int, wide: bool = False):
    """calculate_deps_flat with a device-side RedundantBefore floor: entries
    below the (conservative, batch-global) floor never enter the CSR, so a
    hot store whose durable prefix dominates ships only the live tail (the
    host attribution still applies the exact per-token floors on top)."""
    return flat_csr_local(table, qmat, m, s, k,
                          (prune_msb, prune_lsb, prune_node), wide=wide)


def pack_query_matrix(queries: Sequence[tuple], max_intervals: int) -> np.ndarray:
    """Host packer for the flat/attributed kernels: one int64 matrix
    instead of nine arrays (single device upload).  queries as in
    build_query."""
    b = len(queries)
    m = max_intervals
    q = np.empty((b, 7 + 2 * m), np.int64)
    q[:, 7:7 + m] = PAD_LO
    q[:, 7 + m:] = PAD_HI
    cols = ([], [], [], [], [], [], [])
    for i, item in enumerate(queries):
        (bound, witnesses, toks, rngs), self_id = \
            item[:4], (item[4] if len(item) > 4 else item[0])
        cols[0].append(to_i64(bound.msb))
        cols[1].append(to_i64(bound.lsb))
        cols[2].append(bound.node)
        cols[3].append(witnesses.mask())
        cols[4].append(to_i64(self_id.msb))
        cols[5].append(to_i64(self_id.lsb))
        cols[6].append(self_id.node)
        if len(toks) + len(rngs) > m:
            raise ValueError(f"txn touches > {m} intervals")
        j = 0
        for t in toks:
            q[i, 7 + j] = t
            q[i, 7 + m + j] = t
            j += 1
        for r in rngs:
            q[i, 7 + j] = r.start
            q[i, 7 + m + j] = r.end - 1
            j += 1
    for c in range(7):
        q[:, c] = cols[c]
    return q


# -- host bridge --------------------------------------------------------------

def _intervals_of(txn_keys, txn_ranges, max_intervals: int):
    """(tokens, ranges) -> padded [lo...], [hi...] rows."""
    lo = [PAD_LO] * max_intervals
    hi = [PAD_HI] * max_intervals
    i = 0
    for t in txn_keys:
        if i >= max_intervals:
            raise ValueError(f"txn touches > {max_intervals} intervals")
        lo[i], hi[i] = t, t
        i += 1
    for r in txn_ranges:
        if i >= max_intervals:
            raise ValueError(f"txn touches > {max_intervals} intervals")
        lo[i], hi[i] = r.start, r.end - 1
        i += 1
    return lo, hi


def build_table(entries: Sequence[Tuple[TxnId, int, list, list]],
                capacity: int, max_intervals: int) -> DepsTable:
    """Host packer: entries = [(txn_id, status, key_tokens, ranges)].

    Capacity is padded; callers should size it to a static bucket so jit
    caches one compilation per bucket.
    """
    ensure_x64()
    n = len(entries)
    if n > capacity:
        raise ValueError(f"{n} entries > capacity {capacity}")
    msb = np.zeros(capacity, np.int64)
    lsb = np.zeros(capacity, np.int64)
    node = np.zeros(capacity, np.int32)
    kind = np.zeros(capacity, np.int32)
    status = np.full(capacity, SLOT_FREE, np.int32)
    lo = np.full((capacity, max_intervals), PAD_LO, np.int64)
    hi = np.full((capacity, max_intervals), PAD_HI, np.int64)
    for i, (tid, st, toks, rngs) in enumerate(entries):
        msb[i] = to_i64(tid.msb)
        lsb[i] = to_i64(tid.lsb)
        node[i] = tid.node
        kind[i] = int(tid.kind())
        status[i] = st
        row_lo, row_hi = _intervals_of(toks, rngs, max_intervals)
        lo[i] = row_lo
        hi[i] = row_hi
    return DepsTable(jnp.asarray(msb), jnp.asarray(lsb), jnp.asarray(node),
                     jnp.asarray(kind), jnp.asarray(status),
                     jnp.asarray(lo), jnp.asarray(hi))


def build_query(queries: Sequence[tuple],
                max_intervals: int) -> DepsQuery:
    """queries = [(started_before, witnesses, key_tokens, ranges)] or
    [(started_before, witnesses, key_tokens, ranges, self_txn_id)].

    When self_txn_id is omitted it defaults to the bound itself (correct for
    PreAccept, where bound == own TxnId); pass it explicitly for Accept-phase
    queries whose bound is the proposed executeAt.  Packs through the same
    matrix encoder as the fused path (one upload, one source of truth for
    the column/interval layout) and slices the columns on device."""
    ensure_x64()
    m = max_intervals
    q = jnp.asarray(pack_query_matrix(queries, m))
    return DepsQuery(q[:, 0], q[:, 1], q[:, 2].astype(jnp.int32),
                     q[:, 3].astype(jnp.int32),
                     q[:, 7:7 + m], q[:, 7 + m:7 + 2 * m],
                     q[:, 4], q[:, 5], q[:, 6].astype(jnp.int32))


def extract_deps(table: DepsTable, dep_mask) -> List[List[TxnId]]:
    """dep_mask bool[B, N] -> per-query sorted TxnId lists (host)."""
    mask = np.asarray(dep_mask)
    msb, lsb, node = (np.asarray(table.msb), np.asarray(table.lsb),
                      np.asarray(table.node))
    out: List[List[TxnId]] = []
    for b in range(mask.shape[0]):
        idx = np.nonzero(mask[b])[0]
        out.append(sorted(unpack_txn_id(msb[j], lsb[j], node[j]) for j in idx))
    return out


# -- device-resident attribution + elision (r15) ------------------------------
#
# r10 moved the exact overlap geometry on-device; what remained host-side was
# the ATTRIBUTION pass: per-token RedundantBefore floors, CommandsForKey
# transitive elision, and the per-(query, token, dep) dedupe — ~6ms/batch of
# numpy on the r13 profile, the last big host tax on every route.  The
# attributed kernel variants below fold all three INTO the device program:
# an entry that a floor or the elision rule would drop never enters the CSR
# (and never crosses the wire), and duplicate (slot, interval) emits reached
# through several query columns collapse in-kernel.  The attribution runs
# POST-COMPACTION — over the thousands of surviving codes, not the
# candidate matrix — so the stage costs O(s), and STATIC leg switches
# (``floors``/``elide``) drop dead legs from the traced program entirely
# (an empty elision index or a trivially-covered floor map compiles to the
# raw kernel plus a dedupe).
#
# Inputs, all device-resident / replicated:
#  - AttrCols: per-slot columns the dep MASK never needed but attribution
#    does — domain (key deps emit at their own footprint points), a FRESH
#    status (live->live moves included; elision reads the
#    TRANSITIVE/COMMITTED grades), the packed dep id (the floor compare;
#    redundant with DepsTable but the mesh bucketed shards have no local
#    slot table), and the decided executeAt.
#  - AttrIndex: the per-store floor + elision index.  Floors are the packed
#    RedundantBefore segment map (searchsorted per emitted token — exactly
#    deps_floor_batch's rule).  Elision is a CSR over the store's elidable
#    tokens: per token the SORTED committed-write executeAt list, flattened,
#    with each exec replaced by its composite rank ``seg * estride + rank``
#    so ONE int64 searchsorted answers "how many committed writes on token
#    t execute before bound b".  The per-query bound ranks (``rankb``) are
#    computed host-side against the same index and ride in as a [B] array —
#    no 128-bit comparisons on device.
#
# Attributed header layout (int32[5 + B]):
#    [0] total entries   [1] overflow-vs-s watermark  [2] overflow-vs-k
#    [3] rows elided as TRANSITIVE   [4] rows elided below a decided pivot
#    [5:] row_end[B]
# The overflow watermarks are the RAW (pre-attribution) totals — the
# learned s/k budgets size the raw compaction — and stay per-shard maxima
# under the mesh merge, so the collect-side re-run check is uniform:
# hdr[1] > s or hdr[2] > k.


class AttrCols(NamedTuple):
    """Per-slot attribution columns (device-resident, scatter-updated in
    lockstep with the DepsTable by the mirror).  The packed dep id rides
    here TOO (redundant with DepsTable.msb/lsb/node): the post-compaction
    attribution stage gathers ids per surviving entry, and the
    mesh-sharded BUCKETED kernel has no local slot table to gather from —
    one column set serves every route."""

    dom: jnp.ndarray      # int32[N]  Domain ordinal (Key == 0)
    status: jnp.ndarray   # int32[N]  fresh SLOT_* (elision reads grades)
    dmsb: jnp.ndarray     # int64[N]  packed TxnId (floor compares)
    dlsb: jnp.ndarray     # int64[N]
    dnode: jnp.ndarray    # int32[N]
    emsb: jnp.ndarray     # int64[N]  decided executeAt (valid iff eknown)
    elsb: jnp.ndarray     # int64[N]
    enode: jnp.ndarray    # int32[N]
    eknown: jnp.ndarray   # bool[N]


@jax.jit
def scatter_attr_cols(attr: "AttrCols", idx, dom, status, dmsb, dlsb,
                      dnode, emsb, elsb, enode, eknown) -> "AttrCols":
    """One fused dirty-row update for the attribution columns (the
    AttrCols sibling of scatter_table_rows); shared by the single-device
    mirror sync and the r21 per-slice store-shard sync."""
    return AttrCols(
        attr.dom.at[idx].set(dom),
        attr.status.at[idx].set(status),
        attr.dmsb.at[idx].set(dmsb),
        attr.dlsb.at[idx].set(dlsb),
        attr.dnode.at[idx].set(dnode),
        attr.emsb.at[idx].set(emsb),
        attr.elsb.at[idx].set(elsb),
        attr.enode.at[idx].set(enode),
        attr.eknown.at[idx].set(eknown))


class AttrIndex(NamedTuple):
    """Replicated per-store floor + elision index (host-built, cached on
    the RedundantBefore / CommandsForKey versions; pow2-padded so jit
    compiles a bounded number of shapes)."""

    fbnd: jnp.ndarray     # int64[F]   floor segment boundaries (pad +INF)
    fmsb: jnp.ndarray     # int64[F+1] per-segment deps_floor triples
    flsb: jnp.ndarray     # int64[F+1]
    fnode: jnp.ndarray    # int32[F+1]
    etok: jnp.ndarray     # int64[T]   elidable tokens, sorted (pad +INF)
    eptr: jnp.ndarray     # int32[T+1] CSR into the exec arrays (pad L)
    erank: jnp.ndarray    # int64[L]   seg*estride+rank composites, asc
    exm: jnp.ndarray      # int64[L]   the pivot executeAt triples
    exl: jnp.ndarray      # int64[L]
    exn: jnp.ndarray      # int32[L]
    estride: jnp.ndarray  # int64[]    U+1 — the composite stride erank used


def _attr_key_masks(tok, dmsb, dlsb, dnode, status, emsb, elsb, enode,
                    eknown, rankb_b, aidx: AttrIndex,
                    floors: bool = True, elide: bool = True):
    """The in-kernel attribution predicate for KEY-domain candidates, all
    elementwise over one candidate shape.  ``tok`` is the emitted token
    (the dep's own footprint point), ``rankb_b`` the per-candidate bound
    rank (broadcast from the query row).  Returns (keep_floor,
    elide_trans, elide_dec) — the caller scopes them to key-domain
    candidates.  ``floors``/``elide`` are STATIC leg switches the
    dispatcher sets per flush: when the exact per-token floors equal the
    already-applied batch prune, or the elision index is empty, the
    corresponding gathers and searches never enter the program."""
    ones = None
    if floors:
        # exact per-token RedundantBefore floor: dep >= deps_floor(token)
        fi = jnp.searchsorted(aidx.fbnd, tok, side="right")
        keep_floor = ~ts_lt(dmsb, dlsb, dnode,
                            aidx.fmsb[fi], aidx.flsb[fi], aidx.fnode[fi])
    else:
        ones = jnp.ones(jnp.broadcast_shapes(tok.shape, dmsb.shape), bool)
        keep_floor = ones
    # transitively-known entries never emit
    elide_trans = status == SLOT_TRANSITIVE
    if not elide:
        z = (~ones) if ones is not None else \
            jnp.zeros(jnp.broadcast_shapes(tok.shape, dmsb.shape), bool)
        return keep_floor, elide_trans, z
    # decided entries executing below the token's latest committed write
    # before the bound are reached through that write's stable deps
    t = aidx.etok.shape[0]
    seg = jnp.searchsorted(aidx.etok, tok)
    seg_c = jnp.minimum(seg, max(t - 1, 0))
    seg_ok = (aidx.etok[seg_c] == tok) if t else jnp.zeros(tok.shape, bool)
    base = aidx.eptr[seg_c]
    cnt = jnp.searchsorted(aidx.erank,
                           seg_c.astype(jnp.int64) * aidx.estride
                           + rankb_b) - base
    has_pivot = seg_ok & (cnt > 0)
    pidx = jnp.clip(base + cnt - 1, 0)
    below = ts_lt(emsb, elsb, enode,
                  aidx.exm[pidx], aidx.exl[pidx], aidx.exn[pidx])
    decided = (status >= SLOT_COMMITTED) & (status <= SLOT_APPLIED) & eknown
    elide_dec = decided & has_pivot & below
    return keep_floor, elide_trans, elide_dec


def _attr_post(tlo, attr: AttrCols, aidx: AttrIndex, rankb: jnp.ndarray,
               hdr_raw, ent, m_t: int, m: int,
               floors: bool = True, elide: bool = True, tok=None):
    """The POST-COMPACTION attribution stage shared by every attributed
    kernel: floors, elision and the key-domain query-column dedupe run
    over the COMPACTED entry buffer — thousands of surviving codes — not
    the candidate matrix (hundreds of thousands of cells).  The raw
    kernels already sorted/compacted, so rows are contiguous and
    same-(slot, col) key emits are adjacent; dropping entries is a mask +
    one global cumsum scatter, no re-sort.

    ``tlo`` is the interval-start matrix the emitted token gathers from
    (the slot table's lo; a mesh-bucketed caller passes ``tok``
    precomputed via a cross-shard psum instead).  Returns the attributed
    (header int32[5+B], entries) pair; the header's overflow watermarks
    are the RAW totals (the learned s/k budgets size the pre-attribution
    compaction)."""
    s = ent.shape[0]
    total = hdr_raw[0].astype(jnp.int64)
    maxc_raw = hdr_raw[1]
    row_end = hdr_raw[2:].astype(jnp.int64)
    b = row_end.shape[0]
    pos = jnp.arange(s, dtype=jnp.int64)
    live = pos < total
    code = ent.astype(jnp.int64)
    mq = m_t * m
    slot = jnp.clip(code // mq, 0)
    col = jnp.clip(code % mq // m, 0, m_t - 1)
    row_of = jnp.searchsorted(row_end, pos, side="right")
    row_of = jnp.minimum(row_of, b - 1)
    key_dep = attr.dom[slot] == 0
    status = attr.status[slot]
    if tok is None:
        tok = tlo[slot, col]
    # the key masks at entry level (1-D gathers only)
    keep_floor, el_trans, el_dec = _attr_key_masks(
        tok, attr.dmsb[slot], attr.dlsb[slot], attr.dnode[slot], status,
        attr.emsb[slot], attr.elsb[slot], attr.enode[slot],
        attr.eknown[slot], rankb[row_of], aidx, floors, elide)
    # key-domain query-column dedupe: codes are (slot, col, q)-ascending
    # within each row, so same-(slot, col) runs are adjacent
    pairkey = row_of * jnp.int64(1 << 40) + code // m
    firstp = jnp.concatenate(
        [jnp.ones(1, bool), pairkey[1:] != pairkey[:-1]])
    drop_key = ~keep_floor | el_trans | el_dec | ~firstp
    keep = live & (~key_dep | ~drop_key)
    n_trans = jnp.sum(live & key_dep & firstp & keep_floor & el_trans)
    n_dec = jnp.sum(live & key_dep & firstp & keep_floor
                    & ~el_trans & el_dec)
    out_pos = jnp.cumsum(keep) - 1
    out = jnp.full(s, -1, ent.dtype).at[
        jnp.where(keep, out_pos, s)].set(ent, mode="drop")
    drops = jnp.zeros(b, jnp.int64).at[
        jnp.where(live & ~keep, row_of, b)].add(1, mode="drop")
    new_end = row_end - jnp.cumsum(drops)
    header = jnp.concatenate(
        [jnp.stack([new_end[-1], total, maxc_raw.astype(jnp.int64),
                    n_trans, n_dec]).astype(jnp.int32),
         new_end.astype(jnp.int32)])
    return header, out


def flat_attr_local(table: DepsTable, attr: AttrCols, aidx: AttrIndex,
                    qmat: jnp.ndarray, rankb: jnp.ndarray,
                    m: int, s: int, k: int, prune=None, wide: bool = False,
                    floors: bool = True, elide: bool = True):
    """flat_csr_local with the attribution pass fused in AFTER the raw
    compaction: per-token floors, elision and the per-(slot, interval)
    key dedupe drop entries from the compacted CSR, so what ships is
    EXACTLY the entry set the host builders will keep.  Range-domain
    entries pass through untouched (the mask's batch-global prune floor
    is their whole floor story, matching the host oracle)."""
    hdr_raw, ent = flat_csr_local(table, qmat, m, s, k, prune, wide=wide)
    return _attr_post(table.lo, attr, aidx, rankb, hdr_raw, ent,
                      table.lo.shape[1], m, floors, elide)


@partial(jax.jit, static_argnames=("m", "s", "k", "wide", "floors",
                                   "elide"))
def calculate_deps_flat_attr(table: DepsTable, attr: AttrCols,
                             aidx: AttrIndex, qmat: jnp.ndarray,
                             rankb: jnp.ndarray,
                             prune_msb: jnp.ndarray, prune_lsb: jnp.ndarray,
                             prune_node: jnp.ndarray,
                             m: int, s: int, k: int, wide: bool = False,
                             floors: bool = True, elide: bool = True):
    """The dispatchable dense attributed kernel (always pruned: the
    attributed paths are the protocol paths, which enable the batch-global
    floor; a zero triple prunes nothing)."""
    return flat_attr_local(table, attr, aidx, qmat, rankb,
                           m, s, k, (prune_msb, prune_lsb, prune_node),
                           wide=wide, floors=floors, elide=elide)


def bucketed_attr(table, attr: AttrCols, aidx: AttrIndex, buckets: BucketTable,
                  qmat: jnp.ndarray, rankb: jnp.ndarray, m: int, span: int,
                  s: int, k: int, prune=None, row_offset=None,
                  keff: int = None, wide: bool = False, m_t: int = None,
                  floors: bool = True, elide: bool = True, tok=None):
    """bucketed_flat with the post-compaction attribution stage.  The
    emitted token gathers from ``table.lo`` by the entry's global
    (slot, col); the mesh-sharded wrapper passes ``tok`` resolved via a
    cross-shard psum instead (its local table holds only a slot slice)."""
    hdr_raw, ent = bucketed_flat(table, buckets, qmat, m, span, s, k,
                                 prune, row_offset=row_offset, keff=keff,
                                 wide=wide, m_t=m_t)
    if m_t is None:
        m_t = table.lo.shape[1]
    tlo = table.lo if table is not None else None
    return _attr_post(tlo, attr, aidx, rankb, hdr_raw, ent, m_t, m,
                      floors, elide, tok=tok)


bucketed_attr_jit = jax.jit(
    bucketed_attr,
    static_argnames=("m", "span", "s", "k", "keff", "wide", "m_t",
                     "floors", "elide"))


def _pad_attr_cols(cols, n: int):
    """Pad one store's attribution columns to ``n`` slots: appended
    slots are FREE (structurally excluded by the mask) so their grades are
    never read."""
    dom, status, dmsb, dlsb, dnode, emsb, elsb, enode, eknown = cols
    pad1 = lambda a, fill: jnp.pad(a, (0, n - a.shape[0]),       # noqa: E731
                                   constant_values=fill)
    return (pad1(dom, 1), pad1(status, SLOT_FREE), pad1(dmsb, 0),
            pad1(dlsb, 0), pad1(dnode, 0), pad1(emsb, 0),
            pad1(elsb, 0), pad1(enode, 0), pad1(eknown, False))


def _pad_attr_index(aidx: AttrIndex, f: int, t: int, l: int):
    """Pad one store's AttrIndex to the fused group's (F, T, L) shapes.
    Floor boundaries and elidable tokens pad with +INF (unreachable by any
    real token); exec composites pad with +INF (sort after every real
    key); eptr pads with the store's own live length so padded segments
    are empty."""
    inf = jnp.int64(np.iinfo(np.int64).max)

    def tail(a, n, fill):
        d = n - a.shape[0]
        return jnp.concatenate([a, jnp.full(d, fill, a.dtype)])

    live_l = aidx.eptr[-1]
    return AttrIndex(
        tail(aidx.fbnd, f, inf),
        tail(aidx.fmsb, f + 1, 0), tail(aidx.flsb, f + 1, 0),
        tail(aidx.fnode, f + 1, 0),
        tail(aidx.etok, t, inf),
        jnp.concatenate([aidx.eptr,
                         jnp.broadcast_to(live_l, (t + 1 - aidx.eptr.shape[0],))
                         .astype(aidx.eptr.dtype)]),
        tail(aidx.erank, l, inf),
        tail(aidx.exm, l, 0), tail(aidx.exl, l, 0), tail(aidx.exn, l, 0),
        aidx.estride)


_FUSED_ATTR_CACHE = {}


def fused_flat_attr(tables: Sequence[DepsTable], stacked_attr: AttrCols,
                    stacked_aidx: AttrIndex, qmats: np.ndarray,
                    rankbs: np.ndarray,
                    prunes: Tuple[np.ndarray, np.ndarray, np.ndarray],
                    m: int, s: int, k: int, wide: bool = False,
                    floors: bool = True, elide: bool = True):
    """One fused launch for S stores' ATTRIBUTED deps scans — the r08
    coalescing shape with the r15 attribution fused in: per-store tables
    are padded to the group maxima and stacked INSIDE the jitted program,
    then flat_attr_local is vmapped over the store axis.  Row i of the
    outputs is exactly the solo calculate_deps_flat_attr answer for store
    i (codes on the GROUP interval width).

    ``stacked_attr`` / ``stacked_aidx`` arrive PRE-STACKED on the leading
    store axis ([S, n_max] / [S, ...]; the dispatcher pads host-side and
    caches on the members' attr versions): passing 16 stores' 20 extra
    pytrees per launch measured ~5ms of pure argument flattening on the
    config-5 tiny-flush regime — the launch-tax the fused path exists to
    amortize."""
    caps = tuple((t.capacity, t.lo.shape[1]) for t in tables)
    b = qmats.shape[1]
    key = (caps, stacked_aidx.fbnd.shape, stacked_aidx.etok.shape,
           stacked_aidx.erank.shape, b, m, s, k, wide, floors, elide)
    fn = _FUSED_ATTR_CACHE.get(key)
    if fn is None:
        n_max = max(c for c, _ in caps)
        m_max = max(mi for _, mi in caps)

        def traced(flat_cols, stacked_a, stacked_i, qm, rb, pm, pl, pn):
            padded = [_pad_table_cols(cols, n_max, m_max)
                      for cols in flat_cols]
            stacked = DepsTable(*(jnp.stack(col) for col in zip(*padded)))
            return jax.vmap(
                lambda t, a, i, q, r, x, y, z: flat_attr_local(
                    t, a, i, q, r, m, s, k, (x, y, z), wide=wide,
                    floors=floors, elide=elide)
            )(stacked, stacked_a, stacked_i, qm, rb, pm, pl, pn)

        fn = _FUSED_ATTR_CACHE[key] = jax.jit(traced)
    return fn(tuple(tuple(t) for t in tables), stacked_attr, stacked_aidx,
              jnp.asarray(qmats), jnp.asarray(rankbs),
              jnp.asarray(prunes[0]), jnp.asarray(prunes[1]),
              jnp.asarray(prunes[2]))
