"""In-process Maelstrom simulation: real MaelstromProcess nodes exchanging
JSON-serialised packets over a seeded random-delay queue, driven by a
generated list-append client workload and checked for strict
serializability.

Rebuild of ref: accord-maelstrom/src/test/java/accord/maelstrom/Runner.java
:40-190 + Cluster.java:70-330 — the same node logic that speaks to the real
Maelstrom harness, exercised deterministically in one process.  Packets are
serialised to JSON strings and parsed on delivery, so the full wire codec is
on the hot path (serde divergence fails the run, not just a unit test).
"""

from __future__ import annotations

import json
from typing import Callable, Dict, List, Optional

from ..sim.cluster import PendingQueue, SimScheduler
from ..sim.verifier import StrictSerializabilityVerifier
from ..utils.random_source import RandomSource
from .node import MaelstromProcess, token_of


class RunResult:
    def __init__(self):
        self.ops_ok = 0
        self.ops_failed = 0
        self.ops_unresolved = 0
        self.packets = 0
        # per-op commit latency in SIMULATED micros (client submit ->
        # txn_ok) — the configs[0]/[1] p99 metric
        self.latencies_micros: List[int] = []
        # the run's obs.Observability (set by the runner that produced
        # this result) — obs_row_fields reads phase latencies from it
        self.obs = None

    def p99_micros(self) -> Optional[int]:
        if not self.latencies_micros:
            return None
        xs = sorted(self.latencies_micros)
        return xs[min(len(xs) - 1, int(len(xs) * 0.99))]

    def obs_row_fields(self) -> dict:
        """Per-phase p50/p99 (sim ms) + fast-path rate from the run's
        observability bundle — the r09 bench config-row fields.  Empty
        under ACCORD_TPU_OBS=off (the row shape degrades, never errors)."""
        obs = self.obs
        if obs is None or obs.spans is None:
            return {}
        phases = {}
        for phase, row in obs.metrics.phase_percentiles().items():
            phases[phase] = {"p50_ms": round(row["p50"] / 1000, 2),
                             "p99_ms": round(row["p99"] / 1000, 2),
                             "n": row["n"]}
        out = {"phases_ms": phases}
        rate = obs.spans.fast_path_rate()
        if rate is not None:
            out["fast_path_rate"] = round(rate, 4)
        return out

    def __repr__(self):
        return (f"RunResult(ok={self.ops_ok}, failed={self.ops_failed}, "
                f"unresolved={self.ops_unresolved}, packets={self.packets})")


class MaelstromRunner:
    """(ref: maelstrom test Runner/Cluster)."""

    def __init__(self, n_nodes: int = 3, seed: int = 0, shards: int = 8,
                 mean_latency_micros: int = 1_000,
                 device_mode: Optional[bool] = None,
                 durability: bool = False):
        # durability defaults OFF in the runner: background rounds keep the
        # simulated queue busy through every time-bounded drain; the
        # durability subsystem has its own deterministic-tick tests
        self.queue = PendingQueue()
        self.rs = RandomSource(seed)
        self.net = self.rs.fork()
        self.names = [f"n{i}" for i in range(1, n_nodes + 1)]
        self.processes: Dict[str, MaelstromProcess] = {}
        self.result = RunResult()
        self.mean_latency = mean_latency_micros
        scheduler = SimScheduler(self.queue)
        # one shared observability bundle (obs.*): every process node's
        # coordinate FSM stamps phase spans in this runner's SIM time, so
        # the bench config rows can report per-phase p50/p99 latency and
        # the fast-path rate (spans None under ACCORD_TPU_OBS=off)
        from ..obs import Observability
        self.obs = Observability(now=lambda: self.queue.now)
        self.result.obs = self.obs
        # client replies (dest "c...") land here
        self.client_handlers: Dict[int, Callable[[dict], None]] = {}
        for name in self.names:
            proc = MaelstromProcess(
                emit=self._make_emit(name), scheduler=scheduler,
                now_micros=lambda: self.queue.now,
                shards=shards, device_mode=device_mode,
                durability=durability, obs=self.obs)
            self.processes[name] = proc
        # init handshake (ref: Runner sends init to every node first)
        for i, name in enumerate(self.names):
            self._deliver(name, {"src": "c0", "dest": name,
                                 "body": {"type": "init", "msg_id": i + 1,
                                          "node_id": name,
                                          "node_ids": list(self.names)}})
        self.queue_drain()

    # -- network ------------------------------------------------------------
    def _make_emit(self, src: str) -> Callable[[str, dict], None]:
        def emit(dest, body: dict) -> None:
            packet = {"src": src, "dest": dest, "body": body}
            line = json.dumps(packet)      # full serde on the hot path
            self.result.packets += 1
            if isinstance(dest, str) and dest.startswith("c"):
                handler = self.client_handlers.get(body.get("in_reply_to"))
                if handler is not None:
                    self.queue.add(self.queue.now,
                                   lambda: handler(json.loads(line)["body"]))
                return
            delay = self.mean_latency // 2 + self.net.next_int(self.mean_latency + 1)
            self.queue.add(self.queue.now + delay,
                           lambda: self._deliver(dest, json.loads(line)))
        return emit

    def _deliver(self, dest: str, packet: dict) -> None:
        proc = self.processes.get(dest)
        if proc is not None:
            proc.handle(packet)

    def queue_drain(self, max_micros: int = 60_000_000) -> None:
        """Run until the queue empties or the simulated-time budget is spent
        (recurring tasks — sweeper, progress-log scans — never exhaust, so
        the bound is time, as in sim.cluster.run_until_quiescent)."""
        deadline = self.queue.now + max_micros
        while self.queue.now <= deadline:
            fn = self.queue.pop()
            if fn is None:
                return
            fn()

    # -- workload (ref: Runner.java:123-190 generated txn bodies) -----------
    def run_workload(self, n_ops: int = 50, n_keys: int = 10,
                     verify: bool = True,
                     keys_per_txn: Optional[int] = None,
                     zipf_skew: Optional[float] = None,
                     spread_ring: bool = False,
                     value_kinds: Optional[tuple] = None) -> RunResult:
        """``keys_per_txn`` pins the txn width (default 1..3 random);
        ``zipf_skew`` draws keys Zipf-distributed over [0, n_keys) —
        configs[1]'s 4-key multi-partition Zipf-0.9 shape.
        ``spread_ring`` strides key values across the whole token ring so
        an N-key space actually lands on every shard (small ints all hash
        into shard 0 otherwise — a 'multi-partition' workload must be).
        ``value_kinds`` cycles appended values through the reference's
        datum kinds (subset of ("long", "string", "double", "hash");
        default None keeps plain unique ints) — values cross the client
        JSON boundary in wire form ({"hash": n} for HASH) and the verifier
        compares their canonical decoded forms."""
        from ..primitives.datum import datum_from_json
        wl = self.rs.fork()
        verifier = StrictSerializabilityVerifier()
        next_val = [0]
        pending = {}
        stride = ((1 << 32) // n_keys) if spread_ring else 1

        def pick_key() -> int:
            k = (wl.next_zipf(n_keys, zipf_skew) if zipf_skew is not None
                 else wl.next_int(n_keys))
            return k * stride

        def make_value(i: int):
            """(client-JSON form, canonical form) for unique value #i —
            mixed datum kinds keep global uniqueness because ``i`` is
            unique and the kind is a function of i."""
            if not value_kinds:
                return i, i
            kind = value_kinds[i % len(value_kinds)]
            if kind == "long":
                vj = (1 << 33) + i       # past int32: a real 64-bit long
            elif kind == "string":
                vj = f"s{i}"
            elif kind == "double":
                vj = i + 0.5
            elif kind == "hash":
                vj = {"hash": i}
            else:
                raise ValueError(f"unknown datum kind {kind!r}")
            return vj, datum_from_json(vj)

        def submit(i: int):
            node = self.names[wl.next_int(len(self.names))]
            n = keys_per_txn if keys_per_txn is not None \
                else wl.next_int(3) + 1
            n = min(n, n_keys)
            chosen = set()
            # redraw until n DISTINCT keys: under zipf the hot key repeats,
            # and silently shrinking the txn would mislabel the metric
            guard = 0
            while len(chosen) < n and guard < 64:
                chosen.add(pick_key())
                guard += 1
            keys = sorted(chosen)
            ops = []
            writes = {}
            reads = []
            for k in keys:
                if wl.decide(0.6):
                    next_val[0] += 1
                    vj, v = make_value(next_val[0])
                    ops.append(["append", k, vj])
                    writes[token_of(k)] = writes.get(token_of(k), ()) + (v,)
                else:
                    ops.append(["r", k, None])
                    reads.append(token_of(k))
            op_id = verifier.begin()
            start = self.queue.now
            pending[i] = True
            msg_id = 10_000 + i

            def on_reply(body: dict):
                pending.pop(i, None)
                if body.get("type") != "txn_ok":
                    self.result.ops_failed += 1
                    return
                self.result.ops_ok += 1
                self.result.latencies_micros.append(self.queue.now - start)
                observed = {}
                for op in body["txn"]:
                    if op[0] == "r":
                        t = token_of(op[1])
                        # canonical datum forms: the store and the writes
                        # census hold decoded values ({"hash": n} -> DatumHash)
                        vals = tuple(datum_from_json(v) for v in op[2])
                        # strip intra-txn own-appends suffix: the verifier
                        # models reads as pre-state
                        own = writes.get(t, ())
                        if own and vals[-len(own):] == own:
                            vals = vals[: len(vals) - len(own)]
                        observed[t] = vals
                verifier.on_result(op_id, start, self.queue.now,
                                   observed, writes)

            self.client_handlers[msg_id] = on_reply
            self._deliver(node, {"src": f"c{i + 1}", "dest": node,
                                 "body": {"type": "txn", "msg_id": msg_id,
                                          "txn": ops}})

        for i in range(n_ops):
            submit(i)
            if wl.decide(0.3):
                self.queue_drain()
        self.queue_drain()
        self.result.ops_unresolved = len(pending)
        if verify:
            # finals: after quiescence every owning replica has the full
            # list; take the longest copy per token across data stores
            finals = {}
            for proc in self.processes.values():
                store = proc.node.data_store
                for token in store.tokens():
                    value = store.get(token)
                    if len(value) > len(finals.get(token, ())):
                        finals[token] = value
            for token, value in finals.items():
                verifier.set_final(token, value)
            verifier.verify()
            for proc in self.processes.values():
                if proc.failures:
                    raise proc.failures[0]
        return self.result
