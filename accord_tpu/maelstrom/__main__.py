"""Real Maelstrom entry point: JSON lines on stdin, replies on stdout.

Rebuild of ref: accord-maelstrom/src/main/java/accord/maelstrom/Main.java
:145-243 (listen loop).  Run under the Maelstrom harness as e.g.:

    maelstrom test -w txn-list-append --bin accord-maelstrom-node ...

where the bin wraps ``python -m accord_tpu.maelstrom``.  Single-threaded:
stdin is polled with a timeout equal to the next due timer, so the timer
heap (progress log scans, callback timeout sweeper) fires without threads.
"""

from __future__ import annotations

import heapq
import itertools
import json
import os
import select
import sys
import time
from typing import Callable, List, Optional, Tuple

from .. import api
from .node import MaelstromProcess


class _Scheduled(api.Scheduled):
    def __init__(self):
        self.cancelled = False

    def cancel(self) -> None:
        self.cancelled = True

    def is_cancelled(self) -> bool:
        return self.cancelled


class WallClockScheduler(api.Scheduler):
    """Timer heap over the wall clock, drained by the stdin loop."""

    def __init__(self, now_micros: Callable[[], int]):
        self.now_micros = now_micros
        self._heap: List[Tuple[int, int, Callable[[], None]]] = []
        self._seq = itertools.count()

    def now(self, run: Callable[[], None]) -> None:
        heapq.heappush(self._heap, (self.now_micros(), next(self._seq), run))

    def once(self, delay_micros: int, run: Callable[[], None]) -> api.Scheduled:
        handle = _Scheduled()

        def fire():
            if not handle.cancelled:
                run()
        heapq.heappush(self._heap,
                       (self.now_micros() + delay_micros, next(self._seq), fire))
        return handle

    def recurring(self, interval_micros: int,
                  run: Callable[[], None]) -> api.Scheduled:
        handle = _Scheduled()

        def fire():
            if handle.cancelled:
                return
            run()
            heapq.heappush(self._heap, (self.now_micros() + interval_micros,
                                        next(self._seq), fire))
        heapq.heappush(self._heap, (self.now_micros() + interval_micros,
                                    next(self._seq), fire))
        return handle

    def next_deadline(self) -> Optional[int]:
        return self._heap[0][0] if self._heap else None

    def run_due(self) -> None:
        now = self.now_micros()
        while self._heap and self._heap[0][0] <= now:
            _, _, fn = heapq.heappop(self._heap)
            fn()


def main() -> None:
    start = time.monotonic_ns()

    def now_micros() -> int:
        return (time.monotonic_ns() - start) // 1_000

    scheduler = WallClockScheduler(now_micros)
    stdout = sys.stdout

    def emit(dest, body: dict) -> None:
        # (self-addressed sends never reach here: MaelstromProcess
        # intercepts dest == own-name and defers them internally)
        packet = {"src": proc.name, "dest": dest, "body": body}
        stdout.write(json.dumps(packet) + "\n")
        stdout.flush()

    proc = MaelstromProcess(emit=emit, scheduler=scheduler,
                            now_micros=now_micros)

    # Read the raw fd ourselves: select() cannot see lines already pulled
    # into a TextIOWrapper's buffer, which would stall burst-delivered
    # packets until the next timer deadline.
    fd = sys.stdin.fileno()
    buf = b""
    eof = False
    while not eof:
        scheduler.run_due()
        deadline = scheduler.next_deadline()
        timeout = (max(0.0, (deadline - now_micros()) / 1e6)
                   if deadline is not None else 1.0)
        ready, _, _ = select.select([fd], [], [], timeout)
        if ready:
            chunk = os.read(fd, 65536)
            if not chunk:
                eof = True
            buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            line = line.strip()
            if not line:
                continue
            try:
                packet = json.loads(line)
            except json.JSONDecodeError:
                # a complete but malformed line: drop it loudly — prepending
                # it to the next line would poison the stream forever
                print(f"discarding malformed input line: {line[:200]!r}",
                      file=sys.stderr)
                continue
            proc.handle(packet)
    # EOF: the harness never closes stdin mid-test, so this is shutdown —
    # but in-flight coordinations may still need a few timer rounds to
    # reply (smoke tests pipe a fixed set of lines and read the output).
    # Drain until no coordination is in flight (recurring scans keep the
    # timer heap perpetually non-empty, so heap emptiness can't be the
    # condition), bounded by a grace window.
    grace_until = now_micros() + 2_000_000
    hard_stop = now_micros() + 30_000_000
    while now_micros() < min(grace_until, hard_stop):
        scheduler.run_due()
        busy = proc.node is not None and proc.node._coordinating
        deadline = scheduler.next_deadline()
        if busy:
            # live coordinations keep the grace window open (first-compile
            # of the device kernels can dominate the first txn); the hard
            # stop bounds a wedged coordination
            grace_until = now_micros() + 2_000_000
        else:
            # coordinations may not have STARTED yet (handle() defers via
            # scheduler.now()): only stop once nothing is due imminently
            if deadline is None or deadline > now_micros() + 10_000:
                break
        if deadline is None:
            break
        time.sleep(min(max(deadline - now_micros(), 0) / 1e6, 0.05))


if __name__ == "__main__":
    main()
