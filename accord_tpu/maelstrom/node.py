"""Maelstrom node: speaks the Maelstrom/Jepsen JSON body protocol over an
emit callback (stdout in ``__main__``, an in-process queue in the Runner).

Rebuild of ref: accord-maelstrom/src/main/java/accord/maelstrom/Main.java
:60-243 (node wiring, StdoutSink w/ timeout sweeper), MaelstromRequest.java
:60-140 ("txn" body -> coordinate -> "txn_ok" reply), TopologyFactory.java
(static hash-space topology), SimpleConfigService.java (single epoch).

Inter-node traffic wraps this project's wire codec (accord_tpu.wire — the
Json.java analogue): requests as ``{"type": "accord_req", "payload": ...}``
bodies, replies correlated by Maelstrom ``msg_id``/``in_reply_to``.

The workload is Maelstrom's list-append ``txn``: ops ``["r", k, null]`` and
``["append", k, v]``; keys (ints or strings) hash onto the token ring.
"""

from __future__ import annotations

import hashlib
from typing import Callable, Dict, List, Optional, Tuple

from .. import api, wire
from ..coordinate.errors import Timeout
from ..impl.config_service import AbstractConfigurationService
from ..local.node import Node
from ..primitives.keys import IntKey, Keys, Range, Ranges
from ..primitives.txn import Txn
from ..primitives.timestamp import TxnKind
from ..sim.kvstore import KVDataStore, KVQuery, KVRead, KVUpdate
from ..topology.shard import Shard
from ..topology.topology import Topology
from ..utils.random_source import RandomSource

TOKEN_SPACE = 1 << 32
# ref: Main.java uses a 1s sweeper; a cold JAX node stalls for seconds per
# first-compile of each kernel shape, so the wall-clock bound here is wider
# (the sim cluster keeps its own simulated-time timeouts)
REQUEST_TIMEOUT_MICROS = 20_000_000
SWEEP_INTERVAL_MICROS = 200_000


def node_name_to_id(name: str) -> int:
    """Maelstrom names are "n1".."nN"; ids must be ints (and nonzero)."""
    digits = "".join(ch for ch in name if ch.isdigit())
    if digits:
        return int(digits) + 1   # "n0" is valid maelstrom; our ids start at 1
    return (int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "big")
            % 1_000_000) + 1


def token_of(key) -> int:
    """Map a Maelstrom key (int or string) onto the token ring."""
    if isinstance(key, bool) or not isinstance(key, int):
        digest = hashlib.sha256(repr(key).encode()).digest()
        return int.from_bytes(digest[:8], "big") % TOKEN_SPACE
    return key % TOKEN_SPACE


def build_maelstrom_topology(node_ids: List[int], shards: int = 16,
                             rf: Optional[int] = None) -> Topology:
    """Static single-epoch topology: the hash space split into ``shards``
    ranges, each replicated rf ways round-robin
    (ref: maelstrom/TopologyFactory.java; Main.java uses (64, 3))."""
    from ..sim.topology_factory import build_topology
    rf = rf if rf is not None else min(3, len(node_ids))
    return build_topology(1, node_ids, rf, shards,
                          min_token=0, max_token=TOKEN_SPACE)


class _Pending:
    __slots__ = ("callback", "to", "deadline")

    def __init__(self, callback, to: int, deadline: int):
        self.callback = callback
        self.to = to
        self.deadline = deadline


class MaelstromSink(api.MessageSink):
    """MessageSink over Maelstrom bodies (ref: Main.StdoutSink).  Replies
    correlate on msg_id; unanswered callbacks time out via a sweeper."""

    def __init__(self, process: "MaelstromProcess"):
        self.process = process
        self._next_msg_id = 0
        self.pending: Dict[int, _Pending] = {}

    def _msg_id(self) -> int:
        self._next_msg_id += 1
        return self._next_msg_id

    def _emit(self, to: int, body: dict) -> None:
        self.process.emit_packet(to, body)

    def send(self, to: int, request) -> None:
        self._emit(to, {"type": "accord_req", "msg_id": self._msg_id(),
                        "payload": wire.encode(request)})

    def send_with_callback(self, to: int, request, callback) -> None:
        msg_id = self._msg_id()
        timeout = REQUEST_TIMEOUT_MICROS
        # barrier reads (commit-fused reads, WaitOnCommit) reply only when
        # the replica's drain releases them — give them room before declaring
        # the replica dead (same policy as the sim NodeSink)
        if getattr(request, "is_slow_read", False):
            timeout *= 10
        self.pending[msg_id] = _Pending(
            callback, to, self.process.now_micros() + timeout)
        self._emit(to, {"type": "accord_req", "msg_id": msg_id,
                        "payload": wire.encode(request)})

    def reply(self, to: int, reply_context, reply) -> None:
        if reply_context is None:
            return   # local requests (Propagate) have no reply path
        self._emit(to, {"type": "accord_rsp", "msg_id": self._msg_id(),
                        "in_reply_to": reply_context,
                        "payload": wire.encode(reply)})

    def reply_with_unknown_failure(self, to: int, reply_context,
                                   failure: BaseException) -> None:
        if reply_context is None:
            # local requests (Propagate) have no reply path, but the
            # failure must not vanish: stderr is maelstrom's log channel
            import sys
            print(f"local request failed: {failure!r}", file=sys.stderr)
            return
        self._emit(to, {"type": "accord_fail", "msg_id": self._msg_id(),
                        "in_reply_to": reply_context,
                        "error": repr(failure)})

    def sweep(self) -> None:
        now = self.process.now_micros()
        expired = [m for m, p in self.pending.items() if p.deadline <= now]
        for m in expired:
            p = self.pending.pop(m)
            p.callback.on_failure(p.to, Timeout(msg=f"timeout to {p.to}"))

    # -- inbound ------------------------------------------------------------
    def on_response(self, from_id: int, in_reply_to: int, reply) -> None:
        p = self.pending.get(in_reply_to)
        if p is None:
            return
        # multi-reply exchanges: a fused Stable+Read replies CommitOk
        # (non-final) then ReadOk — keep the callback until the final reply
        final = reply.is_final() if hasattr(reply, "is_final") else True
        if final:
            del self.pending[in_reply_to]
        p.callback.on_success(from_id, reply)

    def on_failure_response(self, from_id: int, in_reply_to: int,
                            error: str) -> None:
        p = self.pending.pop(in_reply_to, None)
        if p is not None:
            p.callback.on_failure(from_id, RuntimeError(error))


class StaticConfigService(AbstractConfigurationService):
    """Single static epoch on the shared epoch-ledger base
    (ref: maelstrom/SimpleConfigService.java over
    impl/AbstractConfigurationService.java)."""

    def __init__(self, topology: Topology):
        super().__init__()
        self.report_topology(topology)

    def acknowledge_epoch(self, epoch_ready, start_sync: bool = True) -> None:
        pass


class MaelstromAgent(api.Agent):
    """(ref: maelstrom/MaelstromAgent.java)."""

    def __init__(self, process: "MaelstromProcess"):
        self.process = process

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.process.failures.append(failure)

    def on_handled_exception(self, failure: BaseException) -> None:
        pass


class MaelstromProcess:
    """One Maelstrom node process: pre-init buffering, init handshake, then
    client txn bodies + inter-node accord bodies
    (ref: Main.listen :145-243)."""

    def __init__(self, emit: Callable[[str, dict], None],
                 scheduler: api.Scheduler,
                 now_micros: Callable[[], int],
                 num_stores: int = 2,
                 shards: int = 16,
                 device_mode: Optional[bool] = None,
                 durability: bool = True,
                 obs=None):
        self._emit_raw = emit
        self.scheduler = scheduler
        self.now_micros = now_micros
        self.num_stores = num_stores
        self.shards = shards
        self.device_mode = device_mode
        # shared obs.Observability (the in-process runner wires one per
        # run so bench config rows read phase latencies + fast-path rate)
        self.obs = obs
        self.enable_durability = durability
        self.name: Optional[str] = None
        self.node: Optional[Node] = None
        self.sink: Optional[MaelstromSink] = None
        self.failures: List[BaseException] = []
        self._names_by_id: Dict[int, str] = {}
        self._client_msg_id = 0
        self._sweeper = None

    # -- outbound -----------------------------------------------------------
    def emit_packet(self, to, body: dict) -> None:
        dest = self._names_by_id.get(to, to) if isinstance(to, int) else to
        if dest == self.name:
            # loop self-sends back locally (deferred, never reentrant) rather
            # than round-tripping them through the harness network
            self.scheduler.now(
                lambda: self.handle({"src": self.name, "dest": dest,
                                     "body": body}))
            return
        self._emit_raw(dest, body)

    def _reply_client(self, dest: str, in_reply_to: int, body: dict) -> None:
        self._client_msg_id += 1
        body = dict(body)
        body["msg_id"] = self._client_msg_id
        body["in_reply_to"] = in_reply_to
        self._emit_raw(dest, body)

    # -- inbound ------------------------------------------------------------
    def handle(self, packet: dict) -> None:
        """Process one Maelstrom packet {src, dest, body}."""
        body = packet.get("body", {})
        typ = body.get("type")
        src = packet.get("src", "")
        if typ == "init":
            self._handle_init(src, body)
            return
        if self.node is None:
            # Maelstrom guarantees init first; tolerate strays
            return
        if typ == "accord_req":
            request = wire.decode(body["payload"])
            self.node.receive(request, node_name_to_id(src), body["msg_id"])
        elif typ == "accord_rsp":
            reply = wire.decode(body["payload"])
            self.sink.on_response(node_name_to_id(src), body["in_reply_to"],
                                  reply)
        elif typ == "accord_fail":
            self.sink.on_failure_response(node_name_to_id(src),
                                          body["in_reply_to"], body["error"])
        elif typ == "txn":
            self._handle_txn(src, body)

    def _handle_init(self, src: str, body: dict) -> None:
        self.name = body["node_id"]
        names = list(body["node_ids"])
        ids = []
        for n in names:
            nid = node_name_to_id(n)
            self._names_by_id[nid] = n
            ids.append(nid)
        my_id = node_name_to_id(self.name)
        topology = build_maelstrom_topology(ids, shards=self.shards)
        self.sink = MaelstromSink(self)
        self.node = Node(
            node_id=my_id, message_sink=self.sink,
            config_service=StaticConfigService(topology),
            scheduler=self.scheduler,
            data_store=KVDataStore(my_id),
            agent=MaelstromAgent(self),
            random=RandomSource(my_id * 7919),
            now_micros=self.now_micros,
            num_stores=self.num_stores,
            device_mode=self.device_mode)
        self.node.obs = self.obs
        self.node.on_topology_update(topology)
        self._sweeper = self.scheduler.recurring(SWEEP_INTERVAL_MICROS,
                                                 self.sink.sweep)
        # background durability rounds -> watermarks -> truncation
        # (ref: Main.java wires CoordinateDurabilityScheduling)
        if self.enable_durability:
            from ..impl.durability_scheduling import DurabilityScheduling
            self.durability = DurabilityScheduling(
                self.node, shard_cycle_micros=5_000_000,
                global_cycle_micros=15_000_000)
            self.durability.start()
        # warm-compile the device deps kernel BEFORE acking init: Maelstrom
        # sends no work until init_ok, and a cold first compile (seconds)
        # would otherwise race the 1s callback sweeper into spurious
        # client-visible timeouts on the first txns
        from ..primitives.timestamp import Domain, TxnKind
        for store in self.node.command_stores.stores:
            dev = getattr(store, "device", None)
            if dev is None:
                continue
            tid = self.node.next_txn_id(TxnKind.Write, Domain.Key)
            try:
                dev.deps_query_batch(
                    [(tid, tid, tid.kind().witnesses(), [0], [])])
            except Exception:
                pass   # warmup must never block startup
        self._reply_client(src, body["msg_id"], {"type": "init_ok"})

    # -- the list-append "txn" workload --------------------------------------
    def _handle_txn(self, src: str, body: dict) -> None:
        ops = body["txn"]
        msg_id = body["msg_id"]
        read_tokens: List[int] = []
        appends: Dict[int, tuple] = {}
        for op in ops:
            f, k = op[0], op[1]
            t = token_of(k)
            if f == "r":
                read_tokens.append(t)
            elif f == "append":
                appends[t] = appends.get(t, ()) + (op[2],)
            else:
                self._reply_client(src, msg_id, {
                    "type": "error", "code": 10,
                    "text": f"unsupported op {f}"})
                return
        all_tokens = sorted(set(read_tokens) | set(appends))
        keys = Keys([IntKey(t) for t in all_tokens])
        kind = TxnKind.Write if appends else TxnKind.Read
        txn = Txn(kind, keys,
                  KVRead(Keys([IntKey(t) for t in sorted(set(read_tokens))])),
                  KVUpdate(appends) if appends else None, KVQuery())

        def on_done(result, failure):
            if failure is not None:
                # retryable per Maelstrom error semantics (the checker treats
                # it as an indeterminate op, ref: MaelstromReply error paths)
                self._reply_client(src, msg_id, {
                    "type": "error", "code": 11, "text": repr(failure)})
                return
            out_ops = []
            appended_so_far: Dict[int, list] = {}
            for op in ops:
                f, k = op[0], op[1]
                t = token_of(k)
                if f == "r":
                    pre = list(result.reads.get(t, ()))
                    # intra-txn visibility: a read after an append in the
                    # same txn observes it (Elle list-append model)
                    out_ops.append(["r", k, pre + appended_so_far.get(t, [])])
                else:
                    appended_so_far.setdefault(t, []).append(op[2])
                    out_ops.append(op)
            self._reply_client(src, msg_id, {"type": "txn_ok",
                                             "txn": out_ops})

        self.node.coordinate(txn).begin(on_done)
