"""Maelstrom node: speaks the Maelstrom/Jepsen JSON body protocol over an
emit callback (stdout in ``__main__``, an in-process queue in the Runner).

Rebuild of ref: accord-maelstrom/src/main/java/accord/maelstrom/Main.java
:60-243 (node wiring, StdoutSink w/ timeout sweeper), MaelstromRequest.java
:60-140 ("txn" body -> coordinate -> "txn_ok" reply), TopologyFactory.java
(static hash-space topology), SimpleConfigService.java (single epoch).

Inter-node traffic wraps this project's wire codec (accord_tpu.wire — the
Json.java analogue): requests as ``{"type": "accord_req", "payload": ...}``
bodies, replies correlated by Maelstrom ``msg_id``/``in_reply_to``.

The workload is Maelstrom's list-append ``txn``: ops ``["r", k, null]`` and
``["append", k, v]``; keys (ints or strings) hash onto the token ring.
"""

from __future__ import annotations

import hashlib
import heapq
from typing import Callable, Dict, List, Optional, Tuple

from .. import api, wire
from ..coordinate.errors import Timeout
from ..local.fastpath import proto_fastpath_enabled, store_group_enabled
from ..impl.config_service import AbstractConfigurationService
from ..local.node import Node
from ..primitives.datum import datum_from_json, datum_to_json
from ..primitives.keys import IntKey, Keys, Range, Ranges
from ..primitives.txn import Txn
from ..primitives.timestamp import TxnKind
from ..sim.kvstore import KVDataStore, KVQuery, KVRead, KVUpdate
from ..topology.shard import Shard
from ..topology.topology import Topology
from ..utils.random_source import RandomSource

_FASTPATH = proto_fastpath_enabled()
# r20 store-grouped execution: accord_batch envelopes decode in one pass
# and deliver their protocol requests through Node.receive_group (one
# scheduler hop, one SafeCommandStore per (run x store)) instead of N
# recursive per-op handle calls.  ACCORD_TPU_STORE_GROUP=off restores
# the r16 unbatch-at-the-door path.
_STORE_GROUP = store_group_enabled()

TOKEN_SPACE = 1 << 32
# ref: Main.java uses a 1s sweeper; a cold JAX node stalls for seconds per
# first-compile of each kernel shape, so the wall-clock bound here is wider
# (the sim cluster keeps its own simulated-time timeouts); the TCP serving
# surface (accord_tpu.net.server) passes a much tighter bound
REQUEST_TIMEOUT_MICROS = 20_000_000
SWEEP_INTERVAL_MICROS = 200_000
# small deterministic per-request timeout jitter (same bound as the sim
# NodeSink's Cluster.timeout_jitter): co-scheduled fan-out requests must
# not expire at the same instant and fire as a synchronized retry storm
TIMEOUT_JITTER_MICROS = 4096


def node_name_to_id(name: str) -> int:
    """Maelstrom names are "n1".."nN"; ids must be ints (and nonzero)."""
    digits = "".join(ch for ch in name if ch.isdigit())
    if digits:
        return int(digits) + 1   # "n0" is valid maelstrom; our ids start at 1
    return (int.from_bytes(hashlib.sha256(name.encode()).digest()[:4], "big")
            % 1_000_000) + 1


def token_of(key) -> int:
    """Map a Maelstrom key (int or string) onto the token ring."""
    if isinstance(key, bool) or not isinstance(key, int):
        digest = hashlib.sha256(repr(key).encode()).digest()
        return int.from_bytes(digest[:8], "big") % TOKEN_SPACE
    return key % TOKEN_SPACE


def build_maelstrom_topology(node_ids: List[int], shards: int = 16,
                             rf: Optional[int] = None) -> Topology:
    """Static single-epoch topology: the hash space split into ``shards``
    ranges, each replicated rf ways round-robin
    (ref: maelstrom/TopologyFactory.java; Main.java uses (64, 3))."""
    from ..sim.topology_factory import build_topology
    rf = rf if rf is not None else min(3, len(node_ids))
    return build_topology(1, node_ids, rf, shards,
                          min_token=0, max_token=TOKEN_SPACE)


class _Pending:
    __slots__ = ("callback", "to", "deadline", "entry")

    def __init__(self, callback, to: int, deadline: int, entry: List):
        self.callback = callback
        self.to = to
        self.deadline = deadline
        # the pending-timeout heap entry ([deadline, msg_id]); tombstoned
        # (msg_id -> None) the moment the callback resolves
        self.entry = entry


class MaelstromSink(api.MessageSink):
    """MessageSink over Maelstrom bodies (ref: Main.StdoutSink).  Replies
    correlate on msg_id; unanswered callbacks time out via a sweeper over
    a deadline HEAP whose entries are tombstoned the moment a reply
    resolves — the r07 NodeSink fixes ported here (sim/cluster.py:128-159):
    a completed request must not leave a dead callback reachable for the
    full timeout horizon, and per-request deterministic jitter (dedicated
    stream, protocol RNG untouched) desynchronizes co-scheduled timeouts
    so they cannot fire as one retry storm."""

    def __init__(self, process: "MaelstromProcess",
                 jitter: Optional[RandomSource] = None):
        self.process = process
        self._next_msg_id = 0
        self.pending: Dict[int, _Pending] = {}
        self._timeouts: List[List] = []   # [deadline, msg_id] min-heap
        self._tombstones = 0              # resolved entries still heaped
        self._jitter = jitter

    def _msg_id(self) -> int:
        self._next_msg_id += 1
        return self._next_msg_id

    def _emit(self, to: int, body: dict) -> None:
        self.process.emit_packet(to, body)

    def _is_self(self, to: int) -> bool:
        node = getattr(self.process, "node", None)
        return node is not None and to == node.node_id

    def _deliver_local(self, request, msg_id: Optional[int]) -> None:
        """Self-send fast path (r16): a request to our own node skips the
        wire codec entirely — the OBJECT is handed to ``node.receive`` at
        the next scheduler hop (deferred, never reentrant: same policy as
        ``emit_packet``'s body loop-back this replaces on the hot path).
        Object sharing across the node boundary is exactly the sim
        NodeSink's semantics, so the protocol's tolerance of it is already
        pinned by the whole sim suite; with rf == cluster size a third of
        all protocol messages were paying encode+decode to reach their own
        process."""
        node = self.process.node
        my_id = node.node_id
        self.process.scheduler.now(
            lambda: node.receive(request, my_id, msg_id))

    def _encode_request(self, request):
        """wire.encode with per-OBJECT doc reuse (r18): coordinators fan
        ONE PreAccept/Accept/Apply object to every shard replica, and the
        golden-frame gate pins decode∘encode as the identity, so the doc
        cached at first encode (or attached at inbound decode) is
        byte-identical for every later destination."""
        if not _FASTPATH:
            return wire.encode(request)
        doc = getattr(request, "_wire_doc", None)
        if doc is None:
            doc = wire.encode(request)
            try:
                request._wire_doc = doc
            except AttributeError:
                pass   # slotted/exotic request: encode per send
        return doc

    def send(self, to: int, request) -> None:
        if self._is_self(to):
            self._deliver_local(request, self._msg_id())
            return
        self._emit(to, {"type": "accord_req", "msg_id": self._msg_id(),
                        "payload": self._encode_request(request)})

    def send_with_callback(self, to: int, request, callback) -> None:
        msg_id = self._msg_id()
        timeout = self.process.request_timeout_micros
        # barrier reads (commit-fused reads, WaitOnCommit) reply only when
        # the replica's drain releases them — give them room before declaring
        # the replica dead (same policy as the sim NodeSink)
        if getattr(request, "is_slow_read", False):
            timeout *= 10
        if self._jitter is not None:
            timeout += self._jitter.next_int(TIMEOUT_JITTER_MICROS)
        deadline = self.process.now_micros() + timeout
        # [deadline, tiebreak, msg_id]: the tiebreak copy stays immutable
        # so equal-deadline entries never compare a tombstoned None
        entry = [deadline, msg_id, msg_id]
        self.pending[msg_id] = _Pending(callback, to, deadline, entry)
        heapq.heappush(self._timeouts, entry)
        if self._is_self(to):
            # the pending-table entry above still owns the timeout: a
            # self-request wedged behind a stalled store times out exactly
            # like a remote one
            self._deliver_local(request, msg_id)
            return
        self._emit(to, {"type": "accord_req", "msg_id": msg_id,
                        "payload": self._encode_request(request)})

    def _resolve(self, msg_id: int) -> Optional[_Pending]:
        """Pop a pending request and tombstone its heap entry in place
        (the sweeper skips tombstones; no dead callback is held for the
        remaining horizon)."""
        p = self.pending.pop(msg_id, None)
        if p is not None:
            p.entry[2] = None
            self._tombstones += 1
            # r13 fix: a tombstone still OCCUPIES its heap slot until its
            # deadline sweeps past — for slow-read requests that is 10x
            # the base horizon, so a burst of requests resolved against a
            # node that then restarts leaves dead [deadline, tie, None]
            # entries heaped long past the horizon.  Once tombstones
            # outnumber live entries, rebuild the heap from the live set
            # (the entry lists are shared, so later tombstoning of a
            # carried-over entry still works in place).
            if self._tombstones > 64 and self._tombstones > len(self.pending):
                self._compact_timeouts()
        return p

    def _compact_timeouts(self) -> None:
        self._timeouts = [q.entry for q in self.pending.values()]
        heapq.heapify(self._timeouts)
        self._tombstones = 0

    def reply(self, to: int, reply_context, reply) -> None:
        if reply_context is None:
            return   # local requests (Propagate) have no reply path
        if self._is_self(to):
            # self-reply fast path: dispatch the reply OBJECT back into
            # our own response handler at the next scheduler hop — same
            # journal gating as the wire path below (a promise to
            # ourselves is still a promise about durable state)
            my_id = self.process.node.node_id
            deliver = lambda: self.process.scheduler.now(  # noqa: E731
                lambda: self.on_response(my_id, reply_context, reply))
            journal = self.process.durable_journal()
            if journal is not None and journal.gate_protocol_replies():
                journal.commit.after_durable(deliver)
            else:
                deliver()
            return
        body = {"type": "accord_rsp", "msg_id": self._msg_id(),
                "in_reply_to": reply_context,
                "payload": wire.encode(reply)}
        journal = self.process.durable_journal()
        if journal is not None and journal.gate_protocol_replies():
            # strict mode (--journal-sync all): a protocol reply is a
            # PROMISE about this node's state (a PreAcceptOk promises the
            # witness, an AcceptReply the ballot) — it leaves only once
            # the WAL records backing it (journaled at _process entry and
            # during the store update) are fsynced.  One batch fsync
            # releases every reply in the window.
            journal.commit.after_durable(lambda: self._emit(to, body))
        else:
            self._emit(to, body)

    def reply_with_unknown_failure(self, to: int, reply_context,
                                   failure: BaseException) -> None:
        if reply_context is None:
            # local requests (Propagate) have no reply path, but the
            # failure must not vanish: stderr is maelstrom's log channel
            import sys
            print(f"local request failed: {failure!r}", file=sys.stderr)
            return
        if self._is_self(to):
            my_id = self.process.node.node_id
            self.process.scheduler.now(
                lambda: self.on_failure_response(my_id, reply_context,
                                                 repr(failure)))
            return
        self._emit(to, {"type": "accord_fail", "msg_id": self._msg_id(),
                        "in_reply_to": reply_context,
                        "error": repr(failure)})

    def sweep(self) -> None:
        """Fire every expired pending timeout: pop the deadline heap up to
        ``now``, skipping tombstoned entries (already resolved) — O(expired
        + resolved) per sweep instead of O(all pending)."""
        now = self.process.now_micros()
        while self._timeouts and self._timeouts[0][0] <= now:
            _deadline, _tie, msg_id = heapq.heappop(self._timeouts)
            if msg_id is None:
                self._tombstones = max(0, self._tombstones - 1)
                continue   # tombstone: resolved before its deadline
            p = self.pending.pop(msg_id, None)
            if p is None:
                continue
            p.callback.on_failure(p.to, Timeout(msg=f"timeout to {p.to}"))

    # -- inbound ------------------------------------------------------------
    def on_response(self, from_id: int, in_reply_to: int, reply) -> None:
        p = self.pending.get(in_reply_to)
        if p is None:
            return   # idempotent: late duplicate / reply racing a timeout
        # multi-reply exchanges: a fused Stable+Read replies CommitOk
        # (non-final) then ReadOk — keep the callback until the final reply
        final = reply.is_final() if hasattr(reply, "is_final") else True
        if final:
            self._resolve(in_reply_to)
        p.callback.on_success(from_id, reply)

    def on_failure_response(self, from_id: int, in_reply_to: int,
                            error: str) -> None:
        p = self._resolve(in_reply_to)
        if p is not None:
            p.callback.on_failure(from_id, RuntimeError(error))


class StaticConfigService(AbstractConfigurationService):
    """Single static epoch on the shared epoch-ledger base
    (ref: maelstrom/SimpleConfigService.java over
    impl/AbstractConfigurationService.java)."""

    def __init__(self, topology: Topology):
        super().__init__()
        self.report_topology(topology)

    def acknowledge_epoch(self, epoch_ready, start_sync: bool = True) -> None:
        pass


class MaelstromAgent(api.Agent):
    """(ref: maelstrom/MaelstromAgent.java)."""

    def __init__(self, process: "MaelstromProcess"):
        self.process = process

    def on_uncaught_exception(self, failure: BaseException) -> None:
        self.process.failures.append(failure)

    def on_handled_exception(self, failure: BaseException) -> None:
        pass


class MaelstromProcess:
    """One Maelstrom node process: pre-init buffering, init handshake, then
    client txn bodies + inter-node accord bodies
    (ref: Main.listen :145-243)."""

    def __init__(self, emit: Callable[[str, dict], None],
                 scheduler: api.Scheduler,
                 now_micros: Callable[[], int],
                 num_stores: int = 2,
                 shards: int = 16,
                 device_mode: Optional[bool] = None,
                 durability: bool = True,
                 obs=None,
                 request_timeout_micros: Optional[int] = None,
                 journal=None):
        self._emit_raw = emit
        self.scheduler = scheduler
        self.now_micros = now_micros
        self.num_stores = num_stores
        self.shards = shards
        self.device_mode = device_mode
        # shared obs.Observability (the in-process runner wires one per
        # run so bench config rows read phase latencies + fast-path rate)
        self.obs = obs
        self.enable_durability = durability
        # on-disk journal (accord_tpu.journal.DurableJournal) — None means
        # the r12 behaviour: a kill -9 rejoin is fresh-state
        self.journal = journal
        # sink-owned request timeout (the TCP serving surface tightens it;
        # the Maelstrom default stays wide for cold-compile stalls)
        self.request_timeout_micros = (request_timeout_micros
                                       or REQUEST_TIMEOUT_MICROS)
        # admission gate in front of coordinate (accord_tpu.net.admission;
        # None = admit everything — the sim runner and Maelstrom harness)
        self.admission = None
        # elastic-serving reconfiguration manager (accord_tpu.net.reconfig;
        # None = the static single-epoch Maelstrom behaviour).  When set,
        # the node runs on its NetConfigService: epochs propagate over the
        # wire, membership is dynamic, stores bootstrap via FetchSnapshot.
        self.reconfig = None
        # where unknown (non-protocol) bodies go — the TCP server routes
        # them back into its control plane (batch-envelope riders)
        self.control_fallback = None
        self.name: Optional[str] = None
        self.node: Optional[Node] = None
        self.sink: Optional[MaelstromSink] = None
        self.failures: List[BaseException] = []
        self._names_by_id: Dict[int, str] = {}
        self._client_msg_id = 0
        self._sweeper = None

    def durable_journal(self):
        """The armed on-disk journal, or None (also None once its group
        commit has degraded: no gating on a promise it can't keep)."""
        j = self.journal
        if j is None or getattr(j, "commit", None) is None \
                or j.commit.failed:
            return None
        return j

    def note_peer(self, name: str) -> None:
        """Register a peer name->id mapping learned AFTER init (a node
        that joined via reconfiguration): outbound protocol packets to
        its id route to its name."""
        self._names_by_id[node_name_to_id(name)] = name

    # -- outbound -----------------------------------------------------------
    def emit_packet(self, to, body: dict) -> None:
        dest = self._names_by_id.get(to, to) if isinstance(to, int) else to
        if dest == self.name:
            # loop self-sends back locally (deferred, never reentrant) rather
            # than round-tripping them through the harness network
            self.scheduler.now(
                lambda: self.handle({"src": self.name, "dest": dest,
                                     "body": body}))
            return
        self._emit_raw(dest, body)

    def _reply_client(self, dest: str, in_reply_to: int, body: dict) -> None:
        self._client_msg_id += 1
        body = dict(body)
        body["msg_id"] = self._client_msg_id
        body["in_reply_to"] = in_reply_to
        journal = self.journal
        if journal is not None and hasattr(journal, "record_reply") \
                and body.get("type") == "txn_ok":
            # at-most-once across death: the reply this node now OWES is a
            # journal fact (keyed by the client's msg_id; our own msg_id
            # is re-stamped on any re-send).  Under the "all"/"client"
            # sync policies it leaves only once the txn's journal records
            # — and the owed-reply record itself — are fsynced: acked =>
            # durable.  A restarted incarnation answers a duplicate
            # request from this table instead of re-coordinating.  On a
            # DEGRADED journal the table still records in memory (the
            # dedupe contract outlives durability) but nothing gates.
            stored = {k: v for k, v in body.items() if k != "msg_id"}
            journal.record_reply(dest, in_reply_to, stored)
            if self.durable_journal() is not None \
                    and journal.gate_client_replies():
                journal.commit.after_durable(
                    lambda: self._emit_raw(dest, body))
                return
        self._emit_raw(dest, body)

    def _replay_client_reply(self, dest: str, in_reply_to: int,
                             stored: dict) -> None:
        """Re-serve an already-journaled reply to a duplicate request."""
        self._client_msg_id += 1
        body = dict(stored)
        body["msg_id"] = self._client_msg_id
        body["in_reply_to"] = in_reply_to
        self._emit_raw(dest, body)

    # -- inbound ------------------------------------------------------------
    def handle(self, packet: dict, _from_envelope: bool = False) -> None:
        """Process one Maelstrom packet {src, dest, body}."""
        body = packet.get("body", {})
        typ = body.get("type")
        src = packet.get("src", "")
        if typ == "init":
            self._handle_init(src, body)
            return
        if self.node is None:
            # Maelstrom guarantees init first; tolerate strays
            return
        if typ == "accord_batch":
            # cross-request fused fan-out (r16): one envelope carries N
            # ops' bodies from one peer tick.  Under _STORE_GROUP (r20)
            # the envelope's protocol requests decode in ONE pass and
            # deliver as a group (store-grouped execution); otherwise
            # unbatch HERE, at the protocol receiver, into the unchanged
            # per-op path below (the envelope is transport amortization,
            # never protocol state: per-op decisions, deps and replies
            # are byte-identical to N separate frames).  Either way the
            # sub-bodies run in one scheduler tick, so their store
            # flushes coalesce into one deps flush (and one fused device
            # launch under --device-mode) by construction.
            if _STORE_GROUP:
                self._handle_batch_grouped(src, packet)
                return
            import sys
            for sub in body.get("msgs") or ():
                try:
                    self.handle({"src": src, "dest": packet.get("dest"),
                                 "body": sub}, _from_envelope=True)
                except Exception as exc:   # one poisoned sub-body must
                    # not drop the rest of the batch on the floor
                    print(f"batch sub-handler error on "
                          f"{(sub or {}).get('type')}: {exc!r}",
                          file=sys.stderr)
        elif typ == "accord_req":
            request = wire.decode(body["payload"])
            try:
                # r16: the inbound doc IS wire.encode(request) (the
                # golden-frame gate pins decode∘encode as the identity) —
                # the durable journal reuses it instead of re-encoding
                # the whole request at record_message time
                request._wire_doc = body["payload"]
            except AttributeError:
                pass   # slotted/exotic request: journal re-encodes
            self.node.receive(request, node_name_to_id(src), body["msg_id"])
        elif typ == "accord_rsp":
            payload = body["payload"]
            if _from_envelope and self.reconfig is not None \
                    and isinstance(payload, dict) \
                    and payload.get("_t") == "FetchSnapshotOk":
                # bootstrap byte accounting for the one delivery shape
                # the frame layer cannot weigh: an ENVELOPE rider.  Such
                # replies are small by construction (large payloads
                # always leave as direct or chunked frames, counted for
                # free at the server), so the re-encode here is cheap
                # and rare.
                self.reconfig.note_snapshot_reply(body)
            reply = wire.decode(payload)
            self.sink.on_response(node_name_to_id(src), body["in_reply_to"],
                                  reply)
        elif typ == "accord_fail":
            self.sink.on_failure_response(node_name_to_id(src),
                                          body["in_reply_to"], body["error"])
        elif typ == "txn":
            self._handle_txn(src, body)
        elif self.control_fallback is not None:
            # serving-surface control bodies (topo_new / epoch_sync /
            # topo_fetch / codec_hello / accord_chunk) that rode a peer
            # accord_batch envelope: hand them back to the server's
            # control router — without this, any reconfiguration gossip
            # sharing a tick with protocol traffic would be silently
            # dropped at the unbatcher
            self.control_fallback(packet)

    def _handle_batch_grouped(self, src: str, packet: dict) -> None:
        """r20 store-grouped envelope intake: decode the envelope's
        ``accord_req`` sub-bodies in ONE codec dispatch loop (shared
        ``_wire_doc`` stamping) and hand each consecutive run to
        :meth:`Node.receive_group`.  Sub-bodies the grouper cannot prove
        safe to merge — replies (synchronous by contract), control verbs
        and reconfig gossip (``control_fallback`` riders), client txns —
        FLUSH the current run and take the unchanged per-op path, so
        inter-type ordering is exactly the per-op unbatcher's: per-op
        requests defer via one scheduler hop while everything else
        handles synchronously, before the deferred run."""
        import sys
        from_id = node_name_to_id(src)
        group: List = []

        def flush():
            if group:
                self.node.receive_group(group[:], from_id)
                del group[:]

        for sub in packet.get("body", {}).get("msgs") or ():
            styp = (sub or {}).get("type")
            if styp == "accord_req":
                try:
                    request = wire.decode(sub["payload"])
                    try:
                        request._wire_doc = sub["payload"]
                    except AttributeError:
                        pass   # slotted/exotic request: journal re-encodes
                    group.append((request, sub["msg_id"]))
                except Exception as exc:
                    print(f"batch sub-handler error on accord_req: {exc!r}",
                          file=sys.stderr)
                continue
            flush()
            if styp not in ("accord_rsp", "accord_fail", "txn"):
                # control verbs / reconfig gossip riding the envelope:
                # per-op fallback through control_fallback
                self.node.n_group_fallbacks += 1
            try:
                self.handle({"src": src, "dest": packet.get("dest"),
                             "body": sub}, _from_envelope=True)
            except Exception as exc:   # one poisoned sub-body must not
                # drop the rest of the batch on the floor
                print(f"batch sub-handler error on {styp}: {exc!r}",
                      file=sys.stderr)
        flush()

    def _handle_init(self, src: str, body: dict) -> None:
        self.name = body["node_id"]
        names = list(body["node_ids"])
        ids = []
        for n in names:
            nid = node_name_to_id(n)
            self._names_by_id[nid] = n
            ids.append(nid)
        my_id = node_name_to_id(self.name)
        # self-mapping even when we are NOT an epoch-1 member (a joining
        # node's init carries the EXISTING cluster as node_ids): loop-back
        # and self-send detection key on it
        self._names_by_id[my_id] = self.name
        topology = build_maelstrom_topology(ids, shards=self.shards)
        # timeout jitter on a dedicated deterministic stream seeded from
        # the node id — the protocol RandomSource below is untouched
        self.sink = MaelstromSink(self, jitter=RandomSource(
            0x51D ^ (my_id << 12)))
        if self.journal is not None:
            # the data store's appends become journal facts too — the
            # premise 'the data store is durable' that restore() assumes
            from ..journal import JournaledKVDataStore
            data_store = JournaledKVDataStore(my_id, self.journal)
        else:
            data_store = KVDataStore(my_id)
        if self.reconfig is not None:
            # elastic serving: the node runs on the wire-backed epoch
            # ledger; the initial history is epoch 1 (static member list)
            # plus every journaled successor — a node killed -9
            # mid-reconfiguration recovers into the right epoch
            config_service = self.reconfig.config_service
            topologies = self.reconfig.bootstrap_topologies(topology)
        else:
            config_service = StaticConfigService(topology)
            topologies = [topology]
        self.node = Node(
            node_id=my_id, message_sink=self.sink,
            config_service=config_service,
            scheduler=self.scheduler,
            data_store=data_store,
            agent=MaelstromAgent(self),
            random=RandomSource(my_id * 7919),
            now_micros=self.now_micros,
            num_stores=self.num_stores,
            device_mode=self.device_mode,
            journal=self.journal)
        self.node.obs = self.obs
        if self.journal is not None and self.journal.has_restored_state():
            # kill -9 recovery: re-ingest the epoch history WITHOUT
            # re-bootstrapping, seed the fresh data store with the
            # recovered value logs, then rebuild every store's commands
            # through the SAME restore path the sim's restart tests pin
            self.node.restore_topologies(topologies)
            self.journal.install_data(data_store)
            self.journal.restore(self.node)
        else:
            for t in topologies:
                self.node.on_topology_update(t)
        if self.reconfig is not None:
            self.reconfig.attach_node(self.node)
        self._sweeper = self.scheduler.recurring(SWEEP_INTERVAL_MICROS,
                                                 self.sink.sweep)
        # background durability rounds -> watermarks -> truncation
        # (ref: Main.java wires CoordinateDurabilityScheduling)
        if self.enable_durability:
            from ..impl.durability_scheduling import DurabilityScheduling
            self.durability = DurabilityScheduling(
                self.node, shard_cycle_micros=5_000_000,
                global_cycle_micros=15_000_000)
            self.durability.start()
        # warm-compile the device deps kernel BEFORE acking init: Maelstrom
        # sends no work until init_ok, and a cold first compile (seconds)
        # would otherwise race the 1s callback sweeper into spurious
        # client-visible timeouts on the first txns
        from ..primitives.timestamp import Domain, TxnKind
        for store in self.node.command_stores.stores:
            dev = getattr(store, "device", None)
            if dev is None:
                continue
            tid = self.node.next_txn_id(TxnKind.Write, Domain.Key)
            try:
                dev.deps_query_batch(
                    [(tid, tid, tid.kind().witnesses(), [0], [])])
            except Exception:
                pass   # warmup must never block startup
        self._reply_client(src, body["msg_id"], {"type": "init_ok"})

    # -- the list-append "txn" workload --------------------------------------
    def _handle_txn(self, src: str, body: dict) -> None:
        ops = body["txn"]
        msg_id = body["msg_id"]
        journal = self.journal
        if journal is not None and hasattr(journal, "replied_body"):
            # the at-most-once table (journaled, restart-durable): a
            # duplicate of an already-answered request gets the SAME
            # reply back — never a second coordination, never silence.
            # Consulted from the IN-MEMORY table even after the group
            # commit degrades: losing durability must not also lose the
            # dedupe contract for this incarnation's lifetime.
            stored = journal.replied_body(src, msg_id)
            if stored is not None:
                self._replay_client_reply(src, msg_id, stored)
                return
        # admission gate (accord_tpu.net.admission) FIRST: a shed must be
        # the cheapest possible outcome — no token hashing, no datum
        # decode, no coordination state — just a fast, explicit Overloaded
        # wire error (Maelstrom code 11, temporarily-unavailable) the
        # client sink surfaces for retry-with-backoff
        gate = self.admission
        if gate is not None:
            admitted, reason, retry_ms = gate.try_admit()
            if not admitted:
                self._reply_client(src, msg_id, {
                    "type": "error", "code": 11, "text": "overloaded",
                    "overloaded": True, "reason": reason,
                    "retry_after_ms": retry_ms})
                return
        t_admit = self.now_micros()
        released = [False]

        def release_once(ok: bool, record: bool = True) -> None:
            # at-most-once: on_done may have already released when a
            # later exception propagates back through _handle_txn.
            # record=False frees the slot without feeding the AIMD latency
            # window — the instant error paths would otherwise teach the
            # controller the node is microsecond-fast under poison traffic
            if gate is not None and not released[0]:
                released[0] = True
                gate.release(self.now_micros() - t_admit if record else None,
                             ok=ok)

        try:
            self._coordinate_txn(src, msg_id, ops, release_once)
        except BaseException:
            # any synchronous failure between admit and the coordination's
            # own on_done (malformed op shapes, unhashable keys, a raising
            # coordinate) must free the admission slot — a leaked slot is
            # permanent and admit_max of them wedges the node at 100% shed
            release_once(False, record=False)
            raise

    def _coordinate_txn(self, src: str, msg_id: int, ops,
                        release_once) -> None:
        read_tokens: List[int] = []
        appends: Dict[int, tuple] = {}
        for op in ops:
            f, k = op[0], op[1]
            t = token_of(k)
            if f == "r":
                read_tokens.append(t)
            elif f == "append":
                # multi-type datums (ref: maelstrom/Datum.java): string/
                # long/double are native JSON; {"hash": n} becomes DatumHash
                appends[t] = appends.get(t, ()) + (datum_from_json(op[2]),)
            else:
                release_once(False, record=False)
                self._reply_client(src, msg_id, {
                    "type": "error", "code": 10,
                    "text": f"unsupported op {f}"})
                return
        all_tokens = sorted(set(read_tokens) | set(appends))
        keys = Keys([IntKey(t) for t in all_tokens])
        kind = TxnKind.Write if appends else TxnKind.Read
        txn = Txn(kind, keys,
                  KVRead(Keys([IntKey(t) for t in sorted(set(read_tokens))])),
                  KVUpdate(appends) if appends else None, KVQuery())

        def on_done(result, failure):
            # the released duration IS the txn root span (admission ->
            # client reply) — the admission controller's p99 signal
            release_once(failure is None)
            if failure is not None:
                # retryable per Maelstrom error semantics (the checker treats
                # it as an indeterminate op, ref: MaelstromReply error paths)
                self._reply_client(src, msg_id, {
                    "type": "error", "code": 11, "text": repr(failure)})
                return
            out_ops = []
            appended_so_far: Dict[int, list] = {}
            for op in ops:
                f, k = op[0], op[1]
                t = token_of(k)
                if f == "r":
                    pre = [datum_to_json(v)
                           for v in result.reads.get(t, ())]
                    # intra-txn visibility: a read after an append in the
                    # same txn observes it (Elle list-append model)
                    out_ops.append(["r", k, pre + appended_so_far.get(t, [])])
                else:
                    appended_so_far.setdefault(t, []).append(op[2])
                    out_ops.append(op)
            self._reply_client(src, msg_id, {"type": "txn_ok",
                                             "txn": out_ops})

        self.node.coordinate(txn).begin(on_done)
