"""Maelstrom (Jepsen) adapter: a real stdin/stdout JSON node plus an
in-process Runner for deterministic tests.

Rebuild of ref: accord-maelstrom/ — Main.java (node), Json.java (serde; ours
is accord_tpu.wire), MaelstromRequest/Reply (the "txn" list-append
workload), test Runner.java/Cluster.java (in-process sim).
"""

from .node import (MaelstromProcess, build_maelstrom_topology,
                   node_name_to_id, token_of)
from .runner import MaelstromRunner, RunResult

__all__ = ["MaelstromProcess", "MaelstromRunner", "RunResult",
           "build_maelstrom_topology", "node_name_to_id", "token_of"]
