"""Multi-chip parallelism: mesh construction + sharded protocol kernels."""

from .sharded import (STORE_AXIS, make_mesh, shard_table,
                      sharded_calculate_deps, sharded_drain,
                      sharded_protocol_step)

__all__ = ["STORE_AXIS", "make_mesh", "shard_table", "sharded_calculate_deps",
           "sharded_drain", "sharded_protocol_step"]
