"""Multi-chip parallelism: mesh construction + sharded protocol kernels."""

from .sharded import (STORE_AXIS, make_mesh, shard_bucket_table, shard_table,
                      sharded_bucketed_flat, sharded_calculate_deps,
                      sharded_calculate_deps_flat_pruned, sharded_drain,
                      sharded_protocol_step)

__all__ = ["STORE_AXIS", "make_mesh", "shard_bucket_table", "shard_table",
           "sharded_bucketed_flat", "sharded_calculate_deps",
           "sharded_calculate_deps_flat_pruned", "sharded_drain",
           "sharded_protocol_step"]
