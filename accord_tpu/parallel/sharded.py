"""Multi-chip shard parallelism over a jax.sharding.Mesh.

The reference's shard parallelism is key-space ranges -> one single-threaded
CommandStore each, with scatter-gather mapReduce across intersecting stores
(ref: accord-core/src/main/java/accord/local/CommandStores.java:575-643).
Here the analogue is the conflict-index slot dimension sharded across TPU
devices: every device owns a contiguous slice of the SoA table, deps queries
are replicated, each device scans its slice, and cross-shard combination
(the reference's ``Deps.merge`` over PreAccept replies, Deps.java:256) rides
ICI as all-gathers/maxes instead of host fan-in.

Collective pattern per protocol step:
- deps-calc: embarrassingly parallel over slots; dep-mask columns stay
  sharded; per-shard max-conflict is all-gathered and lex-max-reduced.
- drain: row-sharded blocking matrix; each fixpoint sweep all-gathers the
  applied frontier (one small bool vector), does the local masked matvec,
  and contributes its slice of the new frontier — the standard sharded
  matvec recurrence.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.deps_kernel import (SLOT_APPLIED, SLOT_COMMITTED, SLOT_FREE,
                               SLOT_INVALIDATED, SLOT_STABLE, BucketTable,
                               DepsQuery, DepsTable, calculate_deps)
from ..ops.drain_kernel import DrainState
from ..ops.packing import masked_ts_max, ts_lt

STORE_AXIS = "store"


def _shard_map(f, mesh, in_specs, out_specs):
    """jax.shard_map across jax versions: the public ``jax.shard_map``
    (``check_vma``) when present, else the experimental spelling
    (``check_rep``) older jaxes ship."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm
    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(n_devices: int = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(devices, (STORE_AXIS,))


def shard_table(mesh: Mesh, table: DepsTable) -> DepsTable:
    """Place the slot dimension across the mesh; capacity must divide evenly."""
    from ..utils import faults
    faults.check("transfer", "shard_table upload")
    s1 = NamedSharding(mesh, P(STORE_AXIS))
    s2 = NamedSharding(mesh, P(STORE_AXIS, None))
    return DepsTable(
        msb=jax.device_put(table.msb, s1), lsb=jax.device_put(table.lsb, s1),
        node=jax.device_put(table.node, s1), kind=jax.device_put(table.kind, s1),
        status=jax.device_put(table.status, s1),
        lo=jax.device_put(table.lo, s2), hi=jax.device_put(table.hi, s2),
    )


def assemble_slices(mesh: Mesh, shards, shape, two_d: bool = False):
    """Zero-copy assembly of per-device slice buffers into ONE globally
    sharded array (the r21 store-shard residency path): each element of
    ``shards`` is a single-device array already resident on its mesh
    device, and make_array_from_single_device_arrays only records the
    placement — no bytes move.  ``shape`` is the global shape; ``two_d``
    selects the (slot, interval) layout whose second axis is unsharded."""
    spec = P(STORE_AXIS, None) if two_d else P(STORE_AXIS)
    return jax.make_array_from_single_device_arrays(
        tuple(shape), NamedSharding(mesh, spec), list(shards))


def sharded_calculate_deps(mesh: Mesh):
    """Build the pjit-ted cross-shard deps computation for ``mesh``.

    Returns fn(table, query, prune_msb, prune_lsb, prune_node) ->
    (dep_mask bool[B, N] column-sharded, max_conflict (msb, lsb, node)[B]
    replicated).  The prune floor is the store's RedundantBefore watermark,
    replicated to every shard.
    """
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))
    query_specs = DepsQuery(P(), P(), P(), P(), P(None, None), P(None, None),
                            P(), P(), P())

    def local(table: DepsTable, query: DepsQuery, pm, pl, pn):
        dep_mask, (mm, ml, mn) = calculate_deps(table, query, pm, pl, pn)
        # cross-shard Deps.merge: gather every shard's max-conflict candidate
        # and reduce lexicographically (rides ICI; BASELINE.json config #5)
        gm = lax.all_gather(mm, STORE_AXIS, axis=0)   # [n_shards, B]
        gl = lax.all_gather(ml, STORE_AXIS, axis=0)
        gn = lax.all_gather(mn, STORE_AXIS, axis=0)
        nonzero = (gm != 0) | (gl != 0) | (gn != 0)
        mm2, ml2, mn2 = masked_ts_max(gm.swapaxes(0, 1), gl.swapaxes(0, 1),
                                      gn.swapaxes(0, 1), nonzero.swapaxes(0, 1))
        return dep_mask, (mm2, ml2, mn2)

    fn = _shard_map(local, mesh,
                    (table_specs, query_specs, P(), P(), P()),
                    (P(None, STORE_AXIS), (P(), P(), P())))
    jitted = jax.jit(fn)

    def call(table, query, prune_msb=None, prune_lsb=None, prune_node=None):
        if prune_msb is None:
            prune_msb = jnp.zeros((), jnp.int64)
            prune_lsb = jnp.zeros((), jnp.int64)
            prune_node = jnp.zeros((), jnp.int32)
        return jitted(table, query, prune_msb, prune_lsb, prune_node)

    return call


def sharded_drain(mesh: Mesh):
    """Row-sharded fixpoint drain: fn(state) -> (applied[N], newly[N]),
    both replicated on exit."""
    state_specs = DrainState(P(STORE_AXIS, None), P(STORE_AXIS),
                             P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                             P(STORE_AXIS))

    def local(state: DrainState):
        # exec timestamps of potential deps (columns) must be visible to every
        # row shard: gather them once up front.
        full_em = lax.all_gather(state.exec_msb, STORE_AXIS, axis=0, tiled=True)
        full_el = lax.all_gather(state.exec_lsb, STORE_AXIS, axis=0, tiled=True)
        full_en = lax.all_gather(state.exec_node, STORE_AXIS, axis=0, tiled=True)
        full_status = lax.all_gather(state.status, STORE_AXIS, axis=0, tiled=True)
        # blocking matrix with row-local exec vs full-column exec
        undecided = (full_status >= 0) & (full_status < SLOT_COMMITTED)
        dead = (full_status == SLOT_INVALIDATED) | (full_status == SLOT_FREE)
        exec_before = ts_lt(full_em[None, :], full_el[None, :], full_en[None, :],
                            state.exec_msb[:, None], state.exec_lsb[:, None],
                            state.exec_node[:, None])
        blocking = state.adj & (undecided[None, :] | exec_before |
                                state.awaits_all[:, None]) & ~dead[None, :]
        blk = blocking.astype(jnp.bfloat16)

        stable_local = state.status == SLOT_STABLE
        applied_local0 = state.status == SLOT_APPLIED

        def body(carry):
            applied_local, _ = carry
            applied_full = lax.all_gather(applied_local, STORE_AXIS, axis=0,
                                          tiled=True)
            unapplied = (~applied_full).astype(jnp.bfloat16)
            waiting = (blk @ unapplied) > 0.5
            ready = stable_local & ~applied_local & ~waiting
            return applied_local | ready, jnp.any(lax.all_gather(
                ready, STORE_AXIS, axis=0, tiled=True))

        applied_local, _ = lax.while_loop(lambda c: c[1], body,
                                          (applied_local0, jnp.bool_(True)))
        newly_local = applied_local & ~applied_local0
        return applied_local, newly_local

    fn = _shard_map(local, mesh, (state_specs,),
                    (P(STORE_AXIS), P(STORE_AXIS)))
    return jax.jit(fn)


_FRONTIER_CACHE = {}


def sharded_ready_frontier(mesh: Mesh):
    """Row-sharded single frontier sweep — the live ``DeviceState._tick``
    path under a mesh (the fixpoint variant above is ``sharded_drain``; the
    tick wants one sweep because the host re-validates and applies each
    candidate before the next sweep's statuses are known).  fn(state) ->
    ready bool[N] replicated."""
    key = tuple(d.id for d in mesh.devices.flat)
    fn = _FRONTIER_CACHE.get(key)
    if fn is not None:
        return fn
    state_specs = DrainState(P(STORE_AXIS, None), P(STORE_AXIS),
                             P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                             P(STORE_AXIS))

    def local(state: DrainState):
        full_em = lax.all_gather(state.exec_msb, STORE_AXIS, axis=0, tiled=True)
        full_el = lax.all_gather(state.exec_lsb, STORE_AXIS, axis=0, tiled=True)
        full_en = lax.all_gather(state.exec_node, STORE_AXIS, axis=0, tiled=True)
        full_status = lax.all_gather(state.status, STORE_AXIS, axis=0,
                                     tiled=True)
        undecided = (full_status >= 0) & (full_status < SLOT_COMMITTED)
        dead = (full_status == SLOT_INVALIDATED) | (full_status == SLOT_FREE)
        exec_before = ts_lt(full_em[None, :], full_el[None, :], full_en[None, :],
                            state.exec_msb[:, None], state.exec_lsb[:, None],
                            state.exec_node[:, None])
        blocking = state.adj & (undecided[None, :] | exec_before |
                                state.awaits_all[:, None]) & ~dead[None, :]
        applied = full_status == SLOT_APPLIED
        waiting = jnp.any(blocking & ~applied[None, :], axis=1)
        ready_local = (state.status == SLOT_STABLE) & ~waiting
        return lax.all_gather(ready_local, STORE_AXIS, axis=0, tiled=True)

    fn = jax.jit(_shard_map(local, mesh, (state_specs,), P()))
    _FRONTIER_CACHE[key] = fn
    return fn


_FLAT_CACHE = {}


def sharded_calculate_deps_flat(mesh: Mesh, m: int, s: int, k: int,
                                wide: bool = False):
    """Mesh-sharded variant of ops.deps_kernel.calculate_deps_flat: the slot
    dimension lives across the mesh (the reference's CommandStores scatter,
    CommandStores.java:575-643), the query batch is replicated, each device
    scans and CSR-compacts its slice, and the per-shard CSRs concatenate —
    the cross-shard ``Deps.merge`` (Deps.java:256) happens as the host
    merges shard-local slot indices with their shard offsets.

    Returns fn(table_sharded, qmat) -> (header int32[D * (2 + B)],
    entries [D * s]) where each shard block is (total, max_row_count,
    row_end[B]) / (entries[s]) with SHARD-LOCAL triple codes — the host
    fetches headers, then only the live prefix of each shard's entries."""
    from ..ops import deps_kernel as dk
    # key by the mesh's device placement, not just its shape: two equal-
    # shaped meshes with different device orderings must not share a jitted
    # shard_map closed over the first mesh object
    dev_key = tuple(d.id for d in mesh.devices.flat)
    key = (tuple(mesh.shape.items()), dev_key, m, s, k, wide)
    fn = _FLAT_CACHE.get(key)
    if fn is not None:
        return fn
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))

    def local(table: DepsTable, qmat):
        return dk.flat_csr_local(table, qmat, m, s, k, wide=wide)

    fn = jax.jit(_shard_map(local, mesh, (table_specs, P()),
                            (P(STORE_AXIS), P(STORE_AXIS))))
    _FLAT_CACHE[key] = fn
    return fn


_FLATP_CACHE = {}


def sharded_calculate_deps_flat_pruned(mesh: Mesh, m: int, s: int, k: int,
                                       wide: bool = False):
    """sharded_calculate_deps_flat with a device-side RedundantBefore floor:
    the (conservative, batch-global) prune triple is replicated to every
    shard, so entries below the durable watermark never enter any shard's
    CSR — a durable-prefix-dominated store stops shipping redundant history
    off every device (the r05 mesh path hard-disabled this; VERDICT Weak #3).

    Returns fn(table_sharded, qmat, pm, pl, pn) -> (header int32[D*(2+B)],
    entries [D*s]) with SHARD-LOCAL triple codes, same block layout as the
    unpruned variant."""
    from ..ops import deps_kernel as dk
    dev_key = tuple(d.id for d in mesh.devices.flat)
    key = (tuple(mesh.shape.items()), dev_key, m, s, k, wide)
    fn = _FLATP_CACHE.get(key)
    if fn is not None:
        return fn
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))

    def local(table: DepsTable, qmat, pm, pl, pn):
        return dk.flat_csr_local(table, qmat, m, s, k, (pm, pl, pn),
                                 wide=wide)

    fn = jax.jit(_shard_map(local, mesh,
                            (table_specs, P(), P(), P(), P()),
                            (P(STORE_AXIS), P(STORE_AXIS))))
    _FLATP_CACHE[key] = fn
    return fn


_BUCK_CACHE = {}


def sharded_bucketed_flat(mesh: Mesh, m: int, span: int, s: int, k: int,
                          m_t: int = None, keff: int = None,
                          wide: bool = False):
    """Mesh-sharded variant of ops.deps_kernel.bucketed_flat: the bucket
    ROWS (and the wide/straggler list) are row-sharded across the mesh, the
    query batch is replicated, and each shard probes only the bucket rows it
    owns — a query's global bucket-row columns are translated to shard-local
    rows inside the shard_map (rows outside the shard become "no bucket
    here"), so the union of per-shard CSRs is exactly the single-device
    bucketed answer.  Entries carry GLOBAL slot ids inside their overlap
    codes (BucketTable embeds them), so the host merge applies no shard
    offset; a triple whose bucket rows land on different shards can appear
    in several shard blocks — the host-side triple dedupe removes the
    cross-shard duplicates (in-kernel dedupe is per-shard only).  ``m_t``
    is the owning table's interval width (codes scale on it; the mesh local
    has no table to read it from) and ``keff`` the live bucket-occupancy
    slice, both static.

    The prune triple is replicated (pass zeros for no floor, which the
    unsigned ts_lt treats as prune-nothing).  Returns
    fn(buckets_sharded, qmat, pm, pl, pn) -> (header int32[D * (2 + B)],
    entries [D * s])."""
    from ..ops import deps_kernel as dk
    dev_key = tuple(d.id for d in mesh.devices.flat)
    key = (tuple(mesh.shape.items()), dev_key, m, span, s, k, m_t, keff,
           wide)
    fn = _BUCK_CACHE.get(key)
    if fn is not None:
        return fn
    bucket_specs = BucketTable(*([P(STORE_AXIS, None)] * 8),
                               *([P(STORE_AXIS)] * 8))

    def local(buckets: BucketTable, qmat, pm, pl, pn):
        off = lax.axis_index(STORE_AXIS).astype(jnp.int32) \
            * buckets.blo.shape[0]
        return dk.bucketed_flat(None, buckets, qmat, m, span, s, k,
                                (pm, pl, pn), row_offset=off,
                                keff=keff, wide=wide, m_t=m_t)

    fn = jax.jit(_shard_map(local, mesh,
                            (bucket_specs, P(), P(), P(), P()),
                            (P(STORE_AXIS), P(STORE_AXIS))))
    _BUCK_CACHE[key] = fn
    return fn


_FUSEDSH_CACHE = {}


def sharded_fused_flat(mesh: Mesh, n_stores: int, m: int, s: int, k: int,
                       wide: bool = False):
    """Batched-over-stores variant of sharded_calculate_deps_flat — the
    mesh leg of r08 launch coalescing.  Each of the S stores' slot-sharded
    DepsTables rides in as its own (cached, device-resident) sharded
    pytree; inside the shard_map every shard pads its local slices to the
    group maximum (free slots / PAD intervals prune themselves out of the
    mask) and vmaps the exact flat_csr_local trace over the store axis, so
    each store's shard blocks are bit-identical to the solo sharded launch
    they replace.  Per-store prune floors ride as replicated [S] triples
    (zeros prune nothing).

    Returns fn(*tables, qmats, pm, pl, pn) -> (header int32[S, D*(2+B)],
    entries [S, D*s]): store row i holds D shard blocks with SHARD-LOCAL
    triple codes — the host decode offsets slots by the store's OWN
    shard_n (capacity_i / d; padding rows are free and never surface) and
    scales codes on the GROUP interval width m_max."""
    from ..ops import deps_kernel as dk
    dev_key = tuple(d.id for d in mesh.devices.flat)
    key = (dev_key, n_stores, m, s, k, wide)
    fn = _FUSEDSH_CACHE.get(key)
    if fn is not None:
        return fn
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))
    in_specs = tuple([table_specs] * n_stores) + (P(), P(), P(), P())

    def local(*args):
        tables = args[:n_stores]
        qmats, pm, pl, pn = args[n_stores:]
        n_max = max(t.msb.shape[0] for t in tables)
        m_max = max(t.lo.shape[1] for t in tables)
        padded = [dk._pad_table_cols(tuple(t), n_max, m_max)
                  for t in tables]
        stacked = DepsTable(*(jnp.stack(col) for col in zip(*padded)))
        return jax.vmap(
            lambda t, q, a, b, c: dk.flat_csr_local(t, q, m, s, k,
                                                    (a, b, c), wide=wide)
        )(stacked, qmats, pm, pl, pn)

    fn = jax.jit(_shard_map(local, mesh, in_specs,
                            (P(None, STORE_AXIS), P(None, STORE_AXIS))))
    _FUSEDSH_CACHE[key] = fn
    return fn


def shard_bucket_table(mesh: Mesh, buckets: BucketTable) -> BucketTable:
    """Place a BucketTable's bucket-row and wide dimensions across the mesh
    (row counts must divide the device count evenly)."""
    from ..utils import faults
    faults.check("transfer", "shard_bucket_table upload")
    s2 = NamedSharding(mesh, P(STORE_AXIS, None))
    s1 = NamedSharding(mesh, P(STORE_AXIS))
    return BucketTable(*[jax.device_put(a, s2) for a in buckets[:8]],
                       *[jax.device_put(a, s1) for a in buckets[8:]])


def sharded_protocol_step(mesh: Mesh):
    """The fused multi-chip step: deps for a query batch + execution drain.

    This is the unit the driver dry-runs: one device step advancing a sharded
    store through PreAccept deps-calc and the execution frontier.
    """
    deps_fn = sharded_calculate_deps(mesh)
    drain_fn = sharded_drain(mesh)

    def step(table: DepsTable, query: DepsQuery, state: DrainState):
        dep_mask, max_conflict = deps_fn(table, query)
        applied, newly = drain_fn(state)
        return dep_mask, max_conflict, applied, newly

    return step


# -- device-resident attribution (r15): sharded attributed kernels ------------
#
# The attributed variants return ONE merged per-store CSR block instead of D
# per-shard blocks: every shard computes its slice's attributed entries, the
# shard results are all-gathered over ICI, and the cross-shard merge — the
# reference's ``Deps.merge`` — happens ON DEVICE: per-row concatenation in
# (row, code) order via one flat sort, cross-shard dedupe (bucketed only:
# slot-sharded dense slices are disjoint by construction), and a recompacted
# merged row_end.  The host downloads one replicated block (header int32[5+B]
# in the attributed layout, entries int64/int32[d * s]) and hands it straight
# to the shared block finalize — no host-side shard offsetting, no global
# triple dedupe pass.


def _merge_shard_blocks(hdrs, ents, b: int, s: int, codespace: int,
                        dedupe_key_m: int, dom=None, mq: int = None):
    """The on-device cross-shard merge: ``hdrs`` int32[d, 5+B], ``ents``
    [d, s] GLOBAL codes.  ``dedupe_key_m`` > 0 enables the bucketed
    cross-shard dedupe (identical codes + key-domain same-(slot, col)
    runs; needs ``dom``/``mq`` for the key-domain test).  Replicated
    output: (header int32[5+B], entries [d*s])."""
    d = hdrs.shape[0]
    totals = hdrs[:, 0].astype(jnp.int64)
    row_end = hdrs[:, 5:].astype(jnp.int64)                    # [d, B]
    pos = jnp.arange(s, dtype=jnp.int64)
    row_of = jax.vmap(lambda re: jnp.searchsorted(re, pos, side="right"))(
        row_end)                                               # [d, s]
    live = pos[None, :] < totals[:, None]
    inf = jnp.int64(np.iinfo(np.int64).max)
    code = ents.astype(jnp.int64)
    comp = jnp.where(live, row_of * jnp.int64(codespace) + code, inf)
    comp = jnp.sort(comp.reshape(-1))                          # [d*s]
    keep = comp != inf
    if dedupe_key_m:
        first = jnp.concatenate([jnp.ones(1, bool), comp[1:] != comp[:-1]])
        pair = comp // jnp.int64(dedupe_key_m)                 # (row,slot,col)
        firstp = jnp.concatenate([jnp.ones(1, bool), pair[1:] != pair[:-1]])
        mcode = comp % jnp.int64(codespace)
        is_key = dom[jnp.clip(mcode // jnp.int64(mq), 0,
                              dom.shape[0] - 1)] == 0
        keep = keep & first & (~is_key | firstp)
    out_pos = jnp.cumsum(keep) - 1
    merged_row = jnp.where(keep, comp // jnp.int64(codespace), 0)
    counts = jnp.zeros(b, jnp.int64).at[merged_row].add(
        keep.astype(jnp.int64), mode="drop")
    m_end = jnp.cumsum(counts)
    out = jnp.full(d * s, -1, ents.dtype)
    out = out.at[jnp.where(keep, out_pos, d * s)].set(
        (comp % jnp.int64(codespace)).astype(ents.dtype), mode="drop")
    header = jnp.concatenate(
        [jnp.stack([m_end[-1], jnp.max(hdrs[:, 1].astype(jnp.int64)),
                    jnp.max(hdrs[:, 2].astype(jnp.int64)),
                    jnp.sum(hdrs[:, 3].astype(jnp.int64)),
                    jnp.sum(hdrs[:, 4].astype(jnp.int64))]).astype(jnp.int32),
         m_end.astype(jnp.int32)])
    return header, out


_ATTR_SH_CACHE = {}


def sharded_flat_attr(mesh: Mesh, m: int, s: int, k: int,
                      wide: bool = False, floors: bool = True,
                      elide: bool = True):
    """Mesh-sharded calculate_deps_flat_attr: slots sharded, attribution
    columns sharded ALONGSIDE the slots (each shard grades its own slice),
    the floor/elision index and query batch replicated.  Entries are
    globalized in-kernel (local code + shard offset) and merged on device;
    the host sees one block with GLOBAL slot codes.

    Returns fn(table, attr, aidx, qmat, rankb, pm, pl, pn) ->
    (header int32[5+B] replicated, entries [d*s] replicated)."""
    from ..ops import deps_kernel as dk
    dev_key = tuple(d.id for d in mesh.devices.flat)
    key = ("flat", dev_key, m, s, k, wide, floors, elide)
    fn = _ATTR_SH_CACHE.get(key)
    if fn is not None:
        return fn
    d = int(np.prod(list(mesh.shape.values())))
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))
    attr_specs = dk.AttrCols(*([P(STORE_AXIS)] * 9))
    aidx_specs = dk.AttrIndex(*([P()] * 11))

    def local(table, attr, aidx, qmat, rankb, pm, pl, pn):
        hdr, ent = dk.flat_attr_local(table, attr, aidx, qmat, rankb,
                                      m, s, k, (pm, pl, pn), wide=wide,
                                      floors=floors, elide=elide)
        shard_n = table.msb.shape[0]
        m_t = table.lo.shape[1]
        off = lax.axis_index(STORE_AXIS).astype(ent.dtype) \
            * shard_n * m_t * m
        ent = jnp.where(ent >= 0, ent + off, ent)
        hdrs = lax.all_gather(hdr, STORE_AXIS, axis=0)        # [d, 5+B]
        ents = lax.all_gather(ent, STORE_AXIS, axis=0)        # [d, s]
        b = qmat.shape[0]
        codespace = d * shard_n * m_t * m
        return _merge_shard_blocks(hdrs, ents, b, s, codespace, 0)

    fn = jax.jit(_shard_map(local, mesh,
                            (table_specs, attr_specs, aidx_specs,
                             P(), P(), P(), P(), P()),
                            (P(), P())))
    _ATTR_SH_CACHE[key] = fn
    return fn


def sharded_bucketed_attr(mesh: Mesh, m: int, span: int, s: int, k: int,
                          m_t: int, keff: int, wide: bool = False,
                          floors: bool = True, elide: bool = True):
    """Mesh-sharded bucketed_attr: bucket rows and the wide list sharded as
    in sharded_bucketed_flat; the attribution columns are REPLICATED (the
    entries carry global slot ids, and a shard must grade slots whose rows
    it does not own), the floor/elision index replicated.  The on-device
    merge removes cross-shard duplicates (one triple reachable via bucket
    rows on different shards) and applies the key-domain (slot, col) dedupe
    across shards — the host-side global triple dedupe has nothing left to
    do.

    The entry TOKEN (a key dep's own footprint point) lives in the
    slot-sharded interval table, so each shard contributes the tokens of
    the slots it owns and a psum assembles the full per-entry token
    column — the [N, M] interval matrix itself stays sharded.

    Returns fn(buckets, table, attr, aidx, qmat, rankb, pm, pl, pn) ->
    (header int32[5+B] replicated, entries [d*s] replicated)."""
    from ..ops import deps_kernel as dk
    dev_key = tuple(dv.id for dv in mesh.devices.flat)
    key = ("buck", dev_key, m, span, s, k, m_t, keff, wide,
           floors, elide)
    fn = _ATTR_SH_CACHE.get(key)
    if fn is not None:
        return fn
    d = int(np.prod(list(mesh.shape.values())))
    bucket_specs = BucketTable(*([P(STORE_AXIS, None)] * 8),
                               *([P(STORE_AXIS)] * 8))
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))
    attr_specs = dk.AttrCols(*([P()] * 9))
    aidx_specs = dk.AttrIndex(*([P()] * 11))

    def local(buckets, table, attr, aidx, qmat, rankb, pm, pl, pn):
        off = lax.axis_index(STORE_AXIS).astype(jnp.int32) \
            * buckets.blo.shape[0]
        hdr_raw, ent = dk.bucketed_flat(None, buckets, qmat, m, span, s,
                                        k, (pm, pl, pn), row_offset=off,
                                        keff=keff, wide=wide, m_t=m_t)
        # per-entry token via cross-shard psum over the WHOLE gathered
        # entry set: every shard's entries reference global slots, so the
        # codes are all-gathered first, each shard contributes
        # lo[slot, col] for the slots its slice owns (zero elsewhere),
        # and the psum assembles the complete [d, s] token matrix — each
        # shard then attributes its own row
        ents_all = lax.all_gather(ent, STORE_AXIS, axis=0)   # [d, s]
        n_local = table.lo.shape[0]
        soff = lax.axis_index(STORE_AXIS).astype(jnp.int64) * n_local
        code = ents_all.astype(jnp.int64)
        mq = m_t * m
        slot = jnp.clip(code // mq, 0)
        col = jnp.clip(code % mq // m, 0, m_t - 1)
        mine = (slot >= soff) & (slot < soff + n_local) & (code >= 0)
        lslot = jnp.clip(slot - soff, 0, n_local - 1)
        tok_all = lax.psum(jnp.where(mine, table.lo[lslot, col], 0),
                           STORE_AXIS)                       # [d, s]
        me = lax.axis_index(STORE_AXIS)
        hdr, ent = dk._attr_post(None, attr, aidx, rankb, hdr_raw, ent,
                                 m_t, m, floors, elide, tok=tok_all[me])
        hdrs = lax.all_gather(hdr, STORE_AXIS, axis=0)
        ents = lax.all_gather(ent, STORE_AXIS, axis=0)
        b = qmat.shape[0]
        codespace = attr.dom.shape[0] * m_t * m
        return _merge_shard_blocks(hdrs, ents, b, s, codespace,
                                   m, dom=attr.dom, mq=m_t * m)

    fn = jax.jit(_shard_map(local, mesh,
                            (bucket_specs, table_specs, attr_specs,
                             aidx_specs, P(), P(), P(), P(), P()),
                            (P(), P())))
    _ATTR_SH_CACHE[key] = fn
    return fn


def sharded_fused_attr(mesh: Mesh, n_stores: int, m: int, s: int, k: int,
                       wide: bool = False, floors: bool = True,
                       elide: bool = True):
    """Batched-over-stores sharded_flat_attr — the r08 fused launch with
    the attribution pass and the on-device cross-shard merge.  Store row i
    of the outputs is the solo sharded_flat_attr answer for store i (codes
    on the GROUP interval width m_max).

    Returns fn(*tables, *attrs, *aidxs, qmats, rankbs, pm, pl, pn) ->
    (header int32[S, 5+B] replicated, entries [S, d*s] replicated)."""
    from ..ops import deps_kernel as dk
    dev_key = tuple(dv.id for dv in mesh.devices.flat)
    key = ("fused", dev_key, n_stores, m, s, k, wide, floors, elide)
    fn = _ATTR_SH_CACHE.get(key)
    if fn is not None:
        return fn
    d = int(np.prod(list(mesh.shape.values())))
    table_specs = DepsTable(P(STORE_AXIS), P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS), P(STORE_AXIS),
                            P(STORE_AXIS, None), P(STORE_AXIS, None))
    attr_specs = dk.AttrCols(*([P(STORE_AXIS)] * 9))
    aidx_specs = dk.AttrIndex(*([P()] * 11))
    in_specs = tuple([table_specs] * n_stores) \
        + tuple([attr_specs] * n_stores) \
        + tuple([aidx_specs] * n_stores) + (P(), P(), P(), P(), P())

    def local(*args):
        tables = args[:n_stores]
        attrs = args[n_stores:2 * n_stores]
        aidxs = args[2 * n_stores:3 * n_stores]
        qmats, rankbs, pm, pl, pn = args[3 * n_stores:]
        n_max = max(t.msb.shape[0] for t in tables)
        m_max = max(t.lo.shape[1] for t in tables)
        f_max = max(a.fbnd.shape[0] for a in aidxs)
        t_max = max(a.etok.shape[0] for a in aidxs)
        l_max = max(a.erank.shape[0] for a in aidxs)
        padded = [dk._pad_table_cols(tuple(t), n_max, m_max)
                  for t in tables]
        stacked = DepsTable(*(jnp.stack(col) for col in zip(*padded)))
        pa = [dk._pad_attr_cols(tuple(a), n_max) for a in attrs]
        stacked_a = dk.AttrCols(*(jnp.stack(col) for col in zip(*pa)))
        pi = [dk._pad_attr_index(a, f_max, t_max, l_max) for a in aidxs]
        stacked_i = dk.AttrIndex(*(jnp.stack(col) for col in zip(*pi)))
        hdr, ent = jax.vmap(
            lambda t, a, i, q, r, x, y, z: dk.flat_attr_local(
                t, a, i, q, r, m, s, k, (x, y, z), wide=wide,
                floors=floors, elide=elide)
        )(stacked, stacked_a, stacked_i, qmats, rankbs, pm, pl, pn)
        off = lax.axis_index(STORE_AXIS).astype(ent.dtype) \
            * n_max * m_max * m
        ent = jnp.where(ent >= 0, ent + off, ent)
        hdrs = lax.all_gather(hdr, STORE_AXIS, axis=0)       # [d, S, 5+B]
        ents = lax.all_gather(ent, STORE_AXIS, axis=0)       # [d, S, s]
        b = qmats.shape[1]
        codespace = d * n_max * m_max * m
        return jax.vmap(
            lambda h, e: _merge_shard_blocks(h, e, b, s, codespace, 0)
        )(hdrs.swapaxes(0, 1), ents.swapaxes(0, 1))

    fn = jax.jit(_shard_map(local, mesh, in_specs, (P(), P())))
    _ATTR_SH_CACHE[key] = fn
    return fn
