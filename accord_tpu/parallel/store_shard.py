"""Store-sharded device tables (r21): ONE store's slot table partitioned
across the mesh, each device owning a contiguous slot slice.

The r05+ mesh route already *computes* sharded (shard_map over a sharded
upload), but the upload itself was monolithic: any mutation re-shipped the
WHOLE table through ``device_table_sharded``, and the HBM budget ladder
treated a single chip's budget as the store's ceiling — one store could
never outgrow one device.  This module gives the mirror a second, sliced
residency:

- Per-slice buffers.  Slice ``i`` owns slots ``[i*slice_n, (i+1)*slice_n)``
  and keeps its own single-device ``DepsTable`` / ``AttrCols`` shard on
  mesh device ``i``.  Registrations scatter to the OWNING slice only (the
  same fused dirty-row jit the single-device mirror uses, dispatched on the
  slice's device), so steady-state sync cost is O(dirty rows), not
  O(capacity).
- Zero-copy assembly.  The sharded kernels consume one global jax.Array;
  ``sharded.assemble_slices`` stitches the resident slices into it without
  moving bytes, so the collective merge path (all-gather +
  ``_merge_shard_blocks``, global slot codes, one replicated download) is
  exactly the one the attributed mesh kernels already run.
- Per-slice fault ladder.  A device-boundary failure during a sliced flush
  quarantines the SLICE it touched (exponential backoff in flushes, seeded
  jitter — the r07 ladder's shape, one instance per slice).  While a slice
  is quarantined its status shard is masked to SLOT_FREE in the assembled
  table, so healthy slices keep answering on device and the sick slice's
  slots answer from the host twin, byte-identically (the builders' finalize
  is entry-order-insensitive and dedupes, so device + host-twin entry sets
  concatenate safely).  One sick chip degrades a slice, not the node.

Activation is a budget-ladder rung (DeviceState._approve_grow): breach ->
compact -> SPILL TO SHARDED (when a mesh is available) -> host-pinned.
``ACCORD_TPU_STORE_SHARD=off`` disables the rung (and the conftest canary
asserts tier-1 stays green without it).
"""

from __future__ import annotations

import os
from typing import List, Optional

import numpy as np

import jax

from ..ops import deps_kernel as dk
from ..utils import faults
from ..utils.random_source import RandomSource

# flushes a slice stays quarantined after its first failure / ceiling —
# the same ladder constants the whole-device quarantine uses
_BACKOFF_BASE = 4
_BACKOFF_MAX = 256


def store_shard_enabled() -> bool:
    """The escape hatch: ``ACCORD_TPU_STORE_SHARD=off`` (or 0/false/no)
    removes the spill-to-sharded rung — the ladder degrades straight to
    host-pinned, pre-r21 behavior."""
    return os.environ.get("ACCORD_TPU_STORE_SHARD", "").lower() \
        not in ("off", "0", "false", "no")


def _pow2_at_least(n: int, floor: int) -> int:
    out = floor
    while out < n:
        out *= 2
    return out


class StoreShards:
    """Sliced device residency for one store's ``_DepsMirror`` plus the
    per-slice quarantine ladder.  Owned by a DeviceState (which holds the
    counters and fault-event plumbing); the mirror routes
    ``device_table_sharded`` / ``device_attr_cols_sharded`` through here
    while ``active``."""

    def __init__(self, owner, mirror, mesh):
        self.owner = owner          # DeviceState (counters + fault events)
        self.mirror = mirror
        self.mesh = mesh
        self.devices = list(mesh.devices.flat)
        self.d = len(self.devices)
        self.active = False
        # per-slice residency
        self._tables: List[Optional[dk.DepsTable]] = [None] * self.d
        self._attrs: List[Optional[dk.AttrCols]] = [None] * self.d
        self._shape = None          # (capacity, max_intervals) slices match
        self._attr_cap = None
        self._gen = 0               # bumps on any slice table upload
        self._attr_gen = 0
        self._asm = None            # cached assembled DepsTable
        self._asm_key = None
        self._attr_asm = None
        self._attr_asm_key = None
        self._free_masks = {}       # slice_n -> per-device SLOT_FREE shard
        # per-slice quarantine ladder (the r07 state machine, one per
        # slice); jitter seeded from the owner's so schedules are
        # deterministic yet distinct per (node, store, slice)
        self.quar = [0] * self.d            # remaining quarantined flushes
        self.backoff = [0] * self.d
        self.suspect = [False] * self.d     # countdown expired: probing
        node_id = getattr(getattr(owner.store, "node", None), "node_id", 0)
        self._jitter = RandomSource(
            0x5117CE ^ (node_id << 16)
            ^ getattr(owner.store, "store_id", 0))
        # the slice a device-boundary failure should be attributed to: set
        # before every per-slice upload (the only per-slice crossing), read
        # by slice_fault when the flush's failure reaches _device_fault
        self.last_slice_touched: Optional[int] = None

    # -- activation ------------------------------------------------------
    def activate(self) -> None:
        if self.active:
            return
        self.active = True
        self.mirror.shards = self
        self._shape = None          # full per-slice build on next table()
        self._attr_cap = None
        self.mirror._dirty_sh.clear()
        self.mirror._attr_dirty_sh.clear()

    def deactivate(self) -> None:
        self.active = False
        if self.mirror.shards is self:
            self.mirror.shards = None
        self._tables = [None] * self.d
        self._attrs = [None] * self.d
        self._asm = self._attr_asm = None
        self._shape = self._attr_cap = None

    # -- slot-slice geometry ---------------------------------------------
    def slice_n(self) -> int:
        # capacity and d are both powers of two with capacity >= 64 >= d
        return self.mirror.capacity // self.d

    def slice_of(self, slot: int) -> int:
        return min(slot // self.slice_n(), self.d - 1)

    # -- per-slice sync --------------------------------------------------
    def _full_slice(self, i: int) -> None:
        m = self.mirror
        sn = m.capacity // self.d
        lo, hi = i * sn, (i + 1) * sn
        dev = self.devices[i]
        self.last_slice_touched = i
        faults.check("transfer", f"slice {i} slot upload")
        self._tables[i] = dk.DepsTable(
            jax.device_put(m.msb[lo:hi], dev),
            jax.device_put(m.lsb[lo:hi], dev),
            jax.device_put(m.node[lo:hi], dev),
            jax.device_put(m.kind[lo:hi], dev),
            jax.device_put(m.status[lo:hi], dev),
            jax.device_put(m.lo[lo:hi], dev),
            jax.device_put(m.hi[lo:hi], dev))

    def _scatter_slice(self, i: int, rows: np.ndarray) -> None:
        """Dirty-row sync of slice ``i`` (rows are GLOBAL slot indices all
        owned by the slice).  The committed slice table pins the jit
        dispatch to the slice's device; >= half-dirty re-uploads whole."""
        m = self.mirror
        sn = m.capacity // self.d
        if self._tables[i] is None or len(rows) * 2 >= sn:
            self._full_slice(i)
            return
        self.last_slice_touched = i
        faults.check("transfer", f"slice {i} slot upload")
        padded = _pow2_at_least(len(rows), 8)
        rows_p = np.concatenate(
            [rows, np.full(padded - len(rows), rows[-1], np.int64)])
        local = (rows_p - i * sn).astype(np.int32)
        self._tables[i] = dk.scatter_table_rows(
            self._tables[i], jax.device_put(local, self.devices[i]),
            m.msb[rows_p], m.lsb[rows_p], m.node[rows_p], m.kind[rows_p],
            m.status[rows_p], m.lo[rows_p], m.hi[rows_p])

    def _sync_tables(self) -> None:
        m = self.mirror
        shape = (m.capacity, m.max_intervals)
        if shape != self._shape:
            # capacity / interval growth redistributes slots across slices
            # (slot // slice_n changes wholesale): full per-slice rebuild
            m._dirty_sh.clear()
            for i in range(self.d):
                self._full_slice(i)
            self._shape = shape
            self._gen += 1
            return
        if not m._dirty_sh:
            return
        rows = np.array(sorted(m._dirty_sh), np.int64)
        m._dirty_sh.clear()
        sn = m.capacity // self.d
        sl = rows // sn
        for i in range(self.d):
            ri = rows[sl == i]
            if len(ri):
                self._scatter_slice(i, ri)
        self._gen += 1

    def _attr_slice_cols(self, i: int):
        m = self.mirror
        sn = m.capacity // self.d
        lo, hi = i * sn, (i + 1) * sn
        return (m.domain[lo:hi].astype(np.int32), m.status[lo:hi],
                m.msb[lo:hi], m.lsb[lo:hi], m.node[lo:hi],
                m.emsb[lo:hi], m.elsb[lo:hi], m.enode[lo:hi],
                m.eknown[lo:hi])

    def _full_attr_slice(self, i: int) -> None:
        dev = self.devices[i]
        self.last_slice_touched = i
        faults.check("transfer", f"slice {i} attr upload")
        self._attrs[i] = dk.AttrCols(
            *(jax.device_put(a, dev) for a in self._attr_slice_cols(i)))

    def _sync_attrs(self) -> None:
        m = self.mirror
        if m.capacity != self._attr_cap:
            m._attr_dirty_sh.clear()
            for i in range(self.d):
                self._full_attr_slice(i)
            self._attr_cap = m.capacity
            self._attr_gen += 1
            return
        if not m._attr_dirty_sh:
            return
        rows = np.array(sorted(m._attr_dirty_sh), np.int64)
        m._attr_dirty_sh.clear()
        sn = m.capacity // self.d
        sl = rows // sn
        for i in range(self.d):
            ri = rows[sl == i]
            if not len(ri):
                continue
            if self._attrs[i] is None or len(ri) * 2 >= sn:
                self._full_attr_slice(i)
                continue
            self.last_slice_touched = i
            faults.check("transfer", f"slice {i} attr upload")
            padded = _pow2_at_least(len(ri), 8)
            rows_p = np.concatenate(
                [ri, np.full(padded - len(ri), ri[-1], np.int64)])
            local = (rows_p - i * sn).astype(np.int32)
            self._attrs[i] = dk.scatter_attr_cols(
                self._attrs[i], jax.device_put(local, self.devices[i]),
                m.domain[rows_p].astype(np.int32), m.status[rows_p],
                m.msb[rows_p], m.lsb[rows_p], m.node[rows_p],
                m.emsb[rows_p], m.elsb[rows_p], m.enode[rows_p],
                m.eknown[rows_p])
        self._attr_gen += 1

    # -- assembled (globally sharded) views ------------------------------
    def _free_status(self, i: int, sn: int):
        """Cached SLOT_FREE status shard for a quarantined slice: masked
        slots emit nothing from the dep mask, so the host twin is the sole
        authority for them — byte-identity by construction."""
        per = self._free_masks.get(sn)
        if per is None:
            # capacity grew: masks for the old slice width are useless
            self._free_masks = {sn: [None] * self.d}
            per = self._free_masks[sn]
        if per[i] is None:
            per[i] = jax.device_put(
                np.full(sn, dk.SLOT_FREE, np.int32), self.devices[i])
        return per[i]

    def table(self) -> dk.DepsTable:
        """The globally sharded DepsTable the mesh kernels consume, with
        quarantined slices' status masked to SLOT_FREE.  Assembly is
        zero-copy over the resident slices; the cache keys on the upload
        generation and the quarantine mask."""
        self._sync_tables()
        m = self.mirror
        qmask = tuple(q > 0 for q in self.quar)
        key = (self._gen, self._shape, qmask)
        if self._asm is not None and self._asm_key == key:
            return self._asm
        from .sharded import assemble_slices
        sn = m.capacity // self.d
        tabs = self._tables
        status = [self._free_status(i, sn) if qmask[i] else tabs[i].status
                  for i in range(self.d)]
        cap, m_iv = m.capacity, m.max_intervals
        self._asm = dk.DepsTable(
            assemble_slices(self.mesh, [t.msb for t in tabs], (cap,)),
            assemble_slices(self.mesh, [t.lsb for t in tabs], (cap,)),
            assemble_slices(self.mesh, [t.node for t in tabs], (cap,)),
            assemble_slices(self.mesh, [t.kind for t in tabs], (cap,)),
            assemble_slices(self.mesh, status, (cap,)),
            assemble_slices(self.mesh, [t.lo for t in tabs],
                            (cap, m_iv), two_d=True),
            assemble_slices(self.mesh, [t.hi for t in tabs],
                            (cap, m_iv), two_d=True))
        self._asm_key = key
        return self._asm

    def attr_cols(self) -> dk.AttrCols:
        """The slot-sharded AttrCols twin of table().  No masking needed:
        attribution only grades entries the dep mask emitted, and masked
        slots emit nothing."""
        self._sync_attrs()
        key = (self._attr_gen, self._attr_cap)
        if self._attr_asm is not None and self._attr_asm_key == key:
            return self._attr_asm
        from .sharded import assemble_slices
        cap = self._attr_cap
        self._attr_asm = dk.AttrCols(
            *(assemble_slices(self.mesh, [a[f] for a in self._attrs],
                              (cap,))
              for f in range(9)))
        self._attr_asm_key = key
        return self._attr_asm

    # -- per-slice quarantine ladder -------------------------------------
    def tick_flush(self) -> None:
        """One sharded flush is passing the gate: quarantined slices count
        it down; a slice whose countdown expires becomes a SUSPECT — it
        rejoins the device mask, and the flush that includes it is its
        probe (note_success restores, a failure re-quarantines deeper)."""
        for i in range(self.d):
            if self.quar[i] > 0:
                self.quar[i] -= 1
                if self.quar[i] == 0:
                    self.suspect[i] = True
                    self.owner._fault_event("slice.reprobe", f"slice={i}")

    def any_quarantined(self) -> bool:
        return any(q > 0 for q in self.quar)

    def quarantined_slices(self) -> List[int]:
        return [i for i in range(self.d) if self.quar[i] > 0]

    def quarantined_slot_mask(self, cj: np.ndarray) -> np.ndarray:
        """bool mask over GLOBAL slot indices: True where the owning slice
        is quarantined (those entries come from the host twin)."""
        sn = self.mirror.capacity // self.d
        q = np.array([qq > 0 for qq in self.quar], bool)
        return q[np.clip(cj // sn, 0, self.d - 1)]

    def slice_fault(self, kind: str, detail: str = "") -> None:
        """Attribute one device-boundary failure to a slice and quarantine
        it: the slice whose upload was in flight when the failure fired,
        else a probing suspect (its probe failed), else a deterministic
        jitter pick (collects after a merged download can't localize)."""
        i = self.last_slice_touched
        if i is None:
            sus = [s for s in range(self.d) if self.suspect[s]]
            i = sus[0] if sus else self._jitter.next_int(self.d)
        self.last_slice_touched = None
        self.suspect[i] = False
        self.backoff[i] = min(self.backoff[i] + 1, 8)
        base = min(_BACKOFF_BASE << (self.backoff[i] - 1), _BACKOFF_MAX)
        self.quar[i] = base + self._jitter.next_int(max(base // 2, 1))
        self.owner.n_slice_quarantines += 1
        self.owner._fault_event(
            "slice.quarantine",
            f"slice={i} {kind} backoff={self.quar[i]}")

    def note_success(self) -> None:
        """A sharded flush completed end-to-end on device: every probing
        suspect slice is healthy again."""
        for i in range(self.d):
            if self.suspect[i]:
                self.suspect[i] = False
                self.backoff[i] = 0
                self.owner.n_slice_restores += 1
                self.owner._fault_event("slice.restore", f"slice={i}")
        self.last_slice_touched = None
