"""One WAL segment: length+CRC32-framed records in a fixed-size file.

The frame is the journal's only on-disk unit::

    [u32 length][u32 crc32(payload)][payload bytes]

(big-endian, crc32 over the payload only).  A segment starts with a HEADER
frame — canonical JSON ``{"magic", "ver", "seg", "base"}`` — so a scan can
re-derive the segment's index and base sequence number without trusting the
filename, and every later frame is one record payload.

Torn-tail discipline (the crash contract): a kill -9 / power cut may leave
the final frame partially written.  ``scan`` walks frames until the first
one that is short, oversized or CRC-mismatched and reports that offset;
the caller truncates there (``Segment.open_existing``), so a reopened
segment ends at the last VERIFIED frame — a torn tail can lose the
unacknowledged tail records but can never mis-replay bytes as a record.

Every physical I/O consults the seedable disk faults in ``utils.faults``
(torn_write / short_read / failed_fsync), the storage-boundary analogue of
the r07 device faults: draws come only from the injected RandomSource, so
a seeded fault run replays the exact same torn bytes.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import List, Optional, Tuple

from ..utils import faults

MAGIC = "accwal"
VERSION = 1
_HDR = struct.Struct(">II")          # (length, crc32)
# a frame length beyond this is garbage, not a record (same defensive
# posture as net.framing.MAX_FRAME: never allocate from untrusted bytes)
MAX_RECORD = 64 * 1024 * 1024


class SegmentError(RuntimeError):
    """A segment file violates the format in a way truncation can't fix
    (bad magic / unknown version): the operator must intervene."""


def frame(payload: bytes) -> bytes:
    if len(payload) > MAX_RECORD:
        raise SegmentError(f"record of {len(payload)} bytes exceeds "
                           f"MAX_RECORD={MAX_RECORD}")
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def header_payload(seg_index: int, base_seq: int) -> bytes:
    return json.dumps({"magic": MAGIC, "ver": VERSION, "seg": seg_index,
                       "base": base_seq},
                      sort_keys=True, separators=(",", ":")).encode()


def parse_header(payload: bytes) -> Tuple[int, int]:
    doc = json.loads(payload.decode())
    if doc.get("magic") != MAGIC:
        raise SegmentError(f"bad segment magic {doc.get('magic')!r}")
    if doc.get("ver") != VERSION:
        raise SegmentError(f"unknown segment version {doc.get('ver')!r}")
    return int(doc["seg"]), int(doc["base"])


def _read_all(path: str) -> bytes:
    """Whole-file read with the short_read fault at the boundary: a fired
    fault returns a drawn prefix (the transient-I/O shape recovery must
    absorb as an unreadable tail)."""
    with open(path, "rb") as f:
        data = f.read()
    if faults.disk_fault_fires("short_read"):
        cut = int(len(data) * faults.disk_fault_fraction("short_read"))
        return data[:cut]
    return data


def scan(path: str) -> Tuple[Optional[Tuple[int, int]], List[bytes], int, int]:
    """Walk one segment's frames.

    Returns ``(header, payloads, valid_end, file_size)``: the parsed
    ``(seg_index, base_seq)`` header (None if even the header frame is
    unreadable — an empty/torn-at-birth segment), the record payloads in
    order, the byte offset just past the last VALID frame (the truncation
    point for a torn tail) and the actual size read."""
    data = _read_all(path)
    size = len(data)
    off = 0
    header: Optional[Tuple[int, int]] = None
    payloads: List[bytes] = []
    first = True
    while True:
        if off + _HDR.size > size:
            break
        length, crc = _HDR.unpack_from(data, off)
        if length > MAX_RECORD or off + _HDR.size + length > size:
            break
        payload = data[off + _HDR.size: off + _HDR.size + length]
        if zlib.crc32(payload) != crc:
            break
        if first:
            try:
                header = parse_header(payload)
            except (SegmentError, ValueError, KeyError):
                break
            first = False
        else:
            payloads.append(payload)
        off += _HDR.size + length
    return header, payloads, off, size


class Segment:
    """One open-for-append segment.  Writes go straight to the OS (the
    group commit's batching window is the only buffering layer the journal
    has — a second user-space buffer would just double the torn surface);
    ``sync`` is the durability point."""

    def __init__(self, path: str, seg_index: int, base_seq: int,
                 fobj: io.FileIO, size: int, last_seq: int):
        self.path = path
        self.seg_index = seg_index
        self.base_seq = base_seq
        self.last_seq = last_seq        # highest record seq written here
        self._f = fobj
        self.size = size

    # -- creation / reopen ---------------------------------------------------
    @classmethod
    def create(cls, path: str, seg_index: int, base_seq: int) -> "Segment":
        f = open(path, "wb")
        hdr = frame(header_payload(seg_index, base_seq))
        f.write(hdr)
        return cls(path, seg_index, base_seq, f, len(hdr), base_seq - 1)

    @classmethod
    def open_existing(cls, path: str, last_seq: int) -> "Segment":
        """Reopen a scanned segment for append, truncating any torn tail
        first (``scan`` already decided where the last valid frame ends)."""
        header, _payloads, valid_end, size = scan(path)
        if header is None:
            raise SegmentError(f"{path}: unreadable segment header")
        f = open(path, "r+b")
        if valid_end < size:
            f.truncate(valid_end)
        f.seek(valid_end)
        return cls(path, header[0], header[1], f, valid_end, last_seq)

    # -- append / sync -------------------------------------------------------
    def append(self, payload: bytes, seq: int) -> None:
        buf = frame(payload)
        if faults.disk_fault_fires("torn_write"):
            # persist only a drawn prefix, then surface the failure: the
            # in-process analogue of dying mid-write (the next reopen must
            # truncate this tail via the CRC scan)
            cut = int(len(buf) * faults.disk_fault_fraction("torn_write"))
            self._f.write(buf[:cut])
            self._f.flush()
            self.size += cut
            raise faults.TornWriteFault(
                f"injected torn write: {cut}/{len(buf)} bytes of seq {seq}")
        self._f.write(buf)
        self.size += len(buf)
        self.last_seq = seq

    def sync(self) -> None:
        fsync_file(self._f, self.path)

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


def fsync_file(f, path: str = "") -> None:
    """flush + fsync one open file, honoring the injected fsync fault.
    Safe to call from a worker thread while the owning event loop keeps
    appending: the fsync covers at least every byte written before the
    flush, which is all the caller's captured tail promises."""
    f.flush()
    if faults.disk_fault_fires("failed_fsync"):
        raise faults.FailedFsyncFault(f"injected fsync failure on {path}")
    os.fsync(f.fileno())


def fsync_dir(path: str) -> None:
    """Durably record directory-level changes (segment create/rename):
    without this a crash can lose the file NAME even though its bytes were
    fsynced."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)
