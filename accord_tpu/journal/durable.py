"""DurableJournal: the on-disk incarnation of the message-sourced journal.

``local/journal.py`` keeps the reference's split — fixed-width registers
per command plus the side-effecting message bodies everything else
reconstructs from — but lives in process memory, so a kill -9 forgets
every committed transaction.  :class:`DurableJournal` subclasses it and
makes every ``record_*`` fact ALSO a WAL record (wire-codec payloads —
the same serde the golden-frame loopback tests prove round-trips
byte-identically), so the in-memory semantics the sim's determinism
tiers pin are untouched while the facts become crash-durable:

====  =====================================================
kind  fact
====  =====================================================
msg   a side-effecting request witnessed (Node._process)
prop  a local knowledge upgrade (merged CheckStatusOk)
reg   one command's fixed-width registers on one store
wm    a store's durable/redundant watermark snapshot
bs*   bootstrap started / fenced-at / done
hlc   flush-before-issue HLC reservation (synchronous fsync)
reply a client txn reply owed/answered (at-most-once table)
apply one data-store append (token, values, executeAt, txn)
====  =====================================================

Group commit (`journal/commit.py`) batches the fsyncs; snapshots
(`journal/snapshot.py`) bound replay and recycle dead segments; recovery
(`journal/recover.py`) rebuilds this object from disk so ``Node`` takes
it through the exact ``journal=`` parameter and ``restore()`` path the
sim's restart tests already exercise.
"""

from __future__ import annotations

import os
import sys
from collections import deque
from typing import Dict, List, Optional, Tuple

from .. import wire
from ..local.journal import Journal, _Bodies, _Registers
from ..local.status import SaveStatus
from ..primitives.timestamp import TxnId
from ..sim.kvstore import KVDataStore
from .commit import GroupCommit
from .wal import DEFAULT_SEGMENT_BYTES, WriteAheadLog

# client-reply dedupe horizon (same shape as net.client's SEEN_CAP): a
# duplicate request arrives within the client's retry horizon, so the
# most recent replies keep the at-most-once contract exact while a soak
# can't grow the table forever
REPLIED_CAP = 65536
# WAL records between snapshots.  The interval exists to bound the
# kill -9 rejoin wall (replay = records x replay rate) against the cost
# of a whole-state capture; r13 set 8192 against ~4.8k records/s of JSON
# replay, and the r16 binary record codec replays ~5x faster — same
# rejoin bound, 4x fewer whole-state walks (each is O(total state), the
# dominant journal tax once command state has grown)
DEFAULT_SNAPSHOT_EVERY = 32768


class DurableJournal(Journal):
    """On-disk journal.  Construction RECOVERS: any snapshot + WAL tail
    already in ``directory`` is loaded and replayed before the first new
    record lands (``replay_stats`` reports what came back)."""

    # what must be fsync-durable BEFORE which acknowledgement leaves:
    #
    # - "all":    every protocol reply gates on the batch fsync — the
    #   strict mode: a promise (PreAcceptOk witness, AcceptReply ballot)
    #   survives even a whole-box power loss.  Costs one group-commit
    #   cycle per protocol hop; on a slow-fsync filesystem that is the
    #   dominant serving cost.
    # - "client": only the client's ``txn_ok`` gates (default) — the
    #   user-visible durability promise holds ("acked => this txn's
    #   journal records are on disk at the answering node"), protocol
    #   replies ride on write()-to-page-cache.  A kill -9 (process
    #   death) loses NOTHING either way — the page cache survives the
    #   process — so crash recovery is identical; what "client" gives up
    #   is per-hop power-loss durability of un-acked protocol promises,
    #   where replication across nodes is the actual safety story
    #   (the same trade Cassandra's default periodic commitlog makes).
    # - "periodic": nothing gates; the batching window bounds the
    #   fsync lag.  Benchmarks and bulk loads.
    SYNC_POLICIES = ("all", "client", "periodic")

    def __init__(self, directory: str, *,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 snapshot_every: int = DEFAULT_SNAPSHOT_EVERY,
                 window_micros: Optional[int] = None,
                 defer=None, metrics=None, async_exec=None,
                 sync_policy: str = "client",
                 debug_capture: bool = False):
        super().__init__()
        if sync_policy not in self.SYNC_POLICIES:
            raise ValueError(f"sync_policy {sync_policy!r} not in "
                             f"{self.SYNC_POLICIES}")
        self.directory = directory
        self.metrics = metrics
        self.sync_policy = sync_policy
        self.snapshot_every = snapshot_every
        self._replaying = False
        self._snap_inflight = False
        self.replay_errors = 0
        # at-most-once client replies: (src, msg_id) -> reply body
        self.replied: Dict[Tuple[str, int], dict] = {}
        self._replied_order: deque = deque()
        # data-store appends recovered from disk, installed into the fresh
        # KVDataStore by install_data() before the node's restore() runs
        self._restored_data: Dict[int, List[tuple]] = {}
        self.debug_records: Optional[List[dict]] = [] if debug_capture \
            else None
        self.wal = WriteAheadLog(directory, segment_bytes=segment_bytes)
        self.commit = GroupCommit(self.wal, defer=defer,
                                  window_micros=window_micros,
                                  metrics=metrics, async_exec=async_exec)
        # r16: register rows are LATEST-WINS facts (replay installs the
        # last row per (store, txn)), so one group-commit window's worth
        # of transitions for one command serializes once, drained into
        # the batch by the commit's pre_flush hook.  Crash-equivalent:
        # everything appended since the last flush dies together anyway
        # (the r13 crash sweep already pins message-present/register-
        # stale truncation points as valid recovery states).
        self._pending_regs: Dict[tuple, object] = {}
        self.commit.pre_flush = self._drain_pending_registers
        self.commit.deferred_pending = lambda: bool(self._pending_regs)
        from . import recover as recover_mod
        self.replay_stats = recover_mod.replay(self)
        self._snap_floor = self.replay_stats["snapshot_floor"]

    # -- append plumbing -----------------------------------------------------
    def _append(self, doc: dict) -> None:
        if self._replaying:
            return
        try:
            seq = self.commit.append(doc)
        except Exception as exc:   # an unencodable payload must never
            self.replay_errors += 1   # take the node down
            print(f"[journal] append failed for kind "
                  f"{doc.get('k')!r}: {exc!r}", file=sys.stderr)
            return
        if seq is None:
            return   # degraded: the record never landed
        if self.metrics is not None:
            self.metrics.counter("journal_records", kind=doc["k"]).inc()
        if self.debug_records is not None:
            self.debug_records.append(dict(doc, s=seq))

    def has_restored_state(self) -> bool:
        return bool(self._registers or self._bodies or self._restored_data
                    or self.replied or self.hlc_reserved or self.max_hlc
                    or self._topologies)

    def gate_protocol_replies(self) -> bool:
        return self.sync_policy == "all"

    def gate_client_replies(self) -> bool:
        return self.sync_policy in ("all", "client")

    # -- recorded facts (each: WAL first, then the in-memory semantics) ------
    def record_message(self, request, from_id: int) -> None:
        if not self.restoring and not self._replaying:
            txn_id = getattr(request, "txn_id", None)
            if txn_id is not None \
                    and not request.type.name.startswith("PROPAGATE"):
                # PROPAGATE journals through record_propagate below (the
                # base class routes it there; journaling here too would
                # double-record the fact)
                try:
                    # r16: a request that arrived over the wire carries
                    # its own encoded doc (decode∘encode is the identity,
                    # pinned by the golden-frame gate) — re-encoding the
                    # whole payload tree per record was a first-order
                    # journal tax on the serving path
                    doc = getattr(request, "_wire_doc", None)
                    self._append({"k": "msg", "f": from_id,
                                  "p": doc if doc is not None
                                  else wire.encode(request)})
                except TypeError as exc:
                    # a side-effecting verb without a wire codec: loud
                    # once, never fatal (the in-memory journal still
                    # records it; only durability is lost for this verb)
                    self.replay_errors += 1
                    print(f"[journal] no codec for "
                          f"{type(request).__name__}: {exc}",
                          file=sys.stderr)
        super().record_message(request, from_id)

    def record_propagate(self, txn_id, ok) -> None:
        if not self.restoring and not self._replaying:
            self._append({"k": "prop", "t": wire.encode(txn_id),
                          "ok": wire.encode(ok)})
        super().record_propagate(txn_id, ok)

    def record_registers(self, store_id: int, command) -> None:
        if not self._replaying:
            if self.commit.failed:
                # degraded journal: no window ever drains again, so a
                # parked Command per (store, txn) would leak forever on
                # exactly the degraded-but-alive node the bounded-memory
                # contract covers
                self._pending_regs.clear()
            else:
                # park the command snapshot (immutable value object): the
                # window-close drain serializes only the LAST row per
                # (store, txn) — back-to-back transitions (commit+stable
                # in one message) cost one WAL record, not one each
                self._pending_regs[(store_id, command.txn_id)] = command
                self.commit.schedule_window()
        super().record_registers(store_id, command)

    def _drain_pending_registers(self) -> None:
        if not self._pending_regs:
            return
        pend, self._pending_regs = self._pending_regs, {}
        for (store_id, _txn_id), command in pend.items():
            # columnar v2 row: raw (msb, lsb, node) triples + enum NAMES
            # instead of six generic wire.encode walks — reg rows are
            # over half the WAL's records, and this was the serving
            # path's biggest per-record cost.  apply_record keeps the
            # r13 keyed shape decoding forever (journals outlive code).
            t = command.txn_id
            ex = command.execute_at
            pr = command.promised
            ac = command.accepted
            self._append({"k": "reg", "c": [
                store_id, [t.msb, t.lsb, t.node],
                command.save_status.name,
                # executeAt may literally BE the TxnId (the fast path);
                # a 4th element tags that so replay rebuilds the exact
                # type the live journal held (byte-identity contract)
                None if ex is None else
                ([ex.msb, ex.lsb, ex.node, 1] if isinstance(ex, TxnId)
                 else [ex.msb, ex.lsb, ex.node]),
                None if pr is None else [pr.msb, pr.lsb, pr.node],
                None if ac is None else [ac.msb, ac.lsb, ac.node],
                command.durability.name]})

    def record_watermarks(self, store_id: int, durable_entries: list,
                          redundant_entries: list) -> None:
        if not self._replaying:
            self._append({"k": "wm", "sid": store_id,
                          "d": wire.encode(list(durable_entries)),
                          "r": wire.encode(list(redundant_entries))})
        super().record_watermarks(store_id, durable_entries,
                                  redundant_entries)

    def record_bootstrap(self, store_id: int, ranges, epoch: int) -> None:
        if not self._replaying:
            self._append({"k": "bs", "sid": store_id,
                          "rg": wire.encode(ranges), "ep": epoch})
        super().record_bootstrap(store_id, ranges, epoch)

    def record_bootstrapped_at(self, store_id: int, ranges, fence) -> None:
        if not self._replaying:
            self._append({"k": "bsat", "sid": store_id,
                          "rg": wire.encode(ranges),
                          "f": wire.encode(fence)})
        super().record_bootstrapped_at(store_id, ranges, fence)

    def record_bootstrap_done(self, store_id: int, ranges,
                              epoch: int) -> None:
        if not self._replaying:
            self._append({"k": "bsd", "sid": store_id,
                          "rg": wire.encode(ranges), "ep": epoch})
        super().record_bootstrap_done(store_id, ranges, epoch)

    def record_topology(self, doc: dict) -> None:
        """One topology epoch ingested or proposed (r17, elastic serving):
        a WAL fact, so a node killed -9 mid-reconfiguration — proposer
        mid-propose included — recovers holding the exact epoch ledger it
        had.  The doc is already a plain JSON/msgpack payload
        (net.reconfig.topology_to_doc), so it rides the record codec
        as-is."""
        if not self._replaying \
                and not any(d.get("epoch") == doc.get("epoch")
                            for d in self._topologies):
            self._append({"k": "topo", "d": doc})
        super().record_topology(doc)

    def reserve_hlc(self, bound: int) -> None:
        if bound <= self.hlc_reserved:
            return
        if not self._replaying:
            self._append({"k": "hlc", "b": bound})
            # flush-before-issue: the reservation must be ON DISK before
            # any id up to the bound is handed out (one BLOCKING fsync
            # per ~million ids — the restart floor is exact, not a hope)
            self.commit.flush(sync=True)
        super().reserve_hlc(bound)

    # -- durable-only facts --------------------------------------------------
    def record_reply(self, src: str, msg_id: int, body: dict) -> None:
        """A client txn reply this node owes/answered: journaled so a
        restarted incarnation re-serves the SAME reply to a duplicate
        request instead of re-coordinating (at-most-once across death)."""
        if not self._replaying:
            self._append({"k": "reply", "src": src, "m": msg_id, "b": body})
        self._install_reply(src, msg_id, body)

    def replied_body(self, src: str, msg_id: int) -> Optional[dict]:
        return self.replied.get((src, msg_id))

    def _install_reply(self, src: str, msg_id: int, body: dict) -> None:
        key = (src, msg_id)
        if key not in self.replied:
            self._replied_order.append(key)
        self.replied[key] = body
        while len(self._replied_order) > REPLIED_CAP:
            self.replied.pop(self._replied_order.popleft(), None)

    def record_apply(self, token: int, values: tuple, execute_at,
                     txn_id) -> None:
        """One data-store append (the KV log is the node's only other
        durable state; journaling applies + snapshotting the log is what
        makes the 'data store is durable' restore premise true across a
        real process death)."""
        if not self._replaying:
            self._append({"k": "apply", "tok": token,
                          "v": wire.encode(tuple(values)),
                          "at": wire.encode(execute_at),
                          "t": wire.encode(txn_id)})

    def _install_apply(self, token: int, values: tuple, execute_at,
                       txn_id) -> None:
        entries = self._restored_data.setdefault(token, [])
        if any(tid == txn_id for _v, _at, tid in entries):
            return
        entries.append((tuple(values), execute_at, txn_id))

    def install_data(self, data_store: KVDataStore) -> None:
        """Seed a fresh data store with the recovered appends (sorted by
        executeAt, deduped by TxnId — same monotone-union contract as
        install_snapshot)."""
        for token, entries in self._restored_data.items():
            entries.sort(key=lambda e: e[1])
            data_store.log.setdefault(token, []).extend(entries)

    # -- replay (journal/recover.py drives this) -----------------------------
    def apply_record(self, doc: dict) -> None:
        k = doc["k"]
        if k == "msg":
            self.record_message(wire.decode(doc["p"]), doc["f"])
        elif k == "prop":
            self.record_propagate(wire.decode(doc["t"]),
                                  wire.decode(doc["ok"]))
        elif k == "reg":
            if "c" in doc:
                from ..local.status import Durability
                from ..primitives.timestamp import Ballot, Timestamp, TxnId
                sid, t, ss, ex, pr, ac, du = doc["c"]
                if ex is None:
                    ex_v = None
                elif len(ex) == 4:
                    ex_v = TxnId(ex[0], ex[1], ex[2])
                else:
                    ex_v = Timestamp(*ex)
                self._install_register(
                    sid, TxnId(*t), SaveStatus[ss], ex_v,
                    None if pr is None else Ballot(*pr),
                    None if ac is None else Ballot(*ac),
                    Durability[du])
            else:
                # r13/r16 keyed shape: journals on disk outlive code
                self._install_register(
                    doc["sid"], wire.decode(doc["t"]),
                    wire.decode(doc["ss"]), wire.decode(doc["ex"]),
                    wire.decode(doc["pr"]), wire.decode(doc["ac"]),
                    wire.decode(doc["du"]))
        elif k == "wm":
            super().record_watermarks(
                doc["sid"],
                [tuple(e) for e in wire.decode(doc["d"])],
                [tuple(e) for e in wire.decode(doc["r"])])
        elif k == "bs":
            super().record_bootstrap(doc["sid"], wire.decode(doc["rg"]),
                                     doc["ep"])
        elif k == "bsat":
            super().record_bootstrapped_at(doc["sid"],
                                           wire.decode(doc["rg"]),
                                           wire.decode(doc["f"]))
        elif k == "bsd":
            super().record_bootstrap_done(doc["sid"],
                                          wire.decode(doc["rg"]),
                                          doc["ep"])
        elif k == "topo":
            super().record_topology(doc["d"])
        elif k == "hlc":
            super().reserve_hlc(doc["b"])
        elif k == "reply":
            self._install_reply(doc["src"], doc["m"], doc["b"])
        elif k == "apply":
            self._install_apply(doc["tok"], tuple(wire.decode(doc["v"])),
                                wire.decode(doc["at"]),
                                wire.decode(doc["t"]))
        else:
            raise ValueError(f"unknown journal record kind {k!r}")

    def _install_register(self, store_id: int, txn_id, save_status,
                          execute_at, promised, accepted,
                          durability) -> None:
        """Replay-side mirror of Journal.record_registers (which needs a
        live Command; the WAL carries exactly its register columns)."""
        if save_status is SaveStatus.Erased:
            self.drop_register(store_id, txn_id)
            return
        regs = self._registers.setdefault(store_id, {})
        regs[txn_id] = _Registers(save_status, execute_at, promised,
                                  accepted, durability)
        self._note_hlc(txn_id)
        if execute_at is not None:
            self._note_hlc(execute_at)

    # -- whole-state serialization (the snapshot payload) --------------------
    def encode_state(self, data_store: Optional[KVDataStore] = None) -> dict:
        enc = wire.encode

        def enc_req(x):
            # a wire-arrived request carries its own encoded doc
            # (decode∘encode is the identity per the golden-frame gate —
            # the same premise record_message already banks on); the
            # whole-state walk re-encoding every body tree was the
            # snapshot's dominant cost
            d = getattr(x, "_wire_doc", None)
            return d if d is not None else enc(x)

        bodies = []
        for txn_id in sorted(self._bodies):
            b = self._bodies[txn_id]
            bodies.append([enc(txn_id), {
                "txn": enc(b.txn), "route": enc(b.route),
                "accepts": [[enc(bal), enc_req(req)]
                            for bal, req in b.accepts],
                "commit": enc_req(b.commit), "apply": enc_req(b.apply),
                "prop": enc(b.propagate)}])
        registers = []
        for sid in sorted(self._registers):
            regs = self._registers[sid]
            registers.append([sid, [
                [enc(t), [enc(r.save_status), enc(r.execute_at),
                          enc(r.promised), enc(r.accepted),
                          enc(r.durability)]]
                for t, r in sorted(regs.items())]])
        data: Dict[int, List[tuple]] = {}
        for token, entries in self._restored_data.items():
            data[token] = list(entries)
        if data_store is not None:
            for token, entries in data_store.log.items():
                have = {tid for _v, _at, tid in data.get(token, ())}
                data.setdefault(token, []).extend(
                    e for e in entries if e[2] not in have)
        for entries in data.values():
            entries.sort(key=lambda e: e[1])
        return {
            "bodies": bodies,
            "registers": registers,
            "watermarks": [[sid, enc(list(d)), enc(list(r))]
                           for sid, (d, r) in sorted(
                               self._watermarks.items())],
            "bs_started": [[sid, enc(r)] for sid, r in sorted(
                self._bs_started.items())],
            "bs_done": [[sid, enc(r)] for sid, r in sorted(
                self._bs_done.items())],
            "bs_marks": [[sid, [[enc(rg), enc(f)] for rg, f in marks]]
                         for sid, marks in sorted(self._bs_marks.items())],
            "max_hlc": self.max_hlc,
            "hlc_reserved": self.hlc_reserved,
            "replied": [[src, m, self.replied[(src, m)]]
                        for src, m in self._replied_order],
            # topology epoch ledger (r17): plain docs, snapshot-carried so
            # a recovery whose WAL floor passed the topo records still
            # restores the epoch history (absent in pre-r17 snapshots —
            # install_state tolerates the missing key forever)
            "topologies": list(self._topologies),
            "data": [[token, [[enc(v), enc(at), enc(t)]
                              for v, at, t in entries]]
                     for token, entries in sorted(data.items())],
        }

    def install_state(self, state: dict) -> None:
        dec = wire.decode
        for tdoc, bdoc in state["bodies"]:
            b = _Bodies()
            b.txn = dec(bdoc["txn"])
            b.route = dec(bdoc["route"])
            b.accepts = [(dec(bal), dec(req))
                         for bal, req in bdoc["accepts"]]
            b.commit = dec(bdoc["commit"])
            b.apply = dec(bdoc["apply"])
            b.propagate = dec(bdoc["prop"])
            self._bodies[dec(tdoc)] = b
        for sid, regs in state["registers"]:
            out = self._registers.setdefault(sid, {})
            for tdoc, cols in regs:
                out[dec(tdoc)] = _Registers(dec(cols[0]), dec(cols[1]),
                                            dec(cols[2]), dec(cols[3]),
                                            dec(cols[4]))
        for sid, d, r in state["watermarks"]:
            self._watermarks[sid] = ([tuple(e) for e in dec(d)],
                                     [tuple(e) for e in dec(r)])
        for sid, r in state["bs_started"]:
            self._bs_started[sid] = dec(r)
        for sid, r in state["bs_done"]:
            self._bs_done[sid] = dec(r)
        for sid, marks in state["bs_marks"]:
            self._bs_marks[sid] = [(dec(rg), dec(f)) for rg, f in marks]
        self.max_hlc = state["max_hlc"]
        self.hlc_reserved = state["hlc_reserved"]
        for src, m, body in state["replied"]:
            self._install_reply(src, m, body)
        for token, entries in state["data"]:
            self._restored_data[token] = [
                (tuple(dec(v)), dec(at), dec(t)) for v, at, t in entries]
        for doc in state.get("topologies", ()):   # absent pre-r17
            self.record_topology(doc)

    def canonical_state_json(self,
                             data_store: Optional[KVDataStore] = None) -> str:
        """Canonical bytes of the whole journal state — the crash-point
        sweep's byte-identity oracle."""
        import json
        return json.dumps(self.encode_state(data_store), sort_keys=True,
                          separators=(",", ":"))

    # -- snapshot + compaction ----------------------------------------------
    def maybe_snapshot(self, data_store: Optional[KVDataStore] = None,
                       force: bool = False, busy: bool = False) -> bool:
        """Write a snapshot when enough WAL has accumulated since the last
        floor; recycle every segment the new floor strands.

        Serving path (``async_exec`` wired, POSIX): the capture forks —
        the child encodes + writes + ``_exit``s against the fork-instant
        copy-on-write image (the BGSAVE shape), so the whole-state
        ``encode_state`` walk (measured: 300-600ms once the command state
        has grown) never stalls the protocol thread, and consistency is
        the fork's memory snapshot instead of a loop-thread capture.  The
        parent polls for the child and advances the floor on success.

        Fallback (no fork / fork failed): the state is captured on the
        calling (protocol) thread — consistency — and the file write +
        fsync ride the commit's worker when one is wired: an inline
        multi-ms snapshot fsync would stall every peer and client on the
        single event loop (the same stall class the async group commit
        exists to avoid)."""
        if self.commit.failed or self._replaying or self._snap_inflight:
            return False
        since = self.wal.tail_seq - self._snap_floor
        if not force and since < self.snapshot_every:
            return False
        if busy and not force and since < 4 * self.snapshot_every:
            # maintenance yields to traffic (the compaction-throttling
            # discipline): a loaded node defers the whole-state walk to
            # the next load valley — replay stays bounded by the 4x hard
            # cap, past which the snapshot runs regardless
            return False
        from .snapshot import write_snapshot
        floor = self.wal.tail_seq
        if (self.commit.async_exec is not None
                and self.commit.defer is not None and hasattr(os, "fork")):
            forked = self._snapshot_in_child(data_store, floor)
            if forked:
                return True
            # fork failed: fall through to the capture-on-thread paths
        state = self.encode_state(data_store)
        if self.commit.async_exec is not None:
            self._snap_inflight = True

            def work():
                write_snapshot(self.directory, floor, state,
                               metrics=self.metrics)

            def done(exc) -> None:
                self._snap_inflight = False
                if exc is not None:
                    print(f"[journal] snapshot failed: {exc!r}",
                          file=sys.stderr)
                    return
                self._snap_floor = floor
                self.wal.drop_below(floor)

            self.commit.async_exec(work, done)
            return True
        try:
            write_snapshot(self.directory, floor, state,
                           metrics=self.metrics)
        except OSError as exc:
            print(f"[journal] snapshot failed: {exc!r}", file=sys.stderr)
            return False
        self._snap_floor = floor
        self.wal.drop_below(floor)
        return True

    def _snapshot_in_child(self, data_store, floor: int) -> bool:
        """Fork; the child serializes the fork-instant state and writes
        the snapshot file, the parent polls and owns the floor advance.
        Returns False when the fork itself failed (caller falls back)."""
        from .snapshot import write_snapshot
        try:
            import warnings
            with warnings.catch_warnings():
                # jax warns on ANY os.fork in a process with its
                # threads; this child never touches jax (or any lock a
                # worker thread could hold at fork) — it runs pure-python
                # encode + raw file IO and os._exit()s
                warnings.simplefilter("ignore", RuntimeWarning)
                pid = os.fork()
        except OSError as exc:
            print(f"[journal] snapshot fork failed: {exc!r}",
                  file=sys.stderr)
            return False
        if pid == 0:
            # child: encode + write + _exit.  os._exit is REQUIRED — a
            # normal exit would flush the forked copy of the WAL's
            # buffered writer into the SHARED file offset (duplicate
            # bytes under the parent's tail).  No metrics (the parent
            # accounts on reap), no loop, no locks beyond a fresh GIL.
            code = 0
            try:
                write_snapshot(self.directory, floor,
                               self.encode_state(data_store), metrics=None)
            except BaseException:
                code = 1
            os._exit(code)
        self._snap_inflight = True

        def _reap() -> None:
            try:
                done_pid, status = os.waitpid(pid, os.WNOHANG)
                if done_pid == 0:
                    self.commit.defer(0.05, _reap)
                    return
                ok = os.WIFEXITED(status) and os.WEXITSTATUS(status) == 0
            except ChildProcessError:
                # reaped elsewhere (a stray SIGCHLD handler): trust the
                # artifact, not the lost exit status
                ok = os.path.exists(os.path.join(
                    self.directory, f"snap-{floor:016d}.snap"))
            self._snap_inflight = False
            if ok:
                if self.metrics is not None:
                    self.metrics.counter("journal_snapshots").inc()
                    self.metrics.gauge("journal_snapshot_floor").set(floor)
                self._snap_floor = floor
                self.wal.drop_below(floor)
            else:
                print("[journal] snapshot child failed", file=sys.stderr)

        self.commit.defer(0.05, _reap)
        return True

    # -- surface -------------------------------------------------------------
    def stats(self) -> dict:
        return {
            "wal": self.wal.stats(),
            "commit": self.commit.stats(),
            "replay": self.replay_stats,
            "snapshot_floor": self._snap_floor,
            "snapshot_every": self.snapshot_every,
            "registers": sum(len(r) for r in self._registers.values()),
            "bodies": len(self._bodies),
            "replied": len(self.replied),
            "replay_errors": self.replay_errors,
        }

    def close(self) -> None:
        try:
            # BLOCKING final flush: the async path would dispatch to the
            # worker and return, letting wal.close() mark the tail
            # durable without its fsync and close fds under the worker
            self.commit.flush(sync=True)
        finally:
            self.wal.close()


class JournaledKVDataStore(KVDataStore):
    """KVDataStore whose appends are journal facts: with this + the apply
    records, a fresh process recovers the exact value logs — the premise
    'the data store is durable' that Journal.restore() assumes becomes
    true across a real kill -9."""

    def __init__(self, node_id: int, journal: DurableJournal):
        super().__init__(node_id)
        self.journal = journal

    def apply_append(self, token, values, execute_at, txn_id) -> None:
        if not any(tid == txn_id
                   for _v, _at, tid in self.log.get(token, ())):
            self.journal.record_apply(token, values, execute_at, txn_id)
        super().apply_append(token, values, execute_at, txn_id)
