"""Group commit: one fsync acknowledges every append in the batch.

The durability tax of a WAL is the fsync, not the write: appends are
page-cache stores (~µs) while an fsync is device-dependent (~50µs on a
fast NVMe, ~10ms on spinning rust, ~wild on a loaded CI box).  Syncing
per record would put that full cost on EVERY transaction; group commit
opens a short *batching window* after the first un-synced append and one
fsync at window close acknowledges the whole batch — per-txn durability
cost amortizes to fsync/batch_size, exactly the shape of the r08 fused
launches (one launch answers every member store).

The window is PRICED, never a hard threshold (the r06 router discipline):
``probe_fsync_micros`` measures this directory's actual fsync cost once
per process (median of a few 4KB write+fsync rounds) and the window is a
small multiple of it, clamped to sane bounds — a fast device flushes
almost eagerly (window ≈ its own fsync cost: batching can't win much, so
latency isn't spent chasing it), a slow device batches harder (the window
buys proportionally more amortization).

Two more priced decisions joined at r16 (a flush CYCLE has a fixed CPU
cost beyond the fsync — begin/complete bookkeeping, accounting, and the
worker-thread hop when one is wired — and on a fast device that fixed
cost, not the fsync, dominates the journal's per-txn serving tax):

- *offload only when it pays*: the fsync rides ``async_exec`` only when
  the probed fsync cost exceeds ``probe_offload_micros`` (the probed
  round-trip of handing work to a worker thread).  A tmpfs-class fsync
  (~µs) runs inline — burning a ~100µs hop to avoid a ~2µs wait was the
  single largest journal overhead at saturation — while a slow
  filesystem still keeps its multi-ms fsyncs off the event loop.
- *lazy waiter-less windows*: a window close with NO ``after_durable``
  waiter defers once to the ``LAZY_MAX_LAG_MICROS`` horizon instead of
  flushing, so records nobody gates on — protocol facts under
  ``sync=client``, everything under ``periodic`` — and parked
  latest-wins register rows (``deferred_pending``/``pre_flush``) batch
  across windows and pay one flush cycle per lag bound instead of one
  per window.  Crash-equivalent: un-fsynced records die together either
  way; a waiter arriving mid-lag gets a window-delay timer, keeping the
  normal gate-latency bound (and on the eager-gate path its flush skips
  the register drain entirely — ``flush(drain=False)`` — so gating a
  reply never forces parked rows to serialize early).

``after_durable(fn)`` is the acknowledgement edge the serving node hangs
replies on: fn runs once every record appended so far is fsynced — either
immediately (nothing pending) or at the batch's fsync.

Failed fsync is terminal for the durability PROMISE (the postgres
fsync-gate lesson: the kernel may have dropped the dirty pages, so a
retry that "succeeds" proves nothing).  Policy is the r07 ladder's:
degrade loudly, never die — the journal marks itself failed, releases
every waiter (availability over a guarantee it can no longer make),
counts it, and the owner stands journaling down.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Tuple

from .wal import WriteAheadLog

# window = clamp(WINDOW_FACTOR * probed_fsync, MIN, MAX) micros
WINDOW_FACTOR = 2.0
WINDOW_MIN_MICROS = 200
WINDOW_MAX_MICROS = 8_000

# r16: a window close with NO durability waiter defers ONCE to a lag
# horizon instead of flushing (a flush cycle has a real fixed CPU cost —
# begin/fsync/complete/account, plus the offload hop when one is wired —
# and a record nobody is waiting on only needs BOUNDED lag, not a prompt
# fsync; under sync=client the protocol records explicitly ride page
# cache anyway).  The horizon also sets how long latest-wins deferred
# facts (register rows, see ``deferred_pending``) may coalesce before
# they serialize — roughly a command's transition lifetime, so
# back-to-back status rows merge into one record.
LAZY_MAX_LAG_MICROS = 10_000

# once-per-process fsync cost per directory's filesystem (keyed on the
# device id so every journal on one mount shares the probe)
_probe_cache: Dict[int, int] = {}
_offload_probe: List[int] = []


def probe_fsync_micros(directory: str, rounds: int = 5) -> int:
    """Median write+fsync cost of a small record in ``directory``."""
    try:
        dev = os.stat(directory).st_dev
    except OSError:
        dev = -1
    cached = _probe_cache.get(dev)
    if cached is not None:
        return cached
    samples = []
    try:
        fd, path = tempfile.mkstemp(prefix=".fsync-probe-", dir=directory)
        try:
            with os.fdopen(fd, "wb") as f:
                payload = b"\x00" * 4096
                for _ in range(rounds):
                    t0 = time.perf_counter_ns()
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                    samples.append((time.perf_counter_ns() - t0) // 1_000)
        finally:
            os.unlink(path)
    except OSError:
        samples = [1_000]
    samples.sort()
    cost = max(1, samples[len(samples) // 2])
    _probe_cache[dev] = cost
    return cost


def priced_window_micros(directory: str) -> int:
    cost = probe_fsync_micros(directory)
    return max(WINDOW_MIN_MICROS,
               min(WINDOW_MAX_MICROS, int(cost * WINDOW_FACTOR)))


def probe_offload_micros(rounds: int = 64) -> int:
    """Median round-trip of handing a no-op to a worker thread — the
    fixed price of offloading ONE fsync off the event loop.  Probed once
    per process (same discipline as the fsync probe): on a tmpfs-class
    device the fsync is cheaper than the hop and offloading it BURNS
    cpu to avoid a shorter wait, while on a slow filesystem the hop is
    noise against a multi-ms fsync.  ``flush`` compares the two probes
    instead of hardcoding a device class."""
    if _offload_probe:
        return _offload_probe[0]
    import concurrent.futures
    samples = []
    with concurrent.futures.ThreadPoolExecutor(1) as ex:
        ex.submit(lambda: None).result()      # thread spawn off the clock
        for _ in range(rounds):
            t0 = time.perf_counter_ns()
            ex.submit(lambda: None).result()
            samples.append((time.perf_counter_ns() - t0) // 1_000)
    samples.sort()
    cost = max(1, samples[len(samples) // 2])
    _offload_probe.append(cost)
    return cost


class GroupCommit:
    """Batching layer over one :class:`WriteAheadLog`.

    ``defer(delay_seconds, fn)`` schedules the window-close flush (the
    serving node passes ``loop.call_later``); with ``defer=None`` the
    commit runs SYNCHRONOUS — every append flushes immediately (tests,
    and any caller that wants classic sync-per-record semantics)."""

    def __init__(self, wal: WriteAheadLog,
                 defer: Optional[Callable[[float, Callable[[], None]],
                                          object]] = None,
                 window_micros: Optional[int] = None,
                 metrics=None,
                 async_exec: Optional[Callable] = None):
        self.wal = wal
        self.defer = defer
        # async_exec(work, done): run ``work`` OFF the owning thread and
        # call ``done(exception_or_None)`` back ON it.  The serving node
        # passes run_in_executor: an fsync is milliseconds of IO-wait,
        # and paying it inline would stall the single protocol thread
        # for the whole batch window (measured: ~3x goodput loss on a
        # slow /tmp).  None = fsync inline (tests, sim, CLI callers).
        self.async_exec = async_exec
        self.window_micros = (window_micros if window_micros is not None
                              else priced_window_micros(wal.directory))
        self.metrics = metrics
        # r16: optional drain hook run at the top of every flush — the
        # durable journal parks latest-wins facts (register rows) here so
        # one window's worth of transitions serializes ONCE, inside the
        # same write+fsync the window already pays.  Everything buffered
        # since the last flush dies together on a crash either way, so
        # deferring a latest-wins record to the flush it would have died
        # with changes no recoverable state.
        self.pre_flush: Optional[Callable[[], None]] = None
        # offload the fsync only when it costs more than the hop that
        # offloads it (both probed once per process; a tmpfs-class fsync
        # is cheaper inline, a slow filesystem still rides the worker)
        self._offload_pays = (async_exec is not None and
                              probe_fsync_micros(wal.directory)
                              >= probe_offload_micros())
        # serving nodes (worker wired) on a cheap-fsync device flush AT
        # the gate point: the window amortizes fsyncs, and an fsync
        # cheaper than a thread hop is also far cheaper than the timer
        # lateness a gated reply pays on a busy event loop (measured:
        # the dominant journal-on latency tax at saturation, not CPU)
        self._eager_gate = (async_exec is not None
                            and not self._offload_pays)
        # owner-supplied predicate: latest-wins facts parked outside the
        # WAL (register rows) that the next DRAINING flush serializes —
        # a waiter-less window with only these pending defers to the lag
        # horizon so they coalesce instead of flushing per window
        self.deferred_pending: Optional[Callable[[], bool]] = None
        self._lazy_armed = False
        self._timer_gen = 0
        self.failed = False
        self.n_flushes = 0
        self.n_fsync_failures = 0
        self.n_batch_records = 0
        self.n_lazy_rearms = 0
        self._waiters: List[Tuple[int, Callable[[], None]]] = []
        self._flush_scheduled = False
        self._sync_inflight = False
        # the async batch's captured files: a concurrent flush(sync=True)
        # must fsync these TOO before it may advance durable_seq past
        # records the worker has not confirmed yet
        self._inflight_files: List[tuple] = []

    # -- append / acknowledge ------------------------------------------------
    def append(self, doc: dict) -> Optional[int]:
        """One record into the current batch; returns its seq, or None
        when the record did NOT land (journal already degraded, or this
        very write failed and degraded it).  Raises nothing — after
        degrade, appends are absorbed and acked immediately (the
        in-memory journal remains the node's working state)."""
        if self.failed:
            return None
        try:
            seq = self.wal.append(doc)
        except OSError as exc:
            self._degrade(f"append failed: {exc!r}")
            return None
        if self.defer is None:
            self.flush()
        else:
            self._schedule_flush()
        return seq

    def after_durable(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` once everything appended so far is durable."""
        if self.failed or self.wal.durable_seq >= self.wal.tail_seq:
            fn()
            return
        self._waiters.append((self.wal.tail_seq, fn))
        if self._eager_gate:
            self.flush(drain=False)
            return
        self._schedule_flush()

    def _schedule_flush(self) -> None:
        if self.defer is None or self.failed:
            return
        if self._flush_scheduled:
            if self._lazy_armed and self._waiters:
                # the armed timer sits at the lag horizon but a waiter
                # just appeared: supersede it with a window-delay timer
                # so gate latency keeps its normal bound (the generation
                # stamp makes the lazy timer's later firing a no-op)
                self._lazy_armed = False
                self._arm(self.window_micros / 1e6)
            return
        self._flush_scheduled = True
        self._arm(self.window_micros / 1e6)

    def _arm(self, delay_s: float) -> None:
        # generation-stamp every armed timer: re-arming invalidates any
        # outstanding timer, whose late firing would otherwise burn an
        # extra flush / lazy-rearm cycle per supersession
        self._timer_gen += 1
        gen = self._timer_gen
        self.defer(delay_s, lambda: self._window_close(gen))

    def schedule_window(self) -> None:
        """Public arm for callers that parked a deferred record (see
        ``pre_flush``) without appending: the next window close must run
        even if nothing else lands.  Synchronous mode (defer=None)
        flushes immediately — the deferral degenerates to eager."""
        if self.defer is None:
            self.flush()
        else:
            self._schedule_flush()

    def _window_close(self, gen: Optional[int] = None) -> None:
        if gen is not None and gen != self._timer_gen:
            return   # superseded timer
        was_lazy = self._lazy_armed
        self._flush_scheduled = False
        self._lazy_armed = False
        if (not self._waiters and not was_lazy and not self.failed
                and self.defer is not None
                and (self.wal.tail_seq > self.wal.durable_seq
                     or (self.deferred_pending is not None
                         and self.deferred_pending()))):
            # nobody is waiting on durability: ONE deferral to the lag
            # horizon instead of a flush cycle per window — appended
            # records and parked latest-wins facts batch until then (a
            # waiter arriving meanwhile gets a window-delay timer from
            # _schedule_flush, keeping its normal latency bound)
            self.n_lazy_rearms += 1
            self._flush_scheduled = True
            self._lazy_armed = True
            self._arm(LAZY_MAX_LAG_MICROS / 1e6)
            return
        self.flush()

    # -- the durability point ------------------------------------------------
    def flush(self, sync: bool = False, drain: bool = True) -> None:
        """fsync the batch and release every waiter it covers.  With
        ``async_exec`` wired the fsync runs on a worker thread (one in
        flight at a time; a batch that lands mid-sync triggers a
        follow-up); ``sync=True`` forces the inline path — the
        flush-before-issue HLC reservation needs a blocking guarantee.
        ``drain=False`` skips the ``pre_flush`` drain of parked
        latest-wins facts: the at-gate eager flush syncs exactly what a
        waiter gates on, and register rows keep coalescing toward their
        own lag-horizon flush (crash-equivalent — a latest-wins fact
        deferred to the flush it would have died with changes no
        recoverable state)."""
        if drain and self.pre_flush is not None:
            try:
                # drain deferred latest-wins records INTO this batch (the
                # tail_seq read below must see them)
                self.pre_flush()
            except Exception as exc:   # a drain bug must not wedge the
                import sys             # durability point
                print(f"[journal] pre_flush failed: {exc!r}",
                      file=sys.stderr)
        if self.failed:
            self._release(self.wal.tail_seq)
            return
        pending = self.wal.tail_seq - self.wal.durable_seq
        if pending <= 0:
            self._release(self.wal.durable_seq)
            return
        if self._offload_pays and not sync:
            self._flush_async()
            return
        # inline path (sync=True, or no worker wired).  If a worker batch
        # is in flight its files were removed from the dirty set — fsync
        # them HERE TOO before claiming their records durable (concurrent
        # fsync of one fd is kernel-safe; the worker's own completion
        # then lands as a no-op behind the max() guard).
        t0 = time.perf_counter_ns()
        tail, files = self.wal.begin_sync()
        try:
            self.wal.sync_files(files + self._inflight_files)
        except OSError as exc:
            self._degrade(f"fsync failed: {exc!r}")
            self._release(self.wal.tail_seq)
            return
        self.wal.complete_sync(tail, reap=not self._sync_inflight)
        self._account(pending, (time.perf_counter_ns() - t0) // 1_000)
        self._release(tail)

    def _flush_async(self) -> None:
        if self._sync_inflight:
            # the in-flight sync's completion re-checks for new records
            return
        self._sync_inflight = True
        base = self.wal.durable_seq
        tail, files = self.wal.begin_sync()
        self._inflight_files = files
        t0 = time.perf_counter_ns()

        def work():
            self.wal.sync_files(files)

        def done(exc) -> None:
            self._sync_inflight = False
            self._inflight_files = []
            if exc is not None:
                # ValueError = file closed under the worker (shutdown
                # race): same degrade path as a failed fsync, never an
                # unhandled loop exception
                if isinstance(exc, (OSError, ValueError)):
                    self._degrade(f"fsync failed: {exc!r}")
                    self._release(self.wal.tail_seq)
                    return
                raise exc
            self.wal.complete_sync(tail)
            self._account(tail - base,
                          (time.perf_counter_ns() - t0) // 1_000)
            self._release(tail)
            # records that landed while the batch was syncing: open the
            # next window (don't fsync back-to-back for a near-empty
            # batch unless someone is waiting)
            if self.wal.tail_seq > tail and (self._waiters
                                             or self.defer is None):
                if self.defer is not None:
                    self._schedule_flush()
                else:
                    self.flush()

        self.async_exec(work, done)

    def _account(self, batch: int, micros: int) -> None:
        self.n_flushes += 1
        self.n_batch_records += batch
        if self.metrics is not None:
            self.metrics.counter("journal_fsyncs").inc()
            self.metrics.histogram("journal_fsync_micros").observe(micros)
            self.metrics.histogram("journal_commit_batch").observe(batch)

    def _release(self, durable_seq: int) -> None:
        if not self._waiters:
            return
        ready = [fn for seq, fn in self._waiters if seq <= durable_seq]
        self._waiters = [(seq, fn) for seq, fn in self._waiters
                         if seq > durable_seq]
        for fn in ready:
            fn()

    def _degrade(self, why: str) -> None:
        """Durability can no longer be promised: loud, counted, alive."""
        if not self.failed:
            self.failed = True
            self.n_fsync_failures += 1
            if self.metrics is not None:
                self.metrics.counter("journal_fsync_failures").inc()
            print(f"[journal] DEGRADED (durability off): {why}",
                  file=sys.stderr, flush=True)
        # a failed journal still releases everyone: availability over a
        # promise it can no longer make
        self._release(self.wal.tail_seq)

    def stats(self) -> dict:
        return {
            "window_micros": self.window_micros,
            "flushes": self.n_flushes,
            "batch_records": self.n_batch_records,
            "fsync_failures": self.n_fsync_failures,
            "lazy_rearms": self.n_lazy_rearms,
            "fsync_offloaded": self._offload_pays,
            "failed": self.failed,
            "pending_waiters": len(self._waiters),
        }
