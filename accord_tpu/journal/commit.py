"""Group commit: one fsync acknowledges every append in the batch.

The durability tax of a WAL is the fsync, not the write: appends are
page-cache stores (~µs) while an fsync is device-dependent (~50µs on a
fast NVMe, ~10ms on spinning rust, ~wild on a loaded CI box).  Syncing
per record would put that full cost on EVERY transaction; group commit
opens a short *batching window* after the first un-synced append and one
fsync at window close acknowledges the whole batch — per-txn durability
cost amortizes to fsync/batch_size, exactly the shape of the r08 fused
launches (one launch answers every member store).

The window is PRICED, never a hard threshold (the r06 router discipline):
``probe_fsync_micros`` measures this directory's actual fsync cost once
per process (median of a few 4KB write+fsync rounds) and the window is a
small multiple of it, clamped to sane bounds — a fast device flushes
almost eagerly (window ≈ its own fsync cost: batching can't win much, so
latency isn't spent chasing it), a slow device batches harder (the window
buys proportionally more amortization).

``after_durable(fn)`` is the acknowledgement edge the serving node hangs
replies on: fn runs once every record appended so far is fsynced — either
immediately (nothing pending) or at the batch's fsync.

Failed fsync is terminal for the durability PROMISE (the postgres
fsync-gate lesson: the kernel may have dropped the dirty pages, so a
retry that "succeeds" proves nothing).  Policy is the r07 ladder's:
degrade loudly, never die — the journal marks itself failed, releases
every waiter (availability over a guarantee it can no longer make),
counts it, and the owner stands journaling down.
"""

from __future__ import annotations

import os
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Tuple

from .wal import WriteAheadLog

# window = clamp(WINDOW_FACTOR * probed_fsync, MIN, MAX) micros
WINDOW_FACTOR = 2.0
WINDOW_MIN_MICROS = 200
WINDOW_MAX_MICROS = 8_000

# once-per-process fsync cost per directory's filesystem (keyed on the
# device id so every journal on one mount shares the probe)
_probe_cache: Dict[int, int] = {}


def probe_fsync_micros(directory: str, rounds: int = 5) -> int:
    """Median write+fsync cost of a small record in ``directory``."""
    try:
        dev = os.stat(directory).st_dev
    except OSError:
        dev = -1
    cached = _probe_cache.get(dev)
    if cached is not None:
        return cached
    samples = []
    try:
        fd, path = tempfile.mkstemp(prefix=".fsync-probe-", dir=directory)
        try:
            with os.fdopen(fd, "wb") as f:
                payload = b"\x00" * 4096
                for _ in range(rounds):
                    t0 = time.perf_counter_ns()
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                    samples.append((time.perf_counter_ns() - t0) // 1_000)
        finally:
            os.unlink(path)
    except OSError:
        samples = [1_000]
    samples.sort()
    cost = max(1, samples[len(samples) // 2])
    _probe_cache[dev] = cost
    return cost


def priced_window_micros(directory: str) -> int:
    cost = probe_fsync_micros(directory)
    return max(WINDOW_MIN_MICROS,
               min(WINDOW_MAX_MICROS, int(cost * WINDOW_FACTOR)))


class GroupCommit:
    """Batching layer over one :class:`WriteAheadLog`.

    ``defer(delay_seconds, fn)`` schedules the window-close flush (the
    serving node passes ``loop.call_later``); with ``defer=None`` the
    commit runs SYNCHRONOUS — every append flushes immediately (tests,
    and any caller that wants classic sync-per-record semantics)."""

    def __init__(self, wal: WriteAheadLog,
                 defer: Optional[Callable[[float, Callable[[], None]],
                                          object]] = None,
                 window_micros: Optional[int] = None,
                 metrics=None,
                 async_exec: Optional[Callable] = None):
        self.wal = wal
        self.defer = defer
        # async_exec(work, done): run ``work`` OFF the owning thread and
        # call ``done(exception_or_None)`` back ON it.  The serving node
        # passes run_in_executor: an fsync is milliseconds of IO-wait,
        # and paying it inline would stall the single protocol thread
        # for the whole batch window (measured: ~3x goodput loss on a
        # slow /tmp).  None = fsync inline (tests, sim, CLI callers).
        self.async_exec = async_exec
        self.window_micros = (window_micros if window_micros is not None
                              else priced_window_micros(wal.directory))
        self.metrics = metrics
        self.failed = False
        self.n_flushes = 0
        self.n_fsync_failures = 0
        self.n_batch_records = 0
        self._waiters: List[Tuple[int, Callable[[], None]]] = []
        self._flush_scheduled = False
        self._sync_inflight = False
        # the async batch's captured files: a concurrent flush(sync=True)
        # must fsync these TOO before it may advance durable_seq past
        # records the worker has not confirmed yet
        self._inflight_files: List[tuple] = []

    # -- append / acknowledge ------------------------------------------------
    def append(self, doc: dict) -> Optional[int]:
        """One record into the current batch; returns its seq, or None
        when the record did NOT land (journal already degraded, or this
        very write failed and degraded it).  Raises nothing — after
        degrade, appends are absorbed and acked immediately (the
        in-memory journal remains the node's working state)."""
        if self.failed:
            return None
        try:
            seq = self.wal.append(doc)
        except OSError as exc:
            self._degrade(f"append failed: {exc!r}")
            return None
        if self.defer is None:
            self.flush()
        else:
            self._schedule_flush()
        return seq

    def after_durable(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` once everything appended so far is durable."""
        if self.failed or self.wal.durable_seq >= self.wal.tail_seq:
            fn()
            return
        self._waiters.append((self.wal.tail_seq, fn))
        self._schedule_flush()

    def _schedule_flush(self) -> None:
        if self._flush_scheduled or self.defer is None or self.failed:
            return
        self._flush_scheduled = True
        self.defer(self.window_micros / 1e6, self._window_close)

    def _window_close(self) -> None:
        self._flush_scheduled = False
        self.flush()

    # -- the durability point ------------------------------------------------
    def flush(self, sync: bool = False) -> None:
        """fsync the batch and release every waiter it covers.  With
        ``async_exec`` wired the fsync runs on a worker thread (one in
        flight at a time; a batch that lands mid-sync triggers a
        follow-up); ``sync=True`` forces the inline path — the
        flush-before-issue HLC reservation needs a blocking guarantee."""
        if self.failed:
            self._release(self.wal.tail_seq)
            return
        pending = self.wal.tail_seq - self.wal.durable_seq
        if pending <= 0:
            self._release(self.wal.durable_seq)
            return
        if self.async_exec is not None and not sync:
            self._flush_async()
            return
        # inline path (sync=True, or no worker wired).  If a worker batch
        # is in flight its files were removed from the dirty set — fsync
        # them HERE TOO before claiming their records durable (concurrent
        # fsync of one fd is kernel-safe; the worker's own completion
        # then lands as a no-op behind the max() guard).
        t0 = time.perf_counter_ns()
        tail, files = self.wal.begin_sync()
        try:
            self.wal.sync_files(files + self._inflight_files)
        except OSError as exc:
            self._degrade(f"fsync failed: {exc!r}")
            self._release(self.wal.tail_seq)
            return
        self.wal.complete_sync(tail, reap=not self._sync_inflight)
        self._account(pending, (time.perf_counter_ns() - t0) // 1_000)
        self._release(tail)

    def _flush_async(self) -> None:
        if self._sync_inflight:
            # the in-flight sync's completion re-checks for new records
            return
        self._sync_inflight = True
        base = self.wal.durable_seq
        tail, files = self.wal.begin_sync()
        self._inflight_files = files
        t0 = time.perf_counter_ns()

        def work():
            self.wal.sync_files(files)

        def done(exc) -> None:
            self._sync_inflight = False
            self._inflight_files = []
            if exc is not None:
                # ValueError = file closed under the worker (shutdown
                # race): same degrade path as a failed fsync, never an
                # unhandled loop exception
                if isinstance(exc, (OSError, ValueError)):
                    self._degrade(f"fsync failed: {exc!r}")
                    self._release(self.wal.tail_seq)
                    return
                raise exc
            self.wal.complete_sync(tail)
            self._account(tail - base,
                          (time.perf_counter_ns() - t0) // 1_000)
            self._release(tail)
            # records that landed while the batch was syncing: open the
            # next window (don't fsync back-to-back for a near-empty
            # batch unless someone is waiting)
            if self.wal.tail_seq > tail and (self._waiters
                                             or self.defer is None):
                if self.defer is not None:
                    self._schedule_flush()
                else:
                    self.flush()

        self.async_exec(work, done)

    def _account(self, batch: int, micros: int) -> None:
        self.n_flushes += 1
        self.n_batch_records += batch
        if self.metrics is not None:
            self.metrics.counter("journal_fsyncs").inc()
            self.metrics.histogram("journal_fsync_micros").observe(micros)
            self.metrics.histogram("journal_commit_batch").observe(batch)

    def _release(self, durable_seq: int) -> None:
        if not self._waiters:
            return
        ready = [fn for seq, fn in self._waiters if seq <= durable_seq]
        self._waiters = [(seq, fn) for seq, fn in self._waiters
                         if seq > durable_seq]
        for fn in ready:
            fn()

    def _degrade(self, why: str) -> None:
        """Durability can no longer be promised: loud, counted, alive."""
        if not self.failed:
            self.failed = True
            self.n_fsync_failures += 1
            if self.metrics is not None:
                self.metrics.counter("journal_fsync_failures").inc()
            print(f"[journal] DEGRADED (durability off): {why}",
                  file=sys.stderr, flush=True)
        # a failed journal still releases everyone: availability over a
        # promise it can no longer make
        self._release(self.wal.tail_seq)

    def stats(self) -> dict:
        return {
            "window_micros": self.window_micros,
            "flushes": self.n_flushes,
            "batch_records": self.n_batch_records,
            "fsync_failures": self.n_fsync_failures,
            "failed": self.failed,
            "pending_waiters": len(self._waiters),
        }
