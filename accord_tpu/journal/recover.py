"""Crash-recovery replay: snapshot floor first, then the WAL tail.

``replay(journal)`` runs inside :class:`DurableJournal` construction:

1. load the newest VALID snapshot (``snapshot.load_latest`` — CRC-checked,
   an intact runner-up backstops a torn newest) and install its state;
2. replay every WAL record with ``seq > floor`` through the journal's own
   record semantics (``apply_record`` — messages re-enter
   ``record_message``, registers re-install their fixed-width columns, so
   the recovered object is bit-for-bit the journal a crash interrupted);
3. recycle segments the floor strands.

The WAL scan itself (torn-tail truncation, CRC rejection, dropped
unreachable segments) already happened when ``WriteAheadLog`` opened; this
module turns the surviving records back into journal state and reports
the census (``replay_stats``).

The recovered journal then takes the EXISTING restart path: the server
builds its ``Node`` with ``journal=`` and calls ``restore(node)`` —
identical to the sim's ``Cluster.restart_node`` — so one reconstruction
code path serves simulated restarts and real kill -9 recovery.

``open_journal`` is the serving node's one-call entry point.
"""

from __future__ import annotations

import sys
import time
from typing import Optional

from . import snapshot as snapshot_mod


def replay(journal) -> dict:
    """Rebuild ``journal``'s in-memory state from its directory.  Returns
    the replay census (also stored as ``journal.replay_stats``)."""
    t0 = time.perf_counter_ns()
    floor, state = snapshot_mod.load_latest(journal.directory)
    journal._replaying = True
    replayed = skipped = bad = 0
    try:
        if state is not None:
            journal.install_state(state)
        for doc in journal.wal.recovered:
            if doc["s"] <= floor:
                skipped += 1     # already inside the snapshot
                continue
            try:
                journal.apply_record(doc)
                replayed += 1
            except Exception as exc:   # one bad record must not lose the
                bad += 1               # rest of the tail
                print(f"[journal] replay skipped record "
                      f"seq={doc.get('s')} kind={doc.get('k')!r}: "
                      f"{exc!r}", file=sys.stderr)
    finally:
        journal._replaying = False
    journal.wal.drop_below(floor)
    # the parsed tail served its one purpose — holding every record doc
    # for the process lifetime would pin the whole WAL in memory
    journal.wal.recovered = []
    wall = (time.perf_counter_ns() - t0) // 1_000
    stats = {
        "snapshot_floor": floor,
        "snapshot_loaded": state is not None,
        "replayed": replayed,
        "skipped": skipped,
        "bad_records": bad,
        "torn_tail_bytes": journal.wal.n_truncated_bytes,
        "dropped_segments": journal.wal.n_dropped_segments,
        "wall_micros": wall,
        "records_per_sec": (replayed * 1_000_000 // wall) if wall else 0,
    }
    if journal.metrics is not None:
        journal.metrics.gauge("journal_replay_records").set(replayed)
        journal.metrics.gauge("journal_replay_micros").set(wall)
        journal.metrics.gauge("journal_torn_tail_bytes").set(
            journal.wal.n_truncated_bytes)
    return stats


def open_journal(directory: str, *,
                 segment_bytes: Optional[int] = None,
                 snapshot_every: Optional[int] = None,
                 window_micros: Optional[int] = None,
                 defer=None, metrics=None, async_exec=None,
                 sync_policy: Optional[str] = None):
    """The serving node's entry point: open-or-recover a DurableJournal
    at ``directory`` (created if absent)."""
    from .durable import DEFAULT_SNAPSHOT_EVERY, DurableJournal
    from .wal import DEFAULT_SEGMENT_BYTES
    j = DurableJournal(
        directory,
        segment_bytes=segment_bytes or DEFAULT_SEGMENT_BYTES,
        snapshot_every=snapshot_every or DEFAULT_SNAPSHOT_EVERY,
        window_micros=window_micros, defer=defer, metrics=metrics,
        async_exec=async_exec, sync_policy=sync_policy or "client")
    rs = j.replay_stats
    if rs["replayed"] or rs["snapshot_loaded"]:
        print(f"[journal] recovered {directory}: "
              f"snapshot_floor={rs['snapshot_floor']} "
              f"replayed={rs['replayed']} records in "
              f"{rs['wall_micros'] / 1e3:.1f}ms "
              f"(torn_tail={rs['torn_tail_bytes']}B "
              f"bad={rs['bad_records']})", file=sys.stderr, flush=True)
    return j
