"""Versioned binary record format for WAL and snapshot payloads (r16).

The r13 journal serialized every record doc as canonical JSON inside its
CRC frame.  Once the wire went binary (``net/codec.py``) the WAL's
json.dumps/json.loads per record became the largest per-txn serving tax
(`durability_verdict` measured journal-on goodput at ~0.70x journal-off
against a 0.9 floor) — so the record payload gets the SAME discipline as
the wire: a magic byte that can never begin a JSON document, a format
version byte, and a msgpack body, with canonical JSON retained as the
debug codec and as the per-record fallback for values msgpack cannot
carry (>64-bit integers, possible in principle for arbitrary-precision
timestamp words).

Layout (version 1), inside the segment CRC frame::

    [0]    0xB2 magic   (distinct from the wire codec's 0xB1)
    [1]    version (0x01)
    [2:]   record doc as one msgpack document

Decoding SNIFFS per payload — a journal written by a JSON-codec process
replays under a binary-codec process and vice versa, and one segment may
legally mix both (per-record fallback).  An unknown version byte raises
:class:`RecordError` out of the WAL open — the same operator-must-
intervene posture as an unknown SEGMENT version (downgrade under a newer
journal must fail loudly, never silently truncate CRC-valid records as
if they were a torn tail).  The golden pins in
``tests/test_wal.py`` freeze the v1 bytes exactly as the wire pins
freeze theirs: an unversioned format change fails tier-1, and every
supported version's pins must decode forever.

Knob: ``ACCORD_TPU_WAL_CODEC=json|binary`` (default binary; JSON is the
human-greppable debug codec, same role as ``--wire-codec json``).
"""

from __future__ import annotations

import json
import os

try:
    import msgpack as _msgpack
except Exception:   # pragma: no cover - msgpack is baked into the image
    _msgpack = None

MAGIC = 0xB2
VERSION = 1
# versions this decoder accepts (grows on format bumps: old journals on
# disk must keep replaying forever — the golden-pin compatibility gate)
SUPPORTED_VERSIONS = (1,)
_PREFIX = bytes((MAGIC, VERSION))


class RecordError(ValueError):
    """Record-layer format violation (unknown version byte)."""


def binary_available() -> bool:
    return _msgpack is not None


def default_codec() -> str:
    """Resolve the process default: binary unless the debug knob or a
    missing msgpack says JSON."""
    want = os.environ.get("ACCORD_TPU_WAL_CODEC", "binary")
    if want not in ("json", "binary"):
        raise ValueError(f"ACCORD_TPU_WAL_CODEC={want!r} "
                         f"(want json|binary)")
    return want if _msgpack is not None else "json"


def encode_record(doc: dict, codec: str = "binary") -> bytes:
    """One record doc -> payload bytes (no CRC frame).  Binary falls back
    to canonical JSON per-record when msgpack is missing or a value
    exceeds its integer range — the sniffing decoder makes the fallback
    free and lossless."""
    if codec == "binary" and _msgpack is not None:
        try:
            return _PREFIX + _msgpack.packb(doc)
        except (OverflowError, TypeError, ValueError):
            pass   # out-of-range int / exotic value: JSON carries it
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()


def decode_record(payload: bytes) -> dict:
    """Payload bytes -> record doc, sniffing the codec per record."""
    if len(payload) > 1 and payload[0] == MAGIC:
        version = payload[1]
        if version not in SUPPORTED_VERSIONS:
            raise RecordError(
                f"unsupported WAL record version {version} "
                f"(supported: {SUPPORTED_VERSIONS})")
        if _msgpack is None:   # pragma: no cover - image has msgpack
            raise RecordError(
                "binary WAL record but msgpack is unavailable")
        return _msgpack.unpackb(payload[2:])
    return json.loads(payload.decode())
