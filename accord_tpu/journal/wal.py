"""Segmented write-ahead log: monotonic record sequence over segment files.

Layout of a journal directory::

    wal-00000000.seg     segment 0 (base seq 1)
    wal-00000001.seg     segment 1 (base seq = 1 + records in segment 0)
    recycle-0.seg        fully-snapshotted segment awaiting reuse
    snap-<floor>.snap    snapshots (journal/snapshot.py)

Records are docs serialized by the versioned record codec
(``journal/record.py``: 0xB2+version+msgpack by default, canonical JSON
as the debug codec and per-record fallback — decode sniffs, so mixed
journals replay fine); ``append`` stamps each with the next sequence
number under key ``"s"`` and frames it (segment.frame).  Segments roll at
``segment_bytes``; rolling creates (or RECYCLES) the next file and the
old one stays until the snapshot floor passes its last record, at which
point ``drop_below`` moves it into the recycle pool — reusing an
already-allocated file instead of paying create/unlink churn on every
roll (the reference's pre-allocated segment recycling).

Open-time recovery (``recovered`` after construction): segments are
scanned in index order; a torn/corrupt frame truncates that segment and
DROPS every later segment (sequence continuity is the replay contract —
bytes past a corruption are not attributable records), counting what was
lost.  The LAST segment reopens for append at its truncation point.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from . import record as rec_mod
from . import segment as seg_mod
from .segment import Segment, fsync_dir

_SEG_RE = re.compile(r"^wal-(\d{8})\.seg$")
_RECYCLE_RE = re.compile(r"^recycle-(\d+)\.seg$")
DEFAULT_SEGMENT_BYTES = 4 << 20
RECYCLE_POOL_CAP = 4


class _SealedInfo:
    """A closed-for-append segment the floor has not passed yet.  The
    file handle stays open (fobj) while the segment may still need an
    fsync from a batch that spanned a roll; closed when dropped."""

    __slots__ = ("path", "seg_index", "base_seq", "last_seq", "fobj")

    def __init__(self, path: str, seg_index: int, base_seq: int,
                 last_seq: int, fobj=None):
        self.path = path
        self.seg_index = seg_index
        self.base_seq = base_seq
        self.last_seq = last_seq
        self.fobj = fobj


class WriteAheadLog:
    def __init__(self, directory: str,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 record_codec: Optional[str] = None):
        self.directory = directory
        self.segment_bytes = segment_bytes
        # record payload codec for NEW appends; decode always sniffs, so
        # this never constrains what an existing journal may contain
        self.record_codec = (record_codec if record_codec is not None
                             else rec_mod.default_codec())
        os.makedirs(directory, exist_ok=True)
        # counters (mirrored into obs by the owning journal)
        self.n_appended = 0
        self.n_bytes = 0
        self.n_rolled = 0
        self.n_recycled = 0
        self.n_truncated_bytes = 0
        self.n_dropped_segments = 0
        self.recovered: List[dict] = []      # record docs found at open
        self._sealed: List[_SealedInfo] = []  # closed-for-append, live
        self._active: Optional[Segment] = None
        # (fileobj, path) written since the last sync began (a roll
        # mid-batch leaves TWO dirty files; one group-commit fsync must
        # cover both).  begin_sync() hands the list to the syncer —
        # possibly a worker thread — and new appends re-dirty the active
        # file for the NEXT batch.
        self._dirty: List[tuple] = []
        # handles of dropped segments awaiting close (a background sync
        # may still hold them: rename/unlink of an open fd is safe on
        # POSIX, fsync of a CLOSED one is not — so closing defers to the
        # next complete_sync, when no sync is in flight)
        self._retired: List[object] = []
        self._open_or_create()

    # -- open-time scan ------------------------------------------------------
    def _segment_paths(self) -> List[str]:
        out = []
        for name in os.listdir(self.directory):
            if _SEG_RE.match(name):
                out.append(os.path.join(self.directory, name))
        return sorted(out)

    def _recycle_paths(self) -> List[str]:
        out = []
        for name in os.listdir(self.directory):
            if _RECYCLE_RE.match(name):
                out.append(os.path.join(self.directory, name))
        return sorted(out)

    def _open_or_create(self) -> None:
        paths = self._segment_paths()
        tail_seq = 0
        live: List[_SealedInfo] = []     # (path, seg_index, base, last_seq)
        corrupt = False
        for i, path in enumerate(paths):
            if corrupt:
                # continuity broken earlier: these records are not
                # attributable — drop the file
                self.n_dropped_segments += 1
                os.unlink(path)
                continue
            header, payloads, valid_end, size = seg_mod.scan(path)
            if header is None:
                # torn at birth (crash between create and header sync)
                self.n_dropped_segments += 1
                self.n_truncated_bytes += size
                os.unlink(path)
                corrupt = True
                continue
            # identity + continuity checks: a crash between recycling a
            # pool file under a new wal-NN name and persisting its
            # truncate+header can leave the OLD segment's fully CRC-valid
            # frames under the new name — the header's own seg index then
            # disagrees with the filename (and its base gaps the
            # sequence).  Such a file is stale bytes, not records.
            fname_idx = int(_SEG_RE.match(os.path.basename(path)).group(1))
            stale = header[0] != fname_idx or (live and
                                               header[1] != tail_seq + 1)
            if stale:
                self.n_dropped_segments += 1
                self.n_truncated_bytes += size
                os.unlink(path)
                corrupt = True
                continue
            # a payload-less segment still pins the sequence: its base
            # says how many records preceded it (the predecessors may all
            # be recycled below the snapshot floor) — without this a
            # header-only tail reopens at tail_seq=0 and REISSUES seqs
            # under the floor, which the next recovery would skip
            if header[1] - 1 > tail_seq:
                tail_seq = header[1] - 1
            torn = size - valid_end
            if torn > 0:
                self.n_truncated_bytes += torn
                if i < len(paths) - 1:
                    # corruption mid-chain: later segments' records would
                    # gap the sequence — unreachable for replay
                    corrupt = True
            for payload in payloads:
                doc = rec_mod.decode_record(payload)
                tail_seq = int(doc["s"])
                self.recovered.append(doc)
            live.append(_SealedInfo(path, header[0], header[1], tail_seq))
        self.tail_seq = tail_seq
        self.durable_seq = tail_seq      # everything scanned IS on disk
        if live:
            self._active = Segment.open_existing(live[-1].path, tail_seq)
            self._sealed = live[:-1]
        else:
            self._active = self._new_segment(0, tail_seq + 1)

    def _new_segment(self, seg_index: int, base_seq: int) -> Segment:
        path = os.path.join(self.directory, f"wal-{seg_index:08d}.seg")
        pool = self._recycle_paths()
        if pool:
            # recycle: rename an already-allocated file over the new name
            # (truncate happens in create's "wb" open)
            os.replace(pool[0], path)
            self.n_recycled += 1
        s = Segment.create(path, seg_index, base_seq)
        fsync_dir(self.directory)
        return s

    # -- append / roll / sync ------------------------------------------------
    def append(self, doc: dict) -> int:
        """Stamp + frame + write one record; returns its sequence number.
        NOT durable until ``sync`` — the group commit owns that window."""
        seq = self.tail_seq + 1
        doc = dict(doc)
        doc["s"] = seq
        payload = rec_mod.encode_record(doc, self.record_codec)
        if self._active.size >= self.segment_bytes:
            self._roll(seq)
        if not any(f is self._active._f for f, _p in self._dirty):
            self._dirty.append((self._active._f, self._active.path))
        self._active.append(payload, seq)
        self.tail_seq = seq
        self.n_appended += 1
        self.n_bytes += len(payload)
        return seq

    def _roll(self, next_seq: int) -> None:
        """Seal the active segment and open (or recycle) the next.  The
        sealed file handle stays open and DIRTY — the next batch fsync
        covers it; closing here would block the caller on a sync."""
        old = self._active
        old._f.flush()
        self._sealed.append(_SealedInfo(old.path, old.seg_index,
                                        old.base_seq, old.last_seq,
                                        fobj=old._f))
        self._active = self._new_segment(old.seg_index + 1, next_seq)
        self.n_rolled += 1

    # -- the durability point (two-phase so a worker thread can own the
    #    fsyncs while the event loop keeps appending) ------------------------
    def begin_sync(self):
        """Capture the batch: (tail_seq_promised, [(fileobj, path)...]).
        New appends after this call re-dirty files for the NEXT batch."""
        files = self._dirty
        self._dirty = []
        return self.tail_seq, files

    @staticmethod
    def sync_files(files) -> None:
        """flush+fsync the captured files — safe OFF the owning thread."""
        from .segment import fsync_file
        for f, path in files:
            fsync_file(f, path)

    def complete_sync(self, tail_seq: int, reap: bool = True) -> None:
        if tail_seq > self.durable_seq:
            self.durable_seq = tail_seq
        # handles retired by drop_below close only when the caller can
        # vouch no sync still holds them (fsync of a closed fd raises;
        # of a renamed/unlinked open one is fine)
        if reap:
            for f in self._retired:
                try:
                    f.close()
                except OSError:
                    pass
            self._retired = []

    def sync(self) -> int:
        """Synchronous fsync of every dirty segment; returns the durable
        tail.  (The group commit's async mode drives the three-phase API
        directly.)"""
        tail, files = self.begin_sync()
        try:
            self.sync_files(files)
        except OSError:
            # the batch did NOT become durable; re-dirty for the caller's
            # degrade handling (the files may still close cleanly later)
            self._dirty = files + self._dirty
            raise
        self.complete_sync(tail)
        return self.durable_seq

    # -- compaction ----------------------------------------------------------
    def drop_below(self, floor_seq: int) -> int:
        """Recycle sealed segments whose every record is <= floor_seq
        (covered by a durable snapshot).  Returns segments dropped."""
        dropped = 0
        keep: List[_SealedInfo] = []
        for s in self._sealed:
            if s.last_seq <= floor_seq:
                self._recycle_file(s.path)
                if s.fobj is not None:
                    self._retired.append(s.fobj)
                dropped += 1
            else:
                keep.append(s)
        self._sealed = keep
        if dropped:
            fsync_dir(self.directory)
        return dropped

    def _recycle_file(self, path: str) -> None:
        pool = self._recycle_paths()
        if len(pool) >= RECYCLE_POOL_CAP:
            os.unlink(path)
            return
        used = {int(_RECYCLE_RE.match(os.path.basename(p)).group(1))
                for p in pool}
        n = 0
        while n in used:
            n += 1
        os.replace(path, os.path.join(self.directory, f"recycle-{n}.seg"))

    # -- lifecycle -----------------------------------------------------------
    def close(self) -> None:
        if self._active is not None:
            try:
                self.sync()
            except OSError:
                pass
            self._active.close()
            self._active = None
        for s in self._sealed:
            if s.fobj is not None:
                try:
                    s.fobj.close()
                except OSError:
                    pass
                s.fobj = None
        for f in self._retired:
            try:
                f.close()
            except OSError:
                pass
        self._retired = []

    def stats(self) -> Dict[str, int]:
        return {
            "tail_seq": self.tail_seq,
            "durable_seq": self.durable_seq,
            "appended": self.n_appended,
            "bytes": self.n_bytes,
            "rolled": self.n_rolled,
            "recycled": self.n_recycled,
            "truncated_tail_bytes": self.n_truncated_bytes,
            "dropped_segments": self.n_dropped_segments,
            "live_segments": len(self._sealed) + 1,
        }
