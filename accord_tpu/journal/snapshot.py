"""Snapshot + compaction: bound the WAL by periodically serializing state.

A pure WAL replays from the beginning of time; the snapshot is the floor
that lets it forget.  ``write_snapshot`` serializes the journal's whole
in-memory state (registers + message bodies + watermarks + HLC
reservation + client-reply dedupe + data-store log) stamped with the WAL
sequence it covers, using the same CRC frame as a segment record so a
torn snapshot is detected exactly like a torn WAL tail.  Recovery loads
the NEWEST snapshot that validates (an older intact one backstops a torn
newest — which is why the previous snapshot is kept until the next one
lands) and replays only WAL records past its floor.

Segments wholly below the floor are recycled by the caller
(``WriteAheadLog.drop_below``) — the same RedundantBefore-floor shape the
attribution/cleanup path uses: state below a durable watermark is
answered by the watermark, so the log entries that built it are dead.

Write protocol (crash-safe on POSIX rename semantics): tmp file → write
frame → fsync → rename to final name → fsync dir.  A crash anywhere
leaves either the old snapshot set or the new one, never a half-visible
file under the final name.
"""

from __future__ import annotations

import os
import re
import zlib
from typing import List, Optional, Tuple

from . import record as rec_mod
from . import segment as seg_mod
from .segment import fsync_dir, frame

_SNAP_RE = re.compile(r"^snap-(\d{16})\.snap$")
KEEP_SNAPSHOTS = 2


def _snap_paths(directory: str) -> List[Tuple[int, str]]:
    out = []
    for name in os.listdir(directory):
        m = _SNAP_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def write_snapshot(directory: str, floor_seq: int, state: dict,
                   metrics=None) -> str:
    """Durably persist ``state`` covering WAL records <= floor_seq."""
    # same versioned record codec as the WAL (sniffed on load, so a JSON
    # snapshot from an older process keeps loading): the whole-state doc
    # is megabytes at scale, and serializing it shares the GIL with the
    # protocol thread even on the commit worker
    payload = rec_mod.encode_record({"floor": floor_seq, "state": state},
                                    rec_mod.default_codec())
    final = os.path.join(directory, f"snap-{floor_seq:016d}.snap")
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(frame(payload))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    fsync_dir(directory)
    if metrics is not None:
        metrics.counter("journal_snapshots").inc()
        metrics.gauge("journal_snapshot_floor").set(floor_seq)
    # retire all but the newest KEEP_SNAPSHOTS (the runner-up backstops a
    # torn newest; anything older is dead weight)
    snaps = _snap_paths(directory)
    for _floor, path in snaps[:-KEEP_SNAPSHOTS]:
        try:
            os.unlink(path)
        except OSError:
            pass
    return final


def load_latest(directory: str) -> Tuple[int, Optional[dict]]:
    """Newest VALID snapshot as ``(floor_seq, state)``; ``(0, None)``
    when none validates (fresh directory, or every snapshot torn — the
    WAL then replays from its own beginning)."""
    if not os.path.isdir(directory):
        return 0, None
    for floor, path in reversed(_snap_paths(directory)):
        try:
            data = open(path, "rb").read()
        except OSError:
            continue
        # one frame: reuse the segment scanner's CRC discipline by hand
        if len(data) < seg_mod._HDR.size:
            continue
        length, crc = seg_mod._HDR.unpack_from(data, 0)
        payload = data[seg_mod._HDR.size: seg_mod._HDR.size + length]
        if len(payload) != length or zlib.crc32(payload) != crc:
            continue   # torn/corrupt: fall back to the previous snapshot
        try:
            doc = rec_mod.decode_record(payload)
        except rec_mod.RecordError:
            # CRC-valid but unsupported version: a downgrade, not a torn
            # file — falling back to an older snapshot would silently
            # regress acked-durable state
            raise
        except ValueError:
            continue
        return int(doc["floor"]), doc["state"]
    return 0, None
