"""Seeded disk-fault / crash-point self-test — the fault matrix's disk leg.

    python -m accord_tpu.journal.selftest [--seeds 0 5 11]

For every injectable disk-fault class (``utils.faults.DISK_FAULT_KINDS``:
torn_write / short_read / failed_fsync) × seed, plus a seeded
crash-point truncation sweep, the harness:

1. writes a deterministic synthetic record stream (real wire-encoded
   primitives across every record kind) through the full
   WAL + group-commit stack with the fault armed;
2. recovers the directory cold and asserts the PREFIX CONTRACT: the
   recovered state is byte-identical (canonical JSON) to an in-memory
   replay of exactly the records that survived on disk — a fault may
   cost the un-synced tail, never a mis-replay, never a crash;
3. runs every leg TWICE with the same seed and asserts the recovered
   bytes match — the same determinism bar the device and socket halves
   of ``tools/run_fault_matrix.sh`` hold.

Exit 0 on a clean matrix, 1 with a per-leg problem list otherwise.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile
from typing import List, Tuple

from .. import wire
from ..local.status import Durability, SaveStatus
from ..primitives.keys import Range, Ranges
from ..primitives.timestamp import Ballot, Domain, TxnId, TxnKind
from ..utils import faults
from ..utils.random_source import RandomSource
from .durable import DurableJournal


def gen_docs(seed: int, n: int) -> List[dict]:
    """Deterministic mixed-kind record stream from real primitives."""
    rs = RandomSource(seed)
    enc = wire.encode
    docs: List[dict] = []
    for i in range(n):
        tid = TxnId.create(1, 1000 + i * 3 + rs.next_int(2), TxnKind.Write,
                           Domain.Key, 1 + rs.next_int(3))
        kind = rs.next_int(6)
        if kind == 0:
            docs.append({"k": "reg", "sid": rs.next_int(2), "t": enc(tid),
                         "ss": enc(SaveStatus(2 + rs.next_int(8))),
                         "ex": enc(tid), "pr": enc(Ballot.ZERO),
                         "ac": enc(Ballot.ZERO),
                         "du": enc(Durability.NotDurable)})
        elif kind == 1:
            docs.append({"k": "hlc", "b": 1_000_000 + i * 1000})
        elif kind == 2:
            docs.append({"k": "reply", "src": f"c{rs.next_int(4)}",
                         "m": i, "b": {"type": "txn_ok", "txn": [
                             ["append", rs.next_int(64), i]]}})
        elif kind == 3:
            docs.append({"k": "apply", "tok": rs.next_int(64),
                         "v": enc((i, f"v{i}")), "at": enc(tid),
                         "t": enc(tid)})
        elif kind == 4:
            docs.append({"k": "wm", "sid": rs.next_int(2),
                         "d": enc([(0, 1 << 32, tid, tid)]),
                         "r": enc([(0, 1 << 16, tid)])})
        else:
            docs.append({"k": "bsat", "sid": rs.next_int(2),
                         "rg": enc(Ranges.of(Range(0, 1 << 20))),
                         "f": enc(tid)})
    return docs


def reference_state(docs: List[dict], upto_seq: int, workdir: str) -> str:
    """Canonical state of an in-memory replay of records seq <= upto_seq
    (seq is 1-based position in the stream)."""
    ref_dir = os.path.join(workdir, "ref")
    shutil.rmtree(ref_dir, ignore_errors=True)
    j = DurableJournal(ref_dir, defer=None, window_micros=0)
    j._replaying = True
    try:
        for i, doc in enumerate(docs):
            if i + 1 > upto_seq:
                break
            j.apply_record(doc)
    finally:
        j._replaying = False
    out = j.canonical_state_json()
    j.close()
    return out


def write_stream(directory: str, docs: List[dict],
                 segment_bytes: int = 2048) -> DurableJournal:
    """Append the stream through the real stack (tiny segments so legs
    cross roll boundaries); a fired fault stops the stream early, exactly
    like the crash it models."""
    j = DurableJournal(directory, defer=None, window_micros=0,
                       segment_bytes=segment_bytes)
    for doc in docs:
        j.commit.append(doc)
        if j.commit.failed:
            break
    return j


def run_leg(kind: str, seed: int, workdir: str, n: int = 120) -> Tuple[str, dict]:
    """One fault leg: returns (canonical recovered state, census)."""
    docs = gen_docs(seed, n)
    live_dir = os.path.join(workdir, f"live-{kind}-{seed}")
    shutil.rmtree(live_dir, ignore_errors=True)
    if kind == "clean":
        j = write_stream(live_dir, docs)
        written = j.wal.tail_seq
        j.close()
    else:
        prob = {"torn_write": 0.05, "short_read": 0.0,
                "failed_fsync": 0.04}[kind]
        with faults.disk_fault(kind, prob, RandomSource(seed ^ 0xD15C)):
            j = write_stream(live_dir, docs)
        written = j.wal.tail_seq
        # abandon, don't close: close() syncs, and the leg models a death
        j.wal._dirty = []
        try:
            j.wal._active._f.close()
        except Exception:
            pass
    # cold recovery (short_read armed HERE for its leg: the fault is a
    # read-side failure)
    if kind == "short_read":
        with faults.disk_fault(kind, 0.5, RandomSource(seed ^ 0x5EAD)):
            r = DurableJournal(live_dir, defer=None, window_micros=0)
    else:
        r = DurableJournal(live_dir, defer=None, window_micros=0)
    recovered = r.canonical_state_json()
    tail = r.wal.tail_seq
    # census compares across the double-run: deterministic fields ONLY
    # (replay wall-clock stays out)
    census = {"written": written, "recovered_seq": tail,
              "torn_bytes": r.wal.n_truncated_bytes,
              "replayed": r.replay_stats["replayed"],
              "bad": r.replay_stats["bad_records"]}
    r.close()
    # prefix contract: recovered == replay of exactly the surviving seqs
    want = reference_state(docs, tail, workdir)
    if recovered != want:
        raise AssertionError(
            f"{kind} seed {seed}: recovered state diverged from the "
            f"replay of its own surviving prefix (seq<={tail})")
    if tail > written:
        raise AssertionError(
            f"{kind} seed {seed}: recovered MORE records ({tail}) than "
            f"were ever written ({written})")
    return recovered, census


def crash_point_sweep(seed: int, workdir: str, points: int = 40) -> int:
    """Seeded truncation sweep: write a clean stream, then chop the WAL
    at ``points`` drawn byte offsets (mid-frame included) and assert
    every recovery equals the replay of its surviving prefix."""
    docs = gen_docs(seed, 100)
    base = os.path.join(workdir, f"sweep-{seed}")
    shutil.rmtree(base, ignore_errors=True)
    write_stream(base, docs).close()
    seg_paths = sorted(
        os.path.join(base, p) for p in os.listdir(base)
        if p.startswith("wal-"))
    blobs = [open(p, "rb").read() for p in seg_paths]
    total = sum(len(b) for b in blobs)
    rs = RandomSource(seed ^ 0xC4A5)
    checked = 0
    for _ in range(points):
        cut = rs.next_int(total) + 1
        case = os.path.join(workdir, "sweep-case")
        shutil.rmtree(case, ignore_errors=True)
        os.makedirs(case)
        left = cut
        for p, blob in zip(seg_paths, blobs):
            take = min(left, len(blob))
            left -= take
            if take > 0:
                with open(os.path.join(case, os.path.basename(p)),
                          "wb") as f:
                    f.write(blob[:take])
        r = DurableJournal(case, defer=None, window_micros=0)
        got = r.canonical_state_json()
        tail = r.wal.tail_seq
        r.close()
        want = reference_state(docs, tail, workdir)
        if got != want:
            raise AssertionError(
                f"sweep seed {seed} cut {cut}: recovered state != replay "
                f"of surviving prefix (seq<={tail})")
        checked += 1
    return checked


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description="journal disk-fault self-test")
    p.add_argument("--seeds", type=int, nargs="+", default=[0, 5, 11])
    p.add_argument("--workdir", default=None)
    args = p.parse_args(argv)
    workdir = args.workdir or tempfile.mkdtemp(prefix="accord_journal_st_")
    kinds = ["clean"] + sorted(faults.DISK_FAULT_KINDS)
    failures = []
    for seed in args.seeds:
        for kind in kinds:
            try:
                a, ca = run_leg(kind, seed, workdir)
                b, cb = run_leg(kind, seed, workdir)
                line = (f"seed {seed} {kind:>13}: written={ca['written']} "
                        f"recovered={ca['recovered_seq']} "
                        f"torn_bytes={ca['torn_bytes']}")
                if a != b or ca != cb:
                    failures.append(f"seed {seed} {kind}: NONDETERMINISTIC "
                                    f"recovery")
                    line += "  <-- NONDETERMINISTIC"
            except AssertionError as exc:
                failures.append(str(exc))
                line = f"seed {seed} {kind:>13}: FAILED {exc}"
            print(line, flush=True)
        try:
            n = crash_point_sweep(seed, workdir)
            print(f"seed {seed}   crash-sweep: {n} truncation points clean",
                  flush=True)
        except AssertionError as exc:
            failures.append(str(exc))
            print(f"seed {seed}   crash-sweep: FAILED {exc}", flush=True)
    shutil.rmtree(workdir, ignore_errors=True)
    if failures:
        print("\nDISK FAULT LEG FAILED:")
        for f in failures:
            print("  " + f)
        return 1
    print("\ndisk fault leg clean: every class x seed deterministic, "
          "recovery == replay of the surviving prefix")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
