"""Durable storage beneath the message-sourced journal (ISSUE r13).

Layers (each its own module, composable and separately testable):

- ``segment``  — length+CRC32-framed records in fixed-size files;
  torn-tail truncation on open; seedable disk faults at every I/O.
- ``wal``      — the segmented append-only log: monotonic sequence,
  rolling, recycling of fully-snapshotted segments.
- ``commit``   — group commit: one fsync acknowledges the batch, the
  batching window priced off a once-per-process fsync micro-probe.
- ``snapshot`` — whole-state snapshots that bound replay and set the
  segment-recycling floor.
- ``durable``  — :class:`DurableJournal`, the drop-in ``Journal``
  subclass the serving node hands to ``Node(journal=...)``, plus
  :class:`JournaledKVDataStore`.
- ``recover``  — crash-recovery replay and the ``open_journal`` entry
  point.
- ``selftest`` — the seeded disk-fault/crash-point harness the fault
  matrix runs (``ACCORD_TPU_FAULT_MATRIX=disk``).
"""

from .durable import DurableJournal, JournaledKVDataStore  # noqa: F401
from .recover import open_journal                          # noqa: F401
from .wal import WriteAheadLog                             # noqa: F401
