"""The SPI (ports) an integration implements.

TPU-native rebuild of the reference's accord.api package
(ref: accord-core/src/main/java/accord/api/ — Agent.java:33-70,
DataStore.java:39-111, MessageSink.java:28, ConfigurationService.java:59,
ProgressLog.java:59-213, Scheduler.java:26, TopologySorter.java,
Read.java/Update.java/Query.java, EventsListener.java:26-60,
config/LocalConfig.java:23-29).

These are the seams that the simulator, the maelstrom adapter, tests, and a
production integration plug into.  All are duck-typed ABCs; the data-plane
interfaces (Read/Write/Update/Query) return AsyncChains so store execution
can be batched onto the device without changing callers.
"""

from __future__ import annotations

import abc
from typing import Callable, List, Optional, Sequence

from ..primitives.keys import Ranges, Seekables
from ..primitives.timestamp import Timestamp, TxnId
from ..utils import async_chain


# ---------------------------------------------------------------------------
# Data plane (workload-defined)
# ---------------------------------------------------------------------------

class Data(abc.ABC):
    """Result of reads, mergeable across shards (ref: api/Data.java)."""

    @abc.abstractmethod
    def merge(self, other: "Data") -> "Data": ...


class Result:
    """Marker for the client-visible result (ref: api/Result.java)."""


class Read(abc.ABC):
    """(ref: api/Read.java) — read() returns an AsyncChain of Data."""

    @abc.abstractmethod
    def keys(self) -> Seekables: ...

    @abc.abstractmethod
    def read(self, key, safe_store, execute_at: Timestamp,
             store: "DataStore") -> "async_chain.AsyncChain[Data]": ...

    @abc.abstractmethod
    def slice(self, ranges: Ranges) -> "Read": ...

    @abc.abstractmethod
    def merge(self, other: Optional["Read"]) -> "Read": ...


class Write(abc.ABC):
    """(ref: api/Write.java)."""

    @abc.abstractmethod
    def apply(self, key, txn_id: TxnId, execute_at: Timestamp,
              store: "DataStore") -> "async_chain.AsyncChain": ...


class Update(abc.ABC):
    """(ref: api/Update.java)."""

    @abc.abstractmethod
    def keys(self) -> Seekables: ...

    @abc.abstractmethod
    def apply(self, execute_at: Timestamp, data: Optional[Data]) -> Write: ...

    @abc.abstractmethod
    def slice(self, ranges: Ranges) -> "Update": ...

    @abc.abstractmethod
    def merge(self, other: Optional["Update"]) -> "Update": ...


class Query(abc.ABC):
    """(ref: api/Query.java)."""

    @abc.abstractmethod
    def compute(self, txn_id: TxnId, execute_at: Timestamp, keys: Seekables,
                data: Optional[Data], read: Optional[Read],
                update: Optional[Update]) -> Result: ...


# ---------------------------------------------------------------------------
# DataStore + bootstrap fetch contract
# ---------------------------------------------------------------------------

class FetchRanges(abc.ABC):
    """Callbacks a fetch implementation reports into
    (ref: api/DataStore.java:49-86 StartingRangeFetch lifecycle)."""

    @abc.abstractmethod
    def starting(self, ranges: Ranges) -> "AbortFetch": ...

    @abc.abstractmethod
    def fetched(self, ranges: Ranges) -> None: ...

    @abc.abstractmethod
    def fail(self, ranges: Ranges, failure: BaseException) -> None: ...


class AbortFetch(abc.ABC):
    @abc.abstractmethod
    def abort(self) -> None: ...


class FetchResult(async_chain.AsyncResult):
    """Completes with the Ranges successfully fetched; cancellable
    (ref: api/DataStore.java:88-111)."""

    def abort(self) -> None:
        pass


class DataStore(abc.ABC):
    """Storage marker + snapshot fetch for bootstrap
    (ref: api/DataStore.java:39-111)."""

    def fetch(self, node, safe_store, ranges: Ranges, sync_point,
              fetch_ranges: FetchRanges) -> FetchResult:
        raise NotImplementedError

    def snapshot(self, ranges: Ranges) -> object:
        """Export the store's content for ``ranges`` (bootstrap donor side).
        The return value is opaque to the framework — it is shipped to the
        joining replica and handed to install_snapshot."""
        raise NotImplementedError

    def install_snapshot(self, snapshot: object) -> None:
        """Install a snapshot exported by a peer's snapshot() (bootstrap
        recipient side).  Must be idempotent and must keep any newer local
        writes (per-key last-writer-wins on executeAt)."""
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Node-level callbacks
# ---------------------------------------------------------------------------

class Agent(abc.ABC):
    """Node-level integration callbacks (ref: api/Agent.java:33-70)."""

    def on_recover(self, node, success_result, fail) -> None:
        pass

    def on_inconsistent_timestamp(self, command, prev: Timestamp, next_ts: Timestamp) -> None:
        raise AssertionError(f"inconsistent timestamp: {prev} vs {next_ts}")

    def on_failed_bootstrap(self, phase: str, ranges: Ranges,
                            retry: Callable[[], None], failure: BaseException) -> None:
        retry()

    def on_stale(self, stale_since: Timestamp, ranges: Ranges) -> None:
        pass

    def on_uncaught_exception(self, failure: BaseException) -> None:
        raise failure

    def on_handled_exception(self, failure: BaseException) -> None:
        pass

    def is_expired(self, initiated_at: TxnId, now_micros: int) -> bool:
        """PreAccept timeout policy (ref: Agent.java preAcceptTimeout)."""
        return now_micros - initiated_at.hlc() > 1_000_000

    def expensive_to_coordinate(self, txn_id: TxnId) -> bool:
        return False

    def events_listener(self) -> "EventsListener":
        return NOOP_EVENTS


# ---------------------------------------------------------------------------
# Network out
# ---------------------------------------------------------------------------

class Callback(abc.ABC):
    """Reply handler for a request (ref: messages/Callback.java)."""

    @abc.abstractmethod
    def on_success(self, from_id: int, reply) -> None: ...

    @abc.abstractmethod
    def on_failure(self, from_id: int, failure: BaseException) -> None: ...

    def on_callback_failure(self, from_id: int, failure: BaseException) -> None:
        raise failure


class MessageSink(abc.ABC):
    """Network out (ref: api/MessageSink.java:28)."""

    @abc.abstractmethod
    def send(self, to: int, request) -> None: ...

    @abc.abstractmethod
    def send_with_callback(self, to: int, request, callback: Callback) -> None: ...

    @abc.abstractmethod
    def reply(self, to: int, reply_context, reply) -> None: ...

    def reply_with_unknown_failure(self, to: int, reply_context, failure: BaseException) -> None:
        from ..messages.base import FailureReply
        self.reply(to, reply_context, FailureReply(failure))


# ---------------------------------------------------------------------------
# Topology epoch source
# ---------------------------------------------------------------------------

class EpochReady:
    """Four-phase epoch readiness futures
    (ref: api/ConfigurationService.java EpochReady {metadata, coordination,
    data, reads})."""

    __slots__ = ("epoch", "metadata", "coordination", "data", "reads")

    def __init__(self, epoch: int,
                 metadata: async_chain.AsyncResult,
                 coordination: async_chain.AsyncResult,
                 data: async_chain.AsyncResult,
                 reads: async_chain.AsyncResult):
        self.epoch = epoch
        self.metadata = metadata
        self.coordination = coordination
        self.data = data
        self.reads = reads

    @classmethod
    def done(cls, epoch: int) -> "EpochReady":
        r = async_chain.AsyncResult()
        r.set_success(None)
        return cls(epoch, r, r, r, r)


class ConfigurationServiceListener(abc.ABC):
    def on_topology_update(self, topology, started_sync) -> async_chain.AsyncResult: ...
    def on_remote_sync_complete(self, node_id: int, epoch: int) -> None: ...
    def on_epoch_closed(self, ranges: Ranges, epoch: int) -> None: ...
    def on_epoch_redundant(self, ranges: Ranges, epoch: int) -> None: ...


class ConfigurationService(abc.ABC):
    """(ref: api/ConfigurationService.java:59)."""

    @abc.abstractmethod
    def register_listener(self, listener: ConfigurationServiceListener) -> None: ...

    @abc.abstractmethod
    def current_topology(self): ...

    @abc.abstractmethod
    def get_topology_for_epoch(self, epoch: int): ...

    @abc.abstractmethod
    def fetch_topology_for_epoch(self, epoch: int) -> None: ...

    @abc.abstractmethod
    def acknowledge_epoch(self, epoch_ready: EpochReady, start_sync: bool) -> None: ...

    def report_epoch_closed(self, ranges: Ranges, epoch: int) -> None:
        pass

    def report_epoch_redundant(self, ranges: Ranges, epoch: int) -> None:
        pass


# ---------------------------------------------------------------------------
# Liveness driver
# ---------------------------------------------------------------------------

class ProgressLog(abc.ABC):
    """Per-store liveness hooks, invoked on every status transition
    (ref: api/ProgressLog.java:59-213)."""

    def unwitnessed(self, safe_store, txn_id: TxnId) -> None: ...
    def pre_accepted(self, safe_store, txn_id: TxnId) -> None: ...
    def accepted(self, safe_store, txn_id: TxnId) -> None: ...
    def precommitted(self, safe_store, txn_id: TxnId) -> None: ...
    def stable(self, safe_store, txn_id: TxnId) -> None: ...
    def ready_to_execute(self, safe_store, txn_id: TxnId) -> None: ...
    def executed(self, safe_store, txn_id: TxnId) -> None: ...
    def durable(self, safe_store, txn_id: TxnId) -> None: ...
    def durable_local(self, safe_store, txn_id: TxnId) -> None: ...
    def waiting(self, blocked_by: TxnId, blocked_until: int, route, participants) -> None: ...
    def clear(self, txn_id: TxnId) -> None: ...


class NoOpProgressLog(ProgressLog):
    pass


# ---------------------------------------------------------------------------
# Timers
# ---------------------------------------------------------------------------

class Scheduled(abc.ABC):
    @abc.abstractmethod
    def cancel(self) -> None: ...

    def is_cancelled(self) -> bool:
        return False


class Scheduler(abc.ABC):
    """(ref: api/Scheduler.java:26)."""

    @abc.abstractmethod
    def once(self, delay_micros: int, run: Callable[[], None]) -> Scheduled: ...

    @abc.abstractmethod
    def recurring(self, interval_micros: int, run: Callable[[], None]) -> Scheduled: ...

    @abc.abstractmethod
    def now(self, run: Callable[[], None]) -> None: ...


# ---------------------------------------------------------------------------
# Replica contact ordering
# ---------------------------------------------------------------------------

class TopologySorter(abc.ABC):
    """(ref: api/TopologySorter.java) — compare two replicas for contact
    preference within some Topologies."""

    @abc.abstractmethod
    def compare(self, a: int, b: int, shards) -> int: ...


# ---------------------------------------------------------------------------
# Metrics events
# ---------------------------------------------------------------------------

class EventsListener:
    """(ref: api/EventsListener.java:26-60)."""

    def on_committed(self, txn_id: TxnId) -> None: ...
    def on_stable(self, command) -> None: ...
    def on_executed(self, command) -> None: ...
    def on_applied(self, command, start_nanos: int, end_nanos: int) -> None: ...
    def on_fast_path_taken(self, txn_id: TxnId, deps) -> None: ...
    def on_slow_path_taken(self, txn_id: TxnId, deps) -> None: ...
    def on_recover(self, txn_id: TxnId, outcome) -> None: ...
    def on_preempted(self, txn_id: TxnId) -> None: ...
    def on_timeout(self, txn_id: TxnId) -> None: ...
    def on_invalidated(self, txn_id: TxnId) -> None: ...


NOOP_EVENTS = EventsListener()


# ---------------------------------------------------------------------------
# Local config
# ---------------------------------------------------------------------------

class LocalConfig:
    """(ref: config/LocalConfig.java:23-29)."""

    def progress_log_schedule_delay_micros(self) -> int:
        return 200_000


class MutableLocalConfig(LocalConfig):
    def __init__(self, progress_delay_micros: int = 200_000):
        self._progress_delay = progress_delay_micros

    def progress_log_schedule_delay_micros(self) -> int:
        return self._progress_delay

    def set_progress_log_schedule_delay_micros(self, v: int) -> None:
        self._progress_delay = v
