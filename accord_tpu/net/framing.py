"""Length-prefixed framing for the TCP serving surface.

One frame = 4-byte big-endian payload length + payload.  The payload is
one ``{src, dest, body}`` packet under either wire codec (``net.codec``):
UTF-8 JSON (the r12 format, kept as the debug codec) or the versioned
binary encoding, sniffed per frame by its magic byte.  The decoder is a
plain byte-stream state machine so a frame survives ANY segmentation the
kernel chooses — partial reads mid-header, mid-payload, or many frames
coalesced into one read — and the golden-frame test asserts byte-identical
round trips over a real loopback socket under all three.

A frame larger than ``MAX_FRAME`` is a protocol violation (a desynced or
hostile peer), surfaced as :class:`FrameError` so the connection layer can
drop the link instead of allocating unboundedly.
"""

from __future__ import annotations

import struct
from typing import List

from .codec import decode_payload, encode_packet

_LEN = struct.Struct(">I")

# largest legal payload: generously above any protocol message (a full
# CheckStatusOk with writes), far below anything that smells like reading
# TLS/HTTP bytes as a length prefix
MAX_FRAME = 16 * 1024 * 1024


class FrameError(ValueError):
    """Framing-layer protocol violation (oversized/garbage length)."""


def encode_frame(packet: dict, codec: str = "json") -> bytes:
    """One packet dict -> length-prefixed wire bytes under ``codec``
    ("json" default — the debug codec — or "binary").  Under either,
    key order is preserved, so decode -> re-encode reproduces the exact
    bytes (the golden-frame contract)."""
    return prefix_payload(encode_packet(packet, codec))


def prefix_payload(payload: bytes) -> bytes:
    """Length-prefix an ALREADY-encoded frame payload (the server's
    encode-once send path; chunk streaming re-slices the same bytes)."""
    if len(payload) > MAX_FRAME:
        raise FrameError(f"frame of {len(payload)} bytes exceeds MAX_FRAME")
    return _LEN.pack(len(payload)) + payload


class FrameDecoder:
    """Incremental decoder: ``feed(chunk)`` returns every COMPLETE packet
    the stream holds so far (codec sniffed per frame), buffering any
    trailing partial frame.  ``feed_raw`` returns the undecoded payloads
    instead — the server's pre-decode admission path."""

    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = bytearray()

    def feed_raw(self, data: bytes) -> List[bytes]:
        self._buf.extend(data)
        out: List[bytes] = []
        while True:
            if len(self._buf) < _LEN.size:
                return out
            (n,) = _LEN.unpack_from(self._buf)
            if n > MAX_FRAME:
                raise FrameError(f"frame length {n} exceeds MAX_FRAME "
                                 f"(desynced or non-protocol peer)")
            if len(self._buf) < _LEN.size + n:
                return out
            payload = bytes(self._buf[_LEN.size:_LEN.size + n])
            del self._buf[:_LEN.size + n]
            out.append(payload)

    def feed(self, data: bytes) -> List[dict]:
        return [decode_payload(p) for p in self.feed_raw(data)]

    def pending_bytes(self) -> int:
        return len(self._buf)
