"""Length-prefixed JSON framing for the TCP serving surface.

One frame = 4-byte big-endian payload length + UTF-8 JSON payload (the same
``{src, dest, body}`` packet dicts the Maelstrom adapter exchanges as
stdin/stdout lines).  The decoder is a plain byte-stream state machine so a
frame survives ANY segmentation the kernel chooses — partial reads mid-
header, mid-payload, or many frames coalesced into one read — and the
golden-frame test asserts byte-identical round trips over a real loopback
socket under all three.

A frame larger than ``MAX_FRAME`` is a protocol violation (a desynced or
hostile peer), surfaced as :class:`FrameError` so the connection layer can
drop the link instead of allocating unboundedly.
"""

from __future__ import annotations

import json
import struct
from typing import List

_LEN = struct.Struct(">I")

# largest legal payload: generously above any protocol message (a full
# CheckStatusOk with writes), far below anything that smells like reading
# TLS/HTTP bytes as a length prefix
MAX_FRAME = 16 * 1024 * 1024


class FrameError(ValueError):
    """Framing-layer protocol violation (oversized/garbage length)."""


def encode_frame(packet: dict) -> bytes:
    """One packet dict -> length-prefixed wire bytes.  Encoding is plain
    ``json.dumps`` with compact separators; key order is preserved, so
    decode -> re-encode reproduces the exact bytes (the golden-frame
    contract)."""
    payload = json.dumps(packet, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME:
        raise FrameError(f"frame of {len(payload)} bytes exceeds MAX_FRAME")
    return _LEN.pack(len(payload)) + payload


class FrameDecoder:
    """Incremental decoder: ``feed(chunk)`` returns every COMPLETE packet
    the stream holds so far, buffering any trailing partial frame."""

    __slots__ = ("_buf",)

    def __init__(self):
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[dict]:
        self._buf.extend(data)
        out: List[dict] = []
        while True:
            if len(self._buf) < _LEN.size:
                return out
            (n,) = _LEN.unpack_from(self._buf)
            if n > MAX_FRAME:
                raise FrameError(f"frame length {n} exceeds MAX_FRAME "
                                 f"(desynced or non-protocol peer)")
            if len(self._buf) < _LEN.size + n:
                return out
            payload = bytes(self._buf[_LEN.size:_LEN.size + n])
            del self._buf[:_LEN.size + n]
            out.append(json.loads(payload.decode("utf-8")))

    def pending_bytes(self) -> int:
        return len(self._buf)
